"""Builder functions for the cross-host data-plane tests (imported by the
dcn worker subprocesses via --builder tests/dcn_jobs.py:NAME)."""

import numpy as np

from flink_tpu.runtime.dcn import DCNJobSpec, GeneratorPartitionSource

N_KEYS = 977           # prime: keys spread over all key groups
TOTAL_PER_HOST = 40_000
WIN_MS = 1_000
TS_DIV = 16            # ts advances 1ms per TS_DIV records


def _source(pid, nproc, total=TOTAL_PER_HOST):
    # host p ingests ONLY keys congruent to p mod nproc — a genuinely
    # DISJOINT key slice per host (key % nproc identifies the ingesting
    # host), so any key firing on the other host provably crossed the
    # process boundary through the all_to_all
    per_host = N_KEYS // nproc

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        keys = pid + nproc * (idx % per_host)
        ts = idx // TS_DIV
        return keys, ts, np.ones(n, np.float32)

    return GeneratorPartitionSource(gen, total)


def two_host_window():
    return DCNJobSpec(
        source_factory=_source,
        size_ms=WIN_MS,
        capacity_per_shard=2048,
        max_parallelism=64,
        batch_per_host=2048,
        fires_per_step=4,
    )


def expected(nproc):
    """Per-(key, window_end) expected sums across all hosts."""
    per_host = N_KEYS // nproc
    exp = {}
    for pid in range(nproc):
        for i in range(TOTAL_PER_HOST):
            k = pid + nproc * (i % per_host)
            w = ((i // TS_DIV) // WIN_MS + 1) * WIN_MS
            exp[(k, w)] = exp.get((k, w), 0) + 1.0
    return exp


# -- round 20: per-host resident mode (ISSUE 20b) --------------------------

RESIDENT_DEPTH = 4


def two_host_window_resident():
    spec = two_host_window()
    spec.resident = True
    spec.resident_ring_depth = RESIDENT_DEPTH
    return spec


def skewed_window_rebalanced_resident():
    """Rebalance side channel + resident drains: the peer exchange runs
    only at drain boundaries with the frame deadline scaled by the
    previous drain's slot count."""
    spec = skewed_window_rebalanced()
    spec.resident = True
    spec.resident_ring_depth = RESIDENT_DEPTH
    return spec


# -- round 5: generalized plane (sliding + sessions + env.execute) --------

SLIDE_MS = 500
GAP_MS = 40
SESSION_TOTAL = 12_000
SESSION_KEYS = 61        # small key set: sessions interleave heavily
BURST = 5                # events per session burst
IDLE = 120               # ms between a key's bursts (> GAP_MS: new session)


def two_host_sliding():
    """size=1000/slide=500: every record lands in 2 windows."""
    return DCNJobSpec(
        source_factory=_source,
        size_ms=WIN_MS,
        slide_ms=SLIDE_MS,
        capacity_per_shard=2048,
        max_parallelism=64,
        batch_per_host=2048,
        fires_per_step=4,
    )


def expected_sliding(nproc):
    per_host = N_KEYS // nproc
    exp = {}
    for pid in range(nproc):
        for i in range(TOTAL_PER_HOST):
            k = pid + nproc * (i % per_host)
            ts = i // TS_DIV
            # windows [end-size, end) containing ts, ends on slide grid
            first_end = (ts // SLIDE_MS + 1) * SLIDE_MS
            end = first_end
            while end < ts + WIN_MS + 1:
                if end - WIN_MS <= ts < end:
                    exp[(k, end)] = exp.get((k, end), 0) + 1.0
                end += SLIDE_MS
    return exp


def _session_source(pid, nproc):
    """Host p ingests keys ≡ p (mod nproc); each key emits bursts of
    BURST events 1ms apart, separated by IDLE ms (> gap: session break).
    ts is globally nondecreasing per host so the monotonic watermark is
    valid."""
    per_host = SESSION_KEYS // nproc

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        keys = pid + nproc * (idx % per_host)
        burst = idx // (per_host * BURST)       # which burst round
        within = (idx // per_host) % BURST      # position inside burst
        ts = burst * IDLE + within
        return keys, ts, np.ones(n, np.float32)

    return GeneratorPartitionSource(gen, SESSION_TOTAL)


def two_host_session():
    return DCNJobSpec(
        source_factory=_session_source,
        window_kind="session",
        gap_ms=GAP_MS,
        capacity_per_shard=1024,
        max_parallelism=64,
        batch_per_host=1024,
    )


def expected_sessions(nproc):
    """{(key, start, end): sum} from the scalar merging model."""
    events = []
    per_host = SESSION_KEYS // nproc
    for pid in range(nproc):
        for i in range(SESSION_TOTAL):
            k = pid + nproc * (i % per_host)
            burst = i // (per_host * BURST)
            within = (i // per_host) % BURST
            events.append((k, burst * IDLE + within))
    sessions = {}
    for k, ts in events:
        lst = sessions.setdefault(k, [])
        hit = None
        for s in lst:
            if s[0] - GAP_MS <= ts <= s[1] + GAP_MS:
                s[0] = min(s[0], ts)
                s[1] = max(s[1], ts)
                s[2] += 1.0
                hit = s
                break
        if hit is None:
            lst.append([ts, ts, 1.0])
    return {
        (k, s[0], s[1] + GAP_MS): s[2]
        for k, lst in sessions.items() for s in lst
    }


# -- round 5: physical rebalance (90/10 skewed hosts) ---------------------

SKEW_TOTAL = 60_000      # records across BOTH hosts
SKEW_FRAC = 0.9          # host 0 ingests 90%


def _skewed_source(pid, nproc):
    """Host 0 holds 90% of the stream, host 1 the rest (the skewed
    partition assignment RebalancePartitioner exists for). Keys/ts are a
    GLOBAL schedule indexed by each host's slice so expectations don't
    depend on which host processes a record."""
    assert nproc == 2
    n0 = int(SKEW_TOTAL * SKEW_FRAC)
    base = 0 if pid == 0 else n0
    total = n0 if pid == 0 else SKEW_TOTAL - n0

    def gen(offset, n):
        idx = np.arange(base + offset, base + offset + n, dtype=np.int64)
        keys = idx % N_KEYS
        ts = idx // TS_DIV     # monotonic in idx: per-host watermarks valid
        return keys, ts, np.ones(n, np.float32)

    return GeneratorPartitionSource(gen, total)


def skewed_window(rebalance_addrs=None):
    return DCNJobSpec(
        source_factory=_skewed_source,
        size_ms=WIN_MS,
        capacity_per_shard=2048,
        max_parallelism=64,
        batch_per_host=2048,
        fires_per_step=4,
        rebalance=rebalance_addrs is not None,
        rebalance_addrs=rebalance_addrs,
    )


def skewed_window_plain():
    return skewed_window(None)


def skewed_window_rebalanced():
    import os

    addrs = os.environ["FLINK_TPU_TEST_REBALANCE_ADDRS"].split(",")
    return skewed_window(addrs)


def expected_skewed():
    exp = {}
    for i in range(SKEW_TOTAL):
        k = i % N_KEYS
        w = ((i // TS_DIV) // WIN_MS + 1) * WIN_MS
        exp[(k, w)] = exp.get((k, w), 0) + 1.0
    return exp


def skewed_window_shuffled():
    """shuffle ingest partitioner over the same 90/10 skew: the targeted
    ring routes every record to a uniformly random host, restoring lane
    utilization like rebalance does (ref ShufflePartitioner.java)."""
    import os

    spec = skewed_window(None)
    spec.ingest_partitioner = "shuffle"
    spec.rebalance_addrs = \
        os.environ["FLINK_TPU_TEST_REBALANCE_ADDRS"].split(",")
    return spec


def skewed_window_global():
    """global ingest partitioner: every record routed to host 0 (ref
    GlobalPartitioner.java) — results stay exact, host 1's lanes idle."""
    import os

    spec = skewed_window(None)
    spec.ingest_partitioner = "global"
    spec.rebalance_addrs = \
        os.environ["FLINK_TPU_TEST_REBALANCE_ADDRS"].split(",")
    return spec


# -- round 5: rolling keyed reduce over the DCN plane ---------------------

ROLL_TOTAL = 20_000


def _rolling_source(pid, nproc):
    return _source(pid, nproc, total=ROLL_TOTAL)


def two_host_rolling():
    """Rolling per-key count (sum of ones): every record emits its key's
    updated running aggregate from the owner shard."""
    return DCNJobSpec(
        source_factory=_rolling_source,
        window_kind="rolling",
        capacity_per_shard=2048,
        max_parallelism=64,
        batch_per_host=2048,
    )


def expected_rolling(nproc):
    """Per-key record count across hosts (the final rolling value)."""
    per_host = N_KEYS // nproc
    exp = {}
    for pid in range(nproc):
        for i in range(ROLL_TOTAL):
            k = pid + nproc * (i % per_host)
            exp[k] = exp.get(k, 0) + 1.0
    return exp


# -- round 5: CEP pattern matching over the DCN plane ---------------------

CEP_TOTAL = 12_000
CEP_KEYS = 101
CEP_STAGES = 3     # a -> followedBy b -> followedBy c


def _cep_pattern():
    from flink_tpu.cep.pattern import Pattern

    return (Pattern.begin("a").where(lambda e: e == 0)
            .followed_by("b").where(lambda e: e == 1)
            .followed_by("c").where(lambda e: e == 2))


def _cep_event_code(pid, idx):
    """Deterministic per-record event code in {0,1,2,3} (3 = matches no
    stage); mixes by key and position so keys see genuinely different
    sequences."""
    return (idx * 7 + idx // 13 + pid) % 4


def _cep_source(pid, nproc):
    per_host = CEP_KEYS // nproc

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        keys = pid + nproc * (idx % per_host)
        ts = idx // TS_DIV
        code = _cep_event_code(pid, idx)   # array-compatible helper
        # stage-match bits packed into the value lane (bit s = stage s)
        vals = np.zeros(n, np.float32)
        for s in range(CEP_STAGES):
            vals += (code == s).astype(np.float32) * (1 << s)
        return keys, ts, vals

    return GeneratorPartitionSource(gen, CEP_TOTAL)


def two_host_cep():
    return DCNJobSpec(
        source_factory=_cep_source,
        window_kind="cep",
        cep_pattern_factory=_cep_pattern,
        capacity_per_shard=1024,
        max_parallelism=64,
        batch_per_host=1024,
    )


def expected_cep(nproc):
    """Per-key match totals from an INDEPENDENT numpy transcription of
    the count-NFA recurrence (v' = T v applied to the old vector):
      M  += m[S-1] * c[S-2]
      c_s  = keep(s+1)*c_s + m[s]*c_{s-1}   (s > 0)
      c_0  = keep(1)*c_0 + m[0]
    keep(s) = 1 for followedBy (relaxed), 0 for next (strict)."""
    per_host = CEP_KEYS // nproc
    relaxed_keep = [1.0, 1.0]          # b and c are followedBy
    totals = {}
    seqs = {}
    for pid in range(nproc):
        for i in range(CEP_TOTAL):
            k = pid + nproc * (i % per_host)
            seqs.setdefault(k, []).append(_cep_event_code(pid, i))
    for k, codes in seqs.items():
        c = [0.0] * (CEP_STAGES - 1)
        M = 0.0
        for code in codes:
            m = [1.0 if code == s else 0.0 for s in range(CEP_STAGES)]
            old = list(c)
            M += m[CEP_STAGES - 1] * old[CEP_STAGES - 2]
            for s in range(CEP_STAGES - 2, 0, -1):
                c[s] = relaxed_keep[s] * old[s] + m[s] * old[s - 1]
            c[0] = relaxed_keep[0] * old[0] + m[0]
        totals[k] = M
    return totals
