"""Table API + SQL subset semantics (ref flink-table ITCases)."""

import numpy as np
import pytest

from flink_tpu.table import TableEnvironment, col


def _env_with_orders():
    env = TableEnvironment.create()
    t = env.from_columns({
        "user": ["a", "b", "a", "c", "b", "a"],
        "amount": [10.0, 20.0, 30.0, 5.0, 15.0, 7.0],
        "region": ["eu", "us", "eu", "eu", "us", "us"],
    })
    env.register_table("orders", t)
    return env, t


def test_select_where_projection():
    _, t = _env_with_orders()
    out = t.where(col("amount") > 9.0).select(
        col("user"), (col("amount") * 2).alias("double")
    )
    assert out.schema == ["user", "double"]
    assert out.to_rows() == [("a", 20.0), ("b", 40.0), ("a", 60.0), ("b", 30.0)]


def test_group_by_aggregates():
    _, t = _env_with_orders()
    out = t.group_by("user").select(
        "user", col("amount").sum.alias("total"),
        col("amount").count.alias("n"),
    ).order_by("user")
    assert out.to_rows() == [("a", 47.0, 3.0), ("b", 35.0, 2.0), ("c", 5.0, 1.0)]


def test_multi_key_grouping_and_global_agg():
    _, t = _env_with_orders()
    out = t.group_by("user", "region").select(
        "user", "region", col("amount").sum.alias("s")
    )
    d = {(u, r): s for u, r, s in out.to_rows()}
    assert d[("a", "eu")] == 40.0 and d[("a", "us")] == 7.0
    g = t.select(col("amount").max.alias("m"), col("amount").avg.alias("a"))
    assert g.to_rows() == [(30.0, pytest.approx(87.0 / 6))]


def test_join_and_order_limit():
    env, t = _env_with_orders()
    users = env.from_columns({
        "user": ["a", "b", "c"], "country": ["de", "us", "fr"],
    })
    j = t.join(users, "user").group_by("country").select(
        "country", col("amount").sum.alias("total")
    ).order_by("total", ascending=False).limit(1)
    assert j.to_rows() == [("de", 47.0)]


def test_left_join_unmatched():
    env = TableEnvironment.create()
    a = env.from_columns({"k": [1, 2], "v": [10, 20]})
    b = env.from_columns({"k": [1], "w": [100]})
    out = a.join(b, "k", how="left").order_by("k")
    assert out.to_rows() == [(1, 10, 100), (2, 20, None)]


def test_union_distinct():
    env = TableEnvironment.create()
    a = env.from_columns({"x": [1, 2]})
    b = env.from_columns({"x": [2, 3]})
    u = a.union_all(b)
    assert u.count() == 4
    assert sorted(r[0] for r in u.distinct().to_rows()) == [1, 2, 3]


def test_sql_select_where():
    env, _ = _env_with_orders()
    out = env.sql_query(
        "SELECT user, amount FROM orders WHERE amount > 9 AND region = 'eu'"
    )
    assert out.to_rows() == [("a", 10.0), ("a", 30.0)]


def test_sql_group_by_order_limit():
    env, _ = _env_with_orders()
    out = env.sql_query(
        "SELECT user, SUM(amount) AS total, COUNT(*) AS n FROM orders "
        "GROUP BY user ORDER BY total DESC LIMIT 2"
    )
    assert out.to_rows() == [("a", 47.0, 3.0), ("b", 35.0, 2.0)]


def test_sql_expressions():
    env, _ = _env_with_orders()
    out = env.sql_query(
        "SELECT user, amount * 2 + 1 AS x FROM orders LIMIT 1"
    )
    assert out.to_rows() == [("a", 21.0)]


def test_sql_star_and_errors():
    env, t = _env_with_orders()
    assert env.sql_query("SELECT * FROM orders LIMIT 2").count() == 2
    with pytest.raises(ValueError):
        env.sql_query("DELETE FROM orders")


def test_right_and_full_outer_join():
    env = TableEnvironment.create()
    a = env.from_columns({"k": [1, 2], "v": [10, 20]})
    b = env.from_columns({"k": [2, 3], "w": [200, 300]})
    r = a.join(b, "k", how="right").order_by("k")
    assert r.to_rows() == [(2, 20, 200), (3, None, 300)]
    f = a.join(b, "k", how="full").order_by("k")
    assert f.to_rows() == [(1, 10, None), (2, 20, 200), (3, None, 300)]
    with pytest.raises(ValueError):
        a.join(b, "k", how="cross")


def test_sql_string_literals_with_keywords():
    env = TableEnvironment.create()
    t = env.from_columns({
        "tag": ["AND", "a=b", "x", "o'k"],
        "v": [1, 2, 3, 4],
    })
    env.register_table("t", t)
    assert env.sql_query("SELECT v FROM t WHERE tag = 'AND'").to_rows() == [(1,)]
    assert env.sql_query("SELECT v FROM t WHERE tag = 'a=b'").to_rows() == [(2,)]
    assert env.sql_query("SELECT v FROM t WHERE tag = 'o''k'").to_rows() == [(4,)]


def test_order_by_with_nulls_from_outer_join():
    env = TableEnvironment.create()
    a = env.from_columns({"k": [1, 2], "v": [10, 20]})
    b = env.from_columns({"k": [1], "w": [100]})
    out = a.join(b, "k", how="left").order_by("w")
    assert out.to_rows() == [(1, 10, 100), (2, 20, None)]   # NULLS LAST
