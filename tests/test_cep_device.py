"""Device CEP (segmented associative matrix scan) vs the host NFA: match
counts and completion positions must be identical on the reference-semantics
vectors (ref NFA.java:132 computeNextStates:229)."""

from collections import namedtuple

import numpy as np
import jax
import pytest

from flink_tpu.cep import NFA, Pattern
from flink_tpu.cep import device as dcep

Event = namedtuple("Event", ["ts", "name", "value"])


def host_deltas(pattern, events):
    """Per-event completed-match counts from the host NFA."""
    nfa = NFA(pattern)
    partials = nfa.initial_state()
    out = []
    for e in events:
        partials, matches = nfa.process(partials, e, e.ts)
        out.append(len(matches))
    return out


def device_run(pattern, key_events, capacity=64, batches=None):
    """key_events: list of (key_id, event). Returns per-lane deltas."""
    spec = dcep.DevicePatternSpec.from_pattern(pattern)
    state = dcep.init_state(capacity, 8, spec)
    keys = np.asarray([k for k, _ in key_events], np.uint64)
    events = [e for _, e in key_events]
    hi = (keys >> np.uint64(32)).astype(np.uint32) | np.uint32(0x80000000)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    masks = dcep.host_masks(pattern, events)
    deltas = []
    spans = batches or [(0, len(events))]
    for a, b in spans:
        state, d, _tot = dcep.advance(
            state, spec, jax.numpy.asarray(hi[a:b]),
            jax.numpy.asarray(lo[a:b]), jax.numpy.asarray(masks[a:b]),
            jax.numpy.asarray(np.ones(b - a, bool)),
        )
        deltas.extend(np.asarray(d).astype(int).tolist())
    assert int(np.asarray(state.dropped_capacity)) == 0
    return deltas


def test_strict_contiguity_matches_host():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )
    events = [Event(0, "a", 1), Event(1, "b", 2), Event(2, "a", 3),
              Event(3, "x", 0), Event(4, "b", 4)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(7, e) for e in events])
    assert dd == hd == [0, 1, 0, 0, 0]


def test_relaxed_branching_matches_host():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    events = [Event(0, "a", 1), Event(1, "x", 0), Event(2, "b", 2),
              Event(3, "b", 3), Event(4, "a", 5), Event(5, "b", 6)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(9, e) for e in events])
    assert dd == hd
    # branching: the final b completes against BOTH live a-partials
    assert hd[-1] == 2


def test_three_stage_conjunction_matches_host():
    p = (
        Pattern.begin("first").where(lambda e: e.name == "a")
        .followed_by("mid").where(lambda e: e.name == "b")
        .where(lambda e: e.value > 10)
        .followed_by("last").where(lambda e: e.name == "c")
    )
    events = [Event(0, "a", 1), Event(1, "b", 5), Event(2, "b", 20),
              Event(3, "c", 7), Event(4, "c", 8)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(3, e) for e in events])
    assert dd == hd
    assert sum(hd) == 2


def test_single_stage_or_predicate():
    p = Pattern.begin("x").where(lambda e: e.name == "a").or_(
        lambda e: e.value > 100
    )
    events = [Event(0, "a", 1), Event(1, "z", 500), Event(2, "z", 3)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(1, e) for e in events])
    assert dd == hd == [1, 1, 0]


def test_cross_batch_carry():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    events = [Event(0, "a", 1), Event(1, "x", 0), Event(2, "b", 2),
              Event(3, "b", 3)]
    hd = host_deltas(p, events)
    # split mid-stream: the a-partial must survive the batch boundary
    dd = device_run(p, [(5, e) for e in events], batches=[(0, 2), (2, 4)])
    assert dd == hd == [0, 0, 1, 1]


def test_interleaved_keys_independent():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )
    # key 1 sees a,b (match); key 2 sees a,x,b (broken by x)
    ke = [(1, Event(0, "a", 1)), (2, Event(1, "a", 9)),
          (2, Event(2, "x", 0)), (1, Event(3, "b", 2)),
          (2, Event(4, "b", 8))]
    dd = device_run(p, ke)
    assert dd == [0, 0, 0, 1, 0]
    # host equivalent per key
    assert host_deltas(p, [e for k, e in ke if k == 1]) == [0, 1]
    assert host_deltas(p, [e for k, e in ke if k == 2]) == [0, 0, 0]


def test_within_spec_buckets():
    """within() compiles to a pane ring: Q-1 live panes of pane_ms each
    cover the horizon; Q == 1 (flat) without within."""
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b").within(10)
    )
    spec = dcep.DevicePatternSpec.from_pattern(p, within_buckets=8)
    assert spec.pane_ms == 2 and spec.within_panes == 6
    assert spec.dim == (2 - 1) * 6 + 2
    spec_flat = dcep.DevicePatternSpec.from_pattern(
        Pattern.begin("a").where(lambda e: e.name == "a")
    )
    assert spec_flat.within_panes == 1 and spec_flat.dim == 2


def test_branching_explosion_exactness():
    """n a's followed by one b -> n matches (count exactness under
    branching)."""
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    events = [Event(i, "a", i) for i in range(20)] + [Event(99, "b", 0)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(4, e) for e in events])
    assert dd == hd
    assert dd[-1] == 20


# ---------------------------------------------------------------- within()
def device_run_within(pattern, key_events_ts, capacity=64, buckets=8):
    """key_events_ts: list of (key_id, event, batch_ts). Consecutive
    entries with the same batch_ts form one micro-batch (the executor
    passes one timestamp per batch). Returns per-lane deltas."""
    spec = dcep.DevicePatternSpec.from_pattern(pattern,
                                               within_buckets=buckets)
    state = dcep.init_state(capacity, 8, spec)
    deltas = []
    i = 0
    while i < len(key_events_ts):
        j = i
        while j < len(key_events_ts) and \
                key_events_ts[j][2] == key_events_ts[i][2]:
            j += 1
        chunk = key_events_ts[i:j]
        keys = np.asarray([k for k, _e, _t in chunk], np.uint64)
        events = [e for _k, e, _t in chunk]
        hi = (keys >> np.uint64(32)).astype(np.uint32) | np.uint32(0x80000000)
        lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        masks = dcep.host_masks(pattern, events)
        pane = (chunk[0][2] // spec.pane_ms) if spec.pane_ms else 0
        state, d, _ = dcep.advance(
            state, spec, jax.numpy.asarray(hi), jax.numpy.asarray(lo),
            jax.numpy.asarray(masks),
            jax.numpy.asarray(np.ones(len(chunk), bool)),
            np.int32(pane),
        )
        deltas.extend(np.asarray(d).astype(int).tolist())
        i = j
    assert int(np.asarray(state.dropped_capacity)) == 0
    return deltas


def host_deltas_quantized(pattern, events_ts, pane_ms):
    """Host NFA on pane-quantized timestamps — the semantics the device
    path guarantees (device == host on quantized ts)."""
    nfa = NFA(pattern)
    partials = nfa.initial_state()
    out = []
    for e, ts in events_ts:
        tq = (ts // pane_ms) * pane_ms if pane_ms else ts
        partials, matches = nfa.process(partials, e, tq)
        out.append(len(matches))
    return out


def _p_ab(within):
    return (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b").within(within)
    )


def test_within_kills_expired_partials():
    p = _p_ab(100)
    spec = dcep.DevicePatternSpec.from_pattern(p, within_buckets=4)
    # an 'a' at t=0 must match a 'b' at t<=100 and not one at t=200
    seq = [(5, Event(0, "a", 1), 0), (5, Event(200, "b", 1), 200)]
    assert device_run_within(p, seq, buckets=4) == [0, 0]
    seq2 = [(5, Event(0, "a", 1), 0), (5, Event(100, "b", 1), 100)]
    assert device_run_within(p, seq2, buckets=4) == [0, 1]


def test_within_equals_host_on_quantized_ts():
    p = _p_ab(40)
    spec = dcep.DevicePatternSpec.from_pattern(p, within_buckets=8)
    events = [
        ("a", 0), ("x", 10), ("b", 20), ("a", 30), ("b", 45),
        ("b", 80), ("a", 90), ("x", 100), ("b", 120), ("b", 131),
    ]
    seq = [(3, Event(t, n, 1), t) for n, t in events]
    dd = device_run_within(p, seq, buckets=8)
    hd = host_deltas_quantized(
        p, [(Event(t, n, 1), t) for n, t in events], spec.pane_ms
    )
    assert dd == hd


def test_within_strict_stage_and_multikey_fuzz():
    rng = np.random.default_rng(11)
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
        .followed_by("c").where(lambda e: e.name == "c").within(64)
    )
    spec = dcep.DevicePatternSpec.from_pattern(p, within_buckets=8)
    names = np.array(["a", "b", "c", "x"])
    n_ev, n_keys = 160, 5
    # monotone batch timestamps, several events per batch
    ts = np.cumsum(rng.integers(0, 24, n_ev))
    seq, per_key = [], {k: [] for k in range(n_keys)}
    for i in range(n_ev):
        k = int(rng.integers(0, n_keys))
        e = Event(int(ts[i]), str(rng.choice(names)), k)
        seq.append((k, e, int(ts[i])))
        per_key[k].append((e, int(ts[i])))
    dd = device_run_within(p, seq, buckets=8)
    # compare per-key totals against the quantized host NFA
    got = {k: 0 for k in range(n_keys)}
    for (k, _e, _t), d in zip(seq, dd):
        got[k] += d
    want = {
        k: sum(host_deltas_quantized(p, evs, spec.pane_ms))
        for k, evs in per_key.items()
    }
    assert got == want
