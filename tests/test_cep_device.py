"""Device CEP (segmented associative matrix scan) vs the host NFA: match
counts and completion positions must be identical on the reference-semantics
vectors (ref NFA.java:132 computeNextStates:229)."""

from collections import namedtuple

import numpy as np
import jax
import pytest

from flink_tpu.cep import NFA, Pattern
from flink_tpu.cep import device as dcep

Event = namedtuple("Event", ["ts", "name", "value"])


def host_deltas(pattern, events):
    """Per-event completed-match counts from the host NFA."""
    nfa = NFA(pattern)
    partials = nfa.initial_state()
    out = []
    for e in events:
        partials, matches = nfa.process(partials, e, e.ts)
        out.append(len(matches))
    return out


def device_run(pattern, key_events, capacity=64, batches=None):
    """key_events: list of (key_id, event). Returns per-lane deltas."""
    spec = dcep.DevicePatternSpec.from_pattern(pattern)
    state = dcep.init_state(capacity, 8, spec)
    keys = np.asarray([k for k, _ in key_events], np.uint64)
    events = [e for _, e in key_events]
    hi = (keys >> np.uint64(32)).astype(np.uint32) | np.uint32(0x80000000)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    masks = dcep.host_masks(pattern, events)
    deltas = []
    spans = batches or [(0, len(events))]
    for a, b in spans:
        state, d, _tot = dcep.advance(
            state, spec, jax.numpy.asarray(hi[a:b]),
            jax.numpy.asarray(lo[a:b]), jax.numpy.asarray(masks[a:b]),
            jax.numpy.asarray(np.ones(b - a, bool)),
        )
        deltas.extend(np.asarray(d).astype(int).tolist())
    assert int(np.asarray(state.dropped_capacity)) == 0
    return deltas


def test_strict_contiguity_matches_host():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )
    events = [Event(0, "a", 1), Event(1, "b", 2), Event(2, "a", 3),
              Event(3, "x", 0), Event(4, "b", 4)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(7, e) for e in events])
    assert dd == hd == [0, 1, 0, 0, 0]


def test_relaxed_branching_matches_host():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    events = [Event(0, "a", 1), Event(1, "x", 0), Event(2, "b", 2),
              Event(3, "b", 3), Event(4, "a", 5), Event(5, "b", 6)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(9, e) for e in events])
    assert dd == hd
    # branching: the final b completes against BOTH live a-partials
    assert hd[-1] == 2


def test_three_stage_conjunction_matches_host():
    p = (
        Pattern.begin("first").where(lambda e: e.name == "a")
        .followed_by("mid").where(lambda e: e.name == "b")
        .where(lambda e: e.value > 10)
        .followed_by("last").where(lambda e: e.name == "c")
    )
    events = [Event(0, "a", 1), Event(1, "b", 5), Event(2, "b", 20),
              Event(3, "c", 7), Event(4, "c", 8)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(3, e) for e in events])
    assert dd == hd
    assert sum(hd) == 2


def test_single_stage_or_predicate():
    p = Pattern.begin("x").where(lambda e: e.name == "a").or_(
        lambda e: e.value > 100
    )
    events = [Event(0, "a", 1), Event(1, "z", 500), Event(2, "z", 3)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(1, e) for e in events])
    assert dd == hd == [1, 1, 0]


def test_cross_batch_carry():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    events = [Event(0, "a", 1), Event(1, "x", 0), Event(2, "b", 2),
              Event(3, "b", 3)]
    hd = host_deltas(p, events)
    # split mid-stream: the a-partial must survive the batch boundary
    dd = device_run(p, [(5, e) for e in events], batches=[(0, 2), (2, 4)])
    assert dd == hd == [0, 0, 1, 1]


def test_interleaved_keys_independent():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )
    # key 1 sees a,b (match); key 2 sees a,x,b (broken by x)
    ke = [(1, Event(0, "a", 1)), (2, Event(1, "a", 9)),
          (2, Event(2, "x", 0)), (1, Event(3, "b", 2)),
          (2, Event(4, "b", 8))]
    dd = device_run(p, ke)
    assert dd == [0, 0, 0, 1, 0]
    # host equivalent per key
    assert host_deltas(p, [e for k, e in ke if k == 1]) == [0, 1]
    assert host_deltas(p, [e for k, e in ke if k == 2]) == [0, 0, 0]


def test_within_rejected_for_device_path():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b").within(10)
    )
    with pytest.raises(ValueError, match="within"):
        dcep.DevicePatternSpec.from_pattern(p)


def test_branching_explosion_exactness():
    """n a's followed by one b -> n matches (count exactness under
    branching)."""
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    events = [Event(i, "a", i) for i in range(20)] + [Event(99, "b", 0)]
    hd = host_deltas(p, events)
    dd = device_run(p, [(4, e) for e in events])
    assert dd == hd
    assert dd[-1] == 20
