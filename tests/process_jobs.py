"""Job builders loaded BY WORKER PROCESSES in process-cluster tests.

Parameterized through environment variables (the controller ships them at
spawn — the user-code + config distribution seam):

  FLINK_TPU_TEST_OUT     BucketingFileSink base path
  FLINK_TPU_TEST_TOTAL   total records to generate
  FLINK_TPU_TEST_SLEEP_S per-poll throttle (keeps the job alive long
                         enough for fault injection)
"""

import os
import time

import numpy as np

N_KEYS = 64
WINDOW_MS = 1000


def build_window_job():
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.connectors.files import BucketingFileSink
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sources import GeneratorSource

    out = os.environ["FLINK_TPU_TEST_OUT"]
    total = int(os.environ["FLINK_TPU_TEST_TOTAL"])
    sleep_s = float(os.environ.get("FLINK_TPU_TEST_SLEEP_S", "0"))

    env = StreamExecutionEnvironment(Configuration({"keys.reverse-map": True}))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(4096)
    env.batch_size = 512
    env.checkpoint_interval_steps = 4

    def gen(offset, n):
        if sleep_s:
            time.sleep(sleep_s)
        idx = np.arange(offset, offset + n, dtype=np.int64)
        keys = idx % N_KEYS
        # ~8 windows over the run
        ts = (idx * 8 * WINDOW_MS) // total
        return {"key": keys, "value": np.ones(n, np.float32)}, ts

    sink = BucketingFileSink(
        out,
        formatter=lambda r: f"{r.key},{r.window_end_ms},{r.value:.0f}",
    )
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW_MS)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    return env


def expected_cells(total):
    """Scalar model: {(key, window_end_ms): value}."""
    exp = {}
    for i in range(total):
        k = i % N_KEYS
        pane = ((i * 8 * WINDOW_MS) // total) // WINDOW_MS
        cell = (k, (pane + 1) * WINDOW_MS)
        exp[cell] = exp.get(cell, 0.0) + 1.0
    return exp
