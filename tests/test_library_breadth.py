"""Round-3 library breadth: Gelly algorithms (HITS, community detection,
Jaccard, summarization, union/subgraph), FlinkML ALS, and the batch
optimizer's cost-based join strategy.

Ref: flink-gelly library/*, flink-ml recommendation/ALS.scala,
flink-optimizer Optimizer.java:396 (+ JoinHint).
"""

import numpy as np
import pytest

from flink_tpu.gelly import Graph


def _two_triangles():
    # two triangles bridged by one edge: 1-2-3 and 4-5-6, bridge 3-4
    return Graph.from_edge_list(
        [(1, 2), (2, 3), (1, 3), (4, 5), (5, 6), (4, 6), (3, 4)],
        undirected=True,
    )


def test_hits_hubs_and_authorities():
    # star: 1 -> {2,3,4}; 1 is the hub, leaves are the authorities
    g = Graph.from_edge_list([(1, 2), (1, 3), (1, 4)])
    hv = g.hits(num_iterations=20)
    hub_1 = hv[1][0]
    assert hub_1 > 0.99                     # all hub mass on vertex 1
    assert all(hv[k][0] < 1e-3 for k in (2, 3, 4))
    assert all(abs(hv[k][1] - hv[2][1]) < 1e-5 for k in (3, 4))
    assert hv[1][1] < 1e-3                  # no authority for the hub


def test_community_detection_splits_bridge():
    comms = _two_triangles().community_detection(max_supersteps=16)
    # vertices inside one triangle agree; at most the bridge endpoints mix
    assert comms[1] == comms[2]
    assert comms[5] == comms[6] == comms[4]


def test_jaccard_index_triangle():
    g = Graph.from_edge_list([(1, 2), (2, 3), (1, 3), (3, 4)],
                             undirected=True)
    j = g.jaccard_index()
    # 1 and 2 share neighbor 3; union of their neighborhoods = {1,2,3}
    assert abs(j[(1, 2)] - 1 / 3) < 1e-6
    # 3 and 4: N(3)={1,2,4}, N(4)={3} -> no common, union size 4
    assert j[(3, 4)] == 0.0


def test_summarize_condenses_equal_values():
    g = Graph.from_edge_list(
        [(1, 2), (2, 3), (3, 1), (1, 3)],
        vertex_init=lambda v: 0.0 if v in (1, 2) else 1.0,
    )
    s = g.summarize()
    assert s.num_vertices == 2
    # edges between the groups: 2->3, 3->1, 1->3 cross; 1->2 is internal
    assert s.num_edges == 2                 # 0->1 and 1->0 (deduped)


def test_union_and_subgraph():
    a = Graph.from_edge_list([(1, 2)])
    b = Graph(a.vertex_values, a.dst, a.src, None, a.ids)   # reversed
    u = a.union(b)
    assert u.num_edges == 2
    sub = u.subgraph(lambda vals: vals >= 0)   # keep everything
    assert sub.num_edges == 2


def test_als_reconstructs_low_rank_ratings():
    from flink_tpu.ml import ALS

    rng = np.random.default_rng(5)
    U, I, F = 12, 9, 3
    uf = rng.normal(size=(U, F))
    vf = rng.normal(size=(I, F))
    full = uf @ vf.T
    mask = rng.random((U, I)) < 0.7
    train = [(u, i, float(full[u, i]))
             for u in range(U) for i in range(I) if mask[u, i]]
    held = [(u, i, float(full[u, i]))
            for u in range(U) for i in range(I) if not mask[u, i]]

    als = ALS(num_factors=F, lambda_=0.05, iterations=15, seed=1).fit(train)
    pred_train = als.predict([(u, i) for u, i, _ in train])
    err_train = np.abs(
        pred_train - np.asarray([r for _, _, r in train])
    ).mean()
    assert err_train < 0.1                  # fits observed entries
    pred_held = als.predict([(u, i) for u, i, _ in held])
    err_held = np.abs(
        pred_held - np.asarray([r for _, _, r in held])
    ).mean()
    assert err_held < 0.8                   # generalizes (low-rank truth)
    assert als.predict([(999, 0)])[0] == 0.0
    assert als.empirical_risk(train) > 0


def test_join_cost_model_builds_small_side_and_explains():
    from flink_tpu.dataset import ExecutionEnvironment

    env = ExecutionEnvironment.get_execution_environment()
    big = env.from_collection([(i, f"L{i}") for i in range(1000)])
    small = env.from_collection([(i * 100, f"R{i}") for i in range(5)])
    joined = (
        big.join(small).where(lambda e: e[0]).equal_to(lambda e: e[0])
        .apply(lambda l, r: (l[0], l[1], r[1]))
    )
    rows = sorted(joined.collect())
    assert rows == [(i * 100, f"L{i * 100}", f"R{i}") for i in range(5)]
    # the round-5 ship/local planner prefixes the ship strategy; the
    # local strategy must still build the small side
    assert joined.strategy.endswith("hash build-right")  # small side built
    plan = joined.explain()
    assert "inner_join" in plan and "hash build-right" in plan

    # swap: small on the left -> build-left chosen
    j2 = (
        small.join(big).where(lambda e: e[0]).equal_to(lambda e: e[0])
        .apply(lambda l, r: (l[0],))
    )
    j2.collect()
    assert j2.strategy.endswith("hash build-left")

    # hint overrides the cost model
    j3 = (
        big.join(small).where(lambda e: e[0]).equal_to(lambda e: e[0])
        .with_hint("build-left").apply(lambda l, r: (l[0],))
    )
    assert sorted(j3.collect()) == [(i * 100,) for i in range(5)]
    assert "hinted" in j3.strategy


def test_outer_join_semantics_stable_under_either_build_side():
    from flink_tpu.dataset import ExecutionEnvironment

    env = ExecutionEnvironment.get_execution_environment()
    l = env.from_collection([(1, "a"), (2, "b"), (3, "c")])
    r = env.from_collection([(2, "x")])

    for hint in ("build-left", "build-right"):
        out = sorted(
            l.left_outer_join(r).where(lambda e: e[0])
            .equal_to(lambda e: e[0]).with_hint(hint)
            .apply(lambda a, b: (a[0], b[1] if b else None)).collect(),
            key=lambda t: t[0],
        )
        assert out == [(1, None), (2, "x"), (3, None)], hint


# ----------------------------------------------------- gelly breadth (r4)
def _square_with_diagonal():
    # square a-b-c-d-a plus diagonal a-c: two triangles (abc, acd)
    from flink_tpu.gelly.graph import Graph

    return Graph.from_edge_list(
        [("a", "b"), ("b", "c"), ("c", "d"), ("d", "a"), ("a", "c")],
        undirected=True,
    )


def test_clustering_coefficients():
    g = _square_with_diagonal()
    local = g.local_clustering_coefficient()
    # a: deg 3, 2 triangles through it -> 2*2/(3*2) = 2/3; b: deg 2,
    # 1 triangle -> 1.0; same for d; c symmetric to a
    assert abs(local["a"] - 2 / 3) < 1e-6
    assert abs(local["b"] - 1.0) < 1e-6
    assert abs(local["c"] - 2 / 3) < 1e-6
    assert abs(local["d"] - 1.0) < 1e-6
    # global: 2 triangles, triplets = sum C(deg,2) = 3+1+3+1 = 8
    assert abs(g.global_clustering_coefficient() - 6 / 8) < 1e-6


def test_adamic_adar_scores_non_adjacent_pairs():
    g = _square_with_diagonal()
    aa = g.adamic_adar()
    # only non-adjacent pair is (b, d): shared neighbors a and c, both
    # degree 3 -> 2 / ln(3)
    assert set(aa) == {("b", "d")}
    assert abs(aa[("b", "d")] - 2 / np.log(3)) < 1e-6


def test_reduce_on_edges_and_neighbors():
    from flink_tpu.gelly.graph import Graph

    g = Graph.from_edge_list(
        [("a", "b"), ("a", "c"), ("b", "c")],
        edge_values=[1.0, 2.0, 4.0],
        vertex_init=lambda k: {"a": 10.0, "b": 20.0, "c": 30.0}[k],
    )
    # reference semantics: NO result for vertices without edges in the
    # requested direction (a has no in-edges, c no out-edges)
    assert g.reduce_on_edges("sum", "in") == {"b": 1.0, "c": 6.0}
    assert g.reduce_on_edges("sum", "out") == {"a": 3.0, "b": 4.0}
    assert g.reduce_on_edges("max", "all")["a"] == 2.0
    # neighbor VALUES: in-neighbors of c are a and b
    assert g.reduce_on_neighbors("sum", "in")["c"] == 30.0
    assert g.reduce_on_neighbors("min", "all")["b"] == 10.0


def test_graph_mutations():
    from flink_tpu.gelly.graph import Graph

    g = Graph.from_edge_list([("a", "b"), ("b", "c")])
    g2 = g.add_vertices(["d"]).add_edges([("c", "d")])
    assert g2.num_vertices == 4 and g2.num_edges == 3
    assert g2.out_degrees()["c"] == 1
    with pytest.raises(ValueError, match="unknown vertex"):
        g2.add_edges([("a", "zzz")])
    g3 = g2.remove_vertices(["b"])
    assert g3.num_vertices == 3 and g3.num_edges == 1   # only c->d left
    assert set(g3.out_degrees()) == {"a", "c", "d"}
    g4 = g2.remove_edges([("b", "c")])
    assert g4.num_edges == 2


def test_add_vertices_value_alignment():
    """Regression: values align to their ids when some ids already exist."""
    from flink_tpu.gelly.graph import Graph

    g = Graph.from_edge_list([("a", "b")])
    g2 = g.add_vertices(["a", "e"], values=[5.0, 7.0])
    vals = dict(zip(
        (g2.ids if g2.ids is not None else range(g2.num_vertices)).tolist(),
        np.asarray(g2.vertex_values).tolist(),
    ))
    assert vals["e"] == 7.0
    with pytest.raises(ValueError, match="values"):
        g.add_vertices(["x", "y"], values=[1.0])


# ------------------------------------------------------- ml breadth (r4)
def test_gradient_descent_losses_and_penalties():
    """ref optimization/GradientDescent + LossFunction +
    RegularizationPenalty: recover a known linear model; L1 zeroes
    irrelevant coordinates."""
    from flink_tpu.ml.optimization import (
        GradientDescent,
        HingeLoss,
        L1Regularization,
        LogisticLoss,
    )

    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 3)).astype(np.float32)
    w_true = np.array([2.0, -1.0, 0.0], np.float32)
    y = X @ w_true + 0.5

    gd = GradientDescent(iterations=400, stepsize=0.5)
    w, b = gd.optimize(X, y)
    assert np.allclose(w, w_true, atol=0.05) and abs(b - 0.5) < 0.05
    assert gd.empirical_loss(X, y, w, b) < 1e-3

    # L1 drives the dead coordinate to exactly zero
    gd1 = GradientDescent(penalty=L1Regularization(), regularization=0.02,
                          iterations=400, stepsize=0.5)
    w1, _ = gd1.optimize(X, y)
    assert w1[2] == 0.0 and abs(w1[0] - 2.0) < 0.2

    # classification losses separate a linearly separable set
    yc = np.where(X[:, 0] > 0, 1.0, -1.0).astype(np.float32)
    for loss in (HingeLoss(), LogisticLoss()):
        wc, bc = GradientDescent(loss=loss, iterations=300,
                                 stepsize=1.0).optimize(X, yc)
        acc = np.mean(np.sign(X @ wc + bc) == yc)
        assert acc > 0.97, (type(loss).__name__, acc)


def test_distance_metrics():
    from flink_tpu.ml import metrics as dm

    a = np.array([[0.0, 0.0], [1.0, 1.0]])
    b = np.array([[3.0, 4.0]])
    assert np.allclose(dm.euclidean_distance(a, b), [[5.0],
                                                     [np.sqrt(13)]])
    assert np.allclose(dm.squared_euclidean_distance(a, b), [[25.0],
                                                             [13.0]])
    assert np.allclose(dm.manhattan_distance(a, b), [[7.0], [5.0]])
    assert np.allclose(dm.chebyshev_distance(a, b), [[4.0], [3.0]])
    assert np.allclose(
        dm.minkowski_distance(a, b, 2.0), dm.euclidean_distance(a, b)
    )
    # cosine: parallel vectors have distance 0
    assert abs(dm.cosine_distance([[2.0, 0.0]], [[5.0, 0.0]])[0, 0]) < 1e-6
    assert abs(dm.tanimoto_distance([[1.0, 1.0]], [[1.0, 1.0]])[0, 0]) < 1e-6


def test_libsvm_round_trip(tmp_path):
    from flink_tpu.ml.utils import read_libsvm, write_libsvm

    X = np.array([[0.0, 2.5, 0.0], [1.0, 0.0, -3.0]], np.float32)
    y = np.array([1.0, -1.0], np.float32)
    p = str(tmp_path / "data.svm")
    write_libsvm(p, X, y)
    X2, y2 = read_libsvm(p)
    assert np.allclose(X2, X) and np.allclose(y2, y)
    # 1-based index validation
    (tmp_path / "bad.svm").write_text("1.0 0:5.0\n")
    with pytest.raises(ValueError, match="1-based"):
        read_libsvm(str(tmp_path / "bad.svm"))


def test_remove_edges_on_empty_and_duplicate_add_vertices():
    from flink_tpu.gelly.graph import Graph

    g = Graph.from_edge_list([("a", "b")])
    g0 = g.remove_edges([("a", "b")])
    assert g0.num_edges == 0
    assert g0.remove_edges([("a", "b")]).num_edges == 0  # E == 0 safe
    g2 = g.add_vertices(["e", "e"], values=[1.0, 2.0])
    assert g2.num_vertices == 3                          # one 'e' only
