"""KeyedStream.process(): ProcessFunction with keyed state + timers.

Semantics mirrored from the reference's ProcessFunction/KeyedProcessOperator
(1.2 'timely flatmap'): per-element state access under the current key,
event-time timers fired on watermark advance, processing-time timers fired
on clock advance, exactly-once restore of state + timers.
"""

import numpy as np
import pytest

from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.datastream.functions import ProcessFunction
from flink_tpu.runtime import sinks as sk
from flink_tpu.runtime.timers import InternalTimerService
from flink_tpu.state.descriptors import ValueStateDescriptor


class CountThenFire(ProcessFunction):
    """Counts per key; registers an event-time timer at ts+10 on each
    element; emits (key, count) when the timer fires."""

    def open(self, ctx):
        self.count = ctx.get_state(ValueStateDescriptor("count", default=0))

    def process_element(self, value, ctx, out):
        self.count.update(self.count.value() + 1)
        ctx.timer_service().register_event_time_timer(ctx.timestamp() + 10)

    def on_timer(self, timestamp, ctx, out):
        out.collect((ctx.get_current_key(), self.count.value(), timestamp))


def test_event_time_timers_fire_on_watermark():
    env = StreamExecutionEnvironment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    sink = sk.CollectSink()
    # (key, ts): watermark from monotonous strategy trails max ts by 1
    data = [("a", 100), ("a", 105), ("b", 103), ("a", 200), ("b", 300)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .process(CountThenFire())
        .add_sink(sink)
    )
    env.execute("proc")
    # dedup: a@100,a@105 both register distinct timers (110, 115); a@200 -> 210
    got = sorted(sink.results)
    keys_fired = {(k, ts) for k, _, ts in got}
    assert ("a", 110) in keys_fired
    assert ("a", 115) in keys_fired
    assert ("b", 113) in keys_fired
    assert ("a", 210) in keys_fired
    assert ("b", 310) in keys_fired
    # the count at fire time reflects elements seen up to the watermark
    final_counts = {k: c for k, c, _ in got}
    assert final_counts["a"] == 3
    assert final_counts["b"] == 2


def test_timer_dedup_same_key_same_ts():
    svc = InternalTimerService(128)
    fired = []

    class T:
        def on_event_time(self, timer):
            fired.append((timer.key, timer.timestamp))

        def on_processing_time(self, timer):
            pass

    svc.triggerable = T()
    svc.register_event_time_timer((), "k", 50)
    svc.register_event_time_timer((), "k", 50)  # dedup
    svc.register_event_time_timer((), "k", 60)
    svc.delete_event_time_timer((), "k", 60)    # delete before fire
    svc.advance_watermark(100)
    assert fired == [("k", 50)]


def test_timer_snapshot_restore():
    svc = InternalTimerService(128)
    svc.register_event_time_timer((), "a", 10)
    svc.register_processing_time_timer((), "b", 20)
    snap = svc.snapshot()

    svc2 = InternalTimerService(128)
    svc2.restore(snap)
    fired = []

    class T:
        def on_event_time(self, timer):
            fired.append(("e", timer.key, timer.timestamp))

        def on_processing_time(self, timer):
            fired.append(("p", timer.key, timer.timestamp))

    svc2.triggerable = T()
    svc2.advance_watermark(100)
    svc2.advance_processing_time(100)
    assert ("e", "a", 10) in fired
    assert ("p", "b", 20) in fired


class SumOnce(ProcessFunction):
    def open(self, ctx):
        self.total = ctx.get_state(ValueStateDescriptor("total", default=0.0))

    def process_element(self, value, ctx, out):
        self.total.update(self.total.value() + value[1])
        out.collect((value[0], self.total.value()))


def test_process_checkpoint_restore(tmp_path):
    """State survives a checkpoint/restore cycle with source rewind."""
    ckdir = str(tmp_path / "ck")
    data = [("a", 1.0), ("a", 2.0), ("b", 5.0), ("a", 3.0)]

    env = StreamExecutionEnvironment()
    env.batch_size = 2
    env.enable_checkpointing(1, ckdir)  # every step
    sink = sk.CollectSink()
    env.from_collection(data).key_by(0).process(SumOnce()).add_sink(sink)
    env.execute("ck-job")

    # fresh run restored from the last checkpoint: totals continue, not reset
    env2 = StreamExecutionEnvironment()
    env2.batch_size = 2
    sink2 = sk.CollectSink()
    env2.from_collection(data).key_by(0).process(SumOnce()).add_sink(sink2)
    env2.execute("ck-job-2", restore_from=ckdir)
    # restore was at end of stream; re-running replays nothing
    assert sink2.results == []


def test_process_restart_recovers_midstream(tmp_path):
    """A failing function restarts from the checkpoint and converges to the
    exactly-once totals (StateCheckpointedITCase pattern)."""
    ckdir = str(tmp_path / "ck")
    data = [("a", 1.0), ("a", 2.0), ("b", 5.0), ("a", 3.0),
            ("b", 1.0), ("a", 4.0)]
    boom = {"armed": True}

    class FailingSum(ProcessFunction):
        def open(self, ctx):
            self.total = ctx.get_state(
                ValueStateDescriptor("total", default=0.0))

        def process_element(self, value, ctx, out):
            if boom["armed"] and value == ("b", 1.0):
                boom["armed"] = False
                raise RuntimeError("injected failure")
            self.total.update(self.total.value() + value[1])
            out.collect((value[0], self.total.value()))

    env = StreamExecutionEnvironment()
    env.batch_size = 2
    env.enable_checkpointing(1, ckdir)
    env.config.set("restart-strategy", "fixed-delay")
    sink = sk.CollectSink()
    env.from_collection(data).key_by(0).process(FailingSum()).add_sink(sink)
    env.execute("restart-job")
    # the last accumulator per key must equal the exact totals
    finals = {}
    for k, v in sink.results:
        finals[k] = v
    assert finals["a"] == 10.0
    assert finals["b"] == 6.0
