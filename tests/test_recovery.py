"""Fast bounded recovery (ISSUE 6): the task-local snapshot cache,
failure classification + warm in-process restarts, the exponential-
backoff restart strategy, the watchdog restore deadline, and the
crash/restart chaos-cycle soak.

The soak drives one windowed job through repeated injected crashes
(hard ingest-thread kills — the faults.py ``kill`` action) and asserts
the exactly-once oracle, closed manifest chains, and bounded restart
backoff on EVERY cycle; the targeted tests pin each recovery mechanism
individually."""

import json
import os
import time

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.checkpointing.local import (
    LocalCacheMiss,
    LocalSnapshotCache,
)
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.checkpoint import CheckpointStorage, RestartStrategy
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.runtime.watchdog import Watchdog, WatchdogError
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, FaultRule, ThreadKilled

N_KEYS = 200
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    return cols, (idx // 50) * 1000


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def build_env(parallelism, ckpt_dir=None, interval=0, **cfg):
    conf = Configuration(cfg)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("recovery-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


def assert_chains_closed(ckpt_dir):
    st = CheckpointStorage(str(ckpt_dir))
    present = set(st.list_checkpoints())
    for cid in present:
        m = st.read_manifest(cid)
        if m is not None:
            missing = [c for c in m["chain"] if c not in present]
            assert not missing, (
                f"manifest of chk-{cid} chains over missing {missing}"
            )


WARM_CFG = {
    "checkpoint.mode": "incremental",
    "checkpoint.async": True,
    "checkpoint.local.enabled": True,
    "pipeline.prefetch": "on",
    "restart-strategy": "exponential-backoff",
    "restart-strategy.exponential-backoff.initial-delay": 0.01,
    "restart-strategy.exponential-backoff.max-delay": 0.05,
    "restart-strategy.exponential-backoff.jitter": 0.1,
}


# -------------------------------------------------- local cache unit

def _write_chk(st, cid):
    entries = {
        "key_hi": np.arange(4, dtype=np.uint32),
        "key_lo": np.arange(4, dtype=np.uint32),
        "pane": np.zeros(4, np.int32),
        "value": np.full(4, float(cid), np.float32),
        "fresh": np.zeros(4, bool),
    }
    scal = {"watermark": cid, "fired_through": 0, "max_pane": 1,
            "min_pane": 0, "dropped_late": 0, "dropped_capacity": 0}
    st.write(cid, entries, scal, source_offsets={"o": cid}, aux={})


def test_local_cache_mirror_verify_and_prune(tmp_path):
    """Every publish mirrors into the cache; retention follows the
    primary chain-closure GC so the tiers agree about the restorable
    set; a corrupted blob fails verification and drops the entry."""
    cache = LocalSnapshotCache(str(tmp_path / "local"))
    st = CheckpointStorage(str(tmp_path / "chk"), retain=2, local=cache)
    for cid in (1, 2, 3, 4, 5):
        _write_chk(st, cid)
    assert st.list_checkpoints() == cache.list_entries() == [4, 5]
    assert cache.stats["puts"] == 5
    # verified read
    p = cache.verify(5)
    assert os.path.isdir(p) and cache.stats["hits"] == 1
    # corruption -> LocalCacheMiss + entry dropped
    with open(os.path.join(cache.path(4), "entries.npz"), "ab") as f:
        f.write(b"bitrot")
    with pytest.raises(LocalCacheMiss):
        cache.verify(4)
    assert cache.stats["corrupt"] == 1 and not cache.has(4)


def test_local_cache_rejects_stale_incarnation(tmp_path):
    """Wiping + re-creating the primary directory restarts cids at 1;
    a surviving cache entry from the OLD incarnation CRC-verifies
    perfectly, so the storage-identity binding (not the checksums) must
    reject it — restoring another incarnation's chk-1 would be silent
    wrong-state recovery."""
    import shutil

    chk = str(tmp_path / "chk")
    cache = LocalSnapshotCache(str(tmp_path / "local"))
    st = CheckpointStorage(chk, retain=2, local=cache)
    _write_chk(st, 1)
    assert cache.verify(1)          # bound + fresh: verifies
    hits = cache.stats["hits"]
    # operator wipes the primary (token included) and starts over
    shutil.rmtree(chk)
    st2 = CheckpointStorage(chk, retain=2, local=cache)
    assert st2.storage_id != st.storage_id
    # the manifest fast path (read_manifest skips the CRC sweep) must
    # reject the stale entry through the same identity binding
    assert not cache.identity_ok(1)
    with pytest.raises(LocalCacheMiss):
        cache.verify(1)
    assert cache.stats["stale"] == 1 and not cache.has(1)
    assert cache.stats["hits"] == hits
    # the new incarnation's own publishes verify again
    _write_chk(st2, 1)
    assert cache.verify(1)


def test_storage_read_prefers_local_and_falls_back(tmp_path):
    """read() serves from the verified local copy; a corrupt cache
    entry transparently falls back to primary; a GC'd primary directory
    can still restore from the cache (the availability win)."""
    cache = LocalSnapshotCache(str(tmp_path / "local"))
    st = CheckpointStorage(str(tmp_path / "chk"), retain=3, local=cache)
    for cid in (1, 2, 3):
        _write_chk(st, cid)
    _e, _s, offsets, _a = st.read(3)
    assert offsets == {"o": 3} and cache.stats["hits"] >= 1
    # corrupt the cached copy: read falls back to primary and still works
    with open(os.path.join(cache.path(3), "entries.npz"), "ab") as f:
        f.write(b"junk")
    _e, _s, offsets, _a = st.read(3)
    assert offsets == {"o": 3} and cache.stats["corrupt"] == 1
    # primary directory lost, cache intact -> read served locally
    import shutil

    shutil.rmtree(st.path(2))
    _e, _s, offsets, _a = st.read_raw(2)
    assert offsets == {"o": 2}


# ------------------------------------------- warm in-process restart

def test_warm_restart_after_ingest_thread_kill(tmp_path):
    """A hard prefetch-thread death (the faults.py ``kill`` action) is
    classified TRANSIENT and recovered by a warm in-process restart:
    exactly-once results, a warm-mode attempt in the recovery report,
    and the first-fire MTTR stamped."""
    env = build_env(1, tmp_path / "chk", interval=2, **WARM_CFG)
    inj = FaultInjector([FaultRule("ingest.producer", action="kill",
                                   at=8)])
    with faults.active(inj):
        got = run_job(env, 6144)
    assert got == expected(6144)
    m = env.last_job.metrics
    assert m.restarts == 1
    rep = env._recovery_report()
    ok = [a for a in rep["attempts"] if a["ok"]]
    assert ok and ok[-1]["classification"] == "transient"
    assert ok[-1]["mode"].startswith("warm")
    assert ok[-1]["first_fire_ms"] and ok[-1]["first_fire_ms"] > 0
    # warm = no recompile: the kernels compiled at setup are reused
    assert ok[-1]["phases_ms"].get("compile", 0.0) == 0.0
    assert rep["local-cache"]["puts"] >= 1


def test_warm_restart_multi_shard_parity(tmp_path):
    """The dirty-shard splice on a 2-shard mesh produces the same
    results as the no-failure run (clean shards keep their live device
    arrays; only diverged shards re-stage)."""
    env = build_env(2, tmp_path / "chk", interval=2, **WARM_CFG)
    inj = FaultInjector([FaultRule("ingest.producer", action="kill",
                                   at=10)])
    with faults.active(inj):
        got = run_job(env, 6144)
    assert got == expected(6144)
    assert env.last_job.metrics.restarts == 1


def test_warm_restart_opt_out_takes_full_path(tmp_path):
    """recovery.warm-restart: false sends even transient failures down
    the full restore path."""
    env = build_env(1, tmp_path / "chk", interval=2,
                    **{**WARM_CFG, "recovery.warm-restart": False})
    inj = FaultInjector([FaultRule("ingest.producer", action="kill",
                                   at=8)])
    with faults.active(inj):
        got = run_job(env, 6144)
    assert got == expected(6144)
    rep = env._recovery_report()
    ok = [a for a in rep["attempts"] if a["ok"]]
    assert ok and ok[-1]["mode"] == "full"


def test_state_corrupting_failure_takes_full_path(tmp_path):
    """An unclassified exception (a plain RuntimeError out of a sink)
    is state-corrupting: the restore rebuilds every shard from the
    checkpoint instead of trusting the live device state."""
    env = build_env(1, tmp_path / "chk", interval=2, **WARM_CFG)
    blew = []

    class BlowOnceSink(CollectSink):
        def invoke_batch(self, elements):
            if not blew and self.results:
                blew.append(1)
                raise RuntimeError("sink blew a fuse")
            super().invoke_batch(elements)

    sink = BlowOnceSink()
    (
        env.add_source(GeneratorSource(gen, total=6144))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("recovery-job")
    got = {(r.key, r.window_end_ms): r.value for r in sink.results}
    assert got == expected(6144)
    rep = env._recovery_report()
    ok = [a for a in rep["attempts"] if a["ok"]]
    assert ok and ok[-1]["classification"] == "state-corrupting"
    assert ok[-1]["mode"] == "full"


# ------------------------------------------------ double-fault path

def test_double_fault_during_restore_lands_in_budget(tmp_path):
    """A second injected failure DURING the restore (primary read
    failure on the first fetch) consumes another restart-budget slot
    and retries — the job neither hangs nor escapes with the raw
    restore error."""
    env = build_env(1, tmp_path / "chk", interval=2, **{
        **WARM_CFG, "checkpoint.local.enabled": False,
    })
    inj = FaultInjector([
        FaultRule("ingest.producer", action="kill", at=8),
        FaultRule("ckpt.read.primary", exc=OSError("remote blip"), at=0),
    ])
    t0 = time.monotonic()
    with faults.active(inj):
        got = run_job(env, 6144)
    assert time.monotonic() - t0 < 300.0        # no hang
    assert got == expected(6144)
    m = env.last_job.metrics
    assert m.restarts == 2          # original failure + restore retry
    rep = env._recovery_report()
    assert len(rep["attempts"]) == 2
    assert rep["attempts"][0]["ok"] is False
    assert rep["attempts"][1]["ok"] is True
    assert inj.fired_at("ckpt.read.primary")


def test_double_fault_does_not_corrupt_local_cache(tmp_path):
    """With the cache on, a corrupted cache entry + a primary-read
    failure during restore still recovers within the budget, and every
    surviving cache entry verifies afterwards."""
    chk = tmp_path / "chk"
    cache_dir = str(chk) + "-local"

    def corrupt_newest(_ctx):
        entries = sorted(
            int(n[4:]) for n in os.listdir(cache_dir)
            if n.startswith("chk-") and not n.endswith(".tmp")
        )
        if entries:
            p = os.path.join(cache_dir, f"chk-{entries[-1]}",
                             "entries.npz")
            with open(p, "ab") as f:
                f.write(b"bitrot")

    env = build_env(1, chk, interval=2, **WARM_CFG)
    inj = FaultInjector([
        FaultRule("ingest.producer", action="call", fn=corrupt_newest,
                  at=7),
        FaultRule("ingest.producer", action="kill", at=8),
    ])
    with faults.active(inj):
        got = run_job(env, 6144)
    assert got == expected(6144)
    # every surviving cache entry verifies (the corrupted one was
    # dropped at restore time, not served)
    cache = LocalSnapshotCache(cache_dir)
    for cid in cache.list_entries():
        cache.verify(cid)


# ------------------------------------------------ restart strategies

def test_exponential_backoff_grows_caps_and_resets():
    rs = RestartStrategy.exponential_backoff(
        initial_delay_s=0.01, max_delay_s=0.04, multiplier=2.0,
        jitter=0.0, reset_after_s=0.2,
    )
    now = time.time()
    delays = [rs.next_backoff_delay(now + i * 0.001) for i in range(4)]
    assert delays == [0.01, 0.02, 0.04, 0.04]       # grows, then capped
    # a quiet period >= reset-after resets back to the initial delay
    assert rs.next_backoff_delay(now + 1.0) == 0.01


def test_exponential_backoff_jitter_bounded():
    rs = RestartStrategy.exponential_backoff(
        initial_delay_s=0.04, max_delay_s=0.04, multiplier=2.0,
        jitter=0.25, reset_after_s=10.0,
    )
    for _ in range(50):
        d = rs.next_backoff_delay()
        assert 0.04 * 0.75 - 1e-9 <= d <= 0.04 * 1.25 + 1e-9


def test_exponential_backoff_config_plumbing(tmp_path):
    """The executor builds the strategy from the declared ConfigOptions
    (strict coercion: conf-file strings parse, typos raise)."""
    from flink_tpu.runtime.executor import LocalExecutor

    env = build_env(1, **{
        "restart-strategy": "exponential-backoff",
        "restart-strategy.exponential-backoff.initial-delay": "0.5",
        "restart-strategy.exponential-backoff.max-delay": "2.0",
        "restart-strategy.exponential-backoff.multiplier": "3.0",
        "restart-strategy.exponential-backoff.jitter": "0",
        "restart-strategy.exponential-backoff.reset-after": "60",
    })
    rs = LocalExecutor(env)._restart_strategy()
    assert rs.kind == "exponential-backoff"
    assert (rs.initial_delay_s, rs.max_delay_s, rs.multiplier) == \
        (0.5, 2.0, 3.0)
    env = build_env(1, **{"restart-strategy": "sometimes"})
    with pytest.raises(ValueError, match="restart-strategy"):
        LocalExecutor(env)._restart_strategy()


# ---------------------------------------------------- watchdog restore

def test_watchdog_suspend_disarms_step_phases():
    """While a restore is in progress the steady-state phase deadlines
    must not trip; the dedicated restore deadline still does."""
    wd = Watchdog({"fire": 0.1, "restore": 10.0}, interval_s=0.05)
    wd.start()
    try:
        prev = wd.arm("restore")
        wd.suspend()
        # a nested steady-state phase armed during restore gets NO
        # deadline: sleeping past fire's 0.1s must not trip
        p2 = wd.arm("fire")
        time.sleep(0.4)
        wd.disarm(p2)
        wd.unsuspend()
        wd.disarm(prev)
        assert wd.trips == []
    finally:
        wd.stop()


def test_watchdog_restore_deadline_trips():
    wd = Watchdog({"restore": 0.1}, interval_s=0.05)
    wd.start()
    try:
        prev = wd.arm("restore")
        wd.suspend()
        with pytest.raises(WatchdogError, match="restore"):
            time.sleep(5.0)
        wd.unsuspend()
        wd.disarm(prev)
        assert wd.trips and wd.trips[0].phase == "restore"
    finally:
        wd.stop()


# --------------------------------------------------------- kill action

def test_kill_action_escapes_exception_containment():
    """ThreadKilled is a BaseException: an ``except Exception``
    containment layer between the injection point and the thread top
    must NOT swallow it."""
    inj = FaultInjector([FaultRule("p.kill", action="kill", at=0)])
    with faults.active(inj):
        with pytest.raises(ThreadKilled):
            try:
                faults.inject("p.kill")
            except Exception:       # the containment a kill must escape
                pytest.fail("kill was contained by `except Exception`")


# ------------------------------------------------- web + metrics surface

def test_recovery_route_and_gauges(tmp_path):
    """/jobs/<jid>/recovery serves the attempt history for a windowed
    job (and available:false for stages without the tracker); the
    recovery_* gauges ride the Prometheus text exposition."""
    import urllib.request

    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    env = build_env(1, tmp_path / "chk", interval=2, **WARM_CFG)
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=4096))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    inj = FaultInjector([FaultRule("ingest.producer", action="kill",
                                   at=6)])
    try:
        with faults.active(inj):
            jid = cluster.submit(env, "recovery-web-job")
            assert cluster.wait(jid, 240) == "FINISHED"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/jobs/{jid}/recovery", timeout=10
        ) as r:
            body = json.loads(r.read())
        assert body["available"] is True
        assert body["counts"]["total"] >= 1
        assert body["attempts"][-1]["phases_ms"]
        assert body["local-cache"]["puts"] >= 1
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        for gauge in ("recovery_attempts", "recovery_warm_restarts",
                      "recovery_last_first_fire_ms",
                      "recovery_local_hits"):
            assert f"flink_tpu_{gauge}" in text, gauge
        assert 'flink_tpu_recovery_attempts{job="recovery-web-job"} 1' \
            in text
    finally:
        web.stop()


# ------------------------------------------------- chaos-cycle soak

def _cycle_soak(tmp_path, total, kill_hits):
    env = build_env(1, tmp_path / "chk", interval=2, **WARM_CFG)
    rules = [FaultRule("ingest.producer", action="kill", at=h)
             for h in kill_hits]
    inj = FaultInjector(rules, seed=99)
    t0 = time.monotonic()
    with faults.active(inj):
        got = run_job(env, total)
    wall = time.monotonic() - t0
    m = env.last_job.metrics
    # exactly-once oracle across EVERY crash/restart cycle
    assert got == expected(total)
    assert m.restarts >= len(kill_hits)
    assert_chains_closed(tmp_path / "chk")
    # bounded backoff every cycle: the exponential-backoff strategy
    # caps at max-delay * (1 + jitter) (+ scheduling slack)
    rep = env._recovery_report()
    cap_ms = 0.05 * 1.1 * 1000 + 250.0
    backoffs = [a["phases_ms"].get("backoff", 0.0)
                for a in rep["attempts"]]
    assert backoffs and all(b <= cap_ms for b in backoffs), backoffs
    # the cycles actually recovered warm (the fast path is the product)
    assert any((a["mode"] or "").startswith("warm")
               for a in rep["attempts"])
    return m, rep, wall


def test_crash_restart_cycle_soak_fast(tmp_path):
    """Tier-1 variant: 3 injected crash/restart cycles."""
    m, rep, wall = _cycle_soak(tmp_path, total=8192,
                               kill_hits=(8, 16, 24))
    assert wall < 300.0


@pytest.mark.slow
def test_crash_restart_cycle_soak_full(tmp_path):
    """Full soak (the ISSUE 6 acceptance): >= 5 crash/restart cycles
    with exactly-once, closed chains, and bounded backoff per cycle."""
    m, rep, wall = _cycle_soak(
        tmp_path, total=32768, kill_hits=(10, 25, 40, 55, 70, 85),
    )
    assert m.restarts >= 5
    assert wall < 900.0
