"""Key-group assignment semantics (mirrors the role of the reference's
KeyGroupRangeAssignment tests: stability, balance, range math)."""

import numpy as np
import pytest

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    assign_to_key_group,
    compute_key_group_for_key_hash,
    compute_operator_index_for_key_group,
    key_group_range_for_operator,
    murmur3_32,
)


def test_murmur_deterministic_and_scrambles():
    a = murmur3_32(np.uint32(1))
    b = murmur3_32(np.uint32(1))
    c = murmur3_32(np.uint32(2))
    assert a == b
    assert a != c


def test_murmur_matches_reference_vectors():
    # Independent check against a pure-python murmur3_32 of a 4-byte LE word.
    def ref(code):
        def rotl(x, r):
            return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF

        k = (code * 0xCC9E2D51) & 0xFFFFFFFF
        k = rotl(k, 15)
        k = (k * 0x1B873593) & 0xFFFFFFFF
        h = k
        h = rotl(h, 13)
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
        h ^= 4
        h ^= h >> 16
        h = (h * 0x85EBCA6B) & 0xFFFFFFFF
        h ^= h >> 13
        h = (h * 0xC2B2AE35) & 0xFFFFFFFF
        h ^= h >> 16
        return h

    for v in [0, 1, 42, 0xDEADBEEF, 0xFFFFFFFF]:
        assert int(murmur3_32(np.uint32(v))) == ref(v)


def test_key_groups_in_range_and_balanced():
    maxp = 128
    hashes = np.arange(100_000, dtype=np.uint32)
    kgs = assign_to_key_group(hashes, maxp)
    assert kgs.min() >= 0 and kgs.max() < maxp
    counts = np.bincount(kgs, minlength=maxp)
    assert counts.min() > 0.5 * counts.mean()
    assert counts.max() < 1.5 * counts.mean()


def test_ranges_partition_key_groups():
    for maxp, par in [(128, 1), (128, 4), (128, 7), (4096, 13), (32768, 32)]:
        seen = []
        for op in range(par):
            r = key_group_range_for_operator(maxp, par, op)
            seen.extend(list(r))
        assert seen == list(range(maxp))


def test_operator_index_consistent_with_ranges():
    maxp, par = 128, 7
    for kg in range(maxp):
        op = compute_operator_index_for_key_group(maxp, par, kg)
        assert kg in key_group_range_for_operator(maxp, par, op)


def test_vectorized_matches_scalar():
    maxp = 128
    hashes = np.random.default_rng(0).integers(0, 2**32, 1000, dtype=np.uint32)
    vec = compute_key_group_for_key_hash(hashes, maxp)
    for h, kg in zip(hashes[:50], vec[:50]):
        assert int(compute_key_group_for_key_hash(np.uint32(h), maxp)) == kg


def test_key_group_range():
    r = KeyGroupRange(4, 10)
    assert len(r) == 7
    assert 4 in r and 10 in r and 11 not in r
    assert r.intersect(KeyGroupRange(8, 20)) == KeyGroupRange(8, 10)
    assert r.intersect(KeyGroupRange(11, 20)) == KeyGroupRange.EMPTY
    assert len(KeyGroupRange.EMPTY) == 0


def test_parallelism_validation():
    with pytest.raises(ValueError):
        key_group_range_for_operator(128, 256, 0)
