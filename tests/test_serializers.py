"""Type serializer registry (core/serializers.py) — the per-type
serialization seam replacing round-1 blanket pickle.

Ref contracts: TypeSerializer.java:39 (serialize/deserialize round trip),
ExecutionConfig.registerTypeWithKryoSerializer (custom registration),
StateDescriptor.java:50 (descriptor-pinned serializer), and the restore
compatibility stance of TypeSerializerConfigSnapshot (unknown serializer
on restore is an error, not silent corruption).
"""

import dataclasses

import numpy as np
import pytest

from flink_tpu.core.serializers import (
    DoubleSerializer,
    LongSerializer,
    PickleSerializer,
    SerializationError,
    SerializerRegistry,
    StringSerializer,
    TypeSerializer,
)
from flink_tpu.state.backend import HeapKeyedStateBackend, VoidNamespace
from flink_tpu.state.descriptors import ValueStateDescriptor


@pytest.mark.parametrize("value", [
    0, 1, -(2**62), 2**62, 3.14159, -1e300, True, False, "", "héllo",
    b"\x00\xff", (1, "two", 3.0), [1, 2, 3], {"a": 1, "b": (2.0, "x")},
    (), [], {},
])
def test_typed_envelope_round_trip(value):
    reg = SerializerRegistry()
    got = reg.loads_typed(reg.dumps_typed(value))
    assert got == value
    assert type(got) is type(value)


def test_numpy_round_trip():
    reg = SerializerRegistry()
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    got = reg.loads_typed(reg.dumps_typed(arr))
    np.testing.assert_array_equal(got, arr)
    assert got.dtype == arr.dtype


def test_primitive_wire_is_fixed_width_not_pickle():
    assert LongSerializer().serialize(7) == b"\x07" + b"\x00" * 7
    assert len(DoubleSerializer().serialize(1.5)) == 8
    assert StringSerializer().serialize("ab") == b"ab"


def test_bool_does_not_ride_the_int_serializer():
    reg = SerializerRegistry()
    blob = reg.dumps_typed(True)
    assert blob.split(b"\0", 1)[0] == b"bool"
    assert reg.loads_typed(blob) is True


@dataclasses.dataclass
class Point:
    x: int
    y: int


class PointSerializer(TypeSerializer):
    uid = "test-point"

    def serialize(self, value):
        import struct

        return struct.pack("<qq", value.x, value.y)

    def deserialize(self, data):
        import struct

        x, y = struct.unpack("<qq", data)
        return Point(x, y)


def test_custom_registration_and_fallback():
    reg = SerializerRegistry()
    p = Point(3, -4)
    # unregistered: falls back to pickle envelope
    assert reg.dumps_typed(p).split(b"\0", 1)[0] == b"pickle"
    reg.register(Point, PointSerializer())
    blob = reg.dumps_typed(p)
    assert blob.split(b"\0", 1)[0] == b"test-point"
    assert blob == b"test-point\0" + PointSerializer().serialize(p)
    assert reg.loads_typed(blob) == p


def test_unknown_uid_on_restore_is_an_error():
    writer = SerializerRegistry()
    writer.register(Point, PointSerializer())
    blob = writer.dumps_typed(Point(1, 2))
    reader = SerializerRegistry()   # no Point registration
    with pytest.raises(SerializationError, match="test-point"):
        reader.loads_typed(blob)


def test_uid_collision_rejected():
    class Other(TypeSerializer):
        uid = "long"

        def serialize(self, v):
            return b""

        def deserialize(self, d):
            return None

    reg = SerializerRegistry()
    with pytest.raises(ValueError, match="already bound"):
        reg.register(Point, Other())


# ---------------------------------------------------------------- backend


def _roundtrip_backend(src: HeapKeyedStateBackend, dst: HeapKeyedStateBackend):
    dst.restore(src.snapshot())
    return dst


def test_backend_snapshot_uses_registry_format():
    b = HeapKeyedStateBackend(max_parallelism=8)
    desc = ValueStateDescriptor("v")
    for k, v in [("a", 1.5), ("b", (1, "x")), (7, np.float64(2.0))]:
        b.set_current_key(k)
        b.get_partitioned_state(desc).update(v)
    blobs = b.snapshot()
    assert all(blob[:4] == b"FTS2" for blob in blobs.values())
    b2 = _roundtrip_backend(b, HeapKeyedStateBackend(max_parallelism=8))
    for k, v in [("a", 1.5), ("b", (1, "x")), (7, 2.0)]:
        b2.set_current_key(k)
        assert b2.get_partitioned_state(desc).value() == v


def test_backend_descriptor_pinned_serializer():
    b = HeapKeyedStateBackend(max_parallelism=8)
    b.serializer_registry = SerializerRegistry()
    b.serializer_registry.register(Point, PointSerializer())
    desc = ValueStateDescriptor("pts", serializer=PointSerializer())
    b.set_current_key("k1")
    b.get_partitioned_state(desc).update(Point(10, 20))
    blobs = b.snapshot()
    joined = b"".join(blobs.values())
    assert b"test-point" in joined          # pinned uid recorded
    assert b"pickle\0" not in joined        # no pickle fallback involved

    b2 = HeapKeyedStateBackend(max_parallelism=8)
    b2.serializer_registry = b.serializer_registry
    b2._descs["pts"] = desc                 # descriptor known on restore
    # register table first so desc lookup sees the pin
    b2._table_for(desc)
    b2.restore(blobs)
    b2.set_current_key("k1")
    assert b2.get_partitioned_state(desc).value() == Point(10, 20)


def test_backend_custom_type_via_env_registry_round_trip():
    reg = SerializerRegistry()
    reg.register(Point, PointSerializer())
    b = HeapKeyedStateBackend(max_parallelism=8)
    b.serializer_registry = reg
    desc = ValueStateDescriptor("p")
    b.set_current_key(5)
    b.get_partitioned_state(desc).update(Point(-1, 1))
    b2 = HeapKeyedStateBackend(max_parallelism=8)
    b2.serializer_registry = reg
    b2.restore(b.snapshot())
    b2.set_current_key(5)
    assert b2.get_partitioned_state(desc).value() == Point(-1, 1)


def test_backend_legacy_pickle_blob_still_restores():
    import pickle

    legacy = {0: pickle.dumps({"v": {VoidNamespace: {"k": 42}}})}
    b = HeapKeyedStateBackend(max_parallelism=8)
    b.restore(legacy)
    assert b.lookup("v", "k") == 42 or b._tables["v"].maps[0]


def test_env_register_type_serializer_surface():
    from flink_tpu.datastream.environment import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    env.register_type_serializer(Point, PointSerializer())
    assert env.serializer_registry.serializer_for(Point(0, 0)).uid == "test-point"


def test_huge_int_falls_back_instead_of_crashing():
    reg = SerializerRegistry()
    for v in (2**64, -(2**70), 10**30):
        assert reg.loads_typed(reg.dumps_typed(v)) == v


import collections
import enum

NT = collections.namedtuple("NT", "a b")


class Color(enum.IntEnum):
    RED = 1


def test_namedtuple_and_intenum_preserve_type():
    reg = SerializerRegistry()
    got = reg.loads_typed(reg.dumps_typed(NT(1, 2)))
    assert got == NT(1, 2) and got.a == 1     # not degraded to plain tuple
    got2 = reg.loads_typed(reg.dumps_typed(Color.RED))
    assert got2 is Color.RED                  # not degraded to int


def test_registered_user_base_class_covers_subclasses():
    class Base:
        pass

    class Sub(Base):
        pass

    class BaseSer(TypeSerializer):
        uid = "test-base"

        def serialize(self, v):
            return type(v).__name__.encode()

        def deserialize(self, d):
            return d.decode()

    reg = SerializerRegistry()
    reg.register(Base, BaseSer())
    assert reg.serializer_for(Sub()).uid == "test-base"


def test_pinned_descriptor_restores_without_registry_registration():
    # the pin lives ONLY on the descriptor — restore must resolve it from
    # self._descs, not demand a registry registration
    desc = ValueStateDescriptor("pts", serializer=PointSerializer())
    b = HeapKeyedStateBackend(max_parallelism=8)
    b.set_current_key("k")
    b.get_partitioned_state(desc).update(Point(7, 8))
    blobs = b.snapshot()

    b2 = HeapKeyedStateBackend(max_parallelism=8)
    b2._table_for(desc)        # open() registers the descriptor
    b2.restore(blobs)
    b2.set_current_key("k")
    assert b2.get_partitioned_state(desc).value() == Point(7, 8)


def test_registry_fork_carries_user_registrations():
    src = SerializerRegistry()
    src.register(Point, PointSerializer())
    forked = SerializerRegistry(copy_from=src)
    assert forked.serializer_for(Point(0, 0)).uid == "test-point"
    blob = src.dumps_typed(Point(1, 2))
    assert forked.loads_typed(blob) == Point(1, 2)


def test_object_dtype_ndarray_falls_back_to_pickle():
    reg = SerializerRegistry()
    arr = np.array(["a", None, 3], dtype=object)
    got = reg.loads_typed(reg.dumps_typed(arr))
    assert list(got) == ["a", None, 3]


def test_custom_serializer_failure_is_not_swallowed():
    class Fussy(TypeSerializer):
        uid = "fussy"

        def serialize(self, v):
            raise ValueError("bad value")

        def deserialize(self, d):
            return None

    reg = SerializerRegistry()
    reg.register(Point, Fussy())
    with pytest.raises(SerializationError, match="bad value"):
        reg.dumps_typed(Point(1, 2))
    # ... including when nested inside a builtin container: the container's
    # own fallback must NOT swallow the user serializer's failure
    with pytest.raises(SerializationError, match="bad value"):
        reg.dumps_typed((Point(1, 2),))
    with pytest.raises(SerializationError, match="bad value"):
        reg.dumps_typed({"k": [Point(1, 2)]})


def test_lazy_descriptor_pinned_restore_defers_until_registration():
    # snapshot with a pin known only to the descriptor; restore into a
    # backend that has NOT opened the state yet — entries must decode when
    # the descriptor first shows up (lazy state registration)
    desc = ValueStateDescriptor("lazy", serializer=PointSerializer())
    b = HeapKeyedStateBackend(max_parallelism=8)
    b.set_current_key("k")
    b.get_partitioned_state(desc).update(Point(5, 6))
    blobs = b.snapshot()

    b2 = HeapKeyedStateBackend(max_parallelism=8)
    b2.restore(blobs)                       # descriptor unknown: defers
    assert b2._pending_restore
    b2.set_current_key("k")
    st = b2.get_partitioned_state(desc)     # registration resolves it
    assert st.value() == Point(5, 6)
    assert not b2._pending_restore


def test_pending_entries_survive_snapshot_before_registration():
    # restore entries for a lazily-pinned state, snapshot WITHOUT ever
    # opening that state: the re-snapshot must carry the entries verbatim
    desc = ValueStateDescriptor("lazy", serializer=PointSerializer())
    b = HeapKeyedStateBackend(max_parallelism=8)
    b.set_current_key("k")
    b.get_partitioned_state(desc).update(Point(5, 6))
    blobs = b.snapshot()

    b2 = HeapKeyedStateBackend(max_parallelism=8)
    b2.restore(blobs)                 # defers (descriptor unknown)
    blobs2 = b2.snapshot()            # state untouched since restore
    b3 = HeapKeyedStateBackend(max_parallelism=8)
    b3.restore(blobs2)
    b3.set_current_key("k")
    assert b3.get_partitioned_state(desc).value() == Point(5, 6)


def test_second_restore_discards_stale_pending_entries():
    desc = ValueStateDescriptor("lazy", serializer=PointSerializer())
    b = HeapKeyedStateBackend(max_parallelism=8)
    b.set_current_key("k")
    b.get_partitioned_state(desc).update(Point(5, 6))
    blobs_a = b.snapshot()

    b2 = HeapKeyedStateBackend(max_parallelism=8)
    b2.restore(blobs_a)               # defers A's entries
    b2.restore({})                    # checkpoint B: state empty
    b2.set_current_key("k")
    assert b2.get_partitioned_state(desc).value() is None  # A must not leak


def test_config_snapshot_mismatch_refused():
    from flink_tpu.core.serializers import SerializationError

    class PointSerializerV2(PointSerializer):
        # same uid, different wire claim
        def config_snapshot(self):
            return "PointSerializerV2:test-point:v2"

    desc = ValueStateDescriptor("pts", serializer=PointSerializer())
    b = HeapKeyedStateBackend(max_parallelism=8)
    b.set_current_key("k")
    b.get_partitioned_state(desc).update(Point(1, 2))
    blobs = b.snapshot()

    desc2 = ValueStateDescriptor("pts", serializer=PointSerializerV2())
    b2 = HeapKeyedStateBackend(max_parallelism=8)
    b2._table_for(desc2)
    with pytest.raises(SerializationError, match="config"):
        b2.restore(blobs)


def test_latency_samples_bounded_and_accurate():
    from flink_tpu.metrics.latency import LatencySamples

    ls = LatencySamples(max_samples=1000)
    rng = np.random.default_rng(0)
    vals = rng.exponential(10.0, 20_000)
    for v in vals:
        ls.record(1, float(v))
    assert len(ls) <= 1000
    p99 = ls.percentile(99)
    true_p99 = float(np.percentile(vals, 99))
    assert abs(p99 - true_p99) / true_p99 < 0.05


# ----------------------------------------------- TypeExtractor analog (r4)
def test_type_extraction_from_samples():
    from collections import namedtuple
    from dataclasses import dataclass

    from flink_tpu.core import type_info as ti

    assert ti.of(3) == ti.BasicTypeInfo(int)
    assert ti.of(True) == ti.BasicTypeInfo(bool)       # bool before int
    assert ti.of(1.5) == ti.BasicTypeInfo(float)
    assert ti.of("x") == ti.BasicTypeInfo(str)
    t = ti.of((1, "a", 2.0))
    assert isinstance(t, ti.TupleTypeInfo) and t.arity == 3

    Point = namedtuple("Point", ["x", "y"])
    r = ti.of(Point(1.0, 2.0))
    assert isinstance(r, ti.RowTypeInfo)
    assert r.names == ("x", "y")

    @dataclass
    class Ev:
        key: int
        value: float

    r2 = ti.of(Ev(1, 2.0))
    assert r2.names == ("key", "value")
    assert r2.types == (ti.BasicTypeInfo(int), ti.BasicTypeInfo(float))

    arr = ti.of(np.zeros((4, 2), np.float32))
    assert isinstance(arr, ti.PrimitiveArrayTypeInfo)
    assert arr.shape == (4, 2)

    m = ti.of({"a": 1})
    assert m == ti.MapTypeInfo(ti.BasicTypeInfo(str), ti.BasicTypeInfo(int))

    class Weird:
        pass

    assert isinstance(ti.of(Weird()), ti.GenericTypeInfo)


def test_type_extraction_from_hints():
    from typing import Dict, List, Optional, Tuple

    from flink_tpu.core import type_info as ti

    assert ti.from_hint(int) == ti.BasicTypeInfo(int)
    t = ti.from_hint(Tuple[int, str])
    assert t == ti.TupleTypeInfo((ti.BasicTypeInfo(int),
                                  ti.BasicTypeInfo(str)))
    assert ti.from_hint(List[float]) == ti.ListTypeInfo(
        ti.BasicTypeInfo(float)
    )
    assert ti.from_hint(Dict[str, int]) == ti.MapTypeInfo(
        ti.BasicTypeInfo(str), ti.BasicTypeInfo(int)
    )
    # Optional[T] -> T (nullable fields keep their base type)
    assert ti.from_hint(Optional[int]) == ti.BasicTypeInfo(int)
    # Tuple[int, ...] -> homogeneous list
    assert ti.from_hint(Tuple[int, ...]) == ti.ListTypeInfo(
        ti.BasicTypeInfo(int)
    )


def test_type_info_schema_bridge_and_serializer_binding():
    """Flat numeric rows bridge onto the columnar Schema the device path
    consumes; every extracted type round-trips through the registry."""
    from collections import namedtuple

    from flink_tpu.core import type_info as ti
    from flink_tpu.core.serializers import SerializerRegistry

    Ev = namedtuple("Ev", ["key", "value"])
    row = ti.of(Ev(1, 2.0))
    sch = row.to_schema()
    assert sch.names() == ["key", "value"]
    assert sch.fields[0].dtype == np.dtype(np.int64)

    # non-columnar rows refuse a schema loudly
    import pytest as _pytest

    with _pytest.raises(TypeError, match="columnar"):
        ti.of(("a", object())).to_schema()

    reg = SerializerRegistry()
    for sample in (7, 3.5, "s", b"b", (1, "x"), [1, 2], {"k": 1.0}):
        t = ti.of(sample)
        bound = t.create_serializer(reg)
        blob = bound.dumps_typed(sample)
        assert bound.loads_typed(blob) == sample
