"""Checkpoint/savepoint/restore for rolling-reduce and count-window
stages (round 5: removes the last two `_check_no_checkpointing` refusals;
ref AbstractStreamOperator.java:367 — EVERY operator snapshots its state;
rolling aggregates live in ValueState via StreamGroupedReduce)."""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.runtime.sinks import CollectSink


class SnapSink(CollectSink):
    """CollectSink that participates in checkpoints."""

    def snapshot_state(self):
        return list(self.results)

    def restore_state(self, state):
        self.results[:] = state


class FailOnceSink(SnapSink):
    """Raises once mid-stream after `trip_at` results, then behaves."""

    def __init__(self, trip_at):
        super().__init__()
        self.trip_at = trip_at
        self.tripped = False

    def invoke_batch(self, elements):
        if not self.tripped and len(self.results) >= self.trip_at:
            self.tripped = True
            raise RuntimeError("induced sink failure")
        super().invoke_batch(elements)


class KillSink(SnapSink):
    """Simulated process kill: KeyboardInterrupt is not restartable."""

    def __init__(self, kill_at):
        super().__init__()
        self.kill_at = kill_at

    def invoke_batch(self, elements):
        super().invoke_batch(elements)
        if len(self.results) >= self.kill_at:
            raise KeyboardInterrupt("simulated kill")


def _env(tmpdir, capacity=256, extra_cfg=None):
    cfg = {"restart-strategy": "fixed-delay",
           "restart-strategy.fixed-delay.attempts": 3,
           "restart-strategy.fixed-delay.delay": 0}
    cfg.update(extra_cfg or {})
    env = StreamExecutionEnvironment(Configuration(cfg))
    env.set_parallelism(2)
    env.set_max_parallelism(8)
    env.set_state_capacity(capacity)
    env.batch_size = 8
    env.enable_checkpointing(interval_steps=2, directory=str(tmpdir))
    return env


# ---------------------------------------------------------------- rolling

def _rolling_events():
    rng = np.random.default_rng(7)
    return [(int(rng.integers(0, 5)), float(rng.integers(1, 4)))
            for _ in range(120)]


def _rolling_expect(events):
    acc, out = {}, []
    for k, v in events:
        acc[k] = acc.get(k, 0.0) + v
        out.append((k, acc[k]))
    return out


def _rolling_job(env, events, sink):
    (
        env.from_collection(events)
        .key_by(lambda e: e[0])
        .sum(lambda e: e[1])
        .add_sink(sink)
    )
    return env


def test_rolling_checkpoint_restart_exactness(tmp_path):
    """Induced sink failure mid-stream: restore from the last checkpoint
    and the per-record output sequence is exact (no loss, no dupes)."""
    events = _rolling_events()
    sink = FailOnceSink(trip_at=40)
    env = _rolling_job(_env(tmp_path), events, sink)
    job = env.execute("rolling-ckpt")
    assert job.metrics.restarts >= 1
    assert sink.results == _rolling_expect(events)


def test_rolling_kill_and_resume_from_checkpoint(tmp_path):
    """Half the stream, 'kill' (abandon the env), resume a FRESH env from
    the checkpoint directory: output sequence is exact."""
    events = _rolling_events()
    s1 = KillSink(kill_at=60)
    env1 = _rolling_job(_env(tmp_path), events, s1)
    with pytest.raises(KeyboardInterrupt):
        env1.execute("rolling-kill")

    s2 = SnapSink()
    env2 = _rolling_job(_env(tmp_path), events, s2)
    env2.execute("rolling-resume", restore_from=str(tmp_path))
    assert s2.results == _rolling_expect(events)


def test_rolling_restore_validation_failures(tmp_path):
    """Mismatched configuration fails fast at restore, never corrupts."""
    events = _rolling_events()
    env = _rolling_job(_env(tmp_path), events, SnapSink())
    env.execute("rolling-write")

    # wrong state capacity (the compiled step bakes it into its masks)
    bad = _rolling_job(_env(tmp_path, capacity=512), events, SnapSink())
    with pytest.raises(ValueError, match="capacity_per_shard"):
        bad.execute("rolling-bad-cap", restore_from=str(tmp_path))

    # wrong stage kind: a count-window job must refuse this checkpoint
    cnt = _env(tmp_path)
    (
        cnt.from_collection(events)
        .key_by(lambda e: e[0])
        .count_window(3)
        .sum(lambda e: e[1])
        .add_sink(SnapSink())
    )
    with pytest.raises(ValueError, match="count-window"):
        cnt.execute("rolling-bad-kind", restore_from=str(tmp_path))

    # wrong max-parallelism
    bad_mp = _env(tmp_path)
    bad_mp.set_max_parallelism(16)
    _rolling_job(bad_mp, events, SnapSink())
    with pytest.raises(ValueError, match="max-parallelism"):
        bad_mp.execute("rolling-bad-mp", restore_from=str(tmp_path))


# ------------------------------------------------------------ count window

def _count_events():
    rng = np.random.default_rng(11)
    return [(int(rng.integers(0, 4)), float(rng.integers(1, 4)))
            for _ in range(150)]


def _count_expect(events, n):
    acc, cnt, widx = {}, {}, {}
    fires = []
    for k, v in events:
        acc[k] = acc.get(k, 0.0) + v
        cnt[k] = cnt.get(k, 0) + 1
        if cnt[k] == n:
            fires.append((k, widx.get(k, 0), acc[k]))
            widx[k] = widx.get(k, 0) + 1
            acc[k], cnt[k] = 0.0, 0
    return fires


def _count_job(env, events, sink, n=5):
    (
        env.from_collection(events)
        .key_by(lambda e: e[0])
        .count_window(n)
        .sum(lambda e: e[1])
        .add_sink(sink)
    )
    return env


def test_count_checkpoint_restart_exactness(tmp_path):
    events = _count_events()
    sink = FailOnceSink(trip_at=10)
    env = _count_job(_env(tmp_path), events, sink)
    job = env.execute("count-ckpt")
    assert job.metrics.restarts >= 1
    got = [(r.key, r.window_end_ms, r.value) for r in sink.results]
    assert sorted(got) == sorted(_count_expect(events, 5))


def test_count_kill_and_resume_from_checkpoint(tmp_path):
    events = _count_events()
    s1 = KillSink(kill_at=12)
    env1 = _count_job(_env(tmp_path), events, s1)
    with pytest.raises(KeyboardInterrupt):
        env1.execute("count-kill")

    s2 = SnapSink()
    env2 = _count_job(_env(tmp_path), events, s2)
    env2.execute("count-resume", restore_from=str(tmp_path))
    got = [(r.key, r.window_end_ms, r.value) for r in s2.results]
    assert sorted(got) == sorted(_count_expect(events, 5))


def test_count_restore_validation_failures(tmp_path):
    events = _count_events()
    env = _count_job(_env(tmp_path), events, SnapSink())
    env.execute("count-write")

    # wrong window size N (baked into the compiled step)
    bad_n = _count_job(_env(tmp_path), events, SnapSink(), n=7)
    with pytest.raises(ValueError, match="n_per_window"):
        bad_n.execute("count-bad-n", restore_from=str(tmp_path))

    # wrong stage kind: a rolling job must refuse this checkpoint
    roll = _env(tmp_path)
    (
        roll.from_collection(events)
        .key_by(lambda e: e[0])
        .sum(lambda e: e[1])
        .add_sink(SnapSink())
    )
    with pytest.raises(ValueError, match="rolling-reduce"):
        roll.execute("count-bad-kind", restore_from=str(tmp_path))

    # wrong shard count
    bad_sh = _env(tmp_path)
    bad_sh.set_parallelism(4)
    _count_job(bad_sh, events, SnapSink())
    with pytest.raises(ValueError, match="shard"):
        bad_sh.execute("count-bad-shards", restore_from=str(tmp_path))


def test_rolling_foreign_dir_restore_keymap(tmp_path):
    """Restore from a FOREIGN directory (the savepoint story: job A's
    checkpoints seed job B with its own checkpoint dir), then fail and
    restart from job B's OWN storage: the codec reverse map must survive
    both hops — string keys would otherwise decode to raw hash garbage."""
    rng = np.random.default_rng(13)
    events = [("key-%d" % rng.integers(0, 5), float(rng.integers(1, 4)))
              for _ in range(120)]
    dir_a, dir_b = tmp_path / "a", tmp_path / "b"

    s1 = KillSink(kill_at=40)
    env1 = _rolling_job(_env(dir_a), events, s1)
    with pytest.raises(KeyboardInterrupt):
        env1.execute("foreign-seed")

    # resumes from A, checkpoints into B, trips once, restarts from B
    s2 = FailOnceSink(trip_at=80)
    env2 = _rolling_job(_env(dir_b), events, s2)
    job = env2.execute("foreign-resume", restore_from=str(dir_a))
    assert job.metrics.restarts >= 1
    assert s2.results == _rolling_expect(events)
    assert all(isinstance(k, str) and k.startswith("key-")
               for k, _ in s2.results)
