"""Early-exit while-loop drains (ISSUE 20 tentpole (a), runtime/step.py
``build_window_while_drain[_sharded]`` + runtime/executor.py ``while``
resident mode + runtime/ingest.py device publish cursor):

* steady-state correctness with ``pipeline.resident-loop=while`` — exact
  windows with no more drain dispatches than the scan-mode baseline (the
  while body retires every staged slot the HBM cursor exposes, including
  batches published mid-drain),
* the platform gate: ``while`` on CPU without
  ``pipeline.while-drain.cpu-override`` falls back to the scan drain and
  stays exact,
* ``pipeline.while-drain.max-slots`` bounds a single dispatch without
  changing results,
* exactly-once across a MID-WHILE-DRAIN crash (``step.drain`` seam)
  under prefetch + incremental + async checkpoints + packed planes,
* a cursor-race property test over {scan, while} x {1, 4} shards: with
  the device cursor enabled (while mode) the consumer retires slots
  purely from ``device_cursor()`` snapshots — every published slot is
  retired exactly once, snapshots are monotone, and a grabbed cursor
  array is a stable (never-mutated) snapshot even after later commits.
"""

import threading
import time

import jax
import numpy as np
import pytest

from flink_tpu.parallel.mesh import MeshContext
from flink_tpu.runtime import ingest as ingest_mod
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, FaultRule

from test_resident_loop import (  # noqa: F401 — shared job helpers
    RESIDENT_CFG,
    _batch,
    _mk_plan,
    build_env,
    expected,
    run_job,
)

WHILE_CFG = {
    **RESIDENT_CFG,
    "pipeline.resident-loop": "while",
    # CPU has no async dispatch gap to close; tests opt in explicitly so
    # the while kernel itself (not just the gate) is exercised
    "pipeline.while-drain.cpu-override": "on",
}


# ----------------------------------------------------- steady state

def test_while_drain_exact_with_no_more_dispatches_than_scan():
    """While mode is exact and never dispatches MORE drains than the
    scan baseline on the same stream: the loop condition re-reads the
    publish cursor, so slots landing mid-drain retire in the same
    dispatch instead of forcing another one."""
    total = 4096
    env = build_env(1, **WHILE_CFG)
    got = run_job(env, total)
    assert got == expected(total)
    m = env.last_job.metrics
    assert m.resident_drains > 0

    scan_env = build_env(1, **RESIDENT_CFG)
    assert run_job(scan_env, total) == expected(total)
    assert m.resident_drains <= scan_env.last_job.metrics.resident_drains


def test_while_gated_on_cpu_falls_back_to_scan():
    """Without the cpu-override the platform gate keeps the scan drain
    (no while dispatch on a backend with no gap to close) — results are
    identical, drains still happen."""
    cfg = {k: v for k, v in WHILE_CFG.items()
           if k != "pipeline.while-drain.cpu-override"}
    env = build_env(1, **cfg)
    assert run_job(env, 2048) == expected(2048)
    assert env.last_job.metrics.resident_drains > 0


def test_while_max_slots_bounds_dispatch_not_results():
    """``pipeline.while-drain.max-slots`` caps one dispatch's trip count
    (the watchdog deadline scale) — a tight cap of 2 changes dispatch
    granularity only, never the windows."""
    env = build_env(1, **{**WHILE_CFG,
                          "pipeline.while-drain.max-slots": 2})
    assert run_job(env, 4096) == expected(4096)
    assert env.last_job.metrics.resident_drains > 0


def test_while_requires_staging_substrate():
    """``while`` without prefetch+staging is a config error, identical
    to ``on`` — never a silent downgrade."""
    env = build_env(1, **{"pipeline.prefetch": "off",
                          "pipeline.resident-loop": "while"})
    with pytest.raises(ValueError, match="resident-loop"):
        run_job(env, 512)


def test_while_sharded_exact_with_data_parallel():
    """Sharded while drain under data-parallel: per-shard cursor vector,
    per-shard early exit, exact global windows."""
    total = 4096
    env = build_env(4, **{**WHILE_CFG, "pipeline.data-parallel": "on"})
    got = run_job(env, total)
    assert got == expected(total)
    m = env.last_job.metrics
    assert m.resident_drains > 0
    assert m.steps_sharded > 0


# ------------------------------------------ mid-drain crash, exactly-once

def test_while_mid_drain_crash_restore_exactly_once(tmp_path):
    """The round-20 exactly-once criterion for while mode: crash at the
    drain dispatch (``step.drain`` seam, staged slots accumulated + HBM
    cursor ahead of the retired base) under prefetch + incremental +
    async checkpoints + packed planes; restore replays the un-retired
    group from the applied-offset cut — the device cursor is rebuilt
    from the host write cursor on restart, so no slot is skipped or
    double-drained."""
    total = 4096
    env = build_env(
        2, tmp_path / "chk", interval=2, restart=3,
        **{**WHILE_CFG,
           "checkpoint.mode": "incremental", "checkpoint.async": True,
           "state.packed-planes": "on"},
    )
    inj = FaultInjector([
        FaultRule("step.drain",
                  exc=RuntimeError("injected mid-while-drain crash"),
                  at=1),
    ])
    with faults.active(inj):
        got = run_job(env, total)
    m = env.last_job.metrics
    assert inj.fired_at("step.drain"), "drain seam never fired"
    assert m.restarts == 1
    assert m.resident_drains > 0
    assert got == expected(total)


# --------------------------------------- cursor race, {scan,while}x{1,4}

def _sharded_plan(n=4, B=8, cap=8, depth=4):
    ctx = MeshContext.create(n, 128, devices=jax.devices()[:n])
    mask_sh, split_sh = ingest_mod.IngestPlan.shardings_for(ctx.mesh)
    return ingest_mod.IngestPlan(
        td=None, slide_ticks=1000, span_limit=8, B=B, B_step=B,
        n_shards=n, max_parallelism=128,
        kg_ends=np.asarray(ctx.kg_bounds()[1]), exchange_cap=0,
        routes=("mask", "sharded"), staging=True,
        mask_sharding=mask_sh, split_sharding=split_sh,
        ring_depth=depth, shard_cap=cap,
    )


@pytest.mark.parametrize("mode", ["scan", "while"])
@pytest.mark.parametrize("n_shards", [1, 4])
def test_cursor_race_every_slot_retired_exactly_once(mode, n_shards):
    """Threaded producer/consumer over the publish/retire seam, the way
    the executor really drives it in each mode: in ``while`` mode the
    consumer learns progress ONLY from ``device_cursor()`` snapshots
    (host write seq paired with the HBM slot contents, read under one
    lock) and re-stages the slot after every 'dispatch' with
    ``refresh_device_cursor()``; in ``scan`` mode the cursor is disabled
    and retirement follows the host-side published seqs. Either way
    every published slot is retired exactly once, snapshots never move
    backwards, and a grabbed cursor array holds its value even after
    later commits (replace-not-mutate contract — a donated buffer can
    never alias a live snapshot)."""
    depth, B, M = 4, 8, 120
    if n_shards == 1:
        plan = _mk_plan(B=B, depth=depth)
        ring = ingest_mod.DeviceBatchRing(plan, depth)
        cursor_sh = plan.mask_sharding
    else:
        plan = _sharded_plan(n=n_shards, B=B, depth=depth)
        ring = ingest_mod.ShardedDeviceBatchRing(plan, depth)
        cursor_sh = plan.split_sharding
    if mode == "while":
        ring.enable_device_cursor(cursor_sh)
    else:
        assert ring.device_cursor() is None

    published = []                 # per-publish seq records (host truth)
    errs = []
    done = threading.Event()

    def producer():
        try:
            for j in range(M):
                hi, lo, ticks, vals = _batch(j, B, B)
                if n_shards == 1:
                    while True:
                        pub = ring.try_publish(plan, hi, lo, ticks,
                                               vals, B, "mask", epoch=0)
                        if pub is not None:
                            break
                        time.sleep(0.0002)   # full: consumer is behind
                    published.append(pub[0])
                else:
                    # every batch carries all shards, so lanes fill in
                    # lockstep; gating on occupancy (only THIS thread
                    # publishes, so it can't grow concurrently) keeps
                    # every slot ring-resident — no fresh-buffer bypass
                    shard = np.arange(B, dtype=np.int64) % n_shards
                    while ring.occupancy() >= depth:
                        time.sleep(0.0002)
                    seqs, _staged = ring.publish_batch(
                        plan, hi, lo, ticks, vals, shard, B, 0)
                    assert seqs == [j] * n_shards
                    published.append(seqs)
        except Exception as e:  # noqa: BLE001 — surfaced by the assert
            errs.append(e)
        finally:
            done.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    freed = 0
    prev = None                    # (cursor array, host snapshot) pair
    last_snap = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if mode == "while":
            cur, snap = ring.device_cursor()
            # consistency: the HBM slot encodes exactly the host write
            # seq it was paired with under the lock
            got = np.asarray(cur)
            if n_shards == 1:
                assert int(got[0]) == snap
                assert last_snap is None or snap >= last_snap
                if snap > 0:
                    freed += ring.release_through(snap - 1)
            else:
                assert tuple(int(v) for v in got) == snap
                assert last_snap is None or all(
                    a >= b for a, b in zip(snap, last_snap))
                freed += ring.release_shards(
                    [w - 1 if w > 0 else None for w in snap])
            # stability: the PREVIOUS grabbed array still reads its own
            # snapshot after newer commits replaced the live slot
            if prev is not None:
                old_cur, old_snap = prev
                old = np.asarray(old_cur)
                if n_shards == 1:
                    assert int(old[0]) == old_snap
                else:
                    assert tuple(int(v) for v in old) == old_snap
            prev = (cur, snap)
            last_snap = snap
            # the dispatch donated the grabbed array: re-stage
            ring.refresh_device_cursor()
        else:
            k = len(published)
            if k > 0:
                if n_shards == 1:
                    freed += ring.release_through(published[k - 1])
                else:
                    freed += ring.release_shards(published[k - 1])
        if done.is_set() and freed == M * n_shards:
            break
        time.sleep(0.0005)
    t.join(timeout=10)
    assert not errs, errs
    assert len(published) == M
    # exactly once: every slot of every publish freed, none twice
    assert freed == M * n_shards
    assert ring.occupancy() == 0
    if n_shards == 4:
        assert ring.refusals() == [0] * n_shards
    if mode == "while":
        # final snapshot converged on the full stream
        _cur, snap = ring.device_cursor()
        assert snap == (M if n_shards == 1 else (M,) * n_shards)
