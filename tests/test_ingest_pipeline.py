"""Checkpoint-compatible pipelined ingest (ISSUE 3, runtime/ingest.py):

* exactly-once across a mid-stream crash with ``pipeline.prefetch=on``
  and ``checkpoint.mode=incremental`` — the applied-offset cut replays
  in-flight prefetched batches without skipping or double-counting,
* prefetch-thread error delivery (an exception raised in prep reaches
  the driver; the loop does not hang),
* device-staging on/off parity (staged committed arrays compute the
  same windows as host-array dispatch),
* the epoch/pause/resume protocol and the prefix-mask template at the
  unit level.
"""

import threading
import time

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime import ingest as ingest_mod
from flink_tpu.runtime.sinks import CollectSink, CountingSink
from flink_tpu.runtime.sources import GeneratorSource

N_KEYS = 200
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    return cols, (idx // 50) * 1000


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def build_env(parallelism, ckpt_dir=None, interval=0, restart=None, **cfg):
    conf = Configuration(cfg)
    if restart:
        conf.set("restart-strategy", "fixed-delay")
        conf.set("restart-strategy.fixed-delay.attempts", restart)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, source=None, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(source or GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("ingest-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


class FailingSource(GeneratorSource):
    """Raises once when crossing fail_at — ON THE PREFETCH THREAD when
    pipeline.prefetch is on (the poll runs there)."""

    def __init__(self, fn, total, fail_at):
        super().__init__(fn, total)
        self.fail_at = fail_at
        self.failed = False
        self.poll_thread_names = set()

    def poll(self, max_records):
        self.poll_thread_names.add(threading.current_thread().name)
        out = super().poll(max_records)
        if not self.failed and self.offset >= self.fail_at:
            self.failed = True
            raise RuntimeError("injected failure")
        return out


# ------------------------------------------------- exactly-once restore

def test_prefetch_incremental_crash_restore_exactly_once(tmp_path):
    """Crash mid-stream with prefetch=on + checkpoint.mode=incremental,
    restore, and assert exactly-once counts: no skipped and no
    double-counted records even though the prefetch thread had polled
    ahead of the checkpoint cut when the failure hit."""
    total = 4096
    env = build_env(
        2, tmp_path / "chk", interval=2, restart=3,
        **{"pipeline.prefetch": "on", "checkpoint.mode": "incremental",
           "checkpoint.async": True},
    )
    src = FailingSource(gen, total, fail_at=total // 2)
    got = run_job(env, total, source=src)
    assert env.last_job.metrics.restarts == 1
    assert got == expected(total)
    # the poll really ran off the step loop (the scenario under test)
    assert any(
        "ingest" in name for name in src.poll_thread_names
    ), src.poll_thread_names


def test_checkpoint_cut_is_applied_offsets_across_processes(tmp_path):
    """Phase 1 consumes half the stream with prefetch running ahead of
    every checkpoint; phase 2 (a fresh env) restores the latest cut and
    finishes. The merged output must equal the single-run truth — a cut
    taken at the LIVE source position instead of the applied one would
    skip the prefetched-but-unapplied records on restore."""
    total, half = 8192, 4096
    env1 = build_env(1, tmp_path / "chk", interval=1,
                     **{"pipeline.prefetch": "on"})
    got1 = run_job(env1, half)
    assert (env1.last_job.metrics.checkpoint_stats or [])
    env2 = build_env(1, **{"pipeline.prefetch": "on"})
    got2 = run_job(env2, total, restore_from=str(tmp_path / "chk"))
    merged = {**got1, **got2}
    assert merged == expected(total)


# --------------------------------------------------- error delivery

def test_prefetch_thread_error_reaches_driver():
    """An exception raised in prep_batch on the prefetch thread must
    reach the driver as the job failure (no checkpoint, no restart
    strategy — nothing to absorb it), and the loop must not hang."""
    total = 2048
    env = build_env(1, **{"pipeline.prefetch": "on"})
    src = FailingSource(gen, total, fail_at=512)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="injected failure"):
        run_job(env, total, source=src)
    assert time.monotonic() - t0 < 60.0


def test_prep_encode_error_reaches_driver():
    """Not just source errors: a failure in the encode half of prep (a
    key selector raising) also propagates from the prefetch thread."""
    env = build_env(1, **{"pipeline.prefetch": "on"})

    def bad_selector(c):
        raise TypeError("bad key selector")

    sink = CountingSink()
    (
        env.add_source(GeneratorSource(gen, total=1024))
        .key_by(bad_selector)
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    with pytest.raises(TypeError, match="bad key selector"):
        env.execute("bad-selector")


# ------------------------------------------------------ staging parity

@pytest.mark.parametrize("staging", ["on", "off"])
def test_device_staging_parity(staging, tmp_path):
    """Route-aware device staging must be semantics-free: identical
    windows with the staging ring on and off, checkpointing active."""
    total = 4096
    env = build_env(
        2, tmp_path / f"chk-{staging}", interval=4,
        **{"pipeline.prefetch": "on", "pipeline.device-staging": staging},
    )
    got = run_job(env, total)
    assert got == expected(total)


def test_staging_requires_prefetch():
    env = build_env(1, **{"pipeline.prefetch": "off",
                          "pipeline.device-staging": "on"})
    with pytest.raises(ValueError, match="device-staging"):
        run_job(env, 512)


class _NonReplayableSource(GeneratorSource):
    """A source that cannot rewind: the applied-offset cut cannot replay
    batches a restore discards, so prefetch must not run ahead of a
    possible snapshot."""

    def snapshot_offsets(self):
        return None

    def restore_offsets(self, state):
        pass


def test_non_replayable_source_with_checkpointing(tmp_path):
    """auto falls back to inline prep (job completes, results exact);
    an explicit prefetch=on is a config error, not a silent downgrade
    to more-than-at-most-once loss."""
    total = 1024
    env = build_env(1, tmp_path / "chk", interval=4)
    got = run_job(env, total, source=_NonReplayableSource(gen, total))
    assert got == expected(total)
    env = build_env(1, tmp_path / "chk2", interval=4,
                    **{"pipeline.prefetch": "on"})
    with pytest.raises(ValueError, match="replayable"):
        run_job(env, total, source=_NonReplayableSource(gen, total))


# ------------------------------------------------------------- units

def test_prefix_mask_template():
    tmpl = ingest_mod.make_prefix_mask_template(8)
    assert tmpl.dtype == bool and len(tmpl) == 16
    assert not tmpl.flags.writeable
    for n in (0, 1, 5, 8):
        m = ingest_mod.prefix_mask(tmpl, n)
        assert len(m) == 8
        assert m[:n].all() and not m[n:].any()
    # views share the single allocation
    assert ingest_mod.prefix_mask(tmpl, 3).base is tmpl


def test_pipeline_epoch_reset_discards_stale_batches():
    """pause/resume bumps the epoch: batches prepped before the pause
    are discarded by the consumer, and the applied cut re-arms to the
    restored offsets."""
    polled = []

    def prep():
        polled.append(len(polled))
        return ingest_mod.PreppedBatch(
            end=False, n=1, now_ms=0, t_src=0.0, offsets=len(polled),
        )

    p = ingest_mod.IngestPipeline(prep, prefetch=True, initial_offsets=0,
                                  depth=2)
    try:
        first = p.next()
        assert first.offsets == 1
        p.mark_applied(first)
        assert p.applied_offsets() == 1
        # let the producer run ahead, then pause + resume (a restore)
        deadline = time.monotonic() + 5
        while len(polled) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        p.pause()
        stale_epoch = first.epoch
        p.resume(applied_offsets=1)
        assert p.applied_offsets() == 1
        nxt = p.next()
        assert nxt.epoch == stale_epoch + 1   # nothing stale leaked out
    finally:
        p.close()


def test_hard_death_after_resume_still_surfaces():
    """A producer that SURVIVES a pause/resume (restore) serves the new
    epoch — a later hard death (BaseException out of prep, past the
    error-delivery except) must surface as IngestThreadDied, not be
    mistaken for a restore respawn and silently restarted past records
    the dead thread consumed but never delivered."""
    from flink_tpu.testing.faults import ThreadKilled

    state = {"kill": False, "i": 0}

    def prep():
        if state["kill"]:
            state["kill"] = False       # one-shot: a silent respawn
            raise ThreadKilled("boom")  # would poll through unnoticed
        state["i"] += 1
        return ingest_mod.PreppedBatch(
            end=False, n=1, now_ms=0, t_src=0.0, offsets=state["i"],
        )

    p = ingest_mod.IngestPipeline(prep, prefetch=True, initial_offsets=0,
                                  depth=2)
    try:
        p.next()
        p.pause()                  # thread survives, parked
        assert p._thread.is_alive()
        state["kill"] = True       # armed while parked: the FIRST
        p.resume(applied_offsets=0)   # post-resume poll dies
        deadline = time.monotonic() + 5
        while p._thread.is_alive() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not p._thread.is_alive()
        with pytest.raises(ingest_mod.IngestThreadDied):
            for _ in range(20):    # a silent respawn would keep
                p.next()           # returning batches — bounded
    finally:
        p.close()


def test_pipeline_error_then_resume_continues():
    """After delivering an error the producer parks (it does not exit);
    resume() restarts production on the same thread — the restart path
    a restore takes."""
    state = {"fail": True, "i": 0}

    def prep():
        state["i"] += 1
        if state["fail"]:
            state["fail"] = False
            raise RuntimeError("boom")
        return ingest_mod.PreppedBatch(
            end=False, n=1, now_ms=0, t_src=0.0, offsets=state["i"],
        )

    p = ingest_mod.IngestPipeline(prep, prefetch=True, initial_offsets=0,
                                  depth=2)
    try:
        with pytest.raises(RuntimeError, match="boom"):
            p.next()
        p.pause()
        p.resume(applied_offsets=0)
        pb = p.next()
        assert pb.n == 1 and pb.epoch == 1
    finally:
        p.close()
