"""Heap keyed state backend: contracts of the reference state API
(State.java hierarchy, StateTable key-group layout, snapshot/rescale
semantics of StateAssignmentOperation)."""

import numpy as np
import pytest

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    key_group_range_for_operator,
)
from flink_tpu.state.backend import (
    HeapKeyedStateBackend,
    key_group_of,
    rescale_key_group_blobs,
)
from flink_tpu.state.descriptors import (
    AggregatingStateDescriptor,
    FoldingStateDescriptor,
    ListStateDescriptor,
    MapStateDescriptor,
    ReducingStateDescriptor,
    ValueStateDescriptor,
)


def test_value_state_roundtrip():
    b = HeapKeyedStateBackend()
    desc = ValueStateDescriptor("v", default=-1.0)
    b.set_current_key("a")
    st = b.get_partitioned_state(desc)
    assert st.value() == -1.0
    st.update(3.5)
    assert st.value() == 3.5
    b.set_current_key("b")
    assert st.value() == -1.0  # per-key isolation
    b.set_current_key("a")
    st.clear()
    assert st.value() == -1.0


def test_list_reducing_agg_map_states():
    b = HeapKeyedStateBackend()
    b.set_current_key(7)

    ls = b.get_partitioned_state(ListStateDescriptor("l"))
    ls.add(1)
    ls.add(2)
    assert ls.get() == [1, 2]
    ls.update([9])
    assert ls.get() == [9]

    rs = b.get_partitioned_state(ReducingStateDescriptor("r", kind="max"))
    rs.add(3)
    rs.add(1)
    rs.add(5)
    assert rs.get() == 5

    ag = b.get_partitioned_state(AggregatingStateDescriptor(
        "a", add=lambda acc, v: (acc[0] + v, acc[1] + 1),
        merge=lambda x, y: (x[0] + y[0], x[1] + y[1]),
        get_result=lambda acc: acc[0] / acc[1],
        acc_init=(0.0, 0),
    ))
    ag.add(2.0)
    ag.add(4.0)
    assert ag.get() == 3.0  # mean

    ms = b.get_partitioned_state(MapStateDescriptor("m"))
    ms.put("x", 1)
    ms.put("y", 2)
    assert ms.get("x") == 1
    assert ms.contains("y")
    assert sorted(ms.keys()) == ["x", "y"]
    ms.remove("x")
    assert not ms.contains("x")


def test_folding_state_parity():
    b = HeapKeyedStateBackend()
    b.set_current_key("k")
    fs = b.get_partitioned_state(FoldingStateDescriptor(
        "f", fold_fn=lambda acc, v: acc + str(v), acc_init=""
    ))
    fs.add(1)
    fs.add(2)
    assert fs.get() == "12"


def test_namespaces_isolated():
    b = HeapKeyedStateBackend()
    b.set_current_key("k")
    desc = ValueStateDescriptor("v")
    s1 = b.get_partitioned_state(desc, namespace=("w", 100))
    s1.update(1.0)
    s2 = b.get_partitioned_state(desc, namespace=("w", 200))
    assert s2.value() is None
    s2.update(2.0)
    s1b = b.get_partitioned_state(desc, namespace=("w", 100))
    assert s1b.value() == 1.0


def test_snapshot_restore_roundtrip():
    b = HeapKeyedStateBackend(max_parallelism=32)
    desc = ValueStateDescriptor("v")
    for k in range(100):
        b.set_current_key(k)
        b.get_partitioned_state(desc).update(k * 10)
    blobs = b.snapshot()
    assert all(0 <= kg < 32 for kg in blobs)

    b2 = HeapKeyedStateBackend(max_parallelism=32)
    b2.restore(blobs)
    for k in range(100):
        b2.set_current_key(k)
        assert b2.get_partitioned_state(desc).value() == k * 10


def test_rescale_2_to_3_subtasks():
    """Key-grouped snapshots re-slice to a new parallelism without touching
    keys (RescalingITCase semantics)."""
    maxp = 12
    backs = []
    for idx in range(2):
        r = key_group_range_for_operator(maxp, 2, idx)
        backs.append(HeapKeyedStateBackend(r, maxp))
    desc = ValueStateDescriptor("v")
    for k in range(200):
        kg = key_group_of(k, maxp)
        for b in backs:
            if kg in b.kgr:
                b.set_current_key(k)
                b.get_partitioned_state(desc).update(k + 0.5)

    blobs = [b.snapshot() for b in backs]
    new_blobs = rescale_key_group_blobs(blobs, 3, maxp)
    new_backs = []
    for idx in range(3):
        r = key_group_range_for_operator(maxp, 3, idx)
        nb = HeapKeyedStateBackend(r, maxp)
        nb.restore(new_blobs[idx])
        new_backs.append(nb)

    seen = 0
    for k in range(200):
        kg = key_group_of(k, maxp)
        for nb in new_backs:
            if kg in nb.kgr:
                nb.set_current_key(k)
                assert nb.get_partitioned_state(desc).value() == k + 0.5
                seen += 1
    assert seen == 200


def test_lookup_queryable_read_path():
    b = HeapKeyedStateBackend()
    desc = ValueStateDescriptor("total")
    b.set_current_key("alice")
    b.get_partitioned_state(desc).update(42)
    b.set_current_key("bob")  # move the key context away
    assert b.lookup("total", "alice") == 42
    assert b.lookup("total", "nobody") is None
    assert b.lookup("missing-state", "alice") is None
