"""Compiled-graph auditor: trace-tier evidence tests (ISSUE 11).

tests/test_lint.py exercises every rule's fixture pair; this module
pins the EVIDENCE layer underneath the five trace rules:

* the canonical kernel-family grids in runtime/step.py and
  ops/window_kernels.py cover every exported ``build_*`` step factory
  and every donated family really aliases in the lowered module (and,
  for the ``deep`` representatives, in the compiled executable);
* the jaxpr op ledger reflects the structural contracts the rules
  guard (one shared sort on the precombine path, the megastep's scan);
* the ledger round-trip: a hand-edited ledger fails lint with exit
  code 1, ``--update-ledger`` rewrites it byte-identically to the
  checked-in golden, and the rerun is clean;
* both tiers together fit the tier-1 wall-time budget (<30s).
"""

import ast
import json
import os
import shutil
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tools.lint import RepoTree, all_rules, rule_by_name, run_rules  # noqa: E402
from tools.lint.kernel_audit import (  # noqa: E402
    STEP_HOME, get_audit, load_ledger,
)
from tools.lint.rules import op_budget as op_budget_mod  # noqa: E402

STEP_PATH = os.path.join(ROOT, "flink_tpu", "runtime", "step.py")


def _audit():
    a = get_audit(RepoTree(ROOT))
    assert a is not None, "canonical audit must exist for the repo tree"
    return a


# -- grid completeness --------------------------------------------------

def _exported_builders():
    """Top-level ``build_*`` functions of runtime/step.py."""
    with open(STEP_PATH) as f:
        mod = ast.parse(f.read())
    return {
        n.name for n in mod.body
        if isinstance(n, ast.FunctionDef) and n.name.startswith("build_")
    } - {"build_family"}   # the grid's own instantiation helper


def test_step_grid_covers_every_builder():
    """The promise kernel_family_grid() makes in its docstring: every
    exported build_* factory appears in at least one audited family, so
    a NEW builder without an audit entry fails loudly here."""
    from flink_tpu.runtime.step import kernel_family_grid

    grid = kernel_family_grid()
    covered = {fam.builder.__name__ for fam in grid}
    missing = _exported_builders() - covered
    assert not missing, (
        f"step builders missing from kernel_family_grid(): "
        f"{sorted(missing)} — add a KernelFamily for each"
    )
    names = [fam.name for fam in grid]
    assert len(names) == len(set(names)), "family names must be unique"
    assert sum(1 for fam in grid if fam.deep) >= 3, (
        "at least 3 deep (compile-checked) representatives"
    )


def test_wk_grid_names_are_unique_and_traced():
    from flink_tpu.ops.window_kernels import kernel_family_grid

    grid = kernel_family_grid()
    names = [name for name, _fn, _args in grid]
    assert len(names) == len(set(names)) >= 10
    audit = _audit()
    for name in names:
        assert name in audit.traces, f"wk family {name!r} not audited"


# -- donation evidence --------------------------------------------------

def test_every_donated_family_aliases():
    """The tentpole acceptance: for every donated canonical family the
    lowered module aliases every (non-zero-size) donated leaf, and the
    deep representatives keep those aliases through the executable."""
    audit = _audit()
    deep_checked = 0
    for name, tr in sorted(audit.traces.items()):
        if not tr.donated:
            continue
        rep = audit.donation_report(name)
        assert rep["missing_lowered"] == [], (
            f"{name}: donated leaves not aliased in the lowered module: "
            f"{rep['missing_lowered']}"
        )
        assert rep["dropped_by_executable"] == [], (
            f"{name}: executable dropped aliases: "
            f"{rep['dropped_by_executable']}"
        )
        if tr.deep:
            assert rep["executable_checked"], (
                f"{name} is deep but the executable was not checked"
            )
            deep_checked += 1
    assert deep_checked >= 3


def test_deep_state_family_donates_the_whole_state_tree():
    """A full window-state donation is many leaves (table keys, values,
    occupancy, watermark planes, ...) — not one array.  Pin a floor so
    a refactor that silently narrows the donation surface is caught."""
    audit = _audit()
    rep = audit.donation_report("step.update.mask.hash")
    assert len(rep["leaves"]) >= 15, rep["leaves"]


# -- op evidence --------------------------------------------------------

def test_precombine_families_pay_one_sort():
    """The PR 7 seam contract, read off the real jaxprs (the op-budget
    rule enforces it too; this is the direct evidence-level assert)."""
    audit = _audit()
    pre = [n for n in audit.traces if ".precombine" in n]
    assert pre, "grid must include a precombine family"
    for name in pre:
        assert audit.traces[name].op_counts["sort"] == 1, (
            f"{name}: {audit.traces[name].op_counts}"
        )


def test_megastep_families_keep_the_scan():
    audit = _audit()
    mega = [n for n in audit.traces if ".megastep" in n]
    assert mega
    for name in mega:
        assert audit.traces[name].op_counts["while_scan"] >= 1, (
            f"{name}: the megastep must stay a scan, not an unrolled "
            f"loop ({audit.traces[name].op_counts})"
        )


def test_drain_stats_compiles_out_byte_identical_to_pre_pr_ledger():
    """ISSUE 14 acceptance: with ``observability.drain-stats`` off the
    drain kernels are the SAME programs as before the flight recorder
    existed.  The telemetry-OFF drain families' op budgets must stay
    byte-identical to the frozen pre-PR golden, and every builder must
    also appear as a ledgered telemetry-ON ``.dstats`` variant."""
    golden_rel = "tools/lint/ledgers/op_budget_pre_drain_stats.json"
    with open(os.path.join(ROOT, golden_rel)) as f:
        golden = json.load(f)["families"]
    with open(os.path.join(ROOT, LEDGERS[0])) as f:
        live = json.load(f)["families"]
    assert len(golden) == 8
    for name, budget in sorted(golden.items()):
        assert "dstats" not in name, name
        assert live.get(name) == budget, (
            f"{name}: telemetry-OFF drain family drifted from the "
            f"pre-drain-stats golden ({live.get(name)} != {budget}) — "
            f"the payload no longer compiles out"
        )
    on = {n for n in live if n.endswith(".dstats")}
    assert on == {
        "step.resident_drain.mask.hash.d4.dstats",
        "step.resident_drain.exchange.hash.d4.dstats",
        "step.sharded_drain.hash.d4.dstats",
        "step.chained_drain.mask.hash.d4.s2.dstats",
        "step.chained_drain.sharded.hash.d4.s2.dstats",
        # round 20: while / DCN-resident drains carry the recorder too
        "step.while_drain.mask.hash.d4.dstats",
        "step.dcn_resident.hash.d4.dstats",
    }, on
    # the recorder is element-ops-only: an ON variant may not add a
    # single sort/scatter/gather pass over its OFF twin
    for name in sorted(on):
        off = live[name[: -len(".dstats")]]
        assert live[name] == off, (name, live[name], off)


def test_stage_stats_compile_out_byte_identical_to_pre_pr_ledger():
    """ISSUE 17 acceptance, the chained half of the frozen-golden
    discipline: with ``observability.drain-stats`` off the CHAINED
    drain kernels are the SAME programs as before the stage-aware
    flight recorder existed — their op budgets must stay byte-identical
    to the golden frozen at the PR boundary — and each chained
    telemetry-ON twin must cost zero extra passes per op group (the
    per-stage record is jnp.stack/sum/where element ops over planes
    the edge pack already materialized)."""
    golden_rel = "tools/lint/ledgers/op_budget_pre_stage_stats.json"
    with open(os.path.join(ROOT, golden_rel)) as f:
        golden = json.load(f)["families"]
    with open(os.path.join(ROOT, LEDGERS[0])) as f:
        live = json.load(f)["families"]
    assert len(golden) == 3
    for name, budget in sorted(golden.items()):
        assert "dstats" not in name, name
        assert name.startswith("step.chained_drain."), name
        assert live.get(name) == budget, (
            f"{name}: telemetry-OFF chained family drifted from the "
            f"pre-stage-stats golden ({live.get(name)} != {budget}) — "
            f"the stage payload no longer compiles out"
        )
    for name in ("step.chained_drain.mask.hash.d4.s2",
                 "step.chained_drain.sharded.hash.d4.s2"):
        assert live[name + ".dstats"] == live[name], (
            name, live[name + ".dstats"], live[name]
        )


def test_no_family_crosses_the_host_or_widens():
    audit = _audit()
    for name, tr in audit.traces.items():
        assert tr.host_crossings == [], (name, tr.host_crossings)
        assert tr.wide_dtypes == [], (name, tr.wide_dtypes)


# -- ledger round-trip --------------------------------------------------

LEDGERS = ("tools/lint/ledgers/op_budget.json",
           "tools/lint/ledgers/signatures.json")


def _tamper_root(tmp_path):
    """A disk tree that get_audit() recognises as canonical (step.py
    present) but whose op-budget ledger was hand-edited: the sort
    budget of the precombine family bumped to 2."""
    dst = tmp_path / "flink_tpu" / "runtime"
    dst.mkdir(parents=True)
    shutil.copy(STEP_PATH, dst / "step.py")
    for rel in LEDGERS:
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(ROOT, rel), path)
    led = tmp_path / "tools" / "lint" / "ledgers" / "op_budget.json"
    data = json.loads(led.read_text())
    fam = next(n for n in data["families"] if ".precombine" in n)
    data["families"][fam]["sort"] = 2
    led.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return fam


def test_ledger_edit_without_update_flag_is_a_finding(tmp_path):
    fam = _tamper_root(tmp_path)
    findings = run_rules(RepoTree(str(tmp_path)),
                         [rule_by_name("op-budget")])
    assert any(fam in f.message and "drifted" in f.message
               for f in findings), [str(f) for f in findings]


def test_update_ledger_restores_the_golden_byte_for_byte(tmp_path):
    _tamper_root(tmp_path)
    rule = rule_by_name("op-budget")
    rule.update_ledger = True
    assert run_rules(RepoTree(str(tmp_path)), [rule]) == []
    written = (tmp_path / "tools" / "lint" / "ledgers"
               / "op_budget.json").read_text()
    with open(os.path.join(ROOT, LEDGERS[0])) as f:
        golden = f.read()
    assert written == golden, (
        "--update-ledger must regenerate exactly the checked-in ledger "
        "(deterministic serialisation) — if this fails the committed "
        "ledger is stale"
    )
    # and the rerun against the rewritten ledger is clean
    clean = run_rules(RepoTree(str(tmp_path)),
                      [rule_by_name("op-budget")])
    assert clean == [], [str(f) for f in clean]


def test_checked_in_ledgers_parse_and_cover_every_family():
    tree = RepoTree(ROOT)
    audit = _audit()
    for rel in LEDGERS:
        data = load_ledger(tree, rel)
        assert data is not None, f"{rel} missing"
        assert set(data["families"]) == set(audit.traces), rel


def test_precombine_hard_invariant_survives_update_ledger(tmp_path):
    """The one budget that is NOT ledgerable: >1 sort on a precombine
    family stays a finding even while --update-ledger rewrites the
    rest.  Exercised through a fixture tree so the canonical grid's
    real counts stay untouched."""
    src = (
        "# lint-kernel-fixture\n"
        "def lint_kernel_families():\n"
        "    import jax, jax.numpy as jnp\n"
        "    def k(x):\n"
        "        return jnp.sort(jnp.sort(x))\n"
        "    return [{'name': 'fixture.bad.precombine', 'fn': k,\n"
        "             'args': (jax.ShapeDtypeStruct((8,), jnp.float32),)}]\n"
    )
    (tmp_path / "flink_tpu").mkdir()
    (tmp_path / "flink_tpu" / "fixt.py").write_text(src)
    tree = RepoTree(files={"flink_tpu/fixt.py": src})
    findings = run_rules(tree, [rule_by_name("op-budget")])
    assert any("cannot be ledgered away" in f.message for f in findings)


# -- CLI ----------------------------------------------------------------

def _cli(*args, cwd=ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.lint", *args],
        capture_output=True, text=True, cwd=cwd,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_cli_tampered_ledger_exits_one_then_update_exits_zero(tmp_path):
    """ISSUE 11 acceptance, end to end through the CLI: a ledger edit
    without --update-ledger exits 1 (true subprocess — the real exit
    code); the --update-ledger flag wiring and exit 0 are driven
    through main() in-process, which shares this process's already-
    built kernel audit instead of re-tracing the grid in a second
    subprocess."""
    from tools.lint.__main__ import main

    _tamper_root(tmp_path)
    rc = _cli("--root", str(tmp_path), "--rule", "op-budget")
    assert rc.returncode == 1, rc.stdout + rc.stderr
    assert "drifted" in rc.stdout
    assert main(["--root", str(tmp_path), "--rule", "op-budget",
                 "--update-ledger"]) == 0
    assert main(["--root", str(tmp_path), "--rule", "op-budget"]) == 0


def test_cli_tier_filter_and_mismatch():
    rc = _cli("--tier", "ast", "--json")
    assert rc.returncode == 0, rc.stdout + rc.stderr
    payload = json.loads(rc.stdout)
    assert payload["schema"] == 2 and payload["tier"] == "ast"
    assert set(payload["rules"]) == {
        r.name for r in all_rules(tier="ast")
    }
    # asking for an ast rule in the trace tier is a usage error (2)
    rc = _cli("--rule", "donation", "--tier", "trace")
    assert rc.returncode == 2
    assert "internal error" in rc.stderr


# -- wall-time budget ---------------------------------------------------

def test_combined_tier_budget_under_30s():
    """ISSUE 11 budget: both tiers together — AST parse+rules, the
    canonical grid build (traces), the lazy donation evidence (lowers
    + deep compiles), and the trace rules — fit in 30s.  Evidence
    costs are read off the audit's own meters so the assert holds
    regardless of which test warmed the caches first."""
    audit = _audit()
    for name, tr in audit.traces.items():
        if tr.donated:
            audit.donation_report(name)   # force all lazy evidence
    t0 = time.perf_counter()
    findings = run_rules(RepoTree(ROOT), all_rules())
    rules_dt = time.perf_counter() - t0
    assert findings == [], "\n".join(str(f) for f in findings)
    total = audit.build_seconds + audit.donation_seconds + rules_dt
    assert total < 30.0, (
        f"two-tier lint costs {total:.1f}s "
        f"(build {audit.build_seconds:.1f}s + donation "
        f"{audit.donation_seconds:.1f}s + rules {rules_dt:.1f}s; "
        f"budget 30s)"
    )
