"""Interactive shell: local execution through the console, session
transcript recording, and remote submit() shipping REPL-defined
builders to a live ProcessCluster.

Ref flink-scala-shell/.../FlinkShell.scala (pre-bound benv/senv),
FlinkILoop.scala (session class shipping on execute).
"""

import glob
import os
import time

import pytest

from flink_tpu.shell import FlinkShell


def test_local_pipeline_through_console():
    sh = FlinkShell()
    sh.run_source(
        "import numpy as np\n"
        "from flink_tpu.runtime.sources import GeneratorSource\n"
        "from flink_tpu.runtime.sinks import CollectSink\n"
        "def gen(offset, n):\n"
        "    idx = np.arange(offset, offset + n, dtype=np.int64)\n"
        "    return ({'key': idx % 16,\n"
        "             'value': np.ones(n, np.float32)}, idx // 40)\n"
        "sink = CollectSink()\n"
        "(env.add_source(GeneratorSource(gen, total=20000))\n"
        "    .key_by(lambda c: c['key'])\n"
        "    .time_window(500).sum(lambda c: c['value'])\n"
        "    .add_sink(sink))\n"
        "job = env.execute('shell-local')\n"
        "total = sum(float(r.value) for r in sink.results)\n"
    )
    assert sh.namespace["total"] == 20000.0


def test_batch_env_bound():
    sh = FlinkShell()
    sh.run_source(
        "ds = benv.from_collection([1, 2, 3, 4])\n"
        "squares = sorted(ds.map(lambda x: x * x).collect())\n"
    )
    assert sh.namespace["squares"] == [1, 4, 9, 16]


def test_session_transcript_records_compiled_blocks():
    sh = FlinkShell()
    sh.run_source("x = 1\n")
    sh.run_source("def f():\n    return x + 1\n")
    # --execute scripts are programs: a syntax error raises (exit != 0)
    with pytest.raises(SyntaxError):
        sh.run_source("this is a syntax error(\n")
    # interactive typing reports the error and records nothing
    sh.console.push("also a syntax error(")
    src = "\n".join(sh.console.session_lines)
    assert "x = 1" in src and "def f():" in src
    assert "syntax error" not in src


def test_compound_statements_run_whole():
    """try/except, if/else, and decorated defs must not be split at
    their dedented clauses (--execute scripts are full programs)."""
    sh = FlinkShell()
    sh.run_source(
        "try:\n"
        "    x = int('nope')\n"
        "except ValueError:\n"
        "    x = 7\n"
        "if x == 7:\n"
        "    y = 'taken'\n"
        "else:\n"
        "    y = 'not taken'\n"
        "def deco(f):\n"
        "    return f\n"
        "@deco\n"
        "def g():\n"
        "    return y\n"
        "z = g()\n"
    )
    assert sh.namespace["x"] == 7
    assert sh.namespace["z"] == "taken"


def test_shipping_filter_drops_console_actions():
    """Top-level statements touching env/benv/submit stay local; defs,
    imports, and console-independent assignments ship — a shipped file
    must exec cleanly on a worker where the console names don't exist."""
    sh = FlinkShell(controller="127.0.0.1:1")
    sh.run_source(
        "import math\n"
        "N = 41\n"
        "rolled = benv.from_collection([1]).collect()\n"   # console action
        "def build_job():\n"
        "    return N + 1\n"
    )
    blocks = [b for b in sh.console.session_lines if sh._shippable(b)]
    src = "\n".join(blocks)
    assert "import math" in src and "N = 41" in src
    assert "def build_job" in src
    assert "benv" not in src
    # the shipped module execs standalone (the worker's exec_module)
    ns = {}
    exec(src, ns)
    assert ns["build_job"]() == 42


def test_submit_requires_cluster_and_named_fn():
    sh = FlinkShell()
    with pytest.raises(RuntimeError, match="--controller"):
        sh.submit(lambda: None)
    sh2 = FlinkShell(controller="127.0.0.1:1")
    with pytest.raises(ValueError, match="named function"):
        sh2.submit(lambda: None)


def test_remote_submit_ships_repl_defined_builder(tmp_path):
    """A builder DEFINED IN THE SHELL runs on a worker process: the
    session source travels as the job file (FlinkILoop shipping)."""
    from flink_tpu.runtime.process_cluster import ProcessCluster

    cluster = ProcessCluster(heartbeat_timeout_s=10.0)
    cluster.start()
    try:
        sh = FlinkShell(
            controller=f"127.0.0.1:{cluster._port}",
            job_dir=str(tmp_path / "jobs"),
        )
        os.makedirs(sh.job_dir, exist_ok=True)
        out = str(tmp_path / "out")
        sh.run_source(
            "import os\n"
            "import numpy as np\n"
            "def build_job():\n"
            "    from flink_tpu import StreamExecutionEnvironment\n"
            "    from flink_tpu.core.time import TimeCharacteristic\n"
            "    from flink_tpu.connectors.files import BucketingFileSink\n"
            "    from flink_tpu.runtime.sources import GeneratorSource\n"
            "    e = StreamExecutionEnvironment.get_execution_environment()\n"
            "    e.set_parallelism(1)\n"
            "    e.set_max_parallelism(8)\n"
            "    e.set_stream_time_characteristic("
            "TimeCharacteristic.EventTime)\n"
            "    def gen(offset, n):\n"
            "        idx = np.arange(offset, offset + n, dtype=np.int64)\n"
            "        return ({'key': idx % 8,\n"
            "                 'value': np.ones(n, np.float32)},\n"
            "                (idx * 8000) // 20000)\n"
            "    sink = BucketingFileSink(\n"
            f"        {out!r},\n"
            "        formatter=lambda r:"
            " f'{r.key},{r.window_end_ms},{r.value:.0f}')\n"
            "    (e.add_source(GeneratorSource(gen, total=20000))\n"
            "       .key_by(lambda c: c['key'])\n"
            "       .time_window(1000).sum(lambda c: c['value'])\n"
            "       .add_sink(sink))\n"
            "    return e\n"
        )
        wid = sh.submit(sh.namespace["build_job"], job_name="shell-remote")
        assert sh.wait(wid, timeout_s=180) == "FINISHED"
        total = 0.0
        for path in glob.glob(os.path.join(out, "**", "part-0"),
                              recursive=True):
            with open(path) as f:
                for line in f:
                    total += float(line.strip().split(",")[2])
        assert total == 20000.0
    finally:
        cluster.shutdown()