"""Adaptive step tiering: the lookup-only fast update path.

The executor runs the upsert step while new keys arrive and flips to the
insert-free lookup step (wk.update insert=False) once the lagged activity
signal stays quiet; misses in fast mode take the overflow ring -> spill
tier. These tests pin (a) kernel-level equivalence of the two paths,
(b) miss accounting, and (c) end-to-end correctness through the executor
with the tier actually engaging.
"""

import jax.numpy as jnp
import numpy as np

from flink_tpu.ops import hashtable
from flink_tpu.ops import window_kernels as wk


def _split(keys):
    h = np.asarray(keys, np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    return ((h >> np.uint64(32)).astype(np.uint32),
            (h & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def _mk(keys, ts, vals):
    hi, lo = _split(keys)
    return (jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(np.asarray(ts, np.int32)),
            jnp.asarray(np.asarray(vals, np.float32)),
            jnp.ones(len(keys), bool))


def test_fast_path_matches_insert_path():
    win = wk.WindowSpec(size_ticks=10, slide_ticks=10, ring=8,
                        fires_per_step=2, overflow=16)
    red = wk.ReduceSpec(kind="sum")
    keys = [1, 2, 3, 4, 1, 2]
    ts = [0, 1, 2, 3, 4, 5]
    v1 = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]

    st_a = wk.init_state(64, 8, win, red)
    st_a, act0, _ = wk.update(st_a, win, red, *_mk(keys, ts, v1))
    assert int(act0) == 6          # every lane's key was new pre-batch
    st_b = wk.init_state(64, 8, win, red)
    st_b, _, _ = wk.update(st_b, win, red, *_mk(keys, ts, v1))

    # second batch, all-resident keys: fast path == insert path, activity 0
    v2 = [10.0, 20.0, 30.0, 40.0, 50.0, 60.0]
    st_a, act_a, _ = wk.update(st_a, win, red, *_mk(keys, ts, v2), insert=True)
    st_b, act_b, _ = wk.update(st_b, win, red, *_mk(keys, ts, v2), insert=False)
    assert int(act_a) == 0 and int(act_b) == 0
    np.testing.assert_array_equal(np.asarray(st_a.acc), np.asarray(st_b.acc))
    np.testing.assert_array_equal(
        np.asarray(st_a.table.keys), np.asarray(st_b.table.keys)
    )
    assert int(st_b.ovf_n) == 0

    st_a, fr_a = wk.advance_and_fire(st_a, win, red, jnp.int32(20))
    st_b, fr_b = wk.advance_and_fire(st_b, win, red, jnp.int32(20))
    np.testing.assert_allclose(
        np.sort(np.asarray(fr_a.values)[np.asarray(fr_a.mask)]),
        np.sort(np.asarray(fr_b.values)[np.asarray(fr_b.mask)]),
    )


def test_fast_path_misses_take_overflow_ring():
    win = wk.WindowSpec(size_ticks=10, slide_ticks=10, ring=8,
                        fires_per_step=2, overflow=16)
    red = wk.ReduceSpec(kind="sum")
    st = wk.init_state(64, 8, win, red)
    st, _, _ = wk.update(st, win, red, *_mk([1, 2], [0, 1], [1.0, 2.0]))

    # keys 3, 4 are absent: fast path must not insert them
    st, act, _ = wk.update(
        st, win, red, *_mk([1, 3, 4, 3], [2, 3, 4, 5], [10.0, 5.0, 7.0, 6.0]),
        insert=False,
    )
    assert int(act) == 3           # three missing-key lanes
    assert int(st.ovf_n) == 3      # all three in the ring, none dropped
    assert int(st.dropped_capacity) == 0  # ring absorbed them: no loss
    hi3, lo3 = _split([3])
    _, found = hashtable.lookup(st.table, jnp.asarray(hi3), jnp.asarray(lo3))
    assert not bool(found[0])      # table untouched
    # ring contents carry the missed contributions
    ovf_hi = np.asarray(st.ovf_hi)[:3]
    ovf_val = np.asarray(st.ovf_val)[:3]
    hi34, _ = _split([3, 4])
    assert set(ovf_hi.tolist()) == set(hi34.tolist())
    assert sorted(ovf_val.tolist()) == [5.0, 6.0, 7.0]


def test_executor_engages_fast_tier_and_stays_correct():
    """Stream enough repeated-key batches that the lagged tier switch
    engages, then verify sums are exact (fast steps included) and that
    fast steps actually ran."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import GeneratorSource

    B = 64
    n_keys = 8
    total = B * 40                 # 40 steps >> OVF_LAG + quiet checks

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return (
            {"key": idx % n_keys, "value": np.ones(n, np.float32)},
            idx // 64,             # event-time ms: ~40ms span per window
        )

    env = StreamExecutionEnvironment(Configuration({
        "keys.reverse-map": True,
        # force the hash layout: bounded int keys would auto-select the
        # direct-index backend, which has no insert phase to tier
        "state.backend.layout": "hash",
    }))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(64)
    env.batch_size = B

    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(1000)         # one window holds everything
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("tier-test")
    got = {}
    for r in sink.results:
        got[r.key] = got.get(r.key, 0.0) + r.value
    assert got == {k: total / n_keys for k in range(n_keys)}
    assert job.metrics.steps_fast > 0, (
        "fast tier never engaged in a steady-state stream"
    )
    assert job.metrics.dropped_capacity == 0


def test_counting_sink_device_reduce_exact():
    """CountingSink consumes drains via on-chip reduction (Sink.
    device_reduce): totals must match the host columnar path exactly."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    B, n_keys, total = 128, 32, 128 * 24

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return (
            {"key": idx % n_keys,
             "value": (idx % 5).astype(np.float32)},
            idx // 16,             # several window boundaries mid-stream
        )

    class HostCountingSink(CountingSink):
        device_reduce = False     # force the host columnar emit path

    def run(sink):
        env = StreamExecutionEnvironment(
            Configuration({"keys.reverse-map": False}))
        env.set_parallelism(1)
        env.set_max_parallelism(8)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(256)
        env.batch_size = B
        (
            env.add_source(GeneratorSource(gen, total=total))
            .key_by(lambda c: c["key"])
            .time_window(50)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        env.execute("device-reduce-sink")
        return sink

    dev = run(CountingSink())
    host = run(HostCountingSink())
    exp_sum = float(sum(i % 5 for i in range(total)))
    assert dev.value_sum == host.value_sum == exp_sum
    # every (key, window) pair fires exactly once: windows span 50ms of
    # event time = 800 events; all 32 keys appear in each window
    n_windows = (total // 16 + 49) // 50
    assert dev.count == host.count == n_windows * n_keys
