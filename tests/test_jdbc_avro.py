"""Batch-connector breadth (round 4): DB-API (flink-jdbc analog) against
real sqlite3, and the hand-rolled Avro container codec round-trips
(flink-avro analog; spec-implemented — no Avro library in this runtime).
"""

import os
import sqlite3
import zlib

import numpy as np
import pytest

from flink_tpu.connectors.avro import (
    AvroInputFormat,
    AvroOutputFormat,
    read_container,
    write_container,
)
from flink_tpu.connectors.jdbc import (
    DbApiInputFormat,
    DbApiOutputFormat,
    DbApiSink,
)


def _db(tmp_path, n=100):
    path = str(tmp_path / "src.db")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE events (id INTEGER PRIMARY KEY, k INTEGER, "
                 "v REAL)")
    conn.executemany(
        "INSERT INTO events VALUES (?, ?, ?)",
        [(i, i % 7, float(i)) for i in range(n)],
    )
    conn.commit()
    conn.close()
    return path


def test_input_format_reads_splits(tmp_path):
    path = _db(tmp_path)
    src = DbApiInputFormat(
        lambda: sqlite3.connect(path),
        "SELECT id, k, v FROM events WHERE k = ? ORDER BY id",
        parameters=[(i,) for i in range(7)],
        fetch_size=8,
    )
    rows = src.read_all()
    assert len(rows) == 100
    assert sorted(r[0] for r in rows) == list(range(100))


def test_input_format_offset_replay(tmp_path):
    """Snapshot mid-read, resume a fresh instance from the offsets:
    exactly-once union (the FlinkKafkaConsumer offset contract applied
    to query splits)."""
    path = _db(tmp_path, n=60)

    def mk():
        return DbApiInputFormat(
            lambda: sqlite3.connect(path),
            "SELECT id FROM events WHERE k = ? ORDER BY id",
            parameters=[(0,), (1,)], fetch_size=4,
        )

    a = mk()
    a.open()
    got, _ = a.poll(8)
    seen = [r[0] for r in got]
    offs = a.snapshot_offsets()
    a.close()

    b = mk()
    b.restore_offsets(offs)
    b.open()
    end = False
    while not end:
        rows, end = b.poll(16)
        seen.extend(r[0] for r in rows)
    b.close()
    want = sorted(i for i in range(60) if i % 7 in (0, 1))
    assert sorted(seen) == want
    assert len(seen) == len(set(seen)), "duplicate replay"


def test_sink_upsert_is_idempotent(tmp_path):
    path = str(tmp_path / "out.db")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE sums (k INTEGER PRIMARY KEY, total REAL)")
    conn.commit()
    conn.close()
    sink = DbApiSink(
        lambda: sqlite3.connect(path),
        "INSERT OR REPLACE INTO sums VALUES (?, ?)",
    )
    sink.open()
    sink.invoke_batch([(1, 10.0), (2, 20.0)])
    # replay after a simulated restore: same rows again, plus a correction
    sink.invoke_batch([(1, 10.0), (2, 25.0)])
    sink.close()
    conn = sqlite3.connect(path)
    rows = dict(conn.execute("SELECT k, total FROM sums"))
    conn.close()
    assert rows == {1: 10.0, 2: 25.0}


def test_output_format_transactional(tmp_path):
    path = str(tmp_path / "out2.db")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE t (a INTEGER, b TEXT)")
    conn.commit()
    conn.close()
    of = DbApiOutputFormat(lambda: sqlite3.connect(path),
                           "INSERT INTO t VALUES (?, ?)")
    assert of.write([(1, "x"), (2, "y")]) == 2
    # a failing batch rolls back entirely
    with pytest.raises(sqlite3.ProgrammingError):
        of.write([(3, "z"), (4,)])
    conn = sqlite3.connect(path)
    assert conn.execute("SELECT COUNT(*) FROM t").fetchone()[0] == 2
    conn.close()


# ----------------------------------------------------------------- Avro
SCHEMA = {
    "type": "record", "name": "Event", "fields": [
        {"name": "key", "type": "long"},
        {"name": "value", "type": "double"},
        {"name": "flag", "type": "boolean"},
        {"name": "tag", "type": ["null", "string"]},
        {"name": "parts", "type": {"type": "array", "items": "int"}},
        {"name": "attrs", "type": {"type": "map", "values": "string"}},
        {"name": "color", "type": {"type": "enum", "name": "C",
                                   "symbols": ["RED", "BLUE"]}},
    ],
}


def _records(n=500):
    return [
        {"key": i * 7 - 3, "value": i * 0.5, "flag": i % 2 == 0,
         "tag": None if i % 3 == 0 else f"t{i}",
         "parts": list(range(i % 4)),
         "attrs": {"a": str(i)} if i % 5 == 0 else {},
         "color": "RED" if i % 2 else "BLUE"}
        for i in range(n)
    ]


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_avro_container_round_trip(tmp_path, codec):
    path = str(tmp_path / f"events-{codec}.avro")
    recs = _records()
    AvroOutputFormat(path, SCHEMA, codec=codec).write(recs)
    schema, back = read_container(path)
    assert schema == SCHEMA
    assert back == recs
    assert AvroInputFormat(path).read_all() == recs


def test_avro_multi_block_and_sync_validation(tmp_path):
    path = str(tmp_path / "blocks.avro")
    write_container(path, SCHEMA, _records(300), block_records=64)
    _s, back = read_container(path)
    assert len(back) == 300
    # corrupt a sync marker -> loud failure, not silent truncation
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF
    open(path, "wb").write(bytes(data))
    with pytest.raises(ValueError, match="sync"):
        read_container(path)


def test_avro_negative_longs_zigzag(tmp_path):
    """Spec detail: zig-zag keeps small negative longs small."""
    import io

    from flink_tpu.connectors.avro import read_long, write_long

    for v in (0, -1, 1, -2**40, 2**40, -2**62):
        buf = io.BytesIO()
        write_long(buf, v)
        buf.seek(0)
        assert read_long(buf) == v
    buf = io.BytesIO()
    write_long(buf, -1)
    assert buf.getvalue() == b"\x01"       # -1 encodes to one byte


def test_dataset_integration(tmp_path):
    """read_jdbc / read_avro_file feed the DataSet API end to end."""
    from flink_tpu.dataset.environment import ExecutionEnvironment

    db = _db(tmp_path, n=40)
    env = ExecutionEnvironment.get_execution_environment()
    total = (
        env.read_jdbc(lambda: sqlite3.connect(db),
                      "SELECT k, v FROM events ORDER BY id")
        .map(lambda r: r[1])
        .reduce(lambda a, b: a + b)
        .collect()
    )
    assert total == [sum(float(i) for i in range(40))]

    apath = str(tmp_path / "ds.avro")
    AvroOutputFormat(apath, SCHEMA).write(_records(20))
    keys = (
        env.read_avro_file(apath).map(lambda r: r["key"]).collect()
    )
    assert keys == [i * 7 - 3 for i in range(20)]
