"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's in-process mini-cluster testing approach (SURVEY §4):
multi-"worker" behavior is exercised on one host by faking 8 devices.
"""

import os

# FORCE (not setdefault): the host environment may export
# JAX_PLATFORMS=axon, and worker subprocesses spawned by tests inherit
# os.environ — they must come up on the virtual CPU mesh too
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# skip the LLVM -O2 backend pass on test kernels: results are
# bit-identical (no fast-math; reduction order is fixed at the HLO
# level), but compile time — which dominates the tier-1 wall clock on
# a 1-core container — drops ~35% per kernel. Benches ignore this
# (bench.py runs outside pytest), so measured numbers stay honest.
if "xla_backend_optimization_level" not in flags:
    flags = (flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = flags

# NOTE: do NOT enable the jax persistent compilation cache here
# (JAX_COMPILATION_CACHE_DIR) to dedupe the suite's repeated kernel
# builds: on this CPU jaxlib, executables deserialized from the cache
# mid-suite produce wrong results and segfault under donation
# (reproduced in tests/test_checkpoint.py). Compile-time savings must
# come from smaller test dims instead.

import jax  # noqa: E402

# The environment's axon sitecustomize force-sets jax_platforms="axon,cpu",
# which makes any jax.devices() dial the (single, possibly busy) TPU tunnel.
# Tests must run on the virtual 8-device CPU mesh, so override it back before
# any backend initializes.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
