"""Dispatch fusion (ISSUE 5): K-step lax.scan megasteps + update-kernel
pre-combine.

* bit-exact equivalence of one K-fused megastep vs K sequential single
  steps — hash + direct layouts, mask + exchange routes, precombine on
  and off (the scan body IS the single-step body, so nothing may drift),
* duplicate-heavy (hot-key) pre-combine parity against the scalar
  oracle, and precombine-on == precombine-off window sums,
* the fused executor loop end-to-end: exact window sums with K>1, full
  groups actually dispatched as megasteps, K=1 default untouched,
* mid-megastep crash/restore exactly-once with checkpoint.mode:
  incremental + prefetch + K>1 (the megastep-boundary snapshot cut),
* FusedBatchAccumulator grouping contract at the unit level.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops.hashing import hash64_host
from flink_tpu.parallel.mesh import MeshContext
from flink_tpu.runtime import ingest as ingest_mod
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.runtime.step import (
    WindowStageSpec,
    build_window_fire_step,
    build_window_megastep,
    build_window_megastep_exchange,
    build_window_megastep_fired,
    build_window_megastep_fired_exchange,
    build_window_update_step,
    build_window_update_step_exchange,
    init_sharded_state,
)

K = 4
B = 256


def _split(keys):
    h = hash64_host(np.asarray(keys, dtype=np.int64))
    return ((h >> np.uint64(32)).astype(np.uint32),
            (h & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def _spec(layout="hash", precombine=False, red_kind="sum"):
    return WindowStageSpec(
        win=wk.WindowSpec(10, 10, ring=8, fires_per_step=4),
        red=wk.ReduceSpec(red_kind, jnp.float32),
        capacity_per_shard=512, layout=layout, precombine=precombine,
    )


def _batches(rng, layout, k=K):
    out = []
    for i in range(k):
        if layout == "direct":
            hi = np.zeros(B, np.uint32)
            lo = rng.integers(0, 500, B).astype(np.uint32)
        else:
            hi, lo = _split(rng.integers(0, 100, B).astype(np.int64))
        ts = rng.integers(0, 40, B).astype(np.int32)
        vals = rng.integers(1, 5, B).astype(np.float32)
        out.append((hi, lo, ts, vals, np.ones(B, bool),
                    np.full(8, np.int32(i * 3))))
    return out


def _flat(batches):
    return [a for b in batches for a in b[:5]]


def _wmv(batches):
    return np.stack([b[5] for b in batches], axis=1).astype(np.int32)


def _assert_states_bitexact(s1, s2):
    for a, b in zip(jax.tree_util.tree_leaves(s1),
                    jax.tree_util.tree_leaves(s2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("layout", ["hash", "direct"])
@pytest.mark.parametrize("precombine", [False, True])
def test_megastep_bitexact_vs_sequential_mask(rng, layout, precombine):
    """One K-fused mask-route megastep == K sequential single steps,
    bit for bit, across every state leaf (acc, table, counters, dirty
    bits) — for both state layouts and with/without pre-combine."""
    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    spec = _spec(layout, precombine)
    single = build_window_update_step(ctx, spec)
    mega = build_window_megastep(ctx, spec, K)
    s1 = init_sharded_state(ctx, spec)
    s2 = init_sharded_state(ctx, spec)
    batches = _batches(rng, layout)
    for (hi, lo, ts, vals, valid, wm) in batches:
        s1, _ = single(s1, hi, lo, ts, vals, valid, wm)
    s2, mon = mega(s2, *_flat(batches), _wmv(batches))
    _assert_states_bitexact(s1, s2)
    # monitoring shapes match the single step's (shared consumer)
    ovf_n, act, kgf = mon
    assert np.asarray(ovf_n).shape == (8,)
    assert np.asarray(act).shape == (8,)


@pytest.mark.parametrize("precombine", [False, True])
def test_megastep_bitexact_vs_sequential_exchange(rng, precombine):
    """Exchange-route megastep (all_to_all inside the scan body) == K
    sequential exchange steps, bit for bit."""
    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    spec = _spec("hash", precombine)
    bpd = B // 8
    single = build_window_update_step_exchange(ctx, spec, bpd, 2.0)
    mega = build_window_megastep_exchange(ctx, spec, bpd, K, 2.0)
    s1 = init_sharded_state(ctx, spec)
    s2 = init_sharded_state(ctx, spec)
    batches = _batches(rng, "hash")
    for (hi, lo, ts, vals, valid, wm) in batches:
        s1, _ = single(s1, hi, lo, ts, vals, valid, wm)
    s2, _ = mega(s2, *_flat(batches), _wmv(batches))
    _assert_states_bitexact(s1, s2)


# ---------------------------------------------------------- pre-combine

def test_precombine_hot_key_parity_with_scalar_oracle(rng):
    """Duplicate-heavy batches (90% of lanes on 8 hot keys): the
    pre-combined update's fired window sums equal a scalar dict oracle,
    and equal the non-precombined path (sums of small integers are exact
    in float32, so the segmented-scan reorder cannot hide behind
    tolerance)."""
    from flink_tpu.runtime.step import build_window_fire_step

    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    oracle = {}
    results = {}
    for precombine in (False, True):
        spec = _spec("hash", precombine)
        step = build_window_update_step(ctx, spec)
        fire = build_window_fire_step(ctx, spec)
        state = init_sharded_state(ctx, spec)
        r = np.random.default_rng(7)   # same stream for both paths
        for i in range(6):
            n_hot = (9 * B) // 10
            keys = np.concatenate([
                r.integers(0, 8, n_hot),          # hot set
                r.integers(100, 400, B - n_hot),  # long tail
            ]).astype(np.int64)
            r.shuffle(keys)
            ts = np.full(B, i * 10 + 5, np.int32)
            vals = r.integers(1, 4, B).astype(np.float32)
            if not precombine:   # oracle built once
                for k, t, v in zip(keys.tolist(), ts.tolist(),
                                   vals.tolist()):
                    we = (t // 10 + 1) * 10
                    oracle[(we, k)] = oracle.get((we, k), 0.0) + v
            hi, lo = _split(keys)
            state, _ = step(state, hi, lo, ts, vals, np.ones(B, bool),
                            np.full(8, np.int32(i * 10 - 1)))
        got = {}
        kid_of = {}
        for k in set(k for (_, k) in oracle):
            h, l = _split(np.asarray([k]))
            kid_of[(int(h[0]) << 32) | int(l[0])] = k
        while True:   # each fire step evaluates up to F window ends
            state, fr = fire(state, np.full(8, np.int32(10**6)))
            counts = np.asarray(fr.counts)
            lanes = np.asarray(fr.lane_valid)
            ends = np.asarray(fr.window_end_ticks)
            khi = np.asarray(fr.key_hi)
            klo = np.asarray(fr.key_lo)
            values = np.asarray(fr.values)
            for sh in range(counts.shape[0]):
                for f in np.nonzero(lanes[sh])[0]:
                    for j in range(int(counts[sh, f])):
                        kid = (int(khi[sh, f, j]) << 32) | int(
                            klo[sh, f, j]
                        )
                        got[(int(ends[sh, f]), kid_of[kid])] = float(
                            values[sh, f, j]
                        )
            if not lanes.any():
                break
        results[precombine] = got
        assert got == {k: v for k, v in oracle.items()}, (
            f"precombine={precombine} diverged from the scalar oracle"
        )
    assert results[False] == results[True]


def test_precombine_marks_same_dirty_groups(rng):
    """The rep-scatter changelog marking covers exactly the key groups
    the eager per-lane scatter marks (incremental checkpoints must not
    lose coverage to the shared-sort hoist)."""
    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    dirt = {}
    for precombine in (False, True):
        spec = _spec("hash", precombine)
        step = build_window_update_step(ctx, spec)
        state = init_sharded_state(ctx, spec)
        r = np.random.default_rng(11)
        hi, lo = _split(r.integers(0, 50, B).astype(np.int64))
        ts = np.full(B, 5, np.int32)
        state, _ = step(state, hi, lo, ts, np.ones(B, np.float32),
                        np.ones(B, bool), np.full(8, np.int32(-1)))
        dirt[precombine] = np.asarray(state.kg_dirty)
    assert np.array_equal(dirt[False], dirt[True])


# ---------------------------------------------- resident pipeline (fused fire)

_FIRE_FIELDS = ("key_hi", "key_lo", "values", "counts",
                "window_end_ticks", "n_fires", "lane_valid", "value_sums")


def _fire_crossing_batches(rng, layout, k=K):
    """Batches whose watermarks cross pane boundaries MID-group, so the
    in-scan fire path actually fires (slide=10; wm advances ~1.2 panes
    per batch). For k > 1 the first sub-step crosses nothing — the
    gated-eval SKIP branch gets exercised alongside the fire branch;
    a k=1 group starts past the first boundary so it always fires."""
    out = []
    wm0 = 15 if k == 1 else 5
    for i in range(k):
        if layout == "direct":
            hi = np.zeros(B, np.uint32)
            lo = rng.integers(0, 500, B).astype(np.uint32)
        else:
            hi, lo = _split(rng.integers(0, 100, B).astype(np.int64))
        ts = rng.integers(0, 40, B).astype(np.int32)
        vals = rng.integers(1, 5, B).astype(np.float32)
        out.append((hi, lo, ts, vals, np.ones(B, bool),
                    np.full(8, np.int32(i * 12 + wm0))))
    return out


@pytest.mark.parametrize("layout", ["hash", "direct"])
@pytest.mark.parametrize("k", [1, K])
def test_fired_megastep_bitexact_vs_sequential_oracle_mask(rng, layout, k):
    """The resident-pipeline megastep (fire folded into the scan) vs the
    sequential update-then-advance_and_fire oracle: every state leaf bit-
    equal AND every sub-step's compacted fire payload byte-equal — the
    gated eval, the deferred purge, and the post-scan fixup may not
    perturb anything observable."""
    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    spec = _spec(layout)
    single = build_window_update_step(ctx, spec)
    fire = build_window_fire_step(ctx, spec)
    mega = build_window_megastep_fired(ctx, spec, k)
    s1 = init_sharded_state(ctx, spec)
    s2 = init_sharded_state(ctx, spec)
    batches = _fire_crossing_batches(rng, layout, k)
    oracle = []
    for (hi, lo, ts, vals, valid, wm) in batches:
        s1, _ = single(s1, hi, lo, ts, vals, valid, wm)
        s1, fr = fire(s1, wm)
        oracle.append(fr)
    s2, mon, fires = mega(s2, *_flat(batches), _wmv(batches))
    _assert_states_bitexact(s1, s2)
    total = 0
    for i, fr in enumerate(oracle):
        for name in _FIRE_FIELDS:
            a = np.asarray(getattr(fr, name))
            b = np.asarray(getattr(fires, name))[:, i]
            assert np.array_equal(a, b), (name, i)
        total += int(np.asarray(fr.counts).sum())
    assert total > 0, "scenario never fired — the test proves nothing"
    # reduce_fires payload parity: the on-chip reduced quantities the
    # device_reduce sinks consume derive from the same packed fields
    for i, fr in enumerate(oracle):
        assert np.array_equal(np.asarray(fr.value_sums),
                              np.asarray(fires.value_sums)[:, i])
        assert np.array_equal(np.asarray(fr.counts),
                              np.asarray(fires.counts)[:, i])


def test_fired_megastep_bitexact_vs_sequential_oracle_exchange(rng):
    """Exchange-route resident megastep (all_to_all + in-scan fire) ==
    K sequential exchange steps + fire steps, bit for bit, payloads
    included."""
    from flink_tpu.runtime.step import build_window_fire_step

    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    spec = _spec("hash")
    bpd = B // 8
    single = build_window_update_step_exchange(ctx, spec, bpd, 2.0)
    fire = build_window_fire_step(ctx, spec)
    mega = build_window_megastep_fired_exchange(ctx, spec, bpd, K, 2.0)
    s1 = init_sharded_state(ctx, spec)
    s2 = init_sharded_state(ctx, spec)
    batches = _fire_crossing_batches(rng, "hash")
    oracle = []
    for (hi, lo, ts, vals, valid, wm) in batches:
        s1, _ = single(s1, hi, lo, ts, vals, valid, wm)
        s1, fr = fire(s1, wm)
        oracle.append(fr)
    s2, _mon, fires = mega(s2, *_flat(batches), _wmv(batches))
    _assert_states_bitexact(s1, s2)
    total = 0
    for i, fr in enumerate(oracle):
        for name in _FIRE_FIELDS:
            assert np.array_equal(
                np.asarray(getattr(fr, name)),
                np.asarray(getattr(fires, name))[:, i],
            ), (name, i)
        total += int(np.asarray(fr.counts).sum())
    assert total > 0


def test_fired_megastep_kg_dirty_and_kg_fill_equality(rng):
    """The resident megastep's changelog bits and skew counts (the
    4th shared-sort consumer) match the sequential oracle's: kg_dirty
    rides the state compare; the summed kg_fill handle must equal the
    per-batch kg_batch_fill sums."""
    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    for precombine in (False, True):
        spec = _spec("hash", precombine)
        single = build_window_update_step(ctx, spec, kg_fill=True)
        fire = build_window_fire_step(ctx, spec)
        mega = build_window_megastep_fired(ctx, spec, K, kg_fill=True)
        s1 = init_sharded_state(ctx, spec)
        s2 = init_sharded_state(ctx, spec)
        batches = _fire_crossing_batches(rng, "hash")
        kgf_sum = None
        for (hi, lo, ts, vals, valid, wm) in batches:
            s1, (_o, _a, kgf) = single(s1, hi, lo, ts, vals, valid, wm)
            kgf = np.asarray(kgf)
            kgf_sum = kgf if kgf_sum is None else kgf_sum + kgf
            s1, _ = fire(s1, wm)
        s2, (_o, _a, kgf2), _fires = mega(s2, *_flat(batches),
                                          _wmv(batches))
        _assert_states_bitexact(s1, s2)   # includes kg_dirty
        assert np.array_equal(kgf_sum, np.asarray(kgf2)), (
            f"kg_fill diverged (precombine={precombine})"
        )
        assert int(np.asarray(s1.kg_dirty).sum()) > 0


def test_update_kg_fill_precombine_equals_plain(rng):
    """One-sort-feeds-four seam: the kg_fill counts computed from the
    shared sort (precombine on: segment lane-counts at representatives
    + residual late lanes) equal the plain bincount scatter — including
    LATE lanes, which sit outside the sort's validity."""
    import jax

    win = wk.WindowSpec(10, 10, ring=8, fires_per_step=4)
    red = wk.ReduceSpec("sum", jnp.float32)
    results = {}
    for pre in (False, True):
        st = wk.init_state(256, 8, win, red, n_key_groups=64)
        r = np.random.default_rng(23)
        kgfs = []
        for i in range(3):
            hi, lo = _split(r.integers(0, 40, B).astype(np.int64))
            # advance the watermark so later batches carry LATE lanes
            st = __import__("dataclasses").replace(
                st, watermark=jnp.asarray(np.int32(i * 15))
            )
            ts = r.integers(0, 60, B).astype(np.int32)
            st, _act, kgf = wk.update(
                st, win, red, jnp.asarray(hi), jnp.asarray(lo),
                jnp.asarray(ts), jnp.asarray(np.ones(B, np.float32)),
                jnp.asarray(np.ones(B, bool)),
                precombine=pre, kg_fill=64,
            )
            kgfs.append(np.asarray(kgf))
            st, _ = wk.advance_and_fire(st, win, red, np.int32(i * 15))
        results[pre] = np.stack(kgfs)
        assert int(np.asarray(st.dropped_late)) > 0, \
            "no late lanes — residual path untested"
    assert np.array_equal(results[False], results[True])


# ------------------------------------------------- fused executor loop

N_KEYS = 200
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    # slow event time: ~8 micro-batches per pane, so fused groups fill
    return cols, (idx // 2000) * 1000


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 2000) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def build_env(parallelism, ckpt_dir=None, interval=0, restart=None, **cfg):
    conf = Configuration(cfg)
    if restart:
        conf.set("restart-strategy", "fixed-delay")
        conf.set("restart-strategy.fixed-delay.attempts", restart)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = B
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, source=None, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(source or GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("megastep-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


def test_fused_executor_exact_and_actually_fused():
    total = 8192
    env = build_env(2, **{"pipeline.steps-per-dispatch": K})
    got = run_job(env, total)
    assert got == expected(total)
    m = env.last_job.metrics
    # full groups really dispatched as megasteps (not all-partial flush)
    assert m.fused_dispatches > 0
    assert m.steps == total // B


def test_k1_default_has_no_fused_dispatches():
    total = 4096
    env = build_env(2)
    got = run_job(env, total)
    assert got == expected(total)
    assert env.last_job.metrics.fused_dispatches == 0


class FailingSource(GeneratorSource):
    """Raises once when crossing fail_at — mid-stream, while fused
    groups are pending/forming (the poll runs on the prefetch thread)."""

    def __init__(self, fn, total, fail_at):
        super().__init__(fn, total)
        self.fail_at = fail_at
        self.failed = False
        self.poll_thread_names = set()

    def poll(self, max_records):
        self.poll_thread_names.add(threading.current_thread().name)
        out = super().poll(max_records)
        if not self.failed and self.offset >= self.fail_at:
            self.failed = True
            raise RuntimeError("injected failure")
        return out


def test_fused_crash_restore_exactly_once(tmp_path):
    """Mid-megastep crash with checkpoint.mode=incremental + prefetch +
    K>1, restore, exactly-once counts: the snapshot cut is the offsets
    of the LAST batch of the last flushed group, so batches pending in
    the fused slot at the crash replay without double-counting."""
    total = 8192
    env = build_env(
        2, tmp_path / "chk", interval=2, restart=3,
        **{"pipeline.prefetch": "on", "checkpoint.mode": "incremental",
           "checkpoint.async": True, "pipeline.steps-per-dispatch": K},
    )
    src = FailingSource(gen, total, fail_at=total // 2)
    got = run_job(env, total, source=src)
    m = env.last_job.metrics
    assert m.restarts == 1
    assert m.fused_dispatches > 0          # the scenario really fused
    assert got == expected(total)          # no skips, no double counts


def test_fused_checkpoint_cadence_exact(tmp_path):
    """Periodic checkpoints at a cadence that lands MID-group (interval
    3 micro-batches vs K=4): every trigger flushes the fused slot first,
    checkpoints get written, results stay exact, and fusion still
    happens between triggers."""
    total = 8192
    env = build_env(
        2, tmp_path / "chk", interval=3,
        **{"pipeline.prefetch": "on", "checkpoint.mode": "incremental",
           "checkpoint.async": True, "pipeline.steps-per-dispatch": K},
    )
    got = run_job(env, total)
    m = env.last_job.metrics
    assert got == expected(total)
    assert m.checkpoint_stats, "no checkpoints were written"
    assert m.fused_dispatches > 0


def gen_fast(offset, n):
    """Event time advancing ~1 pane every 2.5 micro-batches (B=256), so
    every K=4 fused group contains at least one pane-boundary crossing
    — the resident pipeline's in-scan fire path, not the split drain,
    carries the job."""
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    return cols, (idx // 640) * 1000


def expected_fast(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 640) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def test_fused_fire_executor_exact_with_in_group_crossings():
    """End-to-end resident pipeline: pane boundaries land INSIDE fused
    groups, fires surface from megastep payloads (lagged), results stay
    exact, and the groups really stay fused across the crossings (the
    split path would have broken every one)."""
    total = 8192
    env = build_env(2, **{"pipeline.steps-per-dispatch": K})
    got = run_job(env, total, source=GeneratorSource(gen_fast, total=total))
    assert got == expected_fast(total)
    m = env.last_job.metrics
    assert m.fused_fire_dispatches > 0
    assert m.fused_dispatches == m.fused_fire_dispatches
    assert m.fires == len(expected_fast(total))


def test_fused_fire_off_is_split_path():
    total = 8192
    env = build_env(
        2, **{"pipeline.steps-per-dispatch": K, "pipeline.fused-fire": "off"},
    )
    got = run_job(env, total, source=GeneratorSource(gen_fast, total=total))
    assert got == expected_fast(total)
    m = env.last_job.metrics
    assert m.fused_fire_dispatches == 0


def test_fused_fire_crash_restore_exactly_once_with_in_group_fire(tmp_path):
    """Mid-stream crash while the resident pipeline is firing INSIDE
    fused groups (incremental + async + prefetch + K>1): restore rewinds
    to the megastep-boundary cut, unread in-flight fire payloads are
    discarded and re-fired from the replayed state, and the window
    counts come out exactly once."""
    total = 8192
    env = build_env(
        2, tmp_path / "chk", interval=2, restart=3,
        **{"pipeline.prefetch": "on", "checkpoint.mode": "incremental",
           "checkpoint.async": True, "pipeline.steps-per-dispatch": K},
    )
    src = FailingSource(gen_fast, total, fail_at=total // 2)
    got = run_job(env, total, source=src)
    m = env.last_job.metrics
    assert m.restarts == 1
    assert m.fused_fire_dispatches > 0     # the scenario really fused-fired
    assert got == expected_fast(total)     # no skips, no double counts


def test_fired_megastep_reduced_parity_vs_oracle(rng):
    """The ReducedFires resident variant (device_reduce topologies skip
    the payload stacking) must match the sequential oracle's
    reduce_fires lane-for-lane, and leave state bit-identical to the
    compact variant."""
    from flink_tpu.runtime.step import build_window_fire_step

    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    spec = _spec("hash")
    single = build_window_update_step(ctx, spec)
    fire = build_window_fire_step(ctx, spec)
    mega_r = build_window_megastep_fired(ctx, spec, K, reduced=True)
    s1 = init_sharded_state(ctx, spec)
    s2 = init_sharded_state(ctx, spec)
    batches = _fire_crossing_batches(rng, "hash")
    oracle = []
    for (hi, lo, ts, vals, valid, wm) in batches:
        s1, _ = single(s1, hi, lo, ts, vals, valid, wm)
        # the split fire step's CompactFires carries the same small
        # fields the reduced variant surfaces — compare those directly
        s1, fr = fire(s1, wm)
        oracle.append(fr)
    s2, _mon, fires = mega_r(s2, *_flat(batches), _wmv(batches))
    _assert_states_bitexact(s1, s2)
    assert not hasattr(fires, "key_hi")        # really reduced
    total = 0
    for i, fr in enumerate(oracle):
        for name in ("counts", "window_end_ticks", "n_fires",
                     "lane_valid", "value_sums"):
            assert np.array_equal(
                np.asarray(getattr(fr, name)),
                np.asarray(getattr(fires, name))[:, i],
            ), (name, i)
        total += int(np.asarray(fr.counts).sum())
    assert total > 0


def test_fused_fire_device_reduce_sink_exact():
    """End-to-end resident pipeline with a device_reduce sink
    (CountingSink): the executor auto-selects the ReducedFires fired
    megasteps (no payload planes) and the on-chip-reduced counts/sums
    come out exact."""
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    total = 8192
    env = build_env(2, **{"pipeline.steps-per-dispatch": K})
    sink = CountingSink()
    (
        env.add_source(GeneratorSource(gen_fast, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("megastep-reduced-job")
    m = env.last_job.metrics
    exp = expected_fast(total)
    assert m.fused_fire_dispatches > 0
    assert sink.count == len(exp)
    assert abs(sink.value_sum - sum(exp.values())) < 1e-3


def test_fused_fire_spill_tier_exact():
    """Resident pipeline under STATE CAPACITY pressure: keys overflow the
    table into the device ring -> host spill stores, and windows fire
    INSIDE fused groups. The consumer must see the ring drained before
    merging spill contributions into an emission (the post-scan ovf_n
    handle rides the fire payload for exactly this), or fired values
    silently lose their spilled shares."""
    N = 1500                      # ~3x the 2x256-slot table capacity
    total = 8192

    def gen_spill(offset, n):
        idx = np.arange(offset, offset + n)
        return ({"key": (idx * 48271) % N,
                 "value": np.ones(n, np.float32)}, (idx // 640) * 1000)

    exp = {}
    idx = np.arange(total)
    for k, t in zip(((idx * 48271) % N).tolist(),
                    ((idx // 640) * 1000).tolist()):
        we = (t // WINDOW + 1) * WINDOW
        exp[(k, we)] = exp.get((k, we), 0) + 1.0

    env = build_env(2, **{"pipeline.steps-per-dispatch": K})
    env.set_state_capacity(256)
    got = run_job(env, total,
                  source=GeneratorSource(gen_spill, total=total))
    m = env.last_job.metrics
    assert m.fused_fire_dispatches > 0
    assert m.dropped_capacity == 0       # spill tier absorbed everything
    assert got == exp


def test_fused_fire_invalid_config_rejected():
    env = build_env(2, **{"pipeline.steps-per-dispatch": K,
                          "pipeline.fused-fire": "sometimes"})
    with pytest.raises(ValueError, match="fused-fire"):
        run_job(env, 1024)


# ------------------------------------------------- accumulator contract

def test_fused_accumulator_grouping():
    acc = ingest_mod.FusedBatchAccumulator(3)
    assert len(acc) == 0 and not acc.full()
    assert acc.compatible("mask", True)
    acc.push(("a",), 1, "pb1", "mask", True)
    assert acc.compatible("mask", True)
    assert not acc.compatible("exchange", True)   # route change -> flush
    assert not acc.compatible("mask", False)      # staging change -> flush
    acc.push(("b",), 2, "pb2", "mask", True)
    assert not acc.full()
    acc.push(("c",), 3, "pb3", "mask", True)
    assert acc.full()
    route, staged, items = acc.drain()
    assert route == "mask" and staged is True and len(items) == 3
    assert items[-1][2] == "pb3"                  # last pb = applied cut
    assert len(acc) == 0 and acc.compatible("exchange", False)
    acc.push(("d",), 4, "pb4", "exchange", False)
    acc.clear()                                   # restore path discards
    assert len(acc) == 0
