"""Sketch window aggregations (BASELINE config #3): Count-Min + HLL.

Golden-accuracy tests: device sketches vs exact counts computed in numpy.
"""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.ops import sketches as sk
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource


def _env(parallelism=4, batch=512, capacity=1024):
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(capacity)
    env.batch_size = batch
    return env


def test_hll_unit_estimate():
    """Registers built directly: estimate within 5% at p=12."""
    import jax.numpy as jnp

    h = sk.HyperLogLog(p=12)
    n = 50_000
    hashes = sk.hash32_host(np.arange(n))
    bucket = (hashes >> np.uint32(20)).astype(np.int64)
    # mirror the device rho on the fmix32-mixed hash
    mixed = np.asarray(sk._fmix32(jnp.asarray(hashes)))
    bucket = (mixed >> np.uint32(32 - h.p)).astype(np.int64)
    w = (mixed << np.uint32(h.p)).astype(np.uint32)
    lead = np.where(w == 0, 32, 32 - np.floor(np.log2(
        np.maximum(w.astype(np.float64), 1))) - 1)
    rho = np.where(w == 0, 32 - h.p + 1, lead + 1).astype(np.int32)
    regs = np.zeros(h.m, np.int32)
    np.maximum.at(regs, bucket, rho)
    est = float(np.asarray(h.finalize(jnp.asarray(regs))))
    assert abs(est - n) / n < 0.05


def test_distinct_count_tumbling():
    """Per-key distinct counts per window, vs exact numpy answer."""
    rng = np.random.default_rng(7)
    n = 6000
    keys = rng.integers(0, 8, n)
    items = rng.integers(0, 500, n)  # duplicates guaranteed
    ts = np.sort(rng.integers(0, 20_000, n))

    # 8 distinct keys: a 64-slot table exercises the same hash/evict
    # paths as the 1024 default at a fraction of the [ring, C, m]
    # register-plane compile cost (m=4096 at p=12).
    env = _env(capacity=64)
    sink = CollectSink()

    def gen(offset, nn):
        s = slice(offset, offset + nn)
        return {"key": keys[s], "item": items[s]}, ts[s]

    (
        env.add_source(GeneratorSource(gen, total=n))
        .key_by(lambda cols: cols["key"])
        .time_window(10_000)
        .distinct_count(lambda cols: cols["item"], precision=12)
        .add_sink(sink)
    )
    env.execute("hll")

    exact = {}
    for k, it, t in zip(keys, items, ts):
        exact.setdefault((int(k), (int(t) // 10_000 + 1) * 10_000),
                         set()).add(int(it))
    got = {(r.key, r.window_end_ms): r.value for r in sink.results}
    assert set(got) == set(exact)
    for kw, s in exact.items():
        # per-key cardinality is small (<500): linear-counting regime,
        # expect tight estimates
        assert abs(got[kw] - len(s)) / len(s) < 0.06, (kw, got[kw], len(s))


def test_count_min_sliding_query():
    """Sliding-window CMS point queries >= true count (one-sided error)
    and close to it with width >> cardinality."""
    rng = np.random.default_rng(3)
    n = 4000
    # one stream key, items zipf-ish: item 0 is hot
    items = np.where(rng.random(n) < 0.3, 0, rng.integers(1, 200, n))
    ts = np.sort(rng.integers(0, 12_000, n))
    query = [0, 1, 5, 199]

    # one stream key: 64 slots keep the [ring, C, depth*width] CMS
    # planes small without touching the sketch dims under test.
    env = _env(parallelism=2, capacity=64)
    sink = CollectSink()

    def gen(offset, nn):
        s = slice(offset, offset + nn)
        return {"key": np.zeros(nn - max(0, offset + nn - n), np.int32),
                "item": items[s]}, ts[s]

    (
        env.add_source(GeneratorSource(
            lambda o, m: ({"key": np.zeros(len(items[o:o + m]), np.int32),
                           "item": items[o:o + m]}, ts[o:o + m]),
            total=n))
        .key_by(lambda cols: cols["key"])
        .time_window(8000, 4000)
        .count_min(lambda cols: cols["item"], depth=4, width=1024,
                   query=query)
        .add_sink(sink)
    )
    env.execute("cms")

    got = {r.window_end_ms: np.asarray(r.value) for r in sink.results}
    assert got, "no window fires"
    for end_ms, est in got.items():
        lo_t, hi_t = end_ms - 8000, end_ms
        in_win = (ts >= lo_t) & (ts < hi_t)
        for qi, q in enumerate(query):
            true = int(np.sum(in_win & (items == q)))
            assert est[qi] >= true, (end_ms, q, est[qi], true)
            # depth-4 width-1024 over <=4000 increments: overshoot tiny
            assert est[qi] <= true + 40, (end_ms, q, est[qi], true)


def test_count_min_raw_sketch_host_query():
    """Without a query list the raw registers are emitted and queryable
    host-side via estimate_np."""
    n = 1000
    items = np.arange(n) % 50

    env = _env(parallelism=2, capacity=256)
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(
            lambda o, m: ({"key": np.zeros(len(items[o:o + m]), np.int32),
                           "item": items[o:o + m]},
                          np.full(len(items[o:o + m]), 100)),
            total=n))
        .key_by(lambda cols: cols["key"])
        .time_window(1000)
        .count_min(lambda cols: cols["item"], depth=4, width=256)
        .add_sink(sink)
    )
    env.execute("cms-raw")

    assert len(sink.results) == 1
    sketch = np.asarray(sink.results[0].value)
    cms = sk.CountMinSketch(4, 256)
    est = cms.estimate_np(sketch, [0, 7, 49])
    assert (est >= 20).all() and (est <= 24).all()


def test_hll_merges_across_panes():
    """Sliding windows combine pane registers with max: distinct items
    spread over panes must count once each, not once per pane."""
    # 100 distinct items, each appearing in BOTH halves of a 10s window
    items = np.tile(np.arange(100), 2)
    ts = np.concatenate([np.full(100, 1000), np.full(100, 6000)])

    env = _env(parallelism=2, capacity=256)
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(
            lambda o, m: ({"key": np.zeros(len(items[o:o + m]), np.int32),
                           "item": items[o:o + m]}, ts[o:o + m]),
            total=len(items)))
        .key_by(lambda cols: cols["key"])
        .time_window(10_000, 5000)
        .distinct_count(lambda cols: cols["item"], precision=10)
        .add_sink(sink)
    )
    env.execute("hll-panes")

    got = {r.window_end_ms: r.value for r in sink.results}
    # the window [0,10000) contains both batches -> still ~100 distinct
    assert 10_000 in got
    assert abs(got[10_000] - 100) < 10
