"""Window kernel semantics vs a scalar Python model.

Plays the role of the reference's WindowOperatorTest golden-output tests
(SURVEY §4): out-of-order event-time input, tumbling and sliding windows,
late-data dropping — compared against a dict-based model.
"""

import jax.numpy as jnp
import numpy as np

from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops.hashing import hash64_host


def _split(keys):
    h = hash64_host(np.asarray(keys, dtype=np.int64))
    return (
        (h >> np.uint64(32)).astype(np.uint32),
        (h & np.uint64(0xFFFFFFFF)).astype(np.uint32),
    )


class ScalarModel:
    """Per-record scalar window aggregation (the reference's semantics)."""

    def __init__(self, size, slide):
        self.size, self.slide = size, slide
        self.k = size // slide
        self.panes = {}  # (key, pane) -> sum
        self.wm = -(2**31) + 1
        self.fired_through = None  # last fired window-end pane
        self.dropped = 0
        self.fires = []  # (window_end_tick, key, value)

    def add(self, key, ts, val):
        pane = ts // self.slide
        if self.fired_through is not None and pane + self.k - 1 <= self.fired_through:
            self.dropped += 1
            return
        self.panes[(key, pane)] = self.panes.get((key, pane), 0.0) + val

    def advance(self, wm):
        self.wm = max(self.wm, wm)
        wm_pane = (self.wm + 1 - self.slide) // self.slide
        if not self.panes and self.fired_through is None:
            self.fired_through = wm_pane
            return
        all_panes = [p for (_, p) in self.panes]
        if self.fired_through is None:
            start = min(all_panes) if all_panes else wm_pane + 1
        else:
            start = self.fired_through + 1
        for p in range(start, wm_pane + 1):
            keys = {}
            for (key, q), v in self.panes.items():
                if p - self.k + 1 <= q <= p:
                    keys[key] = keys.get(key, 0.0) + v
            for key, v in sorted(keys.items()):
                self.fires.append(((p + 1) * self.slide, key, v))
            # purge panes fully fired
            self.panes = {
                (key, q): v
                for (key, q), v in self.panes.items()
                if q + self.k - 1 > p
            }
        self.fired_through = max(wm_pane, self.fired_through if self.fired_through is not None else wm_pane)


def run_device(events, batches, size, slide, ring=16, fires_per_step=4,
               capacity=256):
    win = wk.WindowSpec(size, slide, ring=ring, fires_per_step=fires_per_step)
    red = wk.ReduceSpec("sum", jnp.float32)
    st = wk.init_state(capacity, 8, win, red)
    fires = []
    keymap = {}

    def collect(fr, hi, lo):
        mask = np.asarray(fr.mask)
        vals = np.asarray(fr.values)
        ends = np.asarray(fr.window_end_ticks)
        lanes = np.asarray(fr.lane_valid)
        tk = np.asarray(st.table.keys)
        for f in np.nonzero(lanes)[0]:
            for c in np.nonzero(mask[f])[0]:
                kid = (int(tk[c, 0]) << 32) | int(tk[c, 1])
                fires.append((int(ends[f]), keymap[kid], float(vals[f, c])))

    for batch, wm in batches:
        if batch:
            keys = [e[0] for e in batch]
            ts = np.asarray([e[1] for e in batch], np.int32)
            vals = np.asarray([e[2] for e in batch], np.float32)
            hi, lo = _split(keys)
            for key, h, l in zip(keys, hi, lo):
                keymap[(int(h) << 32) | int(l)] = key
            valid = np.ones(len(batch), bool)
            st, _, _ = wk.update(st, win, red, jnp.asarray(hi), jnp.asarray(lo),
                              jnp.asarray(ts), jnp.asarray(vals),
                              jnp.asarray(valid))
        while True:
            st, fr = wk.advance_and_fire(st, win, red, jnp.int32(wm))
            collect(fr, None, None)
            if int(fr.n_fires) < fires_per_step:
                break
    return st, fires


def _compare(model_fires, device_fires):
    assert sorted(model_fires) == sorted(
        [(e, k, round(v, 3)) for e, k, v in device_fires]
    )


def test_tumbling_in_order():
    size = slide = 10
    model = ScalarModel(size, slide)
    batches = []
    rng = np.random.default_rng(1)
    t = 0
    for step in range(10):
        batch = []
        for _ in range(20):
            key = int(rng.integers(0, 5))
            ts = t + int(rng.integers(0, 10))
            v = float(rng.integers(1, 5))
            batch.append((key, ts, v))
            model.add(key, ts, v)
        t += 10
        wm = t - 1
        model.advance(wm)
        batches.append((batch, wm))
    _, fires = run_device(None, batches, size, slide)
    model_fires = [(e, k, round(v, 3)) for e, k, v in model.fires]
    _compare(model_fires, fires)
    assert len(fires) > 0


def test_tumbling_out_of_order_and_late():
    size = slide = 10
    model = ScalarModel(size, slide)
    rng = np.random.default_rng(7)
    batches = []
    wm = -(2**31) + 1
    now = 0
    for step in range(15):
        batch = []
        for _ in range(30):
            key = int(rng.integers(0, 8))
            # timestamps scattered up to 25 ticks behind "now" -> some late
            ts = now - int(rng.integers(0, 25))
            if ts < 0:
                ts = 0
            v = 1.0
            batch.append((key, ts, v))
            model.add(key, ts, v)
        now += 8
        wm = now - 12  # bounded out-of-orderness watermark
        model.advance(wm)
        batches.append((batch, wm))
    # flush
    model.advance(10**6)
    batches.append(([], 10**6))
    st, fires = run_device(None, batches, size, slide)
    _compare([(e, k, round(v, 3)) for e, k, v in model.fires], fires)
    assert int(st.dropped_late) == model.dropped
    assert int(st.dropped_capacity) == 0


def test_sliding_pane_composition():
    size, slide = 30, 10
    model = ScalarModel(size, slide)
    rng = np.random.default_rng(3)
    batches = []
    t = 0
    for step in range(12):
        batch = []
        for _ in range(25):
            key = int(rng.integers(0, 4))
            ts = t + int(rng.integers(0, 10))
            v = float(rng.integers(1, 4))
            batch.append((key, ts, v))
            model.add(key, ts, v)
        t += 10
        wm = t - 1
        model.advance(wm)
        batches.append((batch, wm))
    model.advance(10**6)
    batches.append(([], 10**6))
    _, fires = run_device(None, batches, size, slide)
    _compare([(e, k, round(v, 3)) for e, k, v in model.fires], fires)


def test_generic_combine_max():
    # 'generic' path: max as a generic associative combine
    win = wk.WindowSpec(10, 10, ring=8, fires_per_step=2)
    red = wk.ReduceSpec("generic", jnp.float32,
                        combine=jnp.maximum, neutral=-np.inf)
    st = wk.init_state(64, 8, win, red)
    keys = [1, 2, 1, 2, 1]
    ts = np.asarray([0, 3, 5, 7, 9], np.int32)
    vals = np.asarray([5.0, 2.0, 9.0, 1.0, 4.0], np.float32)
    hi, lo = _split(keys)
    st, _, _ = wk.update(st, win, red, jnp.asarray(hi), jnp.asarray(lo),
                      jnp.asarray(ts), jnp.asarray(vals),
                      jnp.ones(5, dtype=bool))
    st, fr = wk.advance_and_fire(st, win, red, jnp.int32(9))
    assert int(fr.n_fires) == 1
    mask = np.asarray(fr.mask)[0]
    vals_out = np.asarray(fr.values)[0][mask]
    assert sorted(vals_out.tolist()) == [2.0, 9.0]
