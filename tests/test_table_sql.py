"""SQL JOIN lowering + streaming windowed GROUP BY (VERDICT r2 item 8).

Ref: flink-table StreamTableEnvironment.scala (streaming Table/SQL) and
the batch SQL JOIN planning the reference does via Calcite — here lowered
directly to the columnar hash join and the device window kernels.
"""

import numpy as np

from flink_tpu.table import StreamTableEnvironment, TableEnvironment


def _tenv():
    te = TableEnvironment.create()
    te.register_table("orders", te.from_columns({
        "oid": [1, 2, 3, 4],
        "cust": [10, 20, 10, 30],
        "amount": [5.0, 7.0, 11.0, 13.0],
    }))
    te.register_table("customers", te.from_columns({
        "cust": [10, 20, 40],
        "region": ["eu", "us", "ap"],
    }))
    return te


def test_sql_inner_join():
    t = _tenv().sql_query(
        "SELECT oid, region, amount FROM orders "
        "JOIN customers ON orders.cust = customers.cust "
        "ORDER BY oid"
    )
    assert t.to_rows() == [
        (1, "eu", 5.0), (2, "us", 7.0), (3, "eu", 11.0),
    ]


def test_sql_left_join_with_group_by():
    t = _tenv().sql_query(
        "SELECT region, SUM(amount) AS total FROM orders "
        "LEFT JOIN customers ON orders.cust = customers.cust "
        "GROUP BY region ORDER BY region"
    )
    rows = t.to_rows()
    assert (None, 13.0) in rows          # cust 30 has no region
    assert ("eu", 16.0) in rows and ("us", 7.0) in rows


def test_sql_full_join():
    t = _tenv().sql_query(
        "SELECT cust, region FROM orders "
        "FULL JOIN customers ON orders.cust = customers.cust"
    )
    custs = set(t.cols["cust"].tolist())
    assert custs == {10, 20, 30, 40}     # both unmatched sides present


# ---------------------------------------------------------------- streaming

def _stream_env(total=2000, n_keys=4):
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sources import GeneratorSource

    def build():
        env = StreamExecutionEnvironment(Configuration())
        env.set_parallelism(1)
        env.set_max_parallelism(8)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(256)
        env.batch_size = 128

        def gen(offset, n):
            idx = np.arange(offset, offset + n, dtype=np.int64)
            return ({
                "k": idx % n_keys,
                "v": (idx % 7).astype(np.float32),
                "rowtime": idx * 2,        # 2ms per record, as a COLUMN
            }, None)

        return env, env.add_source(GeneratorSource(gen, total=total))

    te = StreamTableEnvironment.create()
    te.register_stream("events", build)
    return te


def test_streaming_tumble_sum():
    te = _stream_env(total=2000, n_keys=4)
    t = te.sql_query(
        "SELECT k, SUM(v) AS total FROM events "
        "GROUP BY k, TUMBLE(rowtime, INTERVAL '1' SECOND)"
    )
    # exact per-(key, window) sums
    exp = {}
    for i in range(2000):
        w = ((i * 2) // 1000 + 1) * 1000
        exp[(i % 4, w)] = exp.get((i % 4, w), 0.0) + float(i % 7)
    got = {}
    for k, wend, v in zip(t.cols["k"].tolist(),
                          t.cols["window_end_ms"].tolist(),
                          t.cols["total"].tolist()):
        got[(k, wend)] = got.get((k, wend), 0.0) + v
    assert got == exp


def test_streaming_hop_count():
    te = _stream_env(total=1000, n_keys=2)
    t = te.sql_query(
        "SELECT k, COUNT(v) AS n FROM events "
        "GROUP BY k, HOP(rowtime, INTERVAL '1' SECOND, "
        "INTERVAL '2' SECOND)"
    )
    # sliding 2s/1s windows: interior windows hold 2s of each key's
    # records = 500 per key
    interior = [
        n for k, wend, n in zip(t.cols["k"].tolist(),
                                t.cols["window_end_ms"].tolist(),
                                t.cols["n"].tolist())
        if 2000 <= wend <= 2000  # exactly covers [0, 2000)
    ]
    assert interior and all(n == 500 for n in interior)
    assert int(np.sum(t.cols["n"][t.cols["window_end_ms"] <= 2000])) > 0


def test_streaming_session_with_where():
    te = _stream_env(total=600, n_keys=3)
    t = te.sql_query(
        "SELECT k, SUM(v) AS total FROM events WHERE v > 0 "
        "GROUP BY k, SESSION(rowtime, INTERVAL '5' SECOND)"
    )
    # 2ms cadence << 5s gap: one session per key spanning everything
    assert len(t.cols["k"]) == 3
    assert set(t.cols["k"].tolist()) == {0, 1, 2}
    exp_total = sum(float(i % 7) for i in range(600) if i % 7 > 0)
    assert float(np.sum(t.cols["total"])) == exp_total
    assert "window_start_ms" in t.cols


def test_streaming_requires_window():
    te = _stream_env()
    try:
        te.sql_query("SELECT k, SUM(v) FROM events GROUP BY k")
    except ValueError as e:
        assert "TUMBLE" in str(e)
    else:
        raise AssertionError("window-less streaming GROUP BY must refuse")


def test_streaming_where_keeps_window_alignment():
    """Regression: WHERE used to shrink the columns while source-side
    timestamps kept pre-filter length, pairing surviving records with
    the wrong rows' times. Rowtime now derives from the named column
    post-filter, so per-window sums stay exact."""
    te = _stream_env(total=2000, n_keys=4)
    t = te.sql_query(
        "SELECT k, SUM(v) AS total FROM events WHERE k > 0 "
        "GROUP BY k, TUMBLE(rowtime, INTERVAL '1' SECOND)"
    )
    exp = {}
    for i in range(2000):
        if i % 4 > 0:
            w = ((i * 2) // 1000 + 1) * 1000
            exp[(i % 4, w)] = exp.get((i % 4, w), 0.0) + float(i % 7)
    got = {}
    for k, wend, v in zip(t.cols["k"].tolist(),
                          t.cols["window_end_ms"].tolist(),
                          t.cols["total"].tolist()):
        got[(k, wend)] = got.get((k, wend), 0.0) + v
    assert got == exp


def test_streaming_composite_group_key():
    """Multiple GROUP BY keys pack into tuple keys (object identities)."""
    te = _stream_env(total=800, n_keys=2)

    # add a second key column derived in the registered stream
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sources import GeneratorSource

    def build():
        env = StreamExecutionEnvironment(Configuration())
        env.set_parallelism(1)
        env.set_max_parallelism(8)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(256)
        env.batch_size = 128

        def gen(offset, n):
            idx = np.arange(offset, offset + n, dtype=np.int64)
            return ({
                "a": idx % 2,
                "b": idx % 3,
                "v": np.ones(n, np.float32),
                "rowtime": idx * 2,
            }, None)

        return env, env.add_source(GeneratorSource(gen, total=800))

    te = StreamTableEnvironment.create()
    te.register_stream("ev2", build)
    t = te.sql_query(
        "SELECT a, b, SUM(v) AS n FROM ev2 "
        "GROUP BY a, b, TUMBLE(rowtime, INTERVAL '2' SECOND)"
    )
    exp = {}
    for i in range(800):
        w = ((i * 2) // 2000 + 1) * 2000
        exp[(i % 2, i % 3, w)] = exp.get((i % 2, i % 3, w), 0.0) + 1.0
    got = {}
    for a, b, wend, n in zip(t.cols["a"].tolist(), t.cols["b"].tolist(),
                             t.cols["window_end_ms"].tolist(),
                             t.cols["n"].tolist()):
        got[(a, b, wend)] = got.get((a, b, wend), 0.0) + n
    assert got == exp


def test_sql_join_respects_on_qualifiers():
    """Regression: ON qualifiers used to be discarded — with clashing
    bare names the join silently paired the wrong columns."""
    te = TableEnvironment.create()
    te.register_table("l", te.from_columns({
        "id": [1, 2, 3], "ref": [30, 10, 20]}))
    te.register_table("r", te.from_columns({
        "id": [10, 20, 30], "ref": [9, 9, 9], "tag": ["a", "b", "c"]}))
    # join l.ref with r.id, stated right-side-first: qualifiers must win
    t = te.sql_query(
        "SELECT id, tag FROM l JOIN r ON r.id = l.ref ORDER BY id"
    )
    assert t.to_rows() == [(1, "c"), (2, "a"), (3, "b")]


# ----------------------------------------------------- round-4 SQL breadth
def _env2():
    from flink_tpu.table.table import TableEnvironment

    tenv = TableEnvironment.create()
    tenv.register_table("orders", tenv.from_columns({
        "id": [1, 2, 3, 4], "cust": [10, 20, 10, 30],
        "amount": [5.0, 15.0, 25.0, 40.0], "ts": [0, 61_000, 3_700_000, 90_000_000],
        "tag": ["Alpha", "beta", "Gamma", "beta"],
    }))
    tenv.register_table("customers", tenv.from_columns({
        "cust": [10, 20, 30], "tier": [1, 2, 3],
        "credit": [20.0, 10.0, 50.0],
    }))
    return tenv


def test_scalar_functions():
    tenv = _env2()
    t = tenv.sql_query(
        "SELECT id, ABS(amount - 20.0) AS dist, UPPER(tag) AS utag, "
        "LENGTH(tag) AS ln, POWER(tier, 2) AS t2 "
        "FROM orders JOIN customers ON orders.cust = customers.cust "
        "ORDER BY id"
    )
    rows = t.to_dicts()
    assert [r["dist"] for r in rows] == [15.0, 5.0, 5.0, 20.0]
    assert [r["utag"] for r in rows] == ["ALPHA", "BETA", "GAMMA", "BETA"]
    assert [r["ln"] for r in rows] == [5, 4, 5, 4]
    assert [r["t2"] for r in rows] == [1, 4, 1, 9]


def test_like_and_concat_and_substring():
    tenv = _env2()
    t = tenv.sql_query(
        "SELECT id, CONCAT(tag, '-', tag) AS dbl, SUBSTRING(tag, 1, 3) AS pre "
        "FROM orders WHERE tag LIKE '%eta' ORDER BY id"
    )
    rows = t.to_dicts()
    assert [r["id"] for r in rows] == [2, 4]
    assert rows[0]["dbl"] == "beta-beta" and rows[0]["pre"] == "bet"


def test_temporal_extract():
    tenv = _env2()
    t = tenv.sql_query(
        "SELECT id, EXTRACT(HOUR FROM ts) AS h, EXTRACT(DAY FROM ts) AS d "
        "FROM orders ORDER BY id"
    )
    rows = t.to_dicts()
    assert [r["h"] for r in rows] == [0, 0, 1, 1]   # 0ms, 61s, ~1.03h, ~25h
    assert [r["d"] for r in rows] == [1, 1, 1, 2]


def test_non_equi_join_residual():
    tenv = _env2()
    # equi conjunct + residual: only orders within the customer's credit
    t = tenv.sql_query(
        "SELECT id, amount, credit FROM orders "
        "JOIN customers ON orders.cust = customers.cust "
        "AND orders.amount < customers.credit ORDER BY id"
    )
    rows = t.to_dicts()
    assert [r["id"] for r in rows] == [1, 4]        # 5<20, 40<50


def test_pure_theta_join_nested_loop():
    tenv = _env2()
    t = tenv.sql_query(
        "SELECT id, tier FROM orders JOIN customers "
        "ON orders.amount > customers.credit ORDER BY id"
    )
    got = {(r["id"], r["tier"]) for r in t.to_dicts()}
    # amount > credit pairs: 15>10(t2), 25>20(t1), 25>10(t2), 40>20, 40>10
    assert got == {(2, 2), (3, 1), (3, 2), (4, 1), (4, 2)}


def test_if_expression():
    tenv = _env2()
    t = tenv.sql_query(
        "SELECT id, IF(amount > 20.0, 1, 0) AS big FROM orders ORDER BY id"
    )
    assert [r["big"] for r in t.to_dicts()] == [0, 0, 1, 1]


def test_explain_shows_plan_and_build_side():
    tenv = _env2()
    plan = tenv.explain(
        "SELECT id, SUM(amount) AS total FROM orders "
        "JOIN customers ON orders.cust = customers.cust "
        "AND orders.amount < customers.credit "
        "WHERE amount > 1.0 GROUP BY id ORDER BY id LIMIT 3"
    )
    assert "Physical Plan" in plan
    assert "Scan(orders, 4 rows" in plan
    assert "HashJoin" in plan and "build=right[3 rows]" in plan
    assert "residual=" in plan
    assert "Filter" in plan and "selectivity" in plan
    assert "HashAggregate" in plan and "Sort" in plan and "Limit(3)" in plan


def test_multi_key_equi_join():
    from flink_tpu.table.table import TableEnvironment

    tenv = TableEnvironment.create()
    tenv.register_table("a", tenv.from_columns({
        "k1": [1, 1, 2], "k2": [1, 2, 1], "v": [10.0, 20.0, 30.0],
    }))
    tenv.register_table("b", tenv.from_columns({
        "k1": [1, 2, 1], "k2": [2, 1, 9], "w": [1.0, 2.0, 3.0],
    }))
    t = tenv.sql_query(
        "SELECT v, w FROM a JOIN b ON a.k1 = b.k1 AND a.k2 = b.k2"
    )
    assert sorted(t.to_rows()) == [(20.0, 1.0), (30.0, 2.0)]


def test_non_equi_residual_with_decimal_literal():
    """Regression: a float literal in the ON residual must not be mangled
    by the qualified-ref rewrite (1.5 is not qual=1, name=5)."""
    tenv = _env2()
    t = tenv.sql_query(
        "SELECT id FROM orders JOIN customers "
        "ON orders.cust = customers.cust AND orders.amount > 14.5 "
        "ORDER BY id"
    )
    assert [r["id"] for r in t.to_dicts()] == [2, 3, 4]


def test_in_operator():
    tenv = _env2()
    t = tenv.sql_query(
        "SELECT id FROM orders WHERE cust IN (10, 30) ORDER BY id")
    got = [r["id"] for r in t.to_dicts()]
    t2 = tenv.sql_query(
        "SELECT id FROM orders WHERE cust NOT IN (10, 30) ORDER BY id")
    got2 = [r["id"] for r in t2.to_dicts()]
    all_ids = [r["id"] for r in tenv.sql_query(
        "SELECT id FROM orders ORDER BY id").to_dicts()]
    assert sorted(got + got2) == all_ids
    assert got and got2


def test_between_operator():
    tenv = _env2()
    t = tenv.sql_query(
        "SELECT id, amount FROM orders "
        "WHERE amount BETWEEN 20.0 AND 100.0 ORDER BY id")
    assert all(20.0 <= r["amount"] <= 100.0 for r in t.to_dicts())
    assert t.n > 0
    # BETWEEN's AND must not be severed by the conjunct splitter, and a
    # trailing real conjunct still splits
    t2 = tenv.sql_query(
        "SELECT id FROM orders "
        "WHERE amount BETWEEN 20.0 AND 100.0 AND cust = 10")
    ref = tenv.sql_query(
        "SELECT id FROM orders "
        "WHERE amount BETWEEN 20.0 AND 100.0 AND cust = 10",
        optimize=False)
    assert sorted(map(tuple, t2.to_rows())) == sorted(
        map(tuple, ref.to_rows()))


def test_between_compound_and_not_between():
    tenv = _env2()
    # arithmetic chain as the left operand bounds the whole expression
    t = tenv.sql_query(
        "SELECT id FROM orders "
        "WHERE amount + amount BETWEEN 40.0 AND 200.0 ORDER BY id")
    amounts = {r["id"]: r["amount"] for r in tenv.sql_query(
        "SELECT id, amount FROM orders").to_dicts()}
    expect = sorted(i for i, a in amounts.items() if 40.0 <= 2 * a <= 200.0)
    assert [r["id"] for r in t.to_dicts()] == expect
    # NOT BETWEEN is the complement
    t2 = tenv.sql_query(
        "SELECT id FROM orders "
        "WHERE amount NOT BETWEEN 20.0 AND 100.0 ORDER BY id")
    expect2 = sorted(i for i, a in amounts.items()
                     if not (20.0 <= a <= 100.0))
    assert [r["id"] for r in t2.to_dicts()] == expect2


def test_single_element_in_list():
    tenv = _env2()
    t = tenv.sql_query("SELECT id FROM orders WHERE cust IN (10)")
    ref = tenv.sql_query("SELECT id FROM orders WHERE cust = 10")
    assert sorted(t.to_rows()) == sorted(ref.to_rows())


def test_case_when_searched():
    t = _tenv().sql_query(
        "SELECT oid, CASE WHEN amount > 10 THEN 'big' "
        "WHEN amount > 6 THEN 'mid' ELSE 'small' END AS bucket "
        "FROM orders ORDER BY oid"
    )
    assert t.to_rows() == [
        (1, "small"), (2, "mid"), (3, "big"), (4, "big"),
    ]


def test_case_when_simple_form_and_where():
    t = _tenv().sql_query(
        "SELECT oid, CASE cust WHEN 10 THEN 1 WHEN 20 THEN 2 ELSE 0 END "
        "AS code FROM orders "
        "WHERE CASE WHEN amount > 6 THEN 1 ELSE 0 END = 1 ORDER BY oid"
    )
    assert t.to_rows() == [(2, 2), (3, 1), (4, 0)]


def test_case_requires_else():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="ELSE"):
        _tenv().sql_query(
            "SELECT CASE WHEN amount > 6 THEN 1 END AS x FROM orders"
        )


def test_nested_case():
    t = _tenv().sql_query(
        "SELECT oid, CASE WHEN amount > 6 THEN "
        "CASE WHEN amount > 10 THEN 'big' ELSE 'mid' END "
        "ELSE 'small' END AS bucket FROM orders ORDER BY oid"
    )
    assert t.to_rows() == [
        (1, "small"), (2, "mid"), (3, "big"), (4, "big"),
    ]


def test_select_distinct():
    t = _tenv().sql_query("SELECT DISTINCT cust FROM orders")
    assert sorted(t.to_rows()) == [(10,), (20,), (30,)]


def test_union_all_and_union():
    te = _tenv()
    t = te.sql_query(
        "SELECT cust FROM orders WHERE amount > 6 "
        "UNION ALL SELECT cust FROM customers"
    )
    assert sorted(t.to_rows()) == [
        (10,), (10,), (20,), (20,), (30,), (40,),
    ]
    t2 = te.sql_query(
        "SELECT cust FROM orders WHERE amount > 6 "
        "UNION SELECT cust FROM customers"
    )
    assert sorted(t2.to_rows()) == [(10,), (20,), (30,), (40,)]


def test_union_schema_mismatch_rejected():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="same columns"):
        _tenv().sql_query(
            "SELECT cust FROM orders UNION ALL "
            "SELECT region FROM customers"
        )


def test_union_keyword_inside_literal_does_not_split():
    te = _tenv()
    t = te.sql_query(
        "SELECT oid, 'credit UNION ALL debit' AS note FROM orders "
        "WHERE oid = 1"
    )
    assert t.to_rows() == [(1, "credit UNION ALL debit")]


def test_explain_union_and_distinct():
    te = _tenv()
    plan = te.explain(
        "SELECT DISTINCT cust FROM orders UNION "
        "SELECT cust FROM customers"
    )
    assert "== UNION DISTINCT ==" in plan
    assert "Distinct(first occurrence)" in plan
    assert plan.count("== Physical Plan ==") == 2
    # explain runs the SAME schema checks as sql_query: a union that
    # cannot execute must not get a plan
    import pytest as _pytest

    with _pytest.raises(ValueError, match="same columns"):
        te.explain(
            "SELECT cust FROM orders UNION ALL "
            "SELECT region FROM customers"
        )


def test_union_trailing_order_and_limit_apply_to_whole_union():
    te = _tenv()
    t = te.sql_query(
        "SELECT cust FROM orders WHERE amount > 6 "
        "UNION ALL SELECT cust FROM customers ORDER BY cust DESC LIMIT 3"
    )
    assert t.to_rows() == [(40,), (30,), (20,)]


def test_distinct_dedupes_before_limit():
    # orders.cust = [10, 20, 10, 30]: SQL takes 3 DISTINCT values, not
    # the distinct values of the first 3 rows
    t = _tenv().sql_query("SELECT DISTINCT cust FROM orders LIMIT 3")
    assert sorted(t.to_rows()) == [(10,), (20,), (30,)]
    t2 = _tenv().sql_query(
        "SELECT DISTINCT cust FROM orders ORDER BY cust DESC LIMIT 2"
    )
    assert t2.to_rows() == [(30,), (20,)]


def test_trailing_clause_inside_literal_not_stripped():
    """ADVICE r5: _strip_trailing is literal-aware — a trailing string
    literal containing 'ORDER BY x' is a VALUE, not a clause, and must
    not be stripped (the old behavior cut the branch mid-literal)."""
    te = _tenv()
    t = te.sql_query(
        "SELECT DISTINCT region FROM customers "
        "WHERE region = 'eu ORDER BY cust'"
    )
    assert t.to_rows() == []      # no such region; branch NOT corrupted
    # a REAL trailing LIMIT still strips with a literal elsewhere
    t2 = te.sql_query(
        "SELECT DISTINCT oid, 'x LIMIT 5' AS tag FROM orders LIMIT 2"
    )
    rows2 = t2.to_rows()
    assert len(rows2) == 2 and all(r[1] == "x LIMIT 5" for r in rows2)
    # CASE/END inside a literal never feeds the CASE rewriter
    t3 = te.sql_query(
        "SELECT oid, 'CASE WHEN END' AS c FROM orders WHERE oid = 1"
    )
    assert t3.to_rows() == [(1, "CASE WHEN END")]


def test_union_dtype_mismatch_rejected():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="mixes string and numeric"):
        _tenv().sql_query(
            "SELECT region AS x FROM customers UNION ALL "
            "SELECT cust AS x FROM orders"
        )
