"""MiniCluster job management, savepoints via control channel, web monitor,
metrics, CLI (ref SURVEY §2.2 JobManager registry, §2.9 CLI/web)."""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.metrics import Histogram, Meter, MetricRegistry
from flink_tpu.runtime.cluster import MiniCluster, control_request
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource


def _slow_infinite_env(batch=32):
    """An unbounded generator job (columnar window sum) for lifecycle tests."""
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = batch
    env.set_state_capacity(4096)

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        time.sleep(0.005)  # throttle so control requests interleave
        cols = {"key": idx % 50, "value": np.ones(n, np.float32)}
        return cols, (idx * 10).astype(np.int64)

    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen))          # infinite
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    return env, sink


def test_cancel_running_job():
    env, _ = _slow_infinite_env()
    cluster = MiniCluster()
    jid = cluster.submit(env, "infinite")
    time.sleep(0.5)
    assert cluster.jobs[jid].status == "RUNNING"
    cluster.cancel(jid)
    assert cluster.wait(jid, 30) == "CANCELED"


def test_savepoint_and_resume(tmp_path):
    env, sink = _slow_infinite_env()
    cluster = MiniCluster()
    jid = cluster.submit(env, "sp-job")
    time.sleep(1.0)
    sp_path = cluster.trigger_savepoint(jid, str(tmp_path / "sp"))
    assert sp_path
    cluster.cancel(jid)
    cluster.wait(jid, 30)
    records_before = env.last_job is None

    # resume a FINITE continuation from the savepoint
    env2 = StreamExecutionEnvironment.get_execution_environment()
    env2.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env2.batch_size = 32
    env2.set_state_capacity(4096)

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {"key": idx % 50, "value": np.ones(n, np.float32)}
        return cols, (idx * 10).astype(np.int64)

    sink2 = CollectSink()
    (
        env2.add_source(GeneratorSource(gen, total=2000))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink2)
    )
    env2.execute("resumed", restore_from=str(tmp_path / "sp"))
    # total across all fires == total records (2000): nothing lost or
    # double-counted despite the mid-stream cut. Windows that fired in
    # phase 1 BEFORE the savepoint live in phase 1's sink (how many
    # depends on how far the slow source got in 1s — load-dependent),
    # and phase 2 re-fires corrected versions of anything after the
    # cut, so merge with phase 2 overriding (the test_rescale pattern).
    # The savepoint cut is load-dependent: phase 1 keeps running between
    # the savepoint and the cancel, and on a fast box it outruns record
    # 2000 BEFORE the savepoint lands. Windows past the replay horizon
    # (2000 records x 10ms = window ends through 20000) are then outside
    # the claim on BOTH sides — phase 1 fires complete windows past it,
    # and phase 2 (whose rewound source has nothing left to generate)
    # still fires the pending partial tail window restored in savepoint
    # state. Bound both sinks to the horizon, then assert the exact
    # per-cell expectation: every (key, window) counted exactly once,
    # nothing lost, nothing double-applied.
    got1 = {(r.key, r.window_end_ms): r.value for r in sink.results
            if r.window_end_ms <= 20_000}
    got2_all = {(r.key, r.window_end_ms): r.value for r in sink2.results}
    assert got2_all, "resumed job re-fired nothing past the savepoint cut"
    got2 = {k: v for k, v in got2_all.items() if k[1] <= 20_000}
    merged = {**got1, **got2}
    expected = {(k, w): 2.0 for k in range(50)
                for w in range(1000, 20_001, 1000)}
    odd = {k: v for k, v in merged.items() if v != expected.get(k)}
    assert merged == expected, (
        f"sum={sum(merged.values())} cells={len(merged)} "
        f"odd_cells={sorted(odd.items())[:20]} "
        f"missing={sorted(set(expected) - set(merged))[:20]} "
        f"len1={len(got1)} len2={len(got2)} raw1={len(sink.results)} "
        f"raw2={len(sink2.results)}"
    )


def test_control_server_and_cli_protocol():
    env, _ = _slow_infinite_env()
    cluster = MiniCluster()
    port = cluster.start_control_server()
    try:
        jid = cluster.submit(env, "ctl-job")
        time.sleep(0.3)
        resp = control_request("127.0.0.1", port, {"action": "list"})
        assert resp["ok"]
        assert any(j["jid"] == jid for j in resp["jobs"])
        resp = control_request("127.0.0.1", port,
                               {"action": "info", "job_id": jid})
        assert resp["job"]["state"] == "RUNNING"
        resp = control_request("127.0.0.1", port,
                               {"action": "cancel", "job_id": jid})
        assert resp["ok"]
        assert cluster.wait(jid, 30) == "CANCELED"
    finally:
        cluster.stop_control_server()


def test_cli_main_list(capsys):
    env, _ = _slow_infinite_env()
    cluster = MiniCluster()
    port = cluster.start_control_server()
    try:
        jid = cluster.submit(env, "cli-job")
        time.sleep(0.2)
        from flink_tpu.cli import main

        rc = main(["list", "-m", f"127.0.0.1:{port}"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert any(j["jid"] == jid for j in out["jobs"])
        cluster.cancel(jid)
        cluster.wait(jid, 30)
    finally:
        cluster.stop_control_server()


def test_web_monitor_endpoints():
    from flink_tpu.runtime.web import WebMonitor

    env, _ = _slow_infinite_env()
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "web-job")
    try:
        time.sleep(0.8)

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return json.loads(r.read())

        ov = get("/overview")
        assert ov["jobs-running"] >= 1
        jobs = get("/jobs")["jobs"]
        assert any(j["jid"] == jid for j in jobs)
        detail = get(f"/jobs/{jid}")
        assert detail["state"] == "RUNNING"
        assert detail["metrics"]["records_in"] > 0
        bp = get(f"/jobs/{jid}/backpressure")
        assert bp["backpressure-level"] in ("ok", "low", "high")
        # cause attribution (BackPressureStatsTracker analog): measured
        # per-phase decomposition, not just cycle-time percentiles
        attr = bp["attribution"]
        assert attr["classification"] in (
            "ok", "source-starved", "host-bound", "device-bound",
            "sink-bound",
        )
        assert set(attr["phase-ewma-ms"]) == {
            "source", "host", "dispatch", "emit"
        }
        # counts may still be 0 here (first cycle compiles); the
        # completed-job counts are asserted in test_backpressure.py
        snap = get(f"/jobs/{jid}/metrics")
        assert any(k.endswith("records_in") for k in snap)
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)
        web.stop()


def test_metric_types():
    reg = MetricRegistry()
    grp = reg.group("tm", "job").add_group("op")
    c = grp.counter("records")
    c.inc(5)
    g = grp.gauge("watermark", lambda: 42)
    h = grp.histogram("lat")
    for v in range(100):
        h.update(v)
    m = grp.meter("rate")
    m.mark_event(10)
    snap = reg.snapshot()
    assert snap["tm.job.op.records"] == 5
    assert snap["tm.job.op.watermark"] == 42
    assert snap["tm.job.op.lat"]["p99"] >= 98
    assert snap["tm.job.op.rate"]["count"] == 10
    # prefix filtering (metric query service)
    assert set(reg.snapshot("tm.job.op.rec")) == {"tm.job.op.records"}


def test_job_metrics_gauges_registered_on_execute():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    sink = CollectSink()
    env.from_collection([1, 2, 3]).map(lambda x: x).add_sink(sink)
    env.execute("metered")
    snap = env.metric_registry.snapshot("jobs.metered")
    assert snap["jobs.metered.records_in"] == 3
    assert snap["jobs.metered.records_out"] == 3


def test_web_checkpoint_stats_and_dashboard(tmp_path):
    """/jobs/<jid>/checkpoints serves the CheckpointStatsTracker-analog
    history (id/duration/bytes/entries + summary), and /web serves the
    HTML dashboard page."""
    import urllib.request

    from flink_tpu.runtime.web import WebMonitor

    import numpy as np

    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import GeneratorSource

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return {"key": idx % 8, "value": np.ones(n, np.float32)}, idx // 8

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(64)
    env.batch_size = 32
    env.checkpoint_dir = str(tmp_path / "ck")
    env.checkpoint_interval_steps = 4
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=32 * 12))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "ck-web-job")
    try:
        assert cluster.wait(jid, 120) == "FINISHED"

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return r.read()

        ck = json.loads(get(f"/jobs/{jid}/checkpoints"))
        assert ck["counts"]["completed"] >= 2
        h = ck["history"][-1]
        assert h["bytes"] > 0 and h["duration_ms"] > 0 and h["entries"] > 0
        assert ck["summary"]["state-size-bytes"]["max"] >= h["bytes"]

        page = get("/web").decode()
        assert "<html" in page and "flink-tpu" in page
        assert "/jobs/" in page          # the page drives the JSON routes
    finally:
        web.stop()


def test_web_plan_exceptions_config_routes():
    """ref JobPlanHandler / JobExceptionsHandler / JobManagerConfigHandler."""
    from flink_tpu.runtime.web import WebMonitor

    env, _ = _slow_infinite_env()
    env.config.set("taskmanager.test-knob", "42")
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "plan-job")
    try:
        time.sleep(0.5)

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return json.loads(r.read())

        plan = get(f"/jobs/{jid}/plan")["plan"]["nodes"]
        types = [n["type"] for n in plan]
        assert "Source" in types and "Sink" in types
        # the DAG is topologically emitted: every input precedes its node
        pos = {n["id"]: i for i, n in enumerate(plan)}
        for n in plan:
            assert all(pos[i] < pos[n["id"]] for i in n["inputs"])

        exc = get(f"/jobs/{jid}/exceptions")
        assert exc["root-exception"] is None

        cfg = get("/config")
        assert {"key": "taskmanager.test-knob", "value": "42"} in cfg
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)
        web.stop()


def test_web_round4_handler_breadth():
    """ref CurrentJobsOverviewHandler / TaskManagersHandler /
    JobDetailsHandler vertices / JobAccumulatorsHandler / JobConfigHandler."""
    from flink_tpu.runtime.web import WebMonitor

    env, _ = _slow_infinite_env()
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "breadth-job")
    try:
        time.sleep(0.5)

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return json.loads(r.read())

        ov = get("/joboverview")
        assert any(j["jid"] == jid for j in ov["running"])
        assert get("/joboverview/running")["jobs"]
        assert get("/joboverview/completed")["jobs"] == [
            j for j in get("/jobs")["jobs"] if j["state"] != "RUNNING"
        ]

        tms = get("/taskmanagers")["taskmanagers"]
        assert len(tms) == 1 and tms[0]["slotsNumber"] == 8
        assert get("/taskmanagers/tm-local")["id"] == "tm-local"
        try:
            get("/taskmanagers/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404

        verts = get(f"/jobs/{jid}/vertices")
        assert {n["type"] for n in verts["vertices"]} >= {"Source", "Sink"}

        acc = get(f"/jobs/{jid}/accumulators")
        assert "user-task-accumulators" in acc

        jcfg = get(f"/jobs/{jid}/config")["execution-config"]
        assert jcfg["job-parallelism"] >= 1
        assert "user-config" in jcfg
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)
        web.stop()


def test_web_subtask_and_checkpoint_detail_routes(tmp_path):
    """Round-5 REST breadth: per-vertex subtask endpoints + checkpoint
    config/details (ref JobVertexDetailsHandler, SubtasksTimesHandler,
    SubtaskCurrentAttemptDetailsHandler, CheckpointConfigHandler,
    CheckpointStatsDetailsHandler)."""
    from flink_tpu.runtime.web import WebMonitor

    env, _ = _slow_infinite_env()
    env.enable_checkpointing(interval_steps=2, directory=str(tmp_path))
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "subtask-routes-job")
    try:
        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return json.loads(r.read())

        def get_code(path):
            import urllib.error
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10
                ) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code

        time.sleep(1.2)
        vx = get(f"/jobs/{jid}/vertices")["vertices"]
        assert vx
        vid = vx[0]["id"]
        # vertex detail: one row per subtask
        vd = get(f"/jobs/{jid}/vertices/{vid}")
        assert vd["name"] and len(vd["subtasks"]) == vd["parallelism"]
        row = vd["subtasks"][0]
        assert {"subtask", "status", "attempt", "host",
                "start-time"} <= set(row)
        assert get(f"/jobs/{jid}/vertices/{vid}/subtasks") == vd
        # subtask times: per-state timestamps
        st = get(f"/jobs/{jid}/vertices/{vid}/subtasktimes")
        assert st["subtasks"][0]["timestamps"].get("CREATED", 0) > 0
        # one subtask's current attempt + addressable attempt history
        s0 = get(f"/jobs/{jid}/vertices/{vid}/subtasks/0")
        assert s0["attempt"] >= 1 and "state-times" in s0
        assert s0["prior-attempts"] == []
        a1 = get(f"/jobs/{jid}/vertices/{vid}/subtasks/0/attempts/1")
        assert a1["attempt"] == 1
        assert get_code(
            f"/jobs/{jid}/vertices/{vid}/subtasks/0/attempts/99") == 404
        assert get_code(f"/jobs/{jid}/vertices/{vid}/subtasks/99") == 404
        assert get_code(f"/jobs/{jid}/vertices/9999") == 404
        # checkpoint config
        cc = get(f"/jobs/{jid}/checkpoints/config")
        assert cc["mode"] == "exactly_once"
        assert cc["interval-steps"] == 2
        assert cc["directory"] == str(tmp_path)
        # checkpoint details for a real completed checkpoint
        deadline = time.time() + 60
        hist = []
        while time.time() < deadline:
            hist = get(f"/jobs/{jid}/checkpoints").get("history", [])
            if hist:
                break
            time.sleep(0.3)
        assert hist, "no checkpoint completed in time"
        cid = hist[-1]["id"]
        cd = get(f"/jobs/{jid}/checkpoints/details/{cid}")
        assert cd["id"] == cid and cd["status"] == "COMPLETED"
        assert cd["duration-ms"] >= 0 and "fused-stage" in cd
        assert cd["tasks"]       # per-operator rows
        assert get_code(f"/jobs/{jid}/checkpoints/details/999999") == 404
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)
        web.stop()


def test_http_job_submission(tmp_path):
    """Round-5 /jars routes (ref JarUploadHandler/JarRunHandler): upload
    a program over HTTP, run it, watch it finish, delete it."""
    from flink_tpu.runtime.web import WebMonitor

    program = '''
import numpy as np
from flink_tpu import StreamExecutionEnvironment
from flink_tpu.connectors.files import BucketingFileSink

OUT = {out!r}

def build():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(1)
    env.set_state_capacity(256)
    env.batch_size = 64
    (
        env.from_collection([(i % 3, 1.0) for i in range(300)])
        .key_by(lambda e: e[0])
        .sum(lambda e: e[1])
        .filter(lambda kv: kv[1] == 100.0)     # final count per key
        .map(lambda kv: f"{{kv[0]}}:{{int(kv[1])}}")
        .add_sink(BucketingFileSink(OUT, formatter=str))
    )
    return env
'''.format(out=str(tmp_path / "out"))

    import urllib.error

    cluster = MiniCluster()
    web = WebMonitor(cluster, jar_dir=str(tmp_path / "jars"))
    port = web.start()
    try:
        def post(path, body=b""):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=body, method="POST"
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        up = post("/jars/upload?name=wordcount.py", program.encode())
        assert up["status"] == "success"
        pid = up["id"]
        listing = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/jars", timeout=10).read())
        assert any(j["id"] == pid for j in listing["files"])

        run = post(f"/jars/{pid}/run?entry=build&job-name=http-job")
        jid = run["jobid"]
        assert cluster.wait(jid, 120) == "FINISHED"
        import glob
        lines = []
        for p in glob.glob(str(tmp_path / "out" / "**" / "part-0"),
                           recursive=True):
            lines += open(p).read().splitlines()
        assert sorted(lines) == ["0:100", "1:100", "2:100"]

        # delete + 404 afterwards
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/jars/{pid}", method="DELETE")
        assert json.loads(urllib.request.urlopen(
            req, timeout=10).read())["status"] == "success"
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(f"/jars/{pid}/run")
        assert ei.value.code == 404
    finally:
        web.stop()


def test_http_submission_requires_token(tmp_path):
    from flink_tpu.core.config import Configuration
    from flink_tpu.runtime.web import WebMonitor
    import urllib.error

    cluster = MiniCluster()
    web = WebMonitor(cluster, config=Configuration(
        {"security.auth.token": "subtok"}))
    port = web.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/jars/upload", data=b"x = 1",
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 401
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{port}/jars/upload?token=subtok",
            data=b"x = 1", method="POST")
        assert json.loads(urllib.request.urlopen(
            req2, timeout=10).read())["status"] == "success"
    finally:
        web.stop()


def test_http_job_cancellation():
    """Round-5 cancel/stop REST handlers (ref JobCancellationHandler)."""
    from flink_tpu.runtime.web import WebMonitor
    import urllib.error

    env, _ = _slow_infinite_env()
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "cancel-me")
    try:
        time.sleep(0.8)

        def post(path):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=b"",
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read())

        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/jobs/nope/cancel")
        assert ei.value.code == 404

        code, body = post(f"/jobs/{jid}/cancel")
        assert code == 202 and "cancel" in body["status"]
        assert cluster.wait(jid, 60) in ("CANCELED", "FINISHED")
    finally:
        web.stop()


def test_http_job_delete_cancels():
    from flink_tpu.runtime.web import WebMonitor

    env, _ = _slow_infinite_env()
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "delete-me")
    try:
        time.sleep(0.8)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/jobs/{jid}", method="DELETE")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 202
        assert cluster.wait(jid, 60) in ("CANCELED", "FINISHED")
    finally:
        web.stop()


def test_http_savepoint_and_vertex_metrics(tmp_path):
    """POST /jobs/<jid>/savepoints triggers a live savepoint; per-vertex
    metrics route serves the job snapshot with explicit attribution."""
    from flink_tpu.runtime.web import WebMonitor

    env, _ = _slow_infinite_env()
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "sp-http")
    try:
        time.sleep(1.0)

        def post(path):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=b"",
                method="POST")
            with urllib.request.urlopen(req, timeout=180) as r:
                return r.status, json.loads(r.read())

        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(f"/jobs/{jid}/savepoints")       # missing target
        assert ei.value.code == 400
        code, body = post(
            f"/jobs/{jid}/savepoints?target-directory={tmp_path}/sp")
        assert code == 200 and body["savepoint-path"]
        import os
        assert os.path.isdir(body["savepoint-path"])

        vx = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/jobs/{jid}/vertices",
            timeout=10).read())["vertices"]
        vm = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/jobs/{jid}/vertices/"
            f"{vx[0]['id']}/metrics", timeout=10).read())
        assert "attribution" in vm and isinstance(vm["metrics"], dict)
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)
        web.stop()


def test_dashboard_html_integrity():
    """The /web dashboard is hand-edited JS with no browser in CI: lock
    in structural integrity — balanced delimiters, every fetched element
    id present in the HTML, and the script's static fetch paths served
    by the router."""
    import re as _re

    from flink_tpu.runtime.web import _DASHBOARD_HTML, WebMonitor

    m = _re.search(r"<script>(.*?)</script>", _DASHBOARD_HTML, _re.S)
    js = m.group(1)
    for pair in ["()", "{}", "[]"]:
        assert js.count(pair[0]) == js.count(pair[1]), pair
    ids_used = set(_re.findall(r'getElementById\("(\w+)"\)', js))
    ids_defined = set(_re.findall(r'id="(\w+)"', _DASHBOARD_HTML))
    assert ids_used <= ids_defined, ids_used - ids_defined

    # static fetch paths (no JS-variable segments) must resolve; the
    # dynamic /jobs/<sel>/... paths are covered by the live-route tests
    web = WebMonitor(MiniCluster())
    web.start()   # stop() blocks unless serve_forever is running
    try:
        for path in set(_re.findall(r'J\("(/[^"]*)"\)', js)):
            assert web._route(path) is not None, path
    finally:
        web.stop()


def test_web_vertex_scoped_and_jar_plan_routes(tmp_path):
    """Round-5 handler-set completion: vertex accumulators, subtask
    accumulators, vertex taskmanagers, vertex checkpoints, jar dry-run
    plan, cancel-with-savepoint (ref JobVertexAccumulatorsHandler,
    SubtasksAllAccumulatorsHandler, JobVertexTaskManagersHandler,
    JobVertexCheckpointsHandler, JarPlanHandler,
    JobCancellationWithSavepointHandlers)."""
    import urllib.error

    from flink_tpu.runtime.web import WebMonitor

    env, _ = _slow_infinite_env()
    env.enable_checkpointing(interval_steps=2, directory=str(tmp_path))
    cluster = MiniCluster()
    web = WebMonitor(cluster, jar_dir=str(tmp_path / "jars"))
    port = web.start()
    jid = cluster.submit(env, "vertex-routes-job")
    try:
        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return json.loads(r.read())

        def post(path, body=b""):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=body,
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())

        time.sleep(1.2)
        vx = get(f"/jobs/{jid}/vertices")["vertices"]
        vid = vx[0]["id"]
        va = get(f"/jobs/{jid}/vertices/{vid}/accumulators")
        assert va["id"] == vid and "user-accumulators" in va
        sa = get(f"/jobs/{jid}/vertices/{vid}/subtasks/accumulators")
        assert len(sa["subtasks"]) == sa["parallelism"]
        assert sa["subtasks"][0]["host"] == "tm-local"
        tm = get(f"/jobs/{jid}/vertices/{vid}/taskmanagers")
        assert tm["taskmanagers"][0]["host"] == "tm-local"
        assert tm["taskmanagers"][0]["subtasks"] >= 1
        assert sum(tm["taskmanagers"][0]["status-counts"].values()) \
            == tm["taskmanagers"][0]["subtasks"]
        vc = get(f"/jobs/{jid}/vertices/{vid}/checkpoints")
        assert vc["id"] == vid and "checkpoints" in vc

        # jar dry-run plan: the DAG without a submission
        program = (
            "from flink_tpu import StreamExecutionEnvironment\n"
            "from flink_tpu.runtime.sinks import DiscardingSink\n"
            "def build():\n"
            "    env = StreamExecutionEnvironment"
            ".get_execution_environment()\n"
            "    env.from_collection([1, 2, 3])"
            ".map(lambda x: x).add_sink(DiscardingSink())\n"
            "    return env\n"
        )
        _, up = post("/jars/upload?name=planonly.py", program.encode())
        plan = get(f"/jars/{up['id']}/plan")
        types = {n["type"] for n in plan["plan"]["nodes"]}
        assert {"Source", "Sink"} <= types
        assert get(f"/jobs/{jid}").get("state") == "RUNNING"  # no submit

        # cancel-with-savepoint: path returned, job cancels
        code, body = post(
            f"/jobs/{jid}/cancel-with-savepoint"
            f"?target-directory={tmp_path / 'sp'}")
        assert code == 200 and body["savepoint-path"]
        assert os.path.isdir(body["savepoint-path"])
        cluster.wait(jid, 30)
        assert cluster.jobs[jid].status in ("CANCELED", "FINISHED")
    finally:
        try:
            cluster.cancel(jid)
            cluster.wait(jid, 30)
        except Exception:
            pass
        web.stop()
