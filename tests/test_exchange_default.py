"""The ICI record exchange is the DEFAULT multi-device path (VERDICT r2
item 4): exchange.mode=auto resolves to all_to_all whenever the mesh has
more than one device, with batch auto-padding; replicate-and-mask remains
an explicit fallback. Plus direct 8-shard equivalence for the session and
count window kernels (shard-boundary bugs the e2e sums can mask).

Ref: KeyGroupStreamPartitioner.java:53, RecordWriter.java:82.
"""

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource


def _run_job(total, n_keys, B, cfg=None, parallelism=8):
    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        # spread keys over the full 64-bit space so every shard owns some
        return ({"key": (idx % n_keys) * 2_654_435_761,
                 "value": np.ones(n, np.float32)}, idx // 16)

    # factor 4: at toy batch sizes (B/n = 12 lanes/shard) natural key-count
    # variance overflows the default 2x bucket bound that large batches
    # stay well inside
    conf = {"exchange.capacity-factor": 4.0}
    conf.update(cfg or {})
    env = StreamExecutionEnvironment(Configuration(conf))
    env.set_parallelism(parallelism)
    env.set_max_parallelism(32)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(512)
    env.batch_size = B
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(100)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("exchange-default")
    got = {}
    for r in sink.results:
        got[(r.key, r.window_end_ms)] = got.get((r.key, r.window_end_ms),
                                                0) + r.value
    return job, got


def test_default_config_multi_device_uses_all_to_all():
    total, n_keys, B = 96 * 20, 37, 96
    job, got = _run_job(total, n_keys, B)
    assert job.metrics.exchange_mode == "adaptive"
    assert job.metrics.steps_exchanged > 0, (
        "balanced batches never took the ICI exchange"
    )
    exp = {}
    for i in range(total):
        k = (i % n_keys) * 2_654_435_761
        w = ((i // 16) // 100 + 1) * 100
        exp[(k, w)] = exp.get((k, w), 0) + 1.0
    assert got == exp
    assert job.metrics.dropped_capacity == 0


def test_auto_pads_batch_not_divisible_by_shards():
    # B=100 is not divisible by 8 shards: the step pads to 104 lanes
    total, n_keys, B = 100 * 12, 23, 100
    job, got = _run_job(total, n_keys, B)
    assert job.metrics.exchange_mode == "adaptive"
    assert job.metrics.steps_exchanged > 0
    assert sum(got.values()) == total


def test_skewed_batches_fall_back_to_mask_without_loss():
    """One hot key: every lane routes to a single shard, overflowing the
    exchange's static per-shard bucket — the adaptive default must take
    the mask step for those batches and lose NOTHING."""
    total, B = 96 * 10, 96
    job, got = _run_job(total, n_keys=1, B=B)
    assert job.metrics.exchange_mode == "adaptive"
    assert job.metrics.steps_exchanged == 0, (
        "a fully-skewed batch must not take the bounded-bucket exchange"
    )
    assert sum(got.values()) == total
    assert job.metrics.dropped_capacity == 0


def test_mask_remains_explicit_fallback():
    job, got = _run_job(96 * 6, 11, 96, cfg={"exchange.mode": "mask"})
    assert job.metrics.exchange_mode == "mask"
    assert sum(got.values()) == 96 * 6


def test_exchange_equals_mask_results():
    total, n_keys, B = 96 * 15, 29, 96
    _, got_ex = _run_job(total, n_keys, B)
    _, got_mask = _run_job(total, n_keys, B, cfg={"exchange.mode": "mask"})
    assert got_ex == got_mask


# ---------------------------------------------------- 8-shard kernel parity

def _split64(k64):
    k = np.asarray(k64, np.uint64)
    return ((k >> np.uint64(32)).astype(np.uint32),
            (k & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def test_session_kernel_8_shard_equivalence():
    """build_session_step at 8 shards emits exactly the same merged
    sessions as at 1 shard (shard-boundary / key-group ownership parity)."""
    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        SessionStageSpec, build_session_step, init_session_state,
    )

    rng = np.random.default_rng(7)
    B = 64
    keys = rng.integers(0, 13, B * 3).astype(np.uint64) * np.uint64(
        0x9E3779B97F4A7C15
    )
    ts = np.sort(rng.integers(0, 4000, B * 3)).astype(np.int32)
    vals = rng.random(B * 3).astype(np.float32)

    def run(n_shards):
        ctx = MeshContext.create(n_shards, 32)
        spec = SessionStageSpec(
            red=wk.ReduceSpec(kind="sum"), gap_ticks=150,
            capacity_per_shard=256,
        )
        st = init_session_state(ctx, spec)
        step = build_session_step(ctx, spec)
        emitted = []

        def collect(st, old_f, mid_f, wm_f):
            # mirror the executor's session emit: old/mid fires carry
            # their own keys; watermark-close fires key via the table
            tkeys = np.asarray(st.table.keys)
            for fire in (old_f, mid_f):
                khi, klo, f_s, f_e, f_v, f_m = map(np.asarray, fire)
                for sh in range(khi.shape[0]):
                    for i in np.nonzero(f_m[sh])[0]:
                        emitted.append((
                            int(khi[sh, i]), int(klo[sh, i]),
                            int(f_s[sh, i]), int(f_e[sh, i]),
                            round(float(f_v[sh, i]), 4),
                        ))
            w_s, w_e, w_v, w_m = map(np.asarray, wm_f)
            for sh in range(w_m.shape[0]):
                for i in np.nonzero(w_m[sh])[0]:
                    emitted.append((
                        int(tkeys[sh, i, 0]), int(tkeys[sh, i, 1]),
                        int(w_s[sh, i]), int(w_e[sh, i]),
                        round(float(w_v[sh, i]), 4),
                    ))

        for c in range(3):
            sl = slice(c * B, (c + 1) * B)
            hi, lo = _split64(keys[sl])
            wm = np.full((n_shards,), np.int32(int(ts[sl].max())))
            st, old_f, mid_f, wm_f = step(
                st, jnp.asarray(hi), jnp.asarray(lo),
                jnp.asarray(ts[sl]), jnp.asarray(vals[sl]),
                jnp.ones(B, bool), wm,
            )
            collect(st, old_f, mid_f, wm_f)
        # final drain at max watermark
        wm = np.full((n_shards,), np.int32(2**31 - 4))
        st, old_f, mid_f, wm_f = step(
            st, jnp.zeros(B, jnp.uint32), jnp.zeros(B, jnp.uint32),
            jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.float32),
            jnp.zeros(B, bool), wm,
        )
        collect(st, old_f, mid_f, wm_f)
        return sorted(emitted)

    assert run(8) == run(1)


def test_count_kernel_8_shard_equivalence():
    """build_count_step at 8 shards emits the same completed count
    windows as at 1 shard."""
    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        CountStageSpec, build_count_step, init_count_state,
    )

    rng = np.random.default_rng(11)
    B = 64
    keys = rng.integers(0, 9, B * 4).astype(np.uint64) * np.uint64(
        0x9E3779B97F4A7C15
    )
    vals = rng.random(B * 4).astype(np.float32)

    def run(n_shards):
        ctx = MeshContext.create(n_shards, 32)
        spec = CountStageSpec(
            red=wk.ReduceSpec(kind="sum"), n_per_window=5,
            capacity_per_shard=128,
        )
        st = init_count_state(ctx, spec)
        step = build_count_step(ctx, spec)
        emitted = []
        for c in range(4):
            sl = slice(c * B, (c + 1) * B)
            hi, lo = _split64(keys[sl])
            st, khi, klo, w, v, mask = step(
                st, jnp.asarray(hi), jnp.asarray(lo),
                jnp.asarray(vals[sl]), jnp.ones(B, bool),
            )
            khi, klo = np.asarray(khi), np.asarray(klo)
            w, v, mask = np.asarray(w), np.asarray(v), np.asarray(mask)
            for s in range(mask.shape[0]):
                fm = mask[s].reshape(-1)
                for i in np.nonzero(fm)[0]:
                    flat = lambda a: a[s].reshape(-1)
                    emitted.append((
                        int(flat(khi)[i]), int(flat(klo)[i]),
                        int(flat(w)[i]), round(float(flat(v)[i]), 4),
                    ))
        return sorted(emitted)

    assert run(8) == run(1)
