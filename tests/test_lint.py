"""Unified hot-path invariant linter wired as tier-1 (ISSUE 9 + 11).

One parametrized module runs every rule of tools/lint — the 7 AST-tier
rules (ISSUE 9) and the 5 trace-tier rules (ISSUE 11, jaxpr/HLO
evidence from the canonical kernel-family grid):

* against the REPO — all 12 rules must come back clean (a regression in
  any guarded invariant fails the suite, exactly like the two
  pre-framework checkers did for their two invariants);
* against a red-team FIXTURE PAIR per rule (tests/lint_fixtures/) —
  the bad snippet must be flagged, the good twin must pass, so a rule
  that silently stops detecting its bug class fails loudly.  Trace-rule
  fixtures carry the ``# lint-kernel-fixture`` marker and define real
  (tiny) kernels that are traced, not parsed;
* suppression syntax: ``# lint: allow(<rule>): <reason>`` silences one
  finding, a reasonless allow is itself reported, and the sort-seam
  rule accepts no suppression at all;
* the shared parse cache keeps the AST tier under its ~5s budget (the
  combined two-tier budget lives in tests/test_lint_trace.py), and the
  CLI's exit codes distinguish clean/findings/broken.
"""

import json
import os
import re
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.lint import RepoTree, all_rules, rule_by_name, run_rules  # noqa: E402
from tools.lint.core import (  # noqa: E402
    SUPPRESS_RE, LintInternalError, Finding,
)

FIXDIR = os.path.join(os.path.dirname(__file__), "lint_fixtures")
RULE_NAMES = [r.name for r in all_rules()]

# auxiliary virtual files some rules need to judge a fixture (the
# config rule resolves reads against declarations + conf + docs; the
# two ledger rules need a fixture-sized golden ledger to diff against)
AUX = {
    "config": {
        "flink_tpu/core/config.py": (
            "from dataclasses import dataclass\n"
            "@dataclass\n"
            "class ConfigOption:\n"
            "    key: str\n"
            "    default: object = None\n"
            "OPT = ConfigOption('demo.knob', 4)\n"
        ),
        "conf/flink-tpu-conf.yaml": "# demo.knob: 4\n",
        "docs/demo.md": "`demo.knob` — the demo knob.\n",
    },
    "op-budget": {
        "tools/lint/ledgers/op_budget.json": json.dumps({
            "families": {
                "fixture.sortk": {
                    "sort": 1, "scatter": 0, "gather": 0,
                    "while_scan": 0, "cond": 0,
                },
            },
        }),
    },
    "compile-signature": {
        "tools/lint/ledgers/signatures.json": json.dumps({
            "families": {
                "fixture.sig": {
                    "digest": "78fe32416724",
                    "signature": "float32[8]",
                },
            },
        }),
    },
}


def load_fixture(kind: str, rule: str):
    path = os.path.join(FIXDIR, f"{kind}_{rule}.py")
    with open(path) as f:
        src = f.read()
    m = re.search(r"# virtual-path:\s*(\S+)", src)
    assert m, f"{path} must declare its '# virtual-path:' header"
    return m.group(1), src


def fixture_tree(kind: str, rule: str) -> RepoTree:
    vpath, src = load_fixture(kind, rule)
    files = dict(AUX.get(rule, {}))
    files[vpath] = src
    return RepoTree(files=files)


# -- every rule: repo clean, bad flagged, good passes -------------------

@pytest.mark.parametrize("rule", RULE_NAMES)
def test_repo_is_clean(rule):
    findings = run_rules(RepoTree(ROOT), [rule_by_name(rule)])
    assert findings == [], "\n".join(str(f) for f in findings)


@pytest.mark.parametrize("rule", RULE_NAMES)
def test_rule_flags_its_bad_fixture(rule):
    findings = run_rules(fixture_tree("bad", rule), [rule_by_name(rule)])
    assert any(f.rule == rule for f in findings), (
        f"rule {rule!r} no longer detects its seeded violation"
    )


@pytest.mark.parametrize("rule", RULE_NAMES)
def test_rule_passes_its_good_fixture(rule):
    findings = run_rules(fixture_tree("good", rule), [rule_by_name(rule)])
    assert findings == [], "\n".join(str(f) for f in findings)


# -- suppression syntax -------------------------------------------------

def _retrace_tree(extra: str) -> RepoTree:
    src = (
        "import numpy as np\n"
        "def run_update(state):\n"
        f"    m = np.ones(8, bool){extra}\n"
        "    return state\n"
    )
    return RepoTree(files={"flink_tpu/runtime/executor.py": src})


def test_reasoned_allow_suppresses_one_finding():
    tree = _retrace_tree(
        "  # lint: allow(retrace): fixture — deliberate tiny buffer"
    )
    assert run_rules(tree, [rule_by_name("retrace")]) == []


def test_reasonless_allow_is_itself_a_finding():
    tree = _retrace_tree("  # lint: allow(retrace)")
    findings = run_rules(tree, [rule_by_name("retrace")])
    assert [f.rule for f in findings] == ["suppression"]
    assert "reason is mandatory" in findings[0].message


def test_allow_for_a_different_rule_does_not_cover():
    tree = _retrace_tree("  # lint: allow(donation): wrong rule entirely")
    findings = run_rules(tree, [rule_by_name("retrace")])
    assert [f.rule for f in findings] == ["retrace"]


def test_sort_seam_accepts_no_suppression():
    src = (
        "import jax.numpy as jnp\n"
        "def rogue(x):\n"
        "    return jnp.argsort(x)"
        "  # lint: allow(sort-seam): should not work\n"
    )
    tree = RepoTree(files={"flink_tpu/ops/rogue.py": src})
    findings = run_rules(tree, [rule_by_name("sort-seam")])
    assert [f.rule for f in findings] == ["sort-seam"]


def test_every_repo_suppression_carries_a_reason():
    """Acceptance criterion: every `# lint: allow(<rule>)` comment in
    the production tree carries a reason. (tests/ is excluded: the
    suppression tests above deliberately exercise reasonless allows.)"""
    bad = []
    for sub in ("flink_tpu", "tools"):
        for dirpath, _dirs, files in os.walk(os.path.join(ROOT, sub)):
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                with open(p) as f:
                    for i, line in enumerate(f, 1):
                        m = SUPPRESS_RE.search(line)
                        if m is not None and not (
                            m.group("reason") or ""
                        ).strip():
                            bad.append(f"{p}:{i}: {line.strip()}")
    assert bad == [], "\n".join(bad)


def test_config_mentions_are_token_bounded():
    """A declared key that PREFIXES another key must not ride its
    sibling's conf/docs mention (the security.auth.token /
    security.auth.token-file shape, and dotted children)."""
    from tools.lint.rules.config_hygiene import _mentions

    assert not _mentions("# security.auth.token-file: /x",
                         "security.auth.token")
    assert _mentions("# security.auth.token: change-me",
                     "security.auth.token")
    assert not _mentions("restart-strategy.fixed-delay.attempts: 3",
                         "restart-strategy")
    assert _mentions("restart-strategy: none", "restart-strategy")
    # a sentence-ending period is still a boundary
    assert _mentions("set checkpoint.local.dir.", "checkpoint.local.dir")


# -- framework mechanics ------------------------------------------------

def test_parse_cache_is_shared():
    tree = RepoTree(ROOT)
    a = tree.module("flink_tpu/runtime/step.py")
    b = tree.module("flink_tpu/runtime/step.py")
    assert a is b and a is not None


def test_donation_rule_resolves_real_builders():
    """Pass 1 of the donation rule must keep resolving runtime/step.py's
    donated factories — including the thin-wrapper exchange variant."""
    from tools.lint.rules.donation import donated_builders

    b = donated_builders(RepoTree(ROOT))
    assert b.get("build_window_update_step") == (0,)
    assert b.get("build_window_megastep") == (0,)
    assert b.get("build_window_fire_step") == (0,)
    assert b.get("build_window_update_step_exchange") == (0,)
    assert len(b) >= 8


def test_unknown_rule_is_internal_error():
    with pytest.raises(LintInternalError):
        rule_by_name("no-such-rule")


def test_rule_catalog_metadata():
    for r in all_rules():
        assert r.name and r.title and r.established, r
        assert r.tier in ("ast", "trace"), r
    assert len({r.name for r in all_rules()}) == 12
    assert len(all_rules(tier="ast")) == 7
    assert len(all_rules(tier="trace")) == 5


def test_wall_time_budget():
    """The AST tier stays under ~5s on this container: every rule rides
    ONE RepoTree parse of each module.  (The combined two-tier budget —
    which includes real jax traces — is asserted in test_lint_trace.py.)"""
    t0 = time.perf_counter()
    run_rules(RepoTree(ROOT), all_rules(tier="ast"))
    dt = time.perf_counter() - t0
    assert dt < 5.0, f"ast-tier lint took {dt:.2f}s (budget 5s)"


# -- CLI ----------------------------------------------------------------

def _cli(*args, cwd=ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.lint", *args],
        capture_output=True, text=True, cwd=cwd,
    )


def test_cli_clean_tree_exits_zero():
    # ast tier only: the trace tier's CLI paths are covered in
    # tests/test_lint_trace.py, and a default (both-tier) run here
    # would rebuild the whole kernel audit in a subprocess
    rc = _cli("--tier", "ast")
    assert rc.returncode == 0, rc.stdout + rc.stderr


def test_cli_findings_exit_one_and_json(tmp_path):
    bad = tmp_path / "flink_tpu" / "ops"
    bad.mkdir(parents=True)
    (bad / "fake.py").write_text(
        "def kernel(x):\n    return x.block_until_ready()\n"
    )
    rc = _cli("--root", str(tmp_path), "--json")
    assert rc.returncode == 1, rc.stdout + rc.stderr
    payload = json.loads(rc.stdout)
    assert payload["schema"] == 2
    assert payload["findings"][0]["rule"] == "hot-path-sync"
    # stable ordering contract: findings sorted by (path, line, rule)
    keys = [(f["path"], f["line"], f["rule"]) for f in payload["findings"]]
    assert keys == sorted(keys)


def test_cli_internal_error_exits_two():
    rc = _cli("--rule", "no-such-rule")
    assert rc.returncode == 2
    assert "internal error" in rc.stderr


def test_cli_single_rule_and_listing():
    rc = _cli("--rule", "sort-seam")
    assert rc.returncode == 0, rc.stdout + rc.stderr
    rc = _cli("--list-rules")
    assert rc.returncode == 0
    for name in RULE_NAMES:
        assert name in rc.stdout
