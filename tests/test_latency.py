"""metrics/latency.py: weighted sampling invariants (ISSUE 2 satellite).

The fire-latency percentiles drive the north-star p99 claim, so the
bounded-compaction machinery must provably (a) conserve total weight and
(b) keep the percentiles it reports within bucket resolution of the
exact distribution across REPEATED compactions — a drifting compactor
would quietly corrupt the headline metric on any long-running job.
"""

import numpy as np

from flink_tpu.metrics.latency import LatencySamples, weighted_percentile


def _exact_percentile(weights, values, q):
    order = np.argsort(values)
    v, w = np.asarray(values)[order], np.asarray(weights)[order]
    cdf = np.cumsum(w) / w.sum()
    return float(v[min(int(np.searchsorted(cdf, q / 100.0)), len(v) - 1)])


# -------------------------------------------------------------- compact

def test_compact_conserves_total_weight():
    ls = LatencySamples(max_samples=64)
    rng = np.random.default_rng(7)
    total = 0
    for _ in range(1000):
        n = int(rng.integers(1, 50))
        total += n
        ls.record(n, float(rng.exponential(10.0)))
    # many compactions happened (1000 records into a 64-slot bound)
    assert len(ls) <= 64
    assert np.isclose(sum(n for n, _ in ls._samples), total)


def test_compact_percentile_drift_bounded():
    """p50/p95/p99 after repeated compaction stay within bucket
    resolution of the exact weighted percentiles. Bucket resolution: one
    compaction merges adjacent sorted pairs, so any value moves at most
    to its merge-partner's weighted mean — bounded by the local bucket
    width, measured here as the max adjacent gap among retained samples
    at the compacted size."""
    rng = np.random.default_rng(42)
    n_emissions = 20_000
    weights = rng.integers(1, 20, n_emissions).astype(float)
    # lognormal latencies: a realistic long-tailed fire-latency shape
    values = rng.lognormal(mean=3.0, sigma=0.7, size=n_emissions)

    ls = LatencySamples(max_samples=512)
    for w, v in zip(weights, values):
        ls.record(int(w), float(v))
    assert len(ls) <= 512          # compacted many times over

    retained = sorted(v for _, v in ls._samples)
    for q in (50.0, 95.0, 99.0):
        exact = _exact_percentile(weights, values, q)
        approx = ls.percentile(q)
        # resolution near the quantile: the widest adjacent gap among
        # retained samples in the exact value's neighborhood
        i = int(np.searchsorted(retained, exact))
        lo = max(0, i - 2)
        hi = min(len(retained) - 1, i + 2)
        resolution = max(
            np.diff(retained[lo:hi + 1]).max(initial=0.0), 1e-9
        )
        assert abs(approx - exact) <= 2 * resolution, (
            q, exact, approx, resolution
        )


def test_compact_handles_odd_sample_count():
    ls = LatencySamples(max_samples=4)
    for i in range(5):             # 5th record triggers an odd compact
        ls.record(1, float(i))
    assert len(ls) == 3            # 2 merged pairs + the odd tail
    assert np.isclose(sum(n for n, _ in ls._samples), 5)


# --------------------------------------------------- weighted_percentile

def test_weighted_percentile_empty_and_single():
    assert weighted_percentile([], 50) is None
    # a single sample answers EVERY quantile with its own value
    for q in (0.0, 50.0, 100.0):
        assert weighted_percentile([(3.0, 42.5)], q) == 42.5


def test_weighted_percentile_q0_and_q100():
    samples = [(1.0, 10.0), (1.0, 20.0), (1.0, 30.0)]
    assert weighted_percentile(samples, 0) == 10.0     # min
    assert weighted_percentile(samples, 100) == 30.0   # max


def test_weighted_percentile_respects_weights():
    # 99 windows at 1ms, 1 window at 100ms: p50 is 1ms, p99.5 is 100ms
    samples = [(99.0, 1.0), (1.0, 100.0)]
    assert weighted_percentile(samples, 50) == 1.0
    assert weighted_percentile(samples, 99.5) == 100.0


def test_record_zero_weight_is_noop():
    ls = LatencySamples()
    ls.record(0, 5.0)
    assert len(ls) == 0 and not ls
    assert ls.percentile(50) is None
