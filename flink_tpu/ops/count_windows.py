"""Count windows: per-key tumbling windows of N elements.

The reference builds these from GlobalWindows + CountTrigger(N) + purging
(KeyedStream.countWindow). TPU redesign: a batch is sorted by state slot;
per-record positions within each key (segmented cumsum) yield absolute
element indices, which partition into count-windows of N. A second segment
level (slot, window) aggregates each window in one pass; windows that fill
exactly to N fire, the trailing partial window stays in state. The whole
batch — any number of fires per key — is one kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from flink_tpu.ops import hashtable
from flink_tpu.ops.hashtable import SlotTable
from flink_tpu.ops import segment
from flink_tpu.ops.segment import _bshape, segmented_reduce_sorted
from flink_tpu.ops.window_kernels import ReduceSpec


@jax.tree_util.register_pytree_node_class
@dataclass
class CountShardState:
    table: SlotTable
    count: jax.Array    # int32 [C] absolute element count per key
    acc: jax.Array      # [C, *vs] partial (trailing) window accumulator
    touched: jax.Array  # [C] partial window has data
    dropped_capacity: jax.Array

    def tree_flatten(self):
        return (self.table, self.count, self.acc, self.touched,
                self.dropped_capacity), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def init_state(capacity: int, probe_len: int, red: ReduceSpec) -> CountShardState:
    neutral = red.neutral_value()
    acc = jnp.broadcast_to(neutral, (capacity,) + red.value_shape).astype(red.dtype)
    return CountShardState(
        table=hashtable.create(capacity, probe_len),
        count=jnp.zeros(capacity, jnp.int32),
        acc=acc + jnp.zeros_like(acc),
        touched=jnp.zeros(capacity, bool),
        dropped_capacity=jnp.zeros((), jnp.int32),
    )


def update(
    state: CountShardState, red: ReduceSpec, n_per_window: int,
    hi, lo, values, valid,
) -> Tuple[CountShardState, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (state', fire_khi [B], fire_klo [B], fire_w [B],
    fire_values [B,*vs], fire_mask [B]): one lane per completed window
    (sorted-lane space); fire_w is the 0-based window ordinal per key."""
    C = state.table.capacity
    N = jnp.int32(n_per_window)
    combine = red.combine_fn()
    neutral = red.neutral_value()

    # 8 claim rounds: no spill tier here — see session_windows.py
    table, slot, ok = hashtable.upsert(state.table, hi, lo, valid,
                                       max_rounds=8)
    n_nofit = jnp.sum(valid & ~ok, dtype=jnp.int32)
    live = valid & ok

    big = jnp.int32(2**31 - 1)
    ids = jnp.where(live, slot, big)
    order = segment.argsort_ids(ids)
    ids_s = ids[order]
    khi_s, klo_s = hi[order], lo[order]
    vals = values.astype(red.dtype)[order]
    live_s = live[order]
    vals = jnp.where(_bshape(live_s, vals), vals, jnp.asarray(neutral, red.dtype))

    slot_start = jnp.concatenate([jnp.ones((1,), bool), ids_s[1:] != ids_s[:-1]])
    # per-record 1-based position within its key segment
    pos = segmented_reduce_sorted(
        jnp.ones_like(ids_s), slot_start, lambda a, b: a + b
    )
    safe = jnp.where(ids_s < C, ids_s, C - 1)
    old_count = jnp.where(ids_s < C, state.count[safe], 0)
    a = old_count + pos                       # absolute element index (1-based)
    w = (a - 1) // N                          # window index
    # (slot, window) sub-segments: already sorted (pos ascending within slot)
    w_start = slot_start | jnp.concatenate(
        [jnp.zeros((1,), bool), w[1:] != w[:-1]]
    )
    rolled = segmented_reduce_sorted(vals, w_start, combine)
    # fold the carried partial accumulator into this key's FIRST window
    first_w = old_count // N
    in_first = (w == first_w) & live_s
    old_partial = state.acc[safe]
    has_partial = state.touched[safe] & (old_count % N != 0)
    rolled = jnp.where(
        _bshape(in_first & has_partial, rolled),
        combine(old_partial, rolled), rolled,
    )

    w_end = jnp.concatenate(
        [(ids_s[1:] != ids_s[:-1]) | (w[1:] != w[:-1]), jnp.ones((1,), bool)]
    )
    rep = w_end & live_s
    complete = rep & (a == (w + 1) * N)       # window filled exactly
    slot_end = jnp.concatenate([ids_s[1:] != ids_s[:-1], jnp.ones((1,), bool)])
    tail = slot_end & live_s & (a % N != 0)   # trailing partial window

    # -- state update -----------------------------------------------------
    cnt_idx = jnp.where(slot_end & live_s, ids_s, C)
    count = state.count.at[cnt_idx].set(a, mode="drop")
    acc_idx = jnp.where(slot_end & live_s, ids_s, C)
    new_acc_val = jnp.where(
        _bshape(tail, rolled), rolled, jnp.asarray(neutral, red.dtype)
    )
    acc = state.acc.at[acc_idx].set(new_acc_val.astype(red.dtype), mode="drop")
    touched = state.touched.at[acc_idx].set(tail, mode="drop")

    new_state = CountShardState(
        table=table, count=count, acc=acc, touched=touched,
        dropped_capacity=state.dropped_capacity + n_nofit,
    )
    return new_state, khi_s, klo_s, w, rolled, complete
