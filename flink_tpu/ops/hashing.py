"""Key hashing: host 64-bit key identity + device 32-bit probe/route hashes.

The reference derives everything from Java ``Object.hashCode()`` (32-bit) and
murmur-scrambles it (MathUtils.murmurHash used at KeyGroupRangeAssignment.java:62).
We use 64-bit key identities so 1M+ key cardinalities have negligible collision
probability, then derive 32-bit hashes on device from the (hi, lo) pair.

Host: splitmix64 (public-domain mix) vectorized in numpy for numeric keys;
stable blake2b-based hash for strings/bytes/other objects (NOT Python's
``hash()``, which is salted per process and would break checkpoint restore).
"""

from __future__ import annotations

import hashlib
from typing import Sequence

import numpy as np

_SM_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SM_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_M2 = np.uint64(0x94D049BB133111EB)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (uint64 -> uint64)."""
    z = np.asarray(x).astype(np.uint64) + _SM_GAMMA
    z = (z ^ (z >> np.uint64(30))) * _SM_M1
    z = (z ^ (z >> np.uint64(27))) * _SM_M2
    return z ^ (z >> np.uint64(31))


def _stable_obj_hash(obj) -> int:
    if isinstance(obj, bytes):
        data = obj
    elif isinstance(obj, str):
        data = obj.encode("utf-8")
    else:
        data = repr(obj).encode("utf-8")
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


def hash64_host(keys) -> np.ndarray:
    """Host keys -> MIXED uint64 hashes (sketch item hashing, state-backend
    addressing — anywhere hash *quality* matters).

    Numeric arrays go through vectorized splitmix64; object sequences through
    a stable per-object hash.
    """
    arr = np.asarray(keys)
    if arr.dtype.kind in "iub":
        return splitmix64(arr.astype(np.uint64))
    if arr.dtype.kind == "f":
        return splitmix64(arr.view(np.uint64) if arr.dtype == np.float64
                          else arr.astype(np.float64).view(np.uint64))
    return np.fromiter(
        (_stable_obj_hash(k) for k in (keys if not isinstance(keys, np.ndarray) else keys.tolist())),
        dtype=np.uint64,
        count=len(keys),
    )


def key_identity64(keys) -> np.ndarray:
    """Host keys -> uint64 key IDENTITIES (KeyCodec).

    An identity only needs to be collision-free and stable — all downstream
    hashing (slot probing, key-group routing) mixes the (hi, lo) pair again
    on device (probe_hash / route_hash, plus the murmur key-group
    scramble). For integers the raw two's-complement bits already ARE a
    perfect identity, ~7x cheaper per batch than splitmix64's uint64
    multiply chain on host — and decode() recovers non-negative ints
    without a reverse map. Floats use their IEEE bits (note -0.0 and +0.0
    are distinct identities, as they already were under splitmix of the
    same bits). Objects fall back to the stable hash.
    """
    arr = np.asarray(keys)
    if arr.dtype.kind in "iub":
        return arr.astype(np.int64, copy=False).view(np.uint64)
    if arr.dtype.kind == "f":
        return (arr.view(np.uint64) if arr.dtype == np.float64
                else arr.astype(np.float64).view(np.uint64))
    return hash64_host(keys)


# ---------------------------------------------------------------- device side

def probe_hash(key_hi, key_lo, xp):
    """(hi, lo) uint32 pair -> uint32 slot-probe hash (device-friendly mix)."""
    h = xp.asarray(key_hi).astype(xp.uint32) * np.uint32(0x85EBCA6B)
    h = h ^ (xp.asarray(key_lo).astype(xp.uint32) * np.uint32(0xC2B2AE35))
    h = h ^ (h >> np.uint32(15))
    h = h * np.uint32(0x2C1B3C6D)
    h = h ^ (h >> np.uint32(12))
    h = h * np.uint32(0x297A2D39)
    return h ^ (h >> np.uint32(15))


def route_hash(key_hi, key_lo, xp):
    """(hi, lo) -> uint32 hash fed to key-group assignment.

    Independent from probe_hash so slot probing and key-group routing don't
    correlate (the reference similarly separates hashCode from murmur scramble).
    """
    h = xp.asarray(key_lo).astype(xp.uint32) ^ (
        xp.asarray(key_hi).astype(xp.uint32) * np.uint32(0x9E3779B9)
    )
    return h
