"""Session windows: per-key gap-separated windows with merging.

The reference implements sessions via MergingWindowSet + mergeable window
state (SURVEY §2.5, EventTimeSessionWindows / MergingWindowSet.java): each
element opens a [ts, ts+gap) window which merges with overlapping ones.

TPU-native redesign (batch sessionization + open-session state):
  * Within a batch: lexsort by (key-slot, ts); a session boundary is a key
    change or a time gap > gap_ticks; segmented reduces give each batch
    session's (start, last, aggregate) in one pass.
  * Across batches: each key holds at most ONE open session in device state
    (start, last, acc, active). A batch session within `gap` of the open
    session merges into it; a batch session beyond the gap *supersedes* it —
    the superseded session fires immediately.
  * Watermark close: open sessions with last + gap <= wm fire and clear
    (whole-shard masked scan, gated on watermark advance).

Deviation from the reference (documented): a key cannot hold two
simultaneously open sessions. When out-of-orderness exceeds the session gap,
a superseded session fires at supersession time instead of at watermark
time, and a record older than the open session's span minus the gap counts
as late. For out-of-orderness <= gap (the normal configuration, since the
watermark bound is usually far below the session gap) the semantics match.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ops import hashtable
from flink_tpu.ops.hashtable import SlotTable
from flink_tpu.ops import segment
from flink_tpu.ops.segment import _bshape, segmented_reduce_sorted
from flink_tpu.ops.window_kernels import ReduceSpec


@jax.tree_util.register_pytree_node_class
@dataclass
class SessionShardState:
    table: SlotTable
    start: jax.Array     # int32 [C] open-session first event ts
    last: jax.Array      # int32 [C] open-session latest event ts
    acc: jax.Array       # [C, *vs]
    active: jax.Array    # bool [C]
    watermark: jax.Array  # int32 scalar
    dropped_late: jax.Array
    dropped_capacity: jax.Array

    def tree_flatten(self):
        return (self.table, self.start, self.last, self.acc, self.active,
                self.watermark, self.dropped_late, self.dropped_capacity), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def init_state(capacity: int, probe_len: int, red: ReduceSpec) -> SessionShardState:
    neutral = red.neutral_value()
    acc = jnp.broadcast_to(neutral, (capacity,) + red.value_shape).astype(red.dtype)
    return SessionShardState(
        table=hashtable.create(capacity, probe_len),
        start=jnp.zeros(capacity, jnp.int32),
        last=jnp.zeros(capacity, jnp.int32),
        acc=acc + jnp.zeros_like(acc),
        active=jnp.zeros(capacity, bool),
        watermark=jnp.asarray(-(2**31) + 1, jnp.int32),
        dropped_late=jnp.zeros((), jnp.int32),
        dropped_capacity=jnp.zeros((), jnp.int32),
    )


def _lexsort_slot_ts(ids, ts):
    """Stable sort by (ids, ts): sort by ts first, then stable by ids."""
    o1 = segment.argsort_ids(ts, stable=True)
    o2 = segment.argsort_ids(ids[o1], stable=True)
    return o1[o2]


def update_and_fire(
    state: SessionShardState, red: ReduceSpec, gap: int,
    hi, lo, ts, values, valid, new_watermark,
):
    """One micro-batch + watermark advance.

    Returns (state', old_fire, mid_fire, wm_fire): two superseded-session
    fire sets in sorted-lane space [B] — each (khi, klo, start, end, vals,
    mask) — plus watermark-close fires in slot space [C] as (start, end,
    vals, mask) with keys from the table.
    Session window end = last + gap (ref TimeWindow semantics for sessions).
    """
    C = state.table.capacity
    G = jnp.int32(gap)
    combine = red.combine_fn()
    neutral = red.neutral_value()

    wm = jnp.maximum(state.watermark, jnp.asarray(new_watermark, jnp.int32))

    # -- late filter against the PRE-batch watermark (elements process
    #    before their own batch's watermark advances, ref operator order):
    #    a record older than wm - gap can never join a live session
    late = valid & (ts + G <= state.watermark)
    n_late = jnp.sum(late, dtype=jnp.int32)
    live = valid & ~late

    # 8 claim rounds: this stage has NO spill tier, so a cold-start claim
    # storm that fails to settle is a counted record LOSS (strict
    # capacity); the extra probe gathers are cheap insurance
    table, slot, ok = hashtable.upsert(state.table, hi, lo, live,
                                       max_rounds=8)
    n_nofit = jnp.sum(live & ~ok, dtype=jnp.int32)
    live = live & ok

    big = jnp.int32(2**31 - 1)
    ids = jnp.where(live, slot, big)
    order = _lexsort_slot_ts(ids, jnp.where(live, ts, big))
    ids_s = ids[order]
    ts_s = jnp.where(live[order], ts[order], big)
    khi_s, klo_s = hi[order], lo[order]
    vals = values.astype(red.dtype)[order]
    live_s = live[order]
    vals = jnp.where(_bshape(live_s, vals), vals, jnp.asarray(neutral, red.dtype))

    slot_change = jnp.concatenate(
        [jnp.ones((1,), bool), ids_s[1:] != ids_s[:-1]]
    )
    time_gap = jnp.concatenate(
        [jnp.ones((1,), bool), (ts_s[1:] - ts_s[:-1]) > G]
    )
    sess_start_flag = slot_change | time_gap

    agg = segmented_reduce_sorted(vals, sess_start_flag, combine)
    smin = segmented_reduce_sorted(ts_s, sess_start_flag, jnp.minimum)
    smax = segmented_reduce_sorted(ts_s, sess_start_flag, jnp.maximum)

    sess_end_flag = jnp.concatenate(
        [sess_start_flag[1:], jnp.ones((1,), bool)]
    )
    rep = sess_end_flag & live_s
    # is this the FIRST session of its slot in the batch?
    first_of_slot = segmented_reduce_sorted(
        slot_change.astype(jnp.int32), sess_start_flag, jnp.maximum
    )  # 1 where the session's lanes include a slot change
    # is this the LAST session of its slot? next session starts new slot
    next_slot_change = jnp.concatenate(
        [ids_s[1:] != ids_s[:-1], jnp.ones((1,), bool)]
    )
    last_of_slot = rep & next_slot_change

    safe = jnp.where(ids_s < C, ids_s, C - 1)
    o_active = state.active[safe] & (ids_s < C)
    o_start = state.start[safe]
    o_last = state.last[safe]
    o_acc = state.acc[safe]

    # merge condition for the first batch session of each slot
    is_first = rep & (first_of_slot > 0)
    merges = is_first & o_active & (smin <= o_last + G) & (smax + G >= o_start)
    merged_acc = jnp.where(
        _bshape(merges, agg), combine(o_acc, agg), agg
    )
    merged_start = jnp.where(merges, jnp.minimum(o_start, smin), smin)
    merged_last = jnp.where(merges, jnp.maximum(o_last, smax), smax)

    # superseded fires, in two independent lane-spaces (a lane can carry
    # both an old-session fire and its own mid-session fire):
    #  a) the previously-open session when the first batch session does NOT
    #     merge with it (fires with its stored values)
    sup_old = is_first & o_active & ~merges
    #  b) every non-last batch session (superseded by the next one)
    sup_mid = rep & ~last_of_slot
    old_fire = (khi_s, klo_s, o_start, o_last + G, o_acc, sup_old)
    mid_fire = (khi_s, klo_s, merged_start, merged_last + G, merged_acc, sup_mid)

    # -- state writeback: last session of each slot becomes the open one --
    wb = last_of_slot
    wb_idx = jnp.where(wb, ids_s, C)
    new_start = state.start.at[wb_idx].set(merged_start, mode="drop")
    new_last = state.last.at[wb_idx].set(merged_last, mode="drop")
    new_acc = state.acc.at[wb_idx].set(merged_acc.astype(red.dtype), mode="drop")
    new_active = state.active.at[wb_idx].set(True, mode="drop")

    # -- watermark close over all slots ----------------------------------
    w_mask = new_active & (new_last + G <= wm)
    w_start = new_start
    w_vals = new_acc
    w_end = new_last + G

    # unconditional masked close: a lax.cond here costs ~30ms/step on the
    # tunneled TPU runtime, while the all-false where is a cheap sweep
    new_acc = jnp.where(
        _bshape(w_mask, new_acc), jnp.asarray(neutral, red.dtype), new_acc
    )
    new_active = new_active & ~w_mask

    new_state = SessionShardState(
        table=table, start=new_start, last=new_last, acc=new_acc,
        active=new_active, watermark=wm,
        dropped_late=state.dropped_late + n_late,
        dropped_capacity=state.dropped_capacity + n_nofit,
    )
    return (
        new_state,
        old_fire,
        mid_fire,
        (w_start, w_end, w_vals, w_mask),
    )
