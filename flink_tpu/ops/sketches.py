"""Probabilistic sketch aggregations as device-array window state.

BASELINE config #3: "sliding-window Count-Min / HyperLogLog sketch
aggregation". In the reference this is user code — a ReduceFunction over a
sketch object held in ``ReducingState`` and merged per record on the heap
(HeapReducingState.add, flink-runtime state/heap/HeapReducingState.java:85).
TPU-native redesign: each (key, pane) holds a flat register array inside the
window accumulator (`WindowShardState.acc` with ``value_shape = registers``);
one micro-batch becomes ONE scatter into the flattened register space:

  * Count-Min: record item -> D row positions -> ``.at[].add`` of the D
    increments. Pane composition (sliding windows) = elementwise ``+``,
    which the generic pane-combine path already does.
  * HyperLogLog: record item -> (bucket, rho) -> ``.at[].max``. Pane
    composition = elementwise ``max``.

Both sketches are *mergeable* monoids, which is exactly what the pane-ring
design of ``window_kernels`` needs: a sliding window's sketch is the combine
of its panes' sketches — no per-record re-scan, matching how the reference's
aligned panes (AbstractKeyedTimePanes.java) compose per-pane aggregates.

A ``finalize`` hook (the analog of Flink's later AggregateFunction.getResult)
turns the combined registers into a small estimate tensor at fire time so
fires ship estimates, not multi-KB sketches, off device.

Items are hashed host-side to uint32 via the same stable hash as keys
(ops/hashing.py) and carried through the routing step in the ``values`` lane.
Device-side, per-row/bucket hashes derive from that base hash with fmix32
mixing, so the wire stays one 32-bit word per record.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ops.hashing import hash64_host, splitmix64


def hash32_host(items) -> np.ndarray:
    """Host items -> uint32 base sketch hashes (stable across processes)."""
    h = hash64_host(items)
    return (h ^ (h >> np.uint64(32))).astype(np.uint32)


def _fmix32(h):
    """murmur3 32-bit finalizer, uint32 wraparound arithmetic (device)."""
    h = jnp.asarray(h, jnp.uint32)
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> np.uint32(16))


def _row_seeds(depth: int) -> np.ndarray:
    return splitmix64(np.arange(1, depth + 1, dtype=np.uint64)).astype(
        np.uint32
    )


def _fmix32_np(h: np.ndarray) -> np.ndarray:
    """numpy mirror of _fmix32 (identical bit pattern, host path)."""
    h = np.asarray(h, np.uint32)
    with np.errstate(over="ignore"):
        h = h ^ (h >> np.uint32(16))
        h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
        h = h ^ (h >> np.uint32(13))
        h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    return h ^ (h >> np.uint32(16))


class CountMinSketch:
    """Count-Min sketch spec: D x W int32 counters per (key, pane).

    query: optional fixed item list; fires then emit the Q point estimates
    (min over rows) instead of raw registers. Width must be a power of two.
    """

    op = "add"  # scatter reducer AND pane-composition combine
    neutral = 0

    def __init__(self, depth: int = 4, width: int = 1024,
                 query: Optional[Sequence] = None):
        if width & (width - 1):
            raise ValueError("count-min width must be a power of two")
        self.depth = depth
        self.width = width
        self.value_shape = (depth * width,)
        self.dtype = jnp.int32
        self.seeds = _row_seeds(depth)
        self.query = list(query) if query is not None else None
        if self.query is not None:
            qh = hash32_host(np.asarray(self.query)
                             if _numeric(self.query) else self.query)
            self.qpos = np.stack(
                [self._positions_np(qh, d) for d in range(depth)]
            )  # [D, Q] int32
            self.result_shape = (len(self.query),)
        else:
            self.qpos = None
            self.result_shape = self.value_shape
        self.result_dtype = jnp.int32

    def _positions_np(self, h32: np.ndarray, d: int) -> np.ndarray:
        h = _fmix32_np((h32 ^ self.seeds[d]).astype(np.uint32))
        return (h & np.uint32(self.width - 1)).astype(np.int32)

    def expand(self, flat, hashes, live):
        """Lane (slot*R+ring) + item hash -> D register updates per record.

        flat: int32 [B]; hashes: uint32 [B]; live: bool [B]
        Returns (eidx int32 [B*D], upd [B*D], mask bool [B*D]) indexing the
        flattened [C*R * D*W] register space.
        """
        seeds = jnp.asarray(self.seeds)
        mixed = _fmix32(hashes[:, None] ^ seeds[None, :])        # [B, D]
        pos = (mixed & np.uint32(self.width - 1)).astype(jnp.int32)
        d_off = (jnp.arange(self.depth, dtype=jnp.int32) * self.width)
        eidx = (
            flat[:, None] * jnp.int32(self.depth * self.width)
            + d_off[None, :] + pos
        )
        upd = jnp.ones_like(eidx, dtype=self.dtype)
        mask = jnp.broadcast_to(live[:, None], eidx.shape)
        return eidx.reshape(-1), upd.reshape(-1), mask.reshape(-1)

    def finalize(self, vals):
        """[..., D*W] registers -> [..., Q] point estimates (min over rows)."""
        if self.qpos is None:
            return vals
        v = vals.reshape(vals.shape[:-1] + (self.depth, self.width))
        rows = jnp.arange(self.depth)[:, None]
        g = v[..., rows, jnp.asarray(self.qpos)]                 # [..., D, Q]
        return jnp.min(g, axis=-2)

    def estimate_np(self, sketch: np.ndarray, items) -> np.ndarray:
        """Host-side point query of a raw [D*W] sketch for arbitrary items."""
        qh = hash32_host(np.asarray(items) if _numeric(items) else items)
        v = np.asarray(sketch).reshape(self.depth, self.width)
        ests = np.stack(
            [v[d, self._positions_np(qh, d)] for d in range(self.depth)]
        )
        return ests.min(axis=0)

    # -- host path (generic window operator: triggers/evictors/sessions) ---
    def host_init(self) -> np.ndarray:
        return np.zeros(self.value_shape, np.int64)

    def host_add(self, acc: np.ndarray, item) -> np.ndarray:
        qh = hash32_host([item])
        for d in range(self.depth):
            acc[d * self.width + int(self._positions_np(qh, d)[0])] += 1
        return acc

    def host_merge(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        return a + b

    def host_result(self, acc: np.ndarray):
        if self.qpos is None:
            return acc.copy()
        v = acc.reshape(self.depth, self.width)
        return v[np.arange(self.depth)[:, None], self.qpos].min(axis=0)


class HyperLogLog:
    """HLL spec: M = 2**p int32 rank registers per (key, pane).

    finalize -> float32 cardinality estimate with the standard small-range
    (linear counting) correction. 32-bit item hashes: fine up to ~1e8
    distinct items, far beyond per-window cardinalities here.
    """

    op = "max"
    neutral = 0

    def __init__(self, p: int = 12):
        if not 4 <= p <= 16:
            raise ValueError("HLL precision p must be in [4, 16]")
        self.p = p
        self.m = 1 << p
        self.value_shape = (self.m,)
        self.dtype = jnp.int32
        self.result_shape = ()
        self.result_dtype = jnp.float32
        m = self.m
        self.alpha = (
            0.673 if m == 16 else 0.697 if m == 32
            else 0.709 if m == 64 else 0.7213 / (1 + 1.079 / m)
        )

    def expand(self, flat, hashes, live):
        h = _fmix32(hashes)  # decorrelate from any host hash structure
        bucket = (h >> np.uint32(32 - self.p)).astype(jnp.int32)
        w = (h << np.uint32(self.p)).astype(jnp.uint32)
        rho = jnp.where(
            w == 0, jnp.int32(32 - self.p + 1),
            jax.lax.clz(w).astype(jnp.int32) + 1,
        )
        eidx = flat * jnp.int32(self.m) + bucket
        return eidx, rho, live

    def finalize(self, regs):
        """[..., M] registers -> float32 cardinality estimate."""
        r = regs.astype(jnp.float32)
        z = jnp.sum(jnp.exp2(-r), axis=-1)
        e = jnp.float32(self.alpha * self.m * self.m) / z
        zeros = jnp.sum(regs == 0, axis=-1).astype(jnp.float32)
        lin = jnp.float32(self.m) * (
            jnp.log(jnp.float32(self.m)) - jnp.log(jnp.maximum(zeros, 1.0))
        )
        use_lin = (e <= 2.5 * self.m) & (zeros > 0)
        return jnp.where(use_lin, lin, e)

    # -- host path (generic window operator: triggers/evictors/sessions) ---
    def host_init(self) -> np.ndarray:
        return np.zeros(self.value_shape, np.int32)

    def host_add(self, acc: np.ndarray, item) -> np.ndarray:
        qh = hash32_host([item])
        h = int(_fmix32_np(qh)[0])
        bucket = h >> (32 - self.p)
        w = (h << self.p) & 0xFFFFFFFF
        rho = (32 - self.p + 1) if w == 0 else (32 - w.bit_length() + 1)
        acc[bucket] = max(acc[bucket], rho)
        return acc

    def host_merge(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        return np.maximum(a, b)

    def host_result(self, acc: np.ndarray) -> float:
        z = float(np.sum(np.exp2(-acc.astype(np.float64))))
        e = self.alpha * self.m * self.m / z
        zeros = int(np.sum(acc == 0))
        if e <= 2.5 * self.m and zeros > 0:
            return float(self.m * np.log(self.m / zeros))
        return float(e)


def _numeric(items) -> bool:
    arr = np.asarray(items)
    return arr.dtype.kind in "iufb"
