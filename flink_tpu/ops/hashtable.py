"""Device-resident open-addressing key index — the heart of keyed state.

The reference's keyed backends resolve ``(key)`` -> state via JVM HashMap
probes per record (HeapKeyedStateBackend/StateTable, SURVEY §2.4) or RocksDB
point lookups. TPU-native replacement: each key-group shard owns a fixed-
capacity open-addressing table held in HBM:

    keys: uint32[C, 2]   -- (hi, lo) 64-bit key identity per slot; the
                            all-ones row is the EMPTY sentinel.

State values live in separate [C, ...] arrays indexed by slot (managed by the
state backend), so one table serves every state descriptor of an operator.

All operations are batched and jit-compatible:

  * ``lookup``  — for B records, gather a P-long linear probe chain
    ([B, P] gathers) and pick the matching or first-empty slot. No scalar
    loops; one XLA gather + reductions.
  * ``upsert``  — insert unseen keys via *iterative scatter-claim*: every
    missing lane scatters its key row into its first empty slot (single
    [2]-wide scatter => row-atomic; duplicate claims -> exactly one winner),
    then re-looks-up. Lanes that lost a claim race retry against the updated
    table. Rounds are STATICALLY UNROLLED (no device control flow — a cond
    costs ~30ms/step on the tunneled TPU runtime, an extra probe gather
    ~0.06ms). Duplicate keys within a batch need no dedup: they follow
    identical probe chains and claim identical slots with identical rows.

Failure is explicit: a lane whose probe chain has neither its key nor an
empty slot reports ok=False (table over capacity) and the runtime surfaces a
state-backend-full error, like RocksDB surfacing disk-full.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ops.hashing import probe_hash

EMPTY = np.uint32(0xFFFFFFFF)


@jax.tree_util.register_pytree_node_class
@dataclass
class SlotTable:
    keys: jax.Array  # uint32[C, 2]
    probe_len: int = 16

    @property
    def capacity(self) -> int:
        return self.keys.shape[0]

    def used_mask(self) -> jax.Array:
        return ~jnp.all(self.keys == EMPTY, axis=1)

    def tree_flatten(self):
        return (self.keys,), (self.probe_len,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])


def create(capacity: int, probe_len: int = 16) -> SlotTable:
    if capacity & (capacity - 1):
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    keys = jnp.full((capacity, 2), EMPTY, dtype=jnp.uint32)
    return SlotTable(keys, probe_len)


def _chain(hi, lo, capacity: int, probe_len: int):
    """[B, P] candidate slot indices along each record's probe chain."""
    base = probe_hash(hi, lo, jnp) & jnp.uint32(capacity - 1)
    offs = jnp.arange(probe_len, dtype=jnp.uint32)
    return ((base[:, None] + offs[None, :]) & jnp.uint32(capacity - 1)).astype(
        jnp.int32
    )


def _probe(table_keys, cand, hi, lo):
    """Gather the chain and classify each candidate slot."""
    rows = table_keys[cand]  # [B, P, 2]
    t_hi, t_lo = rows[..., 0], rows[..., 1]
    empty = (t_hi == EMPTY) & (t_lo == EMPTY)
    match = (~empty) & (t_hi == hi[:, None]) & (t_lo == lo[:, None])
    return match, empty


def lookup(
    table: SlotTable, hi: jax.Array, lo: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Find slots for a batch of keys.

    Returns (slot int32[B], found bool[B]). Unfound lanes get slot=capacity
    (out-of-range => safe to use with mode='drop' scatters / clipped gathers).
    """
    cand = _chain(hi, lo, table.capacity, table.probe_len)
    match, _ = _probe(table.keys, cand, hi, lo)
    found = match.any(axis=1)
    slot = jnp.take_along_axis(
        cand, jnp.argmax(match, axis=1)[:, None], axis=1
    )[:, 0]
    return jnp.where(found, slot, table.capacity), found


def _lookup_or_empty(table_keys, capacity, probe_len, hi, lo):
    cand = _chain(hi, lo, capacity, probe_len)
    match, empty = _probe(table_keys, cand, hi, lo)
    found = match.any(axis=1)
    has_empty = empty.any(axis=1)
    match_slot = jnp.take_along_axis(cand, jnp.argmax(match, 1)[:, None], 1)[:, 0]
    empty_slot = jnp.take_along_axis(cand, jnp.argmax(empty, 1)[:, None], 1)[:, 0]
    return found, match_slot, has_empty, empty_slot


@partial(jax.jit, static_argnums=(3,))
def _upsert_impl(table_keys, hi, lo, static, valid):
    capacity, probe_len, max_rounds = static

    # STATICALLY UNROLLED claim rounds — deliberately no lax.cond /
    # lax.while_loop. On the tunneled TPU runtime, data-dependent control
    # flow in the step costs tens of ms per invocation (measured ~30ms for
    # a never-taken cond wrapping this insert path), while an extra [B, P]
    # probe gather costs ~0.06ms. So every step unconditionally runs
    # `max_rounds` claim+relookup rounds; with no missing keys the claim
    # scatters write nothing (all indices out of range, mode='drop') and
    # the relookups are pure gathers. A lane whose claim loses the
    # slot race to a different key retries against the updated table next
    # round; conflicts decay geometrically, and max_rounds=4 settles even
    # cold-start insert storms at the load factors we run (<=0.5).
    rows = jnp.stack([hi, lo], axis=1)
    found, slot, has_empty, empty_slot = _lookup_or_empty(
        table_keys, capacity, probe_len, hi, lo
    )
    found0 = found
    for _ in range(max_rounds):
        claim = valid & ~found & has_empty
        idx = jnp.where(claim, empty_slot, capacity)
        table_keys = table_keys.at[idx].set(rows, mode="drop")
        found, slot, has_empty, empty_slot = _lookup_or_empty(
            table_keys, capacity, probe_len, hi, lo
        )
    ok = valid & found
    # n_new counts lanes whose key was PLACED this call (absent before,
    # resident after). Lanes that fail to place (chain exhausted) are
    # deliberately excluded: they can never be placed by re-running the
    # insert step either — they belong to the overflow/spill tier, and
    # counting them would permanently pin the executor's step tiering in
    # insert mode for a key population that partially overflows.
    n_new = jnp.sum(valid & ~found0 & found, dtype=jnp.int32)
    slot = jnp.where(ok, slot, capacity)
    return table_keys, slot, ok, n_new


def upsert(
    table: SlotTable, hi: jax.Array, lo: jax.Array, valid: jax.Array,
    max_rounds: int = 4,
) -> Tuple[SlotTable, jax.Array, jax.Array]:
    """Insert-or-find a batch of keys.

    Returns (new_table, slot int32[B], ok bool[B]). ok=False lanes were valid
    records whose key could not be placed (chain exhausted — table too full).
    """
    new_keys, slot, ok, _ = _upsert_impl(
        table.keys, hi, lo, (table.capacity, table.probe_len, max_rounds), valid
    )
    return SlotTable(new_keys, table.probe_len), slot, ok


def upsert_counted(
    table: SlotTable, hi: jax.Array, lo: jax.Array, valid: jax.Array,
    max_rounds: int = 4,
) -> Tuple[SlotTable, jax.Array, jax.Array, jax.Array]:
    """upsert() that also reports n_new: how many valid lanes' keys were
    PLACED by this call (absent before, resident after). Lanes that fail
    to place (probe chain exhausted) are excluded — re-running insert can
    never place them, so they must not hold the executor's adaptive step
    tiering in insert mode. n_new == 0 certifies the batch changed no
    table row (see runtime/step.py / executor tiering)."""
    new_keys, slot, ok, n_new = _upsert_impl(
        table.keys, hi, lo, (table.capacity, table.probe_len, max_rounds), valid
    )
    return SlotTable(new_keys, table.probe_len), slot, ok, n_new


def remove_slots(table: SlotTable, slots: jax.Array, mask: jax.Array) -> SlotTable:
    """Mark slots empty (used by state clear / TTL eviction).

    NOTE: with linear probing, removal must not break other keys' chains.
    We therefore only use this during full-shard compaction (rebuild), not
    point deletes; point "clear" of state zeroes the value arrays instead.
    """
    idx = jnp.where(mask, slots, table.capacity)
    rows = jnp.full((slots.shape[0], 2), EMPTY, dtype=jnp.uint32)
    return SlotTable(table.keys.at[idx].set(rows, mode="drop"), table.probe_len)
