"""Batched pre-aggregation: sort + segmented reduce.

The reference combines per record (HeapReducingState.add = HashMap get ->
user reduce -> put, SURVEY §3.2 "per-record scalar reduce"). TPU-native: a
whole micro-batch is pre-aggregated *per (slot, pane)* in one shot, then a
single scatter-combine touches state. For the built-in reducers this is a
native duplicate-index scatter (`.at[].add/.min/.max`); for arbitrary
associative combine functions we sort by segment id and run a segmented
associative scan (the classic "flagged scan" trick), which works for any
jnp-traceable associative op.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def segmented_reduce_sorted(values, seg_start, combine: Callable):
    """Reduce runs of a sorted array with an arbitrary associative combine.

    values:    [B, ...] sorted so equal segments are adjacent
    seg_start: bool [B], True where a new segment begins
    combine:   (a, b) -> c, associative, jnp-traceable

    Returns [B, ...] where the *last* element of each segment holds the
    segment's reduction (other lanes hold partial prefixes).
    """

    def seg_combine(a, b):
        a_flag, a_val = a
        b_flag, b_val = b
        merged = jax.tree_util.tree_map(
            lambda av, bv: jnp.where(
                _bshape(b_flag, bv), bv, combine(av, bv)
            ),
            a_val,
            b_val,
        )
        return a_flag | b_flag, merged

    _, out = jax.lax.associative_scan(seg_combine, (seg_start, values))
    return out


def _bshape(flag, val):
    """Broadcast a [B] bool against [B, ...] values."""
    extra = val.ndim - flag.ndim
    return flag.reshape(flag.shape + (1,) * extra)


# -- the ONE place device sorts live -----------------------------------
# Every jnp.sort/argsort in flink_tpu/ops goes through these wrappers:
# a sort is the single most expensive reordering primitive the kernels
# use, and the whole pre-combine design is "pay ONE sort, feed every
# consumer from it" (acc scatter, fire eligibility via touched, the
# kg_dirty changelog bits, kg_fill skew telemetry — see
# window_kernels.update). Centralizing the call sites makes that seam
# auditable: tools/check_segment_sort_seam.py (tier-1) fails the build
# when a sort appears anywhere else under ops/, so a future edit cannot
# quietly reintroduce a per-plane sort pass.

def sort_values(x):
    """Ascending sort of a 1-D array (the do_late window-id dedup in
    window_kernels and any future value sort)."""
    return jnp.sort(x)


def argsort_ids(ids, stable: bool = False):
    """Permutation ordering ``ids`` ascending. ``stable=True`` keeps
    equal ids in input order (the session-window chain relies on it)."""
    return jnp.argsort(ids, stable=stable) if stable else jnp.argsort(ids)


def invert_permutation(order):
    """Inverse of a permutation: out[order[i]] = i. One scatter instead
    of the argsort-of-argsort idiom (an O(B log B) sort to invert what a
    single O(B) scatter inverts exactly)."""
    B = order.shape[0]
    return (
        jnp.zeros(B, order.dtype)
        .at[order]
        .set(jnp.arange(B, dtype=order.dtype))
    )


def segment_sort(seg_ids, valid):
    """The ONE sort a batched pre-combine pays: order lanes by segment id
    with invalid lanes pushed to the end (id = INT32_MAX).

    Returns ``(order, ids_s, valid_s, seg_start, rep_mask)`` — the gather
    permutation, the sorted ids, the sorted validity, the new-segment
    flags, and the representative mask (last lane of each valid segment).
    Callers gather any number of per-lane columns through ``order`` and
    reduce them with ``reduce_sorted`` — the update kernel shares this
    sort between the accumulator scatter and the changelog dirty bits
    instead of sweeping the batch once per consumer.
    """
    big = jnp.int32(2**31 - 1)
    ids = jnp.where(valid, seg_ids, big)
    order = argsort_ids(ids)
    ids_s = ids[order]
    valid_s = valid[order]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), ids_s[1:] != ids_s[:-1]]
    )
    # last lane of each segment = lane before the next segment start (or last)
    seg_end = jnp.concatenate([ids_s[1:] != ids_s[:-1], jnp.ones((1,), bool)])
    rep_mask = seg_end & (ids_s != big)
    return order, ids_s, valid_s, seg_start, rep_mask


def reduce_sorted(order, valid_s, seg_start, values, combine: Callable,
                  neutral):
    """Gather a pytree of per-lane columns through a ``segment_sort``
    permutation and reduce each segment (neutral substituted in invalid
    lanes). Returns [B, ...] where the representative (last) lane of each
    segment holds the segment's full reduction."""
    vals_s = jax.tree_util.tree_map(
        lambda v, n: jnp.where(
            _bshape(valid_s, v[order]), v[order], jnp.asarray(n, v.dtype)
        ),
        values,
        neutral,
    )
    return segmented_reduce_sorted(vals_s, seg_start, combine)


def preaggregate(seg_ids, values, valid, combine: Callable, neutral):
    """Pre-aggregate a batch by segment id with a general associative combine.

    seg_ids: int32 [B]  (e.g. slot * num_panes + pane)
    values:  pytree of [B, ...]
    valid:   bool [B]
    combine: associative (a, b) -> c over the pytree leaves
    neutral: pytree of scalars — identity element, substituted in invalid lanes

    Returns (rep_ids int32[B], rep_mask bool[B], reduced values [B, ...]):
    one representative lane per distinct segment carries the full reduction;
    rep_mask selects it. Invalid lanes sort to the end (id = INT32_MAX).
    """
    order, ids_s, valid_s, seg_start, rep_mask = segment_sort(seg_ids, valid)
    reduced = reduce_sorted(order, valid_s, seg_start, values, combine,
                            neutral)
    return ids_s, rep_mask, reduced


def scatter_combine(target, idx, updates, mask, kind: str,
                    unique: bool = False):
    """Scatter a batch into state with a built-in reducer.

    kind: 'add' | 'min' | 'max' | 'set'. idx lanes with mask=False must be
    out of range already (or are forced out here); duplicates are fine for
    add/min/max (hardware-combined) and resolved arbitrarily for 'set'.

    ``unique=True`` asserts the masked-in indices are pairwise distinct
    (e.g. pre-combined segment representatives): XLA then lowers the
    scatter without the duplicate-collision serialization. Masked-out
    lanes get DISTINCT out-of-range indices (base + lane) so the promise
    holds for them too — a shared sentinel would itself be a duplicate.
    """
    n = target.shape[0]
    if unique:
        safe_idx = jnp.where(
            mask, idx, n + jnp.arange(idx.shape[0], dtype=idx.dtype)
        )
    else:
        safe_idx = jnp.where(mask, idx, n)
    at = target.at[safe_idx]
    if kind == "add":
        return at.add(updates, mode="drop", unique_indices=unique)
    if kind == "min":
        return at.min(updates, mode="drop", unique_indices=unique)
    if kind == "max":
        return at.max(updates, mode="drop", unique_indices=unique)
    if kind == "set":
        return at.set(updates, mode="drop", unique_indices=unique)
    raise ValueError(f"unknown scatter kind {kind!r}")


def grouped_reduce(kind: str, gid, vals, n_groups: int):
    """Dictionary-encoded grouped reduction: one XLA scatter-reduce per
    aggregate. Shared by the batch DataSet and Table aggregation paths
    (the device analog of the reference's ReduceCombineDriver).

    gid: [N] int group ids in [0, n_groups); vals: [N] float values
    (ignored for 'count'). Returns a numpy [n_groups] float32 array.
    """
    import numpy as np

    g = jnp.asarray(np.asarray(gid))
    if kind == "count":
        return np.asarray(jnp.zeros(n_groups, jnp.float32).at[g].add(1.0))
    v = jnp.asarray(np.asarray(vals, np.float32))
    if kind == "sum":
        return np.asarray(jnp.zeros(n_groups, jnp.float32).at[g].add(v))
    if kind == "min":
        return np.asarray(
            jnp.full(n_groups, jnp.inf, jnp.float32).at[g].min(v)
        )
    if kind == "max":
        return np.asarray(
            jnp.full(n_groups, -jnp.inf, jnp.float32).at[g].max(v)
        )
    if kind in ("avg", "mean"):
        s = jnp.zeros(n_groups, jnp.float32).at[g].add(v)
        c = jnp.zeros(n_groups, jnp.float32).at[g].add(1.0)
        return np.asarray(s / c)
    raise ValueError(f"unknown aggregate kind {kind!r}")
