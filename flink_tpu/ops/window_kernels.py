"""Keyed window aggregation as whole-shard device kernels.

The reference's WindowOperator (SURVEY §2.5, WindowOperator.java:222) handles
one record at a time: assign windows, HashMap-probe the pane accumulator,
apply the user reduce, maybe register a timer; window fire replays per-key
timer callbacks sequentially (§3.3). TPU-native redesign:

  * Time is divided into aligned *panes* of `slide` ticks. A tumbling window
    is one pane; a sliding window of size k*slide is the combine of k
    consecutive panes (pane composition — the reference's aligned-window
    fast path AbstractKeyedTimePanes has the same idea, per key on heap).
  * Each shard holds accumulators for ALL its keys × a ring of R recent
    panes: acc[C*R, ...]. A micro-batch updates them with one upsert +
    one scatter-combine (built-in reducers) or sort+segmented-scan (general
    associative combines). No per-record control flow.
  * Window fire is watermark-driven and evaluates the ENTIRE key population
    of up to F window-ends per step as masked whole-array reads — the
    vectorized analog of draining the timer queue.

Late records (all their windows already fired) are dropped and counted,
matching the reference's default allowed-lateness=0 behavior
(WindowOperator.isWindowLate). Ring overflow (data older than the R-pane
horizon evicted before firing) is counted separately — R is the configured
out-of-orderness budget.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.ops import hashtable
from flink_tpu.ops.hashing import route_hash
from flink_tpu.ops.hashtable import SlotTable
from flink_tpu.ops.segment import (
    preaggregate,
    reduce_sorted,
    scatter_combine,
    segment_sort,
    sort_values,
)

# np scalar, not jnp: a module-level jnp call would initialize the JAX
# backend at import time (hanging any process whose platform override
# comes after `import flink_tpu`); np.int32 behaves identically inside
# jnp expressions
PANE_NONE = np.int32(-(2**31) + 1)


@dataclass(frozen=True)
class ReduceSpec:
    """How window contents aggregate.

    kind: 'sum' | 'min' | 'max' | 'count' | 'generic' | 'sketch'
    For 'generic', combine must be associative and jnp-traceable and
    neutral its identity element. For 'sketch', `sketch` is a spec object
    (ops/sketches.py) whose register array is the accumulator: records
    scatter-expand into it and panes compose elementwise.
    Mirrors the role of ReduceFunction under ReducingStateDescriptor
    (ref flink-core state API, SURVEY §2.1); `finalize` mirrors the result
    extraction the reference performs in the window function at fire time
    (WindowOperator.fire -> InternalWindowFunction.apply).
    """

    kind: str = "sum"
    dtype: Any = jnp.float32
    value_shape: Tuple[int, ...] = ()
    combine: Optional[Callable] = None
    neutral: Any = None
    sketch: Any = None
    finalize: Optional[Callable] = None      # [..., *value_shape] -> [..., *result_shape]
    result_shape: Optional[Tuple[int, ...]] = None
    result_dtype: Any = None

    @property
    def out_shape(self) -> Tuple[int, ...]:
        return self.value_shape if self.finalize is None else self.result_shape

    @property
    def out_dtype(self):
        return self.dtype if self.result_dtype is None else self.result_dtype

    def neutral_value(self):
        if self.kind == "sketch":
            return jnp.asarray(self.sketch.neutral, self.dtype)
        if self.neutral is not None:
            return jnp.asarray(self.neutral, self.dtype)
        if self.kind in ("sum", "count"):
            return jnp.zeros((), self.dtype)
        if self.kind == "min":
            return jnp.asarray(jnp.finfo(self.dtype).max
                               if jnp.issubdtype(self.dtype, jnp.floating)
                               else jnp.iinfo(self.dtype).max, self.dtype)
        if self.kind == "max":
            return jnp.asarray(jnp.finfo(self.dtype).min
                               if jnp.issubdtype(self.dtype, jnp.floating)
                               else jnp.iinfo(self.dtype).min, self.dtype)
        raise ValueError(f"generic reduce needs an explicit neutral")

    def combine_fn(self) -> Callable:
        if self.kind == "sketch":
            return {"add": lambda a, b: a + b, "max": jnp.maximum}[
                self.sketch.op
            ]
        return {
            "sum": lambda a, b: a + b,
            "count": lambda a, b: a + b,
            "min": jnp.minimum,
            "max": jnp.maximum,
            "generic": self.combine,
        }[self.kind]


@dataclass(frozen=True)
class WindowSpec:
    """Aligned time windows via pane composition.

    size_ticks must be a multiple of slide_ticks; panes_per_window =
    size // slide (1 = tumbling). ring = R panes of history retained;
    fires_per_step = max window-ends emitted per step.
    """

    size_ticks: int
    slide_ticks: int
    ring: int = 8
    fires_per_step: int = 2
    lateness_ticks: int = 0  # allowedLateness: late updates re-fire windows
    # overflow ring lanes (0 = disabled): records whose key finds no table
    # slot append (key, pane, value) here instead of being dropped; the
    # host drains the ring into the spill-store tier at fire boundaries
    # (the RocksDB-analog seam, RocksDBKeyedStateBackend.java:82)
    overflow: int = 0
    # accumulator memory order: "pane" (ring-major, pane columns
    # contiguous — sweeps/fires/purges are sequential-bandwidth passes)
    # or "slot" (slot-major, each key's pane vector contiguous — the
    # scatter writes one cache line per key). The runtime always runs
    # pane-major (measured best for the sweep-dominated step); the
    # device_update_ceiling bench sweeps both so the choice stays
    # grounded per platform instead of asserted.
    acc_layout: str = "pane"

    def __post_init__(self):
        if self.size_ticks % self.slide_ticks:
            raise ValueError("window size must be a multiple of slide")
        if self.panes_per_window + 1 > self.ring:
            raise ValueError(
                f"ring={self.ring} too small for {self.panes_per_window} panes/window"
            )
        if self.acc_layout not in ("pane", "slot"):
            raise ValueError(
                f"acc_layout must be pane|slot, got {self.acc_layout!r}"
            )

    @property
    def panes_per_window(self) -> int:
        return self.size_ticks // self.slide_ticks


@jax.tree_util.register_pytree_node_class
@dataclass
class WindowShardState:
    """All device state of one key-group shard of a window operator."""

    table: SlotTable
    acc: jax.Array          # [C*R, *value_shape] pane accumulators
    touched: jax.Array      # bool [C*R]
    pane_ids: jax.Array     # int32 [R]: absolute pane id in each ring slot
    max_pane: jax.Array     # int32 scalar: newest registered pane
    min_pane: jax.Array     # int32 scalar: oldest pane ever seen (fire start)
    watermark: jax.Array    # int32 scalar
    fired_through: jax.Array  # int32 scalar: last window-end pane emitted
    purged_through: jax.Array  # int32 scalar: panes <= this are known clean
    dropped_late: jax.Array     # int32 counter
    dropped_capacity: jax.Array  # int32 counter (records genuinely lost)
    fresh: jax.Array            # bool [C*R]: late-updated, pending re-fire
    n_fresh: jax.Array          # int32 scalar: count of set fresh flags
    # overflow ring [O] (O = win.overflow, possibly 0): records whose key
    # found no table slot, appended for host drain into the spill tier
    ovf_hi: jax.Array           # uint32 [O]
    ovf_lo: jax.Array           # uint32 [O]
    ovf_pane: jax.Array         # int32 [O]
    ovf_val: jax.Array          # [O, *value_shape] red.dtype
    ovf_n: jax.Array            # int32 scalar: filled lanes
    # changelog dirty bits [n_key_groups] (size 0 = tracking off):
    # kg_dirty[g] is set when a record of key group g touched this shard's
    # state since the host last cleared it — the device half of
    # incremental checkpointing (flink_tpu/checkpointing/): fetched with
    # the scalars at the step-boundary barrier, it tells the snapshot
    # which key groups' entries must ride the next delta
    kg_dirty: jax.Array         # bool [n_key_groups]
    # STATIC plane descriptor (pytree aux data, not a leaf): -1 = split
    # planes (acc + touched are separate arrays, the layout above);
    # >= 0 = PACKED planes — ``acc`` carries a trailing touch column
    # ([C*R, W+1] for a W-wide value, [C*R, 2] for scalars) updated by
    # the SAME scatter/sweep as the values, and ``touched`` is a
    # zero-length placeholder. The int is the logical value ndim (0 for
    # scalar reduces), which disambiguates [*, 2] scalar-packed from a
    # width-1 vector. Self-describing so snapshot/restore/queryable
    # consumers unpack without threading a spec (wk.split_packed).
    packed: int = -1

    def tree_flatten(self):
        return (
            (self.table, self.acc, self.touched, self.pane_ids, self.max_pane,
             self.min_pane, self.watermark, self.fired_through,
             self.purged_through, self.dropped_late, self.dropped_capacity,
             self.fresh, self.n_fresh, self.ovf_hi, self.ovf_lo,
             self.ovf_pane, self.ovf_val, self.ovf_n, self.kg_dirty),
            self.packed,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, packed=aux)


def ring_append(ovf, mask, hi, lo, pane, vals, O: int):
    """Append masked lanes to the overflow ring (shared by the update hot
    path and compaction eviction so the lost-record accounting cannot
    diverge).

    ovf: (ovf_hi, ovf_lo, ovf_pane, ovf_val, ovf_n) current ring.
    Returns (new_ovf, n_lost) where n_lost counts lanes beyond capacity.
    """
    ovf_hi, ovf_lo, ovf_pane, ovf_val, ovf_n = ovf
    O = jnp.int32(O)
    pos = ovf_n + jnp.cumsum(mask.astype(jnp.int32)) - 1
    fits = mask & (pos < O)
    idx = jnp.where(fits, pos, O)
    ovf_hi = ovf_hi.at[idx].set(hi, mode="drop")
    ovf_lo = ovf_lo.at[idx].set(lo, mode="drop")
    ovf_pane = ovf_pane.at[idx].set(pane, mode="drop")
    ovf_val = ovf_val.at[idx].set(vals, mode="drop")
    n_total = jnp.sum(mask, dtype=jnp.int32)
    n_lost = n_total - jnp.sum(fits, dtype=jnp.int32)
    ovf_n = jnp.minimum(ovf_n + n_total, O)
    return (ovf_hi, ovf_lo, ovf_pane, ovf_val, ovf_n), n_lost


def overflow_supported(red: ReduceSpec) -> bool:
    """The overflow tier stores raw record contributions and merges them
    host-side, so it needs a host-computable builtin combine over plain
    scalar blocks and no kernel-side finalize."""
    return red.kind in ("sum", "count", "min", "max") and red.finalize is None


# ------------------------------------------------- packed state planes
# ISSUE 7: the pane-ring accumulator and the touched (fire-eligibility)
# plane can live in ONE wider array — acc[..., :W] holds the values and
# acc[..., -1] a touch column combined under the SAME reducer op — so
# every update issues one scatter over W+1 lanes instead of a value
# scatter plus a bool scatter, and every ring-reset/purge sweep clears
# one plane instead of two. The touch column's neutral IS the untouched
# marker (sweeps that write the packed neutral reset both planes at
# once); any update drives it away from neutral (add: +1 per lane,
# min/max: 0 against the +/-extreme default neutral), so
# ``column != neutral`` recovers the bool plane exactly.

def packed_eligible(red: ReduceSpec) -> bool:
    """Packing needs a builtin combine whose DEFAULT neutral the touch
    marker provably escapes (an explicit user neutral could collide with
    the marker), and an at-most-1-D value (the column rides axis -1)."""
    return (
        red.kind in ("sum", "count", "min", "max")
        and red.neutral is None
        and red.sketch is None
        and len(red.value_shape) <= 1
    )


def _touch_marker(red: ReduceSpec):
    """Per-lane touch-column update: combines to something != neutral."""
    if red.kind in ("sum", "count"):
        return jnp.ones((), red.dtype)     # neutral 0 -> count of touches
    return jnp.zeros((), red.dtype)        # min/max: 0 vs the +/-extreme


def make_packed(acc, touched, red: ReduceSpec):
    """Pack split (acc, touched) planes into the [..., W+1] packed array.
    Works on host numpy and device arrays alike (restore/splice pack on
    the host; the jnp scalars below are compile-time constants)."""
    xp = np if isinstance(acc, np.ndarray) else jnp
    neutral = red.neutral_value().astype(red.dtype)
    marker = _touch_marker(red)
    col = xp.where(touched, marker, neutral).astype(acc.dtype)
    if len(red.value_shape) == 0:
        return xp.stack([acc, col], axis=-1)
    return xp.concatenate([acc, col[..., None]], axis=-1)


def split_packed(acc_packed, vdims: int, red: ReduceSpec):
    """Unpack a packed plane into logical (acc, touched). ``vdims`` is
    the state's ``packed`` descriptor (logical value ndim)."""
    neutral = red.neutral_value().astype(red.dtype)
    if isinstance(acc_packed, np.ndarray):
        # host staging path (checkpoint SYNC phase): keep the compare in
        # numpy — a jnp scalar operand would bounce the whole plane
        # through the device. The scalar constant fetch is the only
        # device touch.
        neutral = np.asarray(neutral)  # host-sync-ok: compile-time scalar constant, snapshot staging runs host-side by contract
    touched = acc_packed[..., -1] != neutral
    acc = acc_packed[..., 0] if vdims == 0 else acc_packed[..., :-1]
    return acc, touched


def acc_view(state: "WindowShardState", red: ReduceSpec):
    """Logical value accumulator regardless of plane packing."""
    if state.packed < 0:
        return state.acc
    return split_packed(state.acc, state.packed, red)[0]


def touched_view(state: "WindowShardState", red: ReduceSpec):
    """Logical bool touched plane regardless of plane packing."""
    if state.packed < 0:
        return state.touched
    return split_packed(state.acc, state.packed, red)[1]


# ------------------------------------------------ accumulator layouts
# Logical shape is always [R, C, ...] (ring rows x key slots); the
# flat storage order is the WindowSpec.acc_layout choice. Every kernel
# goes through these three helpers so pane-major and slot-major cannot
# drift semantically — only the memory walk differs.

def _acc2d(flat_arr, C: int, R: int, slot_major: bool):
    """[C*R, ...] flat storage -> logical [R, C, ...] view."""
    tail = flat_arr.shape[1:]
    if slot_major:
        return flat_arr.reshape((C, R) + tail).swapaxes(0, 1)
    return flat_arr.reshape((R, C) + tail)


def _acc_flat(arr2d, C: int, R: int, slot_major: bool):
    """Logical [R, C, ...] -> [C*R, ...] flat storage order."""
    tail = arr2d.shape[2:]
    if slot_major:
        return arr2d.swapaxes(0, 1).reshape((C * R,) + tail)
    return arr2d.reshape((C * R,) + tail)


def _flat_index(ring, slot, C: int, R: int, slot_major: bool):
    """Per-lane flat scatter index for (ring row, slot)."""
    if slot_major:
        return slot.astype(jnp.int32) * jnp.int32(R) + ring
    return ring * jnp.int32(C) + slot.astype(jnp.int32)


def init_state(capacity: int, probe_len: int, win: WindowSpec,
               red: ReduceSpec, layout: str = "hash",
               n_key_groups: int = 0,
               packed: bool = False) -> WindowShardState:
    """layout="direct": the DIRECT-INDEX state backend. For keys that are
    bounded non-negative ints (identity hi==0, lo < capacity — see
    hashing.key_identity64), the key IS its slot: no probe gathers, no
    claim scatters, no insert phase at all. The table is prefilled with
    identity rows (0, slot), so every consumer of table.keys (fire
    packing, snapshots, queryable reads) works unchanged; keys outside
    the bound take the overflow ring -> spill tier like any other
    non-resident key. The reference has no analog — its HeapKeyedState-
    Backend always pays the HashMap probe (StateTable, SURVEY §2.4);
    array-indexed state is the layout a TPU wants."""
    R = win.ring
    n_elems = capacity * R * int(np.prod(red.value_shape, dtype=np.int64))
    if n_elems > 2**31 - 1:
        raise ValueError(
            f"accumulator of {n_elems} elements overflows int32 scatter "
            f"indices; lower capacity/ring or the sketch register count"
        )
    if win.overflow and not overflow_supported(red):
        raise ValueError(
            f"overflow ring requires a builtin scalar reduce without "
            f"finalize, got kind={red.kind!r}"
        )
    if packed and not packed_eligible(red):
        raise ValueError(
            f"packed state planes require a builtin reduce with the "
            f"default neutral and an at-most-1-D value, got "
            f"kind={red.kind!r}"
        )
    neutral = red.neutral_value()
    if packed:
        # acc + touched in one plane: W value lanes + 1 touch column,
        # all initialized to the neutral (== untouched marker)
        W = int(np.prod(red.value_shape, dtype=np.int64)) or 1
        acc = jnp.broadcast_to(
            neutral, (capacity * R, W + 1)
        ).astype(red.dtype)
    else:
        acc = jnp.broadcast_to(
            neutral, (capacity * R,) + red.value_shape
        ).astype(red.dtype)
    O = win.overflow
    if layout == "direct":
        iota = jnp.arange(capacity, dtype=jnp.uint32)
        table = hashtable.SlotTable(
            jnp.stack([jnp.zeros_like(iota), iota], axis=1), probe_len
        )
    elif layout == "hash":
        table = hashtable.create(capacity, probe_len)
    else:
        raise ValueError(f"unknown state layout {layout!r}")
    return WindowShardState(
        table=table,
        acc=acc + jnp.zeros_like(acc),  # materialize (broadcast_to is a view)
        touched=jnp.zeros(0 if packed else capacity * R, bool),
        pane_ids=jnp.full((R,), PANE_NONE, jnp.int32),
        max_pane=jnp.asarray(PANE_NONE),
        min_pane=jnp.asarray(2**31 - 1, jnp.int32),
        watermark=jnp.asarray(-(2**31) + 1, jnp.int32),
        fired_through=jnp.asarray(PANE_NONE),
        purged_through=jnp.asarray(PANE_NONE),
        dropped_late=jnp.zeros((), jnp.int32),
        dropped_capacity=jnp.zeros((), jnp.int32),
        fresh=jnp.zeros(capacity * R, bool),
        n_fresh=jnp.zeros((), jnp.int32),
        ovf_hi=jnp.zeros(O, jnp.uint32),
        ovf_lo=jnp.zeros(O, jnp.uint32),
        ovf_pane=jnp.full((O,), PANE_NONE, jnp.int32),
        ovf_val=jnp.zeros((O,) + red.value_shape, red.dtype),
        ovf_n=jnp.zeros((), jnp.int32),
        kg_dirty=jnp.zeros(n_key_groups, bool),
        packed=len(red.value_shape) if packed else -1,
    )


def kg_occupancy(state: WindowShardState, n_key_groups: int,
                 red: Optional[ReduceSpec] = None,
                 win: Optional[WindowSpec] = None):
    """Per-key-group live-key occupancy of one shard: how many table keys
    with at least one touched pane hash into each key group. int32
    [n_key_groups].

    The device half of the skew telemetry (ISSUE 2): the reference can
    walk its per-key-group StateTables on the heap, but here the key
    population lives in HBM — a host-side sweep would fetch the whole
    [C, 2] key table plus the touched plane every refresh. On device it
    is one route-hash over the table keys and one scatter-add, and only
    the [n_key_groups] counts cross the link at the existing step-
    boundary barrier (same pattern as the kg_dirty changelog bits).

    ``red`` is required for packed-plane state (the touch column derives
    through the neutral); ``win`` only for a non-default acc layout.
    """
    C = state.table.capacity
    slot_major = win is not None and win.acc_layout == "slot"
    t_flat = touched_view(state, red) if state.packed >= 0 else state.touched
    R = t_flat.shape[0] // C
    touched2 = _acc2d(t_flat, C, R, slot_major)          # [R, C]
    fresh2 = _acc2d(state.fresh, C, R, slot_major)
    alive = touched2.any(axis=0) | fresh2.any(axis=0)
    keys = state.table.keys                              # [C, 2]
    kg = assign_to_key_group(
        route_hash(keys[:, 0], keys[:, 1], jnp), n_key_groups, jnp
    )
    return kg_batch_fill(kg, alive, n_key_groups)


def kg_batch_fill(kg, mask, n_key_groups: int):
    """Per-key-group record counts of one micro-batch: int32
    [n_key_groups] with mask-selected lanes bincounted by their key
    group. O(B) scatter riding the update step (the cheap half of the
    skew telemetry — occupancy says who HOLDS state, fill says who is
    RECEIVING traffic right now). Shared by the mask and exchange step
    bodies so the two routes count identically."""
    idx = jnp.where(mask, kg.astype(jnp.int32), jnp.int32(n_key_groups))
    return jnp.zeros(n_key_groups, jnp.int32).at[idx].add(1, mode="drop")


def _floor_div_pane(ts, slide: int):
    # floor division for possibly-negative ticks
    return jnp.floor_divide(ts, jnp.int32(slide)).astype(jnp.int32)


def compact_table(state: WindowShardState, win: WindowSpec,
                  red: ReduceSpec) -> WindowShardState:
    """Rebuild the key table keeping only keys with live (touched) panes.

    The table never frees slots on purge (linear-probe chains must stay
    intact, hashtable.remove_slots), so long-running streams with key
    churn fill it with dead identities. This whole-shard rebuild is the
    batched analog of RocksDB compaction: re-upsert live keys into a
    fresh table and remap the pane accumulators to the new slots. Run by
    the host at fire boundaries when the overflow ring reported pressure.
    """
    C = state.table.capacity
    R = win.ring
    slot_major = win.acc_layout == "slot"
    packed = state.packed >= 0
    acc3 = _acc2d(state.acc, C, R, slot_major)           # [R, C, ...]
    if packed:
        touched2 = acc3[..., -1] != red.neutral_value().astype(red.dtype)
    else:
        touched2 = _acc2d(state.touched, C, R, slot_major)
    fresh2 = _acc2d(state.fresh, C, R, slot_major)
    alive = touched2.any(axis=0) | fresh2.any(axis=0)   # [C]

    keys = state.table.keys                              # [C, 2]
    fresh_table = hashtable.create(C, state.table.probe_len)
    # re-inserting a whole shard at once has far heavier claim-race
    # contention than incremental batches: probe_len rounds (not the step
    # path's 4) so every key that fit before fits again
    new_keys, slot, ok, _ = hashtable._upsert_impl(
        fresh_table.keys, keys[:, 0], keys[:, 1],
        (C, state.table.probe_len, state.table.probe_len), alive,
    )
    # Parallel re-insert resolves claim races in a different order than
    # the incremental inserts did, so a live key can fail to fit the new
    # arrangement even though it fit the old one. Its pane state must NOT
    # be lost: export (key, pane, acc) rows into the overflow ring — the
    # host drained it immediately before compacting — and only count a
    # drop if even the ring is full.
    failed = alive & ~ok                                 # [C]
    idx = jnp.where(alive & ok, slot, C)                 # old slot -> new

    neutral = red.neutral_value().astype(red.dtype)
    # overflow export needs LOGICAL values; the remap moves the physical
    # plane (packed: values + touch column together, one vmap scatter)
    acc3_logical = acc3[..., :-1] if packed else acc3
    if packed and state.packed == 0:
        acc3_logical = acc3[..., 0]
    tail = acc3.shape[2:]

    ovf = (state.ovf_hi, state.ovf_lo, state.ovf_pane, state.ovf_val,
           state.ovf_n)
    if win.overflow:
        ent = (touched2 & failed[None, :]).reshape(-1)   # [R*C]
        key_rc = jnp.broadcast_to(keys[None, :, :], (R, C, 2)).reshape(-1, 2)
        pane_rc = jnp.broadcast_to(
            state.pane_ids[:, None], (R, C)
        ).reshape(-1)
        ovf, lost = ring_append(
            ovf, ent, key_rc[:, 0], key_rc[:, 1], pane_rc,
            acc3_logical.reshape((R * C,) + red.value_shape), win.overflow,
        )
    else:
        lost = jnp.sum(
            jnp.where(failed[None, :], touched2, False), dtype=jnp.int32
        )
    ovf_hi, ovf_lo, ovf_pane, ovf_val, ovf_n = ovf

    def remap_row(row):
        base = jnp.broadcast_to(neutral, (C,) + tail).astype(
            red.dtype
        ) + jnp.zeros((), red.dtype)
        return base.at[idx].set(row, mode="drop")

    new_acc3 = jax.vmap(remap_row)(acc3)
    new_fresh2 = jax.vmap(
        lambda row: jnp.zeros(C, bool).at[idx].set(row, mode="drop")
    )(fresh2)
    if packed:
        new_touched_flat = state.touched       # [0] placeholder
    else:
        new_touched2 = jax.vmap(
            lambda row: jnp.zeros(C, bool).at[idx].set(row, mode="drop")
        )(touched2)
        new_touched_flat = _acc_flat(new_touched2, C, R, slot_major)

    import dataclasses as _dc

    return _dc.replace(
        state,
        table=hashtable.SlotTable(new_keys, state.table.probe_len),
        acc=_acc_flat(new_acc3, C, R, slot_major),
        touched=new_touched_flat,
        fresh=_acc_flat(new_fresh2, C, R, slot_major),
        dropped_capacity=state.dropped_capacity + lost,
        ovf_hi=ovf_hi,
        ovf_lo=ovf_lo,
        ovf_pane=ovf_pane,
        ovf_val=ovf_val,
        ovf_n=ovf_n,
    )


def update(
    state: WindowShardState,
    win: WindowSpec,
    red: ReduceSpec,
    hi, lo, ts, values, valid,
    insert: bool = True,
    direct: bool = False,
    kg=None,
    precombine: bool = False,
    kg_fill: int = 0,
    clear_rows=None,
    kg_res=None,
):
    """Apply one micro-batch of records to shard state (pure function).

    The caller has already routed records: `valid` is False for lanes not
    owned by this shard. Replaces WindowOperator.processElement +
    HeapReducingState.add for the whole batch at once.

    Returns ``(new_state, activity, kgf)``. ``activity`` (int32 scalar)
    counts lanes whose key was NOT already resident in the table: newly
    inserted keys plus overflowed lanes — ``activity == 0`` certifies the
    batch was a pure in-place update. ``kgf`` is the per-key-group record
    count of this batch (int32 ``[kg_fill]``; ``[0]`` when ``kg_fill=0``)
    counting the PRE-late-check ``valid`` lanes — the traffic half of the
    skew telemetry, computed here so it can ride the shared sort below.

    ``insert=False`` compiles the steady-state FAST path: the key table is
    never mutated — one probe gather instead of upsert's five, and no claim
    scatters (~6x cheaper on TPU, where the statically-unrolled claim
    rounds dominate the step even when every key is already resident).
    Records whose key is absent take the overflow ring -> host spill tier
    (win.overflow must be > 0; their contributions merge back into window
    emissions exactly like capacity overflow). The executor watches
    ``activity`` through the lagged monitoring channel and flips back to
    the insert step while new keys are arriving, so the fast path only
    ever runs when misses are rare (runtime/executor.py step tiering).

    ``precombine=True`` (built-in reducers only) pre-aggregates the batch
    per (slot, pane) BEFORE the state scatter: ONE shared sort by flat
    accumulator index + a segmented scan, and every consumer rides the
    same permutation — the accumulator scatter, the fire-eligibility
    (touched) plane, the changelog kg_dirty bits, and the kg_fill skew
    counts (segment lane-counts scattered at the representatives, plus a
    residual scatter for the rare late/too-old/nofit lanes the sort
    excludes). Duplicate scatter indices serialize on TPU, and a hot-key
    batch is exactly the duplicate-heavy case; the rep scatters carry
    ``unique_indices`` so XLA skips the collision handling entirely.
    tools/check_segment_sort_seam.py keeps this the only sort a batch
    pays.

    ``clear_rows`` (bool ``[R]`` in logical ring-row space) folds a
    DEFERRED purge from the fused-fire scan into this batch's ring-reset
    sweep: rows flagged by the previous sub-step's
    ``advance_and_fire_resident`` clear here for free instead of paying
    their own sweep (every containing window already fired, so nothing
    reads them in between — see the resident-pipeline invariant there).
    Only valid with ``win.lateness_ticks == 0``.

    With PACKED planes (``state.packed >= 0``) the touched bits live in
    the accumulator's trailing column, so the value scatter and the
    ring-reset/purge sweeps maintain both planes in one pass and the
    separate touched scatter disappears.

    ``kg_res`` (bool ``[max_parallelism]``, tiered key-group state —
    ``state.tiers.*``) is this shard's HBM-residency mask: lanes whose
    key group reads False never touch the table or accumulators — they
    fall straight down the overflow ring to the host spill tier, which
    owns cold-group state. The mask is a plain operand, so the compiled
    step is shape-stable as residency changes; diversion is NEVER lossy
    (only ring exhaustion drops, same as any overflow) and requires
    ``win.overflow > 0`` for exactly that reason.
    """
    if kg_res is not None and not win.overflow:
        raise ValueError(
            "kg_res (tiered residency) requires an overflow ring "
            "(win.overflow > 0): non-resident lanes divert to the "
            "host spill tier through it"
        )
    C = state.table.capacity
    R = win.ring
    k = win.panes_per_window
    slot_major = win.acc_layout == "slot"
    packed = state.packed >= 0
    mine = valid            # pre-late-check routing mask (kg_fill contract)

    pane = _floor_div_pane(ts, win.slide_ticks)
    L = win.lateness_ticks

    # -- late check (ref WindowOperator.isWindowLate): drop iff every window
    # containing this pane has passed end-1+allowedLateness at the PRE-batch
    # watermark, or the pane's storage was already purged.
    base = jnp.maximum(
        state.watermark,
        jnp.int32(-(2**31) + 1 + win.slide_ticks) + jnp.int32(L),
    ) - jnp.int32(L)
    wm_pane_l = _floor_div_pane(base + 1 - win.slide_ticks, win.slide_ticks)
    last_end = pane + jnp.int32(k - 1)  # newest window-end pane covering rec
    late = valid & (
        (last_end <= wm_pane_l) | (pane <= state.purged_through)
    )
    n_late = jnp.sum(late, dtype=jnp.int32)
    live = valid & ~late

    # -- register/advance the pane ring -----------------------------------
    batch_max = jnp.max(jnp.where(live, pane, PANE_NONE))
    new_max = jnp.maximum(state.max_pane, batch_max)
    batch_min = jnp.min(jnp.where(live, pane, jnp.int32(2**31 - 1)))
    new_min = jnp.minimum(state.min_pane, batch_min)
    r_idx = jnp.arange(R, dtype=jnp.int32)
    # newest pane with (p % R) == r, p <= new_max
    p_r = new_max - jnp.mod(new_max - r_idx, jnp.int32(R))
    have_data = new_max != PANE_NONE
    p_r = jnp.where(have_data, p_r, PANE_NONE)
    stale = (p_r != state.pane_ids)
    # unfired data being evicted from the ring = capacity drop
    evicted = stale & (state.pane_ids != PANE_NONE) & (
        state.pane_ids + jnp.int32(k - 1) > state.fired_through
    )
    neutral = red.neutral_value()
    # logical [R, C, ...] views of the flat planes (pane-major keeps pane
    # columns CONTIGUOUS so ring resets/fires/purges are sequential-
    # bandwidth sweeps — the difference between ~0.2ms and ~20ms per step
    # on TPU for a 4M-slot shard; slot-major is the bench-swept variant)
    acc2d = _acc2d(state.acc, C, R, slot_major)
    if packed:
        touched2d = acc2d[..., -1] != neutral.astype(red.dtype)
    else:
        touched2d = _acc2d(state.touched, C, R, slot_major)
    n_evicted = jnp.sum(
        jnp.where(evicted[:, None], touched2d, False), dtype=jnp.int32
    )

    # unconditional sweep: a fused full pass costs far less than the
    # operand copies a lax.cond forces on 100MB+ carried buffers.
    # clear_rows (the fused-fire deferred purge) rides the same pass.
    clear = stale if clear_rows is None else (stale | clear_rows)
    acc2d = jnp.where(_expand(clear[:, None], acc2d),
                      neutral.astype(red.dtype), acc2d)
    if not packed:
        touched2d = jnp.where(clear[:, None], False, touched2d)
    if L > 0:
        # with no allowed lateness the fresh plane is never set, so its
        # sweep (and reshape) is statically elided — one fewer full pass
        # per batch
        fresh2d = _acc2d(state.fresh, C, R, slot_major)
        fresh2d = jnp.where(clear[:, None], False, fresh2d)
    pane_ids = jnp.where(stale, p_r, state.pane_ids)
    acc = _acc_flat(acc2d, C, R, slot_major)
    touched = (
        state.touched if packed else _acc_flat(touched2d, C, R, slot_major)
    )

    # -- drop records older than the ring horizon --------------------------
    oldest = new_max - jnp.int32(R - 1)
    too_old = live & (pane < oldest)
    n_too_old = jnp.sum(too_old, dtype=jnp.int32)
    live = live & ~too_old

    # -- changelog dirty bits: every surviving lane is about to mutate
    # this shard's state for its key group (table/accumulator scatter OR
    # the overflow ring -> spill tier), so mark the group dirty BEFORE the
    # fit check — over-marking a spilled lane's group is safe (its delta
    # just covers a group that only changed host-side), under-marking
    # would silently drop its state from the next incremental checkpoint.
    # `kg`: the caller's precomputed per-lane key groups (the routing
    # bodies in runtime/step.py already have them — skip the re-hash).
    # With precombine the marking moves AFTER the upsert so it can ride
    # the shared sort: segment representatives cover every FITTING lane's
    # group (same slot => same key => same group), and the rare nofit
    # lanes get their own scatter below — together exactly the live set
    # this eager scatter covers.
    KG = state.kg_dirty.shape[0]
    if KG and kg_fill and kg_fill != KG:
        raise ValueError(
            f"kg_fill group count {kg_fill} != changelog group count {KG}"
        )
    pre = precombine and red.kind in ("sum", "min", "max", "count")
    n_groups = KG or kg_fill or (
        kg_res.shape[0] if kg_res is not None else 0
    )
    if kg_res is not None and (KG or kg_fill) and \
            kg_res.shape[0] != (KG or kg_fill):
        raise ValueError(
            f"kg_res group count {kg_res.shape[0]} != "
            f"changelog/kg_fill group count {KG or kg_fill}"
        )
    if n_groups and kg is None:
        kg = assign_to_key_group(route_hash(hi, lo, jnp), n_groups, jnp)
    if KG and not pre:
        kg_dirty = state.kg_dirty.at[
            jnp.where(live, kg.astype(jnp.int32), jnp.int32(KG))
        ].set(True, mode="drop")
    else:
        kg_dirty = state.kg_dirty

    # -- tiered residency (state.tiers.*): divert lanes whose key group
    # is cold BEFORE the upsert — they must not claim table slots, and
    # `activity` must stay a pure hot-tier signal (a cold-group burst
    # may not flip the executor's insert/fast step tiering). The dirty
    # marking above deliberately still covers them: their spill-side
    # state changes under the same group.
    if kg_res is not None:
        tier_nonres = live & ~kg_res[kg.astype(jnp.int32)]
        live = live & ~tier_nonres
    else:
        tier_nonres = None

    # -- key upsert / lookup ------------------------------------------------
    # activity = lanes the CURRENT mode failed to handle natively:
    #   insert mode -> newly PLACED keys (population still growing; lanes
    #     that exhaust their probe chain are excluded — re-running insert
    #     can never place them, they belong to the spill tier)
    #   fast mode   -> missing lanes (spilled; the host flips back to
    #     insert mode only when these exceed a churn threshold)
    if direct:
        # direct-index layout (init_state layout="direct"): the key IS the
        # slot. No probe, no table mutation; out-of-bound keys spill.
        table = state.table
        ok = live & (hi == jnp.uint32(0)) & (lo < jnp.uint32(C))
        slot = jnp.where(ok, lo, jnp.uint32(C)).astype(jnp.int32)
        nofit = live & ~ok
        activity = jnp.zeros((), jnp.int32)   # no insert phase to tier
    elif insert:
        table, slot, ok, activity = hashtable.upsert_counted(
            state.table, hi, lo, live
        )
        nofit = live & ~ok
    else:
        table = state.table
        slot, found = hashtable.lookup(state.table, hi, lo)
        ok = found & live
        nofit = live & ~ok
        activity = jnp.sum(nofit, dtype=jnp.int32)
    if tier_nonres is not None:
        # cold-group lanes ride the same overflow ring as capacity
        # overcommit: appended (key, pane, value), host-merged into the
        # spill tier, merged back into emissions at fire — lossless
        nofit = nofit | tier_nonres
    live = live & ok

    # -- overflow ring: nofit records append (key, pane, value) for the
    # host to drain into the spill tier; only ring exhaustion drops
    ovf = (state.ovf_hi, state.ovf_lo, state.ovf_pane, state.ovf_val,
           state.ovf_n)
    if win.overflow:
        contrib = (
            jnp.ones_like(values) if red.kind == "count" else values
        ).astype(red.dtype)
        ovf, n_nofit = ring_append(
            ovf, nofit, hi, lo, pane, contrib, win.overflow
        )
    else:
        n_nofit = jnp.sum(nofit, dtype=jnp.int32)
    ovf_hi, ovf_lo, ovf_pane, ovf_val, ovf_n = ovf

    # -- scatter-combine into (slot, pane-ring) accumulators ----------------
    ring = jnp.mod(pane, jnp.int32(R))
    # flat storage index (layout-aware); slot==C when !ok lands in
    # [0, C*R) only via the scatter mask, which drops those lanes
    flat = _flat_index(ring, slot, C, R, slot_major)
    kgf = jnp.zeros(0, jnp.int32)
    kgf_pending = bool(kg_fill)
    if red.kind == "sketch":
        # records expand to per-register updates in the flattened
        # [C*R * prod(value_shape)] register space; one hardware scatter
        eidx, upd, emask = red.sketch.expand(flat, values, live)
        acc = scatter_combine(
            acc.reshape(-1), eidx, upd.astype(red.dtype), emask,
            red.sketch.op,
        ).reshape((C * R,) + red.value_shape)
    elif red.kind in ("sum", "min", "max", "count"):
        upd = values if red.kind != "count" else jnp.ones_like(values)
        upd = upd.astype(red.dtype)
        if packed:
            # the touch column rides the SAME scatter: marker lanes
            # combine to != neutral under the reducer op
            marker = jnp.broadcast_to(
                _touch_marker(red), upd.shape[: upd.ndim - state.packed]
            ).astype(red.dtype)
            if state.packed == 0:
                upd = jnp.stack([upd, marker], axis=-1)
            else:
                upd = jnp.concatenate([upd, marker[..., None]], axis=-1)
        op = {"sum": "add", "count": "add",
              "min": "min", "max": "max"}[red.kind]
        if pre:
            # duplicate-key collapse: ONE sort by flat accumulator index,
            # a segmented-scan reduce, then unique-index rep scatters —
            # acc (+ its packed touch column), touched, kg_dirty, and the
            # kg_fill counts all consume this single permutation
            order, ids_s, valid_s, seg_start, rep_mask = segment_sort(
                flat, live
            )
            upd_s = reduce_sorted(order, valid_s, seg_start, upd,
                                  red.combine_fn(), neutral)
            acc = scatter_combine(acc, ids_s, upd_s, rep_mask, op,
                                  unique=True)
            if not packed:
                touched = scatter_combine(
                    touched, ids_s, jnp.ones_like(ids_s, bool), rep_mask,
                    "set", unique=True,
                )
            kg32 = kg.astype(jnp.int32) if (KG or kg_fill) else None
            if KG:
                kg_dirty = kg_dirty.at[
                    jnp.where(rep_mask, kg32[order], jnp.int32(KG))
                ].set(True, mode="drop")
                # nofit lanes never reached a slot but still dirtied
                # their group (they spill host-side); usually all-masked
                kg_dirty = kg_dirty.at[
                    jnp.where(nofit, kg32, jnp.int32(KG))
                ].set(True, mode="drop")
            if kg_fill:
                # 4th consumer of the shared sort: per-segment lane
                # counts land at the representatives (same slot => same
                # key => same group), residual pre-late-check traffic
                # (late / too-old / nofit lanes, outside the sort's
                # validity) adds its own mostly-masked scatter
                seg_n = reduce_sorted(
                    order, valid_s, seg_start,
                    jnp.ones_like(ids_s), lambda a, b: a + b,
                    jnp.zeros((), ids_s.dtype),
                )
                kgf = jnp.zeros(kg_fill, jnp.int32).at[
                    jnp.where(rep_mask, kg32[order], jnp.int32(kg_fill))
                ].add(seg_n.astype(jnp.int32), mode="drop")
                resid = mine & ~live
                kgf = kgf.at[
                    jnp.where(resid, kg32, jnp.int32(kg_fill))
                ].add(1, mode="drop")
                kgf_pending = False
        else:
            acc = scatter_combine(acc, flat, upd, live, op)
    else:
        ids, rep_mask, reduced = preaggregate(
            flat, values.astype(red.dtype), live,
            combine=red.combine_fn(), neutral=neutral,
        )
        safe = jnp.where(rep_mask, ids, C * R)
        old = acc.at[safe].get(mode="clip")
        old_touched = touched.at[safe].get(mode="clip") & rep_mask
        merged = jnp.where(
            _expand(old_touched, old), red.combine_fn()(old, reduced), reduced
        )
        acc = acc.at[safe].set(merged, mode="drop")
    if not pre and not packed:
        touched = scatter_combine(
            touched, flat, jnp.ones_like(flat, bool), live, "set"
        )
    if kgf_pending:
        # non-precombined paths: the plain one-scatter bincount
        kgf = kg_batch_fill(kg, mine, kg_fill)

    # -- allowed lateness: records landing in already-fired windows mark
    # their pane "fresh" so those windows re-fire (ref late-firing panes)
    n_fresh = state.n_fresh
    if L > 0:
        fresh = _acc_flat(fresh2d, C, R, slot_major)
        late_upd = live & (pane <= state.fired_through)
        fresh = scatter_combine(
            fresh, flat, jnp.ones_like(flat, bool), late_upd, "set"
        )
        n_fresh = n_fresh + jnp.sum(late_upd, dtype=jnp.int32)
    else:
        fresh = state.fresh

    import dataclasses as _dc

    return _dc.replace(
        state,
        table=table,
        acc=acc,
        touched=touched,
        pane_ids=pane_ids,
        max_pane=new_max,
        min_pane=new_min,
        dropped_late=state.dropped_late + n_late,
        dropped_capacity=state.dropped_capacity + n_too_old + n_nofit + n_evicted,
        fresh=fresh,
        n_fresh=n_fresh,
        ovf_hi=ovf_hi,
        ovf_lo=ovf_lo,
        ovf_pane=ovf_pane,
        ovf_val=ovf_val,
        ovf_n=ovf_n,
        kg_dirty=kg_dirty,
    ), activity, kgf


def _expand(flag, val):
    extra = val.ndim - flag.ndim
    return flag.reshape(flag.shape + (1,) * extra)


@jax.tree_util.register_pytree_node_class
@dataclass
class FireResult:
    """Window fires, whole-shard masked. With allowedLateness the lane count
    doubles: F on-time lanes then F late re-fire lanes.

    mask:     bool [Ft, C] — slot emitted for fire lane f
    values:   [Ft, C, *value_shape]
    window_end_ticks: int32 [Ft] (exclusive end; PANE_NONE when lane unused)
    n_fires:  int32 scalar — number of valid lanes
    lane_valid: bool [Ft]
    """

    mask: jax.Array
    values: jax.Array
    window_end_ticks: jax.Array
    n_fires: jax.Array
    lane_valid: jax.Array

    def tree_flatten(self):
        return (self.mask, self.values, self.window_end_ticks, self.n_fires,
                self.lane_valid), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclass
class CompactFires:
    """Fire output packed on device so the host never transfers the dense
    [Ft, C] mask/value planes or the [C, 2] key table: for lane f, entries
    j < counts[f] are (key_hi[f, j], key_lo[f, j], values[f, j]) and the
    whole lane shares window_end_ticks[f]. The host reads the small fields
    (counts/lane_valid/window_end/n_fires), then slices only [:counts[f]]
    of the packed arrays — O(actual fires) transferred instead of O(F*C).
    """

    key_hi: jax.Array           # uint32 [Ft, C]
    key_lo: jax.Array           # uint32 [Ft, C]
    values: jax.Array           # [Ft, C, *out_shape]
    counts: jax.Array           # int32 [Ft] emitted keys per lane
    window_end_ticks: jax.Array  # int32 [Ft]
    n_fires: jax.Array          # int32 scalar: valid lanes
    lane_valid: jax.Array       # bool [Ft]
    # per-lane scalar reduction of the packed values (sum over emitted
    # slots; unused lanes pack zeros so no mask is needed). Lets a
    # device_reduce sink consume a drain by reading ONLY the small fields
    # — no O(fires) device->host transfer (runtime/sinks.py Sink.
    # device_reduce).
    value_sums: jax.Array       # float32 [Ft]

    def tree_flatten(self):
        return (self.key_hi, self.key_lo, self.values, self.counts,
                self.window_end_ticks, self.n_fires, self.lane_valid,
                self.value_sums), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
@dataclass
class ReducedFires:
    """Fire output reduced ON DEVICE to per-lane scalars — the drain path
    for device_reduce-capable sinks (runtime/sinks.py). Nothing O(C) is
    packed or transferred: the host reads five [Ft]-sized fields and the
    drain is done. Compared to CompactFires this skips the 3 full-capacity
    pack scatters per lane that dominate the fire step's cost (the
    reference's timer drain materializes every (key, window, value) triple;
    a counting/aggregating sink never needs them —
    ref WindowOperator.java:222 emit path).
    """

    counts: jax.Array            # int32 [Ft] fired keys per lane
    window_end_ticks: jax.Array  # int32 [Ft]
    n_fires: jax.Array           # int32 scalar: valid lanes
    lane_valid: jax.Array        # bool [Ft]
    value_sums: jax.Array        # float32 [Ft]

    def tree_flatten(self):
        return (self.counts, self.window_end_ticks, self.n_fires,
                self.lane_valid, self.value_sums), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def reduce_fires(fr: FireResult) -> ReducedFires:
    """Reduce a dense FireResult to per-lane (count, value-sum) scalars."""
    counts = jnp.sum(fr.mask, axis=1, dtype=jnp.int32)          # [Ft]
    masked = jnp.where(_expand(fr.mask, fr.values), fr.values, 0)
    vsums = jnp.sum(
        masked.reshape(masked.shape[0], -1), axis=1
    ).astype(jnp.float32)                                        # [Ft]
    return ReducedFires(counts, fr.window_end_ticks, fr.n_fires,
                        fr.lane_valid, vsums)


def _pack_fire_lanes(table: SlotTable, mask, values):
    """The pack math of compact_fires: per fire lane, compact the dense
    (mask, values) planes into prefix buffers of (key_hi, key_lo, value)
    plus (count, value_sum) scalars. Shared by compact_fires and the
    fused-fire resident advance (the gated in-scan pack) so the payload
    bytes cannot diverge between the split and resident drains.

    Round 7: the stream compaction is GATHER-formulated — cumsum the
    mask, then ``searchsorted`` finds output position i's source lane
    (the first lane whose running count reaches i+1; a vectorized
    binary search, NOT a sort) and three gathers move the payload.
    The previous three row SCATTERS per lane serialized on XLA CPU
    (~60ns/element — the single biggest term of the firing-stream
    ceiling); the gather form is ~8x cheaper there and collision-free
    everywhere, with bit-identical output."""
    C = table.capacity
    tk = table.keys
    ar = jnp.arange(C, dtype=jnp.int32)

    def pack(mask_f, vals_f):
        cs = jnp.cumsum(mask_f.astype(jnp.int32))
        count = cs[-1]
        sel = jnp.searchsorted(cs, ar + 1, side="left")
        ok = ar < count
        selc = jnp.minimum(sel, jnp.int32(C - 1))
        khi = jnp.where(ok, tk[selc, 0], jnp.uint32(0))
        klo = jnp.where(ok, tk[selc, 1], jnp.uint32(0))
        v = jnp.where(_expand(ok, vals_f), vals_f[selc],
                      jnp.zeros((), vals_f.dtype))
        vsum = jnp.sum(
            jnp.where(_expand(mask_f, vals_f), vals_f, 0.0)
        ).astype(jnp.float32)
        return khi, klo, v, count, vsum

    return jax.vmap(pack)(mask, values)


def compact_fires(table: SlotTable, fr: FireResult) -> CompactFires:
    """Pack a dense FireResult into per-lane prefix buffers on device.

    Delegates the compaction to ``_pack_fire_lanes`` (cumsum +
    searchsorted + gathers — see there). Replaces the host-side
    np.nonzero sweep over [Ft, C] masks and the full table.keys transfer
    the round-1 emit path paid every step.
    """
    khi, klo, v, counts, vsums = _pack_fire_lanes(table, fr.mask, fr.values)
    return CompactFires(khi, klo, v, counts, fr.window_end_ticks,
                        fr.n_fires, fr.lane_valid, vsums)


def _fire_plan(state: WindowShardState, win: WindowSpec, new_watermark):
    """Scalar half of a watermark advance: which window-ends are due.

    Shared by the split-dispatch fire step (advance_and_fire) and the
    fused-fire resident advance so the two drains cannot disagree about
    lane scheduling. Pure scalar/[F] math — nothing O(C)."""
    R = win.ring
    k = win.panes_per_window
    F = win.fires_per_step

    wm = jnp.maximum(state.watermark, jnp.asarray(new_watermark, jnp.int32))
    # window ending at pane p covers ticks [(p-k+1)*slide, (p+1)*slide);
    # fires when wm >= end-1. Clamp before the subtraction so the MIN
    # sentinel watermark cannot wrap int32 and spuriously fire everything.
    wm_c = jnp.maximum(wm, jnp.int32(-(2**31) + 1 + win.slide_ticks))
    wm_pane = _floor_div_pane(wm_c + 1 - win.slide_ticks, win.slide_ticks)

    have = state.max_pane != PANE_NONE
    oldest_registered = jnp.maximum(
        state.max_pane - jnp.int32(R - 1), state.min_pane
    )
    start = jnp.maximum(state.fired_through + 1, oldest_registered)
    start = jnp.where(state.fired_through == PANE_NONE,
                      oldest_registered, start)
    # Sliding windows ending up to k-1 panes past max_pane still contain
    # registered panes; only ends beyond max_pane+k-1 are certainly empty.
    end = jnp.where(
        have, jnp.minimum(wm_pane, state.max_pane + jnp.int32(k - 1)),
        start - 1,
    )
    n_due = jnp.maximum(end - start + 1, 0)
    n_now = jnp.minimum(n_due, F)

    f_idx = jnp.arange(F, dtype=jnp.int32)
    p_f = start + f_idx                      # window-end pane per fire lane
    lane_ok = f_idx < n_now
    window_end = jnp.where(
        lane_ok, (p_f + 1) * jnp.int32(win.slide_ticks), PANE_NONE
    )

    new_fired_through = jnp.where(
        n_due > F, start + n_now - 1, jnp.maximum(wm_pane, state.fired_through)
    )
    # Empty shards track wm_pane too, so fired_through stays consistent
    # across shards and a snapshot min() reflects the true global cut.
    new_fired_through = jnp.where(
        have, new_fired_through,
        jnp.maximum(state.fired_through, wm_pane),
    )
    return {
        "wm": wm, "wm_pane": wm_pane, "have": have, "start": start,
        "n_due": n_due, "n_now": n_now, "p_f": p_f, "lane_ok": lane_ok,
        "window_end": window_end, "new_fired_through": new_fired_through,
    }


def _state_fire_views(state: WindowShardState, win: WindowSpec,
                      red: ReduceSpec):
    """(acc3 logical, touched2) read views [R, C(, ...)] of the pane
    planes, regardless of plane packing and accumulator layout."""
    C = state.table.capacity
    R = win.ring
    slot_major = win.acc_layout == "slot"
    accp3 = _acc2d(state.acc, C, R, slot_major)
    if state.packed >= 0:
        neutral = red.neutral_value().astype(red.dtype)
        touched2 = accp3[..., -1] != neutral
        acc3 = accp3[..., 0] if state.packed == 0 else accp3[..., :-1]
    else:
        touched2 = _acc2d(state.touched, C, R, slot_major)
        acc3 = accp3
    return acc3, touched2


def _eval_fire_lanes(acc3, touched2, pane_ids, win: WindowSpec,
                     red: ReduceSpec, p_f, lane_ok, mask2):
    """Evaluate the windows ending at panes ``p_f`` for ALL keys.

    The emission mask comes from ``mask2`` (touched for on-time fires,
    fresh for late re-fires); values always combine every touched pane
    of the window. PANE-INDEXED (round 7): the window ending at pane p
    is the combine of panes p-k+1..p, and pane q can only live in ring
    row q % R — so each lane reads its k rows by direct (dynamic) row
    index, O(k*C) instead of the old O(R*C) sweep over every ring row.
    For a tumbling window (k=1, the throughput topology) that is a
    1/R-th of the old fire-evaluation cost — the single biggest term of
    the firing-stream ceiling (device_update_ceiling fire_grid). A row
    only contributes when its registered id equals q (an unrotated ring
    row still holding an older pane stays masked out)."""
    C = acc3.shape[1]
    R = win.ring
    k = win.panes_per_window
    combine = red.combine_fn()
    neutral = red.neutral_value()

    def fire_one(p, ok):
        vals = jnp.broadcast_to(
            neutral, (C,) + red.value_shape
        ).astype(red.dtype)
        emit = jnp.zeros(C, bool)
        for j in range(k):
            q = p - jnp.int32(k - 1) + jnp.int32(j)
            row = jnp.mod(q, jnp.int32(R))
            present = ok & (pane_ids[row] == q)
            col = acc3[row]
            col_t = touched2[row] & present
            vals = jnp.where(_expand(col_t, vals), combine(vals, col), vals)
            # combine(neutral, col) == col for first touch
            emit = emit | (mask2[row] & present)
        if red.finalize is not None:
            vals = red.finalize(vals)
        return emit, vals

    return jax.vmap(fire_one)(p_f, lane_ok)


def _purge_plan(state: WindowShardState, win: WindowSpec, wm,
                new_fired_through, fresh2=None):
    """Which ring rows purge at this advance, and the purged_through
    scalar. A pane leaves state only once BOTH every containing window
    has fired AND the lateness horizon has passed (and no re-fire is
    pending on it). Clamps before subtracting so the MIN sentinel cannot
    wrap int32."""
    k = win.panes_per_window
    base_l = jnp.maximum(
        wm,
        jnp.int32(-(2**31) + 1 + win.slide_ticks)
        + jnp.int32(win.lateness_ticks),
    ) - jnp.int32(win.lateness_ticks)
    wm_pane_l = _floor_div_pane(base_l + 1 - win.slide_ticks, win.slide_ticks)
    cutoff = jnp.minimum(new_fired_through, wm_pane_l)
    purgeable = (
        (state.pane_ids != PANE_NONE)
        & (state.pane_ids + jnp.int32(k - 1) <= cutoff)
        & (state.pane_ids > state.purged_through)
    )
    if fresh2 is not None:
        purgeable = purgeable & ~jnp.any(fresh2, axis=1)
    new_purged = jnp.where(
        cutoff == PANE_NONE,
        state.purged_through,
        jnp.maximum(
            state.purged_through,
            jnp.maximum(cutoff, PANE_NONE + jnp.int32(k)) - jnp.int32(k - 1),
        ),
    )
    return cutoff, purgeable, new_purged


def _clear_rows_planes(state: WindowShardState, win: WindowSpec,
                       red: ReduceSpec, rows):
    """Clear the flagged ring rows in the acc/touched planes (one sweep
    when packed). Returns (acc_flat, touched_flat)."""
    C = state.table.capacity
    R = win.ring
    slot_major = win.acc_layout == "slot"
    neutral = red.neutral_value().astype(red.dtype)
    accp = _acc2d(state.acc, C, R, slot_major)
    accp = jnp.where(_expand(rows[:, None], accp), neutral, accp)
    if state.packed >= 0:
        return _acc_flat(accp, C, R, slot_major), state.touched
    t2 = _acc2d(state.touched, C, R, slot_major)
    t2 = jnp.where(rows[:, None], False, t2)
    return (_acc_flat(accp, C, R, slot_major),
            _acc_flat(t2, C, R, slot_major))


def apply_pending_purge(state: WindowShardState, win: WindowSpec,
                        red: ReduceSpec, rows) -> WindowShardState:
    """Post-scan fixup of the fused-fire resident pipeline: clear ring
    rows whose purge was deferred into "the next update's ring-reset
    sweep" but whose megastep ended first. After this the state is
    bit-identical to the sequential update/advance_and_fire interleaving
    (the purged_through scalar already advanced at defer time)."""
    import dataclasses as _dc

    acc, touched = _clear_rows_planes(state, win, red, rows)
    return _dc.replace(state, acc=acc, touched=touched)


def advance_and_fire(
    state: WindowShardState,
    win: WindowSpec,
    red: ReduceSpec,
    new_watermark,
) -> Tuple[WindowShardState, FireResult]:
    """Advance the shard watermark and emit due window fires.

    Vectorized analog of HeapInternalTimerService.advanceWatermark +
    WindowOperator.onEventTime per key (ref §3.3): instead of per-key timer
    callbacks, each due window-end is evaluated for ALL keys at once; a
    sliding window combines its panes_per_window ring columns.
    """
    import dataclasses as _dc

    C = state.table.capacity
    R = win.ring
    k = win.panes_per_window
    F = win.fires_per_step
    slot_major = win.acc_layout == "slot"

    plan = _fire_plan(state, win, new_watermark)
    wm = plan["wm"]
    lane_ok = plan["lane_ok"]
    window_end = plan["window_end"]
    new_fired_through = plan["new_fired_through"]
    n_now = plan["n_now"]

    acc3, touched2 = _state_fire_views(state, win, red)
    big = jnp.int32(2**31 - 1)

    mask, values = _eval_fire_lanes(
        acc3, touched2, state.pane_ids, win, red, plan["p_f"], lane_ok,
        touched2,
    )

    # -- late re-fires (allowedLateness): windows <= fired_through whose
    # panes got late updates re-fire with their corrected full value.
    if win.lateness_ticks > 0:
        fresh2 = _acc2d(state.fresh, C, R, slot_major)

        def do_late(fresh2):
            fresh_any = jnp.any(fresh2, axis=1)  # [R]
            j_idx = jnp.arange(k, dtype=jnp.int32)
            wc = state.pane_ids[:, None] + j_idx[None, :]  # [R, k]
            need = (
                fresh_any[:, None]
                & (state.pane_ids != PANE_NONE)[:, None]
                & (wc <= new_fired_through)
            )
            wflat = jnp.where(need.reshape(-1), wc.reshape(-1), big)
            wsort = sort_values(wflat)
            first = jnp.concatenate(
                [jnp.ones((1,), bool), wsort[1:] != wsort[:-1]]
            ) & (wsort < big)
            rank = jnp.cumsum(first) - 1
            sel = jnp.full((F,), big)
            sel = sel.at[jnp.where(first, rank, F)].set(wsort, mode="drop")
            sel_ok = sel < big
            lmask, lvals = _eval_fire_lanes(
                acc3, touched2, state.pane_ids, win, red, sel, sel_ok,
                fresh2,
            )
            # clear fresh panes whose due windows were all covered this pass
            covered_c = (~need) | (wc[:, :, None] == sel[None, None, :]).any(-1)
            pane_done = covered_c.all(axis=1) & fresh_any
            fresh2b = jnp.where(pane_done[:, None], False, fresh2)
            return (lmask, lvals, sel, sel_ok, fresh2b,
                    jnp.sum(fresh2b, dtype=jnp.int32))

        # unconditionally evaluated: with no fresh panes every selection
        # comes back empty and the state is unchanged. A lax.cond here
        # costs ~30ms per invocation on the tunneled TPU runtime — far
        # more than the masked sweep it would skip.
        lmask, lvals, lsel, lsel_ok, fresh2, n_fresh = do_late(fresh2)
        mask = jnp.concatenate([mask, lmask])
        values = jnp.concatenate([values, lvals])
        window_end = jnp.concatenate(
            [window_end,
             jnp.where(lsel_ok, (lsel + 1) * jnp.int32(win.slide_ticks),
                       PANE_NONE)]
        )
        lane_valid = jnp.concatenate([lane_ok, lsel_ok])
        n_fires = n_now + jnp.sum(lsel_ok, dtype=jnp.int32)
    else:
        fresh2 = None
        lane_valid = lane_ok
        n_fires = n_now
        n_fresh = state.n_fresh

    # -- purge (unconditional sweep — see update(): conds copy the big
    # carried buffers)
    _cutoff, purgeable, new_purged = _purge_plan(
        state, win, wm, new_fired_through, fresh2=fresh2
    )
    acc, touched = _clear_rows_planes(state, win, red, purgeable)

    new_state = _dc.replace(
        state,
        acc=acc,
        touched=touched,
        watermark=wm,
        fired_through=new_fired_through,
        purged_through=new_purged,
        fresh=(
            _acc_flat(fresh2, C, R, slot_major)
            if win.lateness_ticks > 0 else state.fresh
        ),
        n_fresh=n_fresh,
        # fires/purges are NOT marked dirty: they are global sweeps fully
        # determined by the scalars (fired_through/watermark), and chain
        # recovery re-applies the same purge cutoff to merged entries
        # (checkpointing/recovery.py), so per-group bits stay precise
    )
    return new_state, FireResult(mask, values, window_end, n_fires, lane_valid)


def advance_and_fire_resident(
    state: WindowShardState,
    win: WindowSpec,
    red: ReduceSpec,
    new_watermark,
    reduced: bool = False,
) -> Tuple[WindowShardState, jax.Array, "CompactFires | ReducedFires"]:
    """Fused-fire advance for the RESIDENT megastep scan (ISSUE 7).

    The split path dispatches fire as its own device step and breaks
    every K-group at a pane boundary; here the whole advance runs inside
    the scan body after each sub-batch's update, with two cost moves
    that make a per-sub-step advance affordable:

    * the O(F*R*C) fire evaluation + payload pack runs under ``lax.cond``
      on ``n_now > 0`` — sub-steps that cross no pane boundary (the
      overwhelming steady-state majority) pay only the scalar plan. The
      cond is READ-ONLY over the big state (its outputs are just the
      packed fire buffers), so no identity-branch state copies arise,
      and the skip branch's all-zero payload is bit-identical to packing
      an empty fire.
    * the purge plane-clears are DEFERRED: this call advances the
      ``purged_through`` scalar immediately but returns the purgeable
      row mask for the NEXT sub-step's update to fold into its ring-
      reset sweep (wk.update ``clear_rows``) — or for
      ``apply_pending_purge`` after the scan. Safe because a deferred
      row's every window already fired: no in-scan reader revisits it
      (fire lanes start past it, late-dropped records cannot scatter
      into it) until a sweep clears it.

    Returns ``(state', purge_rows, fires)`` with ``fires`` a
    CompactFires for THIS sub-step — or, with ``reduced=True``, a
    ReducedFires: per-lane (count, value_sum) scalars only, NO payload
    planes at all. The reduced mode exists because the scan must stack
    a payload slot for EVERY sub-step (crossing or not), and those
    [F, C] zero-writes are the resident pipeline's whole overhead on a
    quiet stream; device_reduce sink topologies (runtime/sinks.py)
    never read the payload, so they skip it — the in-scan analog of
    build_window_fire_reduced_step. With allowed lateness the fresh/
    re-fire machinery is needed every sub-step anyway, so that cold
    path delegates to the classic advance (no gate, no deferral).
    """
    import dataclasses as _dc

    R = win.ring
    if win.lateness_ticks > 0:
        st, fr = advance_and_fire(state, win, red, new_watermark)
        packed_fr = (
            reduce_fires(fr) if reduced else compact_fires(st.table, fr)
        )
        return st, jnp.zeros(R, bool), packed_fr

    C = state.table.capacity
    F = win.fires_per_step

    plan = _fire_plan(state, win, new_watermark)
    wm = plan["wm"]
    n_now = plan["n_now"]
    lane_ok = plan["lane_ok"]

    _cutoff, purgeable, new_purged = _purge_plan(
        state, win, wm, plan["new_fired_through"]
    )

    def _eval_compact():
        acc3, touched2 = _state_fire_views(state, win, red)
        mask, values = _eval_fire_lanes(
            acc3, touched2, state.pane_ids, win, red, plan["p_f"],
            lane_ok, touched2,
        )
        return _pack_fire_lanes(state.table, mask, values)

    def _skip_compact():
        return (
            jnp.zeros((F, C), jnp.uint32),
            jnp.zeros((F, C), jnp.uint32),
            jnp.zeros((F, C) + red.out_shape, red.out_dtype),
            jnp.zeros(F, jnp.int32),
            jnp.zeros(F, jnp.float32),
        )

    def _eval_reduced():
        acc3, touched2 = _state_fire_views(state, win, red)
        mask, values = _eval_fire_lanes(
            acc3, touched2, state.pane_ids, win, red, plan["p_f"],
            lane_ok, touched2,
        )
        # == reduce_fires over this lane set (bit-parity with the
        # split drain's on-chip reduction)
        counts = jnp.sum(mask, axis=1, dtype=jnp.int32)
        masked = jnp.where(_expand(mask, values), values, 0)
        vsums = jnp.sum(
            masked.reshape(masked.shape[0], -1), axis=1
        ).astype(jnp.float32)
        return counts, vsums

    def _skip_reduced():
        return jnp.zeros(F, jnp.int32), jnp.zeros(F, jnp.float32)

    if reduced:
        counts, vsums = jax.lax.cond(n_now > 0, _eval_reduced,
                                     _skip_reduced)
        fires = ReducedFires(counts, plan["window_end"], n_now, lane_ok,
                             vsums)
    else:
        khi, klo, v, counts, vsums = jax.lax.cond(
            n_now > 0, _eval_compact, _skip_compact
        )
        fires = CompactFires(khi, klo, v, counts, plan["window_end"],
                             n_now, lane_ok, vsums)
    new_state = _dc.replace(
        state,
        watermark=wm,
        fired_through=plan["new_fired_through"],
        purged_through=new_purged,
    )
    return new_state, purgeable, fires


# --------------------------------------------- canonical kernel families

def kernel_family_grid(capacity: int = 64, probe_len: int = 4,
                       batch: int = 8):
    """Raw-kernel half of the canonical audit grid (the step-builder
    half lives in runtime/step.py kernel_family_grid, next to the
    builders): ``[(name, fn, example_args)]`` for every public kernel in
    this module, one entry per layout/plane variant the runtime
    dispatches. The compiled-graph auditor (tools/lint trace tier)
    make_jaxprs each entry and holds its primitive counts against the
    checked-in op-budget ledger — the one-sort precombine seam and the
    packed single-scatter plane are contracts here, not prose. None of
    these are jitted or donated: the jit/donation story is the step
    builders'; this grid pins the kernel bodies themselves."""
    win = WindowSpec(4, 2, ring=4, fires_per_step=2, overflow=4)
    red = ReduceSpec("sum", jnp.float32)
    B = batch
    hi = jnp.arange(B, dtype=jnp.uint32) * jnp.uint32(2654435761)
    lo = jnp.arange(B, dtype=jnp.uint32)
    hi_d = jnp.zeros(B, jnp.uint32)
    lo_d = jnp.arange(B, dtype=jnp.uint32) % jnp.uint32(capacity)
    ts = jnp.zeros(B, jnp.int32)
    values = jnp.ones(B, jnp.float32)
    valid = jnp.ones(B, bool)
    wm = jnp.zeros((), jnp.int32)
    st = init_state(capacity, probe_len, win, red)
    st_d = init_state(capacity, probe_len, win, red, layout="direct")
    st_p = init_state(capacity, probe_len, win, red, packed=True)

    def mk_update(direct=False, insert=True, precombine=False):
        def kernel(state, k_hi, k_lo, k_ts, k_values, k_valid):
            return update(state, win, red, k_hi, k_lo, k_ts, k_values,
                          k_valid, insert=insert, direct=direct,
                          precombine=precombine)
        return kernel

    def fire_compact(state, k_wm):
        state, fr = advance_and_fire(state, win, red, k_wm)
        return state, compact_fires(state.table, fr)

    def fire_reduced(state, k_wm):
        state, fr = advance_and_fire(state, win, red, k_wm)
        return state, reduce_fires(fr)

    def fire_resident(state, k_wm):
        return advance_and_fire_resident(state, win, red, k_wm)

    def fire_resident_reduced(state, k_wm):
        return advance_and_fire_resident(state, win, red, k_wm,
                                         reduced=True)

    def compact(state):
        return compact_table(state, win, red)

    def occupancy(state):
        return kg_occupancy(state, 8, red=red, win=win)

    upd = (hi, lo, ts, values, valid)
    upd_d = (hi_d, lo_d, ts, values, valid)
    return [
        ("wk.update.hash", mk_update(), (st,) + upd),
        ("wk.update.direct", mk_update(direct=True), (st_d,) + upd_d),
        ("wk.update.hash.precombine", mk_update(precombine=True),
         (st,) + upd),
        ("wk.update.hash.packed", mk_update(), (st_p,) + upd),
        ("wk.update_fast.hash", mk_update(insert=False), (st,) + upd),
        ("wk.fire.compact", fire_compact, (st, wm)),
        ("wk.fire.reduced", fire_reduced, (st, wm)),
        ("wk.fire.resident", fire_resident, (st, wm)),
        ("wk.fire.resident_reduced", fire_resident_reduced, (st, wm)),
        ("wk.compact_table", compact, (st,)),
        ("wk.occupancy", occupancy, (st,)),
    ]
