"""Rolling (non-windowed) keyed aggregation — StreamGroupedReduce analog.

The reference's StreamGroupedReduce keeps one ValueState per key and emits
the updated accumulator for EVERY input record (SURVEY §2.5 built-in
operators). Batched TPU redesign: sort the batch by state slot, run a
segmented inclusive scan (any associative combine), add the pre-batch
accumulator of each key's segment, emit per-record rolling outputs in the
original lane order, and scatter each segment's total back into state —
one kernel for the whole batch instead of B sequential probe/update/emits.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from flink_tpu.ops import hashtable
from flink_tpu.ops.hashtable import SlotTable
from flink_tpu.ops import segment
from flink_tpu.ops.segment import _bshape, segmented_reduce_sorted
from flink_tpu.ops.window_kernels import ReduceSpec


@jax.tree_util.register_pytree_node_class
@dataclass
class RollingShardState:
    table: SlotTable
    acc: jax.Array      # [C, *value_shape]
    touched: jax.Array  # [C]
    dropped_capacity: jax.Array

    def tree_flatten(self):
        return (self.table, self.acc, self.touched, self.dropped_capacity), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def init_state(capacity: int, probe_len: int, red: ReduceSpec) -> RollingShardState:
    neutral = red.neutral_value()
    acc = jnp.broadcast_to(neutral, (capacity,) + red.value_shape).astype(red.dtype)
    return RollingShardState(
        table=hashtable.create(capacity, probe_len),
        acc=acc + jnp.zeros_like(acc),
        touched=jnp.zeros(capacity, bool),
        dropped_capacity=jnp.zeros((), jnp.int32),
    )


def update(
    state: RollingShardState, red: ReduceSpec, hi, lo, values, valid
) -> Tuple[RollingShardState, jax.Array, jax.Array]:
    """Returns (state', outputs [B, *value_shape], out_valid [B]).

    outputs[i] = accumulator value of record i's key immediately after
    record i is applied (reference rolling-reduce semantics, batch order =
    lane order).
    """
    C = state.table.capacity
    combine = red.combine_fn()
    neutral = red.neutral_value()

    # 8 claim rounds: no spill tier here — see session_windows.py
    table, slot, ok = hashtable.upsert(state.table, hi, lo, valid,
                                       max_rounds=8)
    n_nofit = jnp.sum(valid & ~ok, dtype=jnp.int32)
    live = valid & ok

    big = jnp.int32(2**31 - 1)
    ids = jnp.where(live, slot, big)
    order = segment.argsort_ids(ids)
    ids_s = ids[order]
    vals = values.astype(red.dtype)
    vals_s = jnp.where(
        _bshape(live[order], vals[order]), vals[order],
        jnp.asarray(neutral, red.dtype),
    )
    seg_start = jnp.concatenate([jnp.ones((1,), bool), ids_s[1:] != ids_s[:-1]])
    prefix = segmented_reduce_sorted(vals_s, seg_start, combine)

    # fold the pre-batch accumulator into every lane of touched segments
    safe = jnp.where(ids_s < C, ids_s, C - 1)
    seg_old = state.acc[safe]
    seg_touched = state.touched[safe] & (ids_s < C)
    rolled = jnp.where(
        _bshape(seg_touched, prefix), combine(seg_old, prefix), prefix
    )

    # outputs back in lane order
    inv = segment.invert_permutation(order)
    outputs = rolled[inv]
    out_valid = live

    # segment totals -> state
    seg_end = jnp.concatenate([ids_s[1:] != ids_s[:-1], jnp.ones((1,), bool)])
    rep = seg_end & (ids_s < C)
    rep_idx = jnp.where(rep, ids_s, C)
    acc = state.acc.at[rep_idx].set(rolled.astype(red.dtype), mode="drop")
    touched = state.touched.at[rep_idx].set(True, mode="drop")

    new_state = RollingShardState(
        table=table, acc=acc, touched=touched,
        dropped_capacity=state.dropped_capacity + n_nofit,
    )
    return new_state, outputs, out_valid
