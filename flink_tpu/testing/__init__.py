"""Test-support tooling shipped inside the package (ref
flink-test-utils' role): the deterministic fault-injection harness
(`faults`) lives here so production modules can carry always-present,
no-op-when-disabled injection hooks without importing anything from the
test tree."""
