"""Deterministic fault injection (the chaos-testing seam of the
failure-containment layer, docs/fault-tolerance.md).

Production modules call ``faults.inject("<point>", **ctx)`` at named
injection points. With no injector installed — the production default —
``inject`` is one module-global ``None`` check, so the hooks cost
nothing measurable and nothing test-only leaks into the hot path.
Tests install a :class:`FaultInjector` built from :class:`FaultRule`\\ s
whose triggers are **occurrence-indexed** (fire on the k-th hit of a
point, or every k-th hit, bounded by ``times``) or seeded-random
(``prob`` drawn from one ``random.Random(seed)``), so every run of a
chaos test injects the identical fault schedule.

Injected failures are REAL exception types (``OSError``,
``ConnectionResetError``, ...) so the containment code under test
exercises exactly the branch a production fault would take.

Injection-point catalog (the sites wired in this repo):

    fs.open                 core/filesystem open() of a write handle
    ckpt.entries.write      CheckpointStorage.write, before any file IO
    ckpt.publish            CheckpointStorage.write, before the atomic
                            rename (a crash mid-write)
    ckpt.generic.write      CheckpointStorage.write_generic
    ckpt.manifest.write     checkpointing/manifest.write_manifest; the
                            ``torn`` action writes a truncated
                            manifest.json and then raises
    materializer.task       start of every async materialization task
                            (``sleep`` here is the slow-I/O fault)
    dcn.recv                runtime/dcn ring, before every socket recv
    dcn.send                runtime/dcn ring, before every frame send
                            (ctx carries ``sock`` so a ``call`` rule can
                            hard-close the link — a peer reset)
    ingest.producer         top of the prefetch-thread loop, OUTSIDE its
                            error-delivery try: a raising rule kills the
                            thread without delivering (thread death)
    ckpt.read.primary       runtime/checkpoint CheckpointStorage, before
                            a PRIMARY-storage read of one checkpoint
                            directory (local-cache hits skip it) — a
                            ``sleep`` rule here models remote-storage
                            fetch latency in the MTTR drill
    ckpt.manifest.read      checkpointing/manifest.read_manifest: the
                            restore-time chain walk (the read half of
                            the torn-write story)
    ckpt.local.put          checkpointing/local LocalSnapshotCache.put,
                            inside the best-effort try: an injected
                            OSError exercises "mirror fails, checkpoint
                            stays durable, job lives"
    ckpt.local.verify       LocalSnapshotCache.verify/identity_ok read
                            path: an injected error takes the corrupt-
                            entry branch (drop + fall back to primary)
    dcn.ckpt.write          runtime/dcn per-process checkpoint write: a
                            raising rule models a process crashing mid-
                            cut (restore skips the incomplete cid)
    dcn.ckpt.read           runtime/dcn restore-time read of this
                            process's half of the cut
    step.dispatch           runtime/executor windowed step loop, at the
                            top of every update dispatch (single step
                            and K-fused megastep) — the seam the
                            ``device_loss`` fault class (below) rides:
                            a dying chip surfaces exactly here, as a
                            runtime error out of the dispatch
    step.drain              runtime/executor resident ring drain, before
                            the drain dispatch (warmup drains exempt) —
                            the mid-drain crash seam of the exactly-once
                            drain tests
    tier.demote.write       runtime/tiers.fold_entries, before a demoted
                            key-group's entries fold into the host pane
                            stores — a crash between a demote and its
                            checkpoint loses only process-local host
                            memory the next restore re-seeds from the
                            last cut (tests/test_tiers.py)
    tier.promote.read       runtime/tiers.fetch_group_entries, before a
                            promote pulls a key-group's pending entries
                            out of the pane stores (the read half of the
                            tier swap)
    ckpt.spill.read         native SpillStore.load, before the
                            checksummed file read — a corrupt or torn
                            spill dump surfaces here and the caller
                            falls back instead of restoring bad state
    controller.apply        runtime/executor controller rebalance, after
                            the decision but BEFORE the savepoint-cut
                            _rescale_live — a crash mid-rebalance lands
                            ahead of the cut, so restart must recover
                            exactly-once from the last completed
                            checkpoint with the PRE-rebalance slicing
                            re-latched (tests/test_controller.py)

Actions:

    raise   raise ``exc`` (an exception instance; re-raised by value)
    sleep   time.sleep(delay_s) — stalls/slow I/O
    torn    raise :class:`TornWrite`; the site writes a truncated
            payload first, then fails the operation
    call    invoke ``fn(ctx)`` — e.g. close a socket handed in ctx
    kill    raise :class:`ThreadKilled` (a BaseException): unlike
            ``raise`` it sails through every ``except Exception``
            containment layer between the point and the thread's top
            frame — HARD thread/producer death, the "process segment
            just vanished" failure mode

Fault classes beyond the raw actions: :func:`device_loss_rule` builds
the ``device_loss`` class — a ``raise`` rule at ``step.dispatch``
carrying a :class:`runtime.elastic.DeviceLostError` that names the
lost mesh shard, which the elastic recovery path (docs/fault-
tolerance.md) answers with a re-plan onto the survivors instead of a
crash loop.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


class TornWrite(Exception):
    """Raised by ``inject`` for ``action="torn"``: the site must write a
    truncated payload before failing the operation (a torn write leaves
    PARTIAL bytes on disk, unlike a clean error)."""


class ThreadKilled(BaseException):
    """Raised by ``inject`` for ``action="kill"``. Deliberately a
    BaseException: the containment layers under test catch ``Exception``,
    so a kill rule dies HARD through all of them — the closest userspace
    analog of a thread that simply ceases to run. The survivors (the
    consumer detecting a dead producer, the watchdog detecting the
    resulting stall) are what the rule exercises."""


@dataclass
class FaultRule:
    """One scheduled fault. Trigger precedence: ``at`` (0-based hit
    index) > ``every`` (every k-th hit) > ``prob`` (per-hit coin flip on
    the injector's seeded RNG). ``times`` bounds total firings."""

    point: str
    action: str = "raise"            # raise | sleep | torn | call | kill
    exc: Optional[BaseException] = None
    delay_s: float = 0.0
    fn: Optional[Callable[[dict], Any]] = None
    at: Optional[int] = None
    every: Optional[int] = None
    prob: float = 0.0
    times: int = 1
    fired: int = field(default=0, compare=False)

    def wants(self, hit_index: int, rng: random.Random) -> bool:
        if self.times and self.fired >= self.times:
            return False
        if self.at is not None:
            return hit_index == self.at
        if self.every is not None:
            return self.every > 0 and hit_index % self.every == 0
        if self.prob:
            return rng.random() < self.prob
        return True                   # unconditional (bounded by times)


class FaultInjector:
    """Seeded, occurrence-indexed fault scheduler. Thread-safe: hit
    counters and the RNG are guarded (injection points fire from the
    step loop, the materializer thread, the prefetch thread, and DCN
    ring peers); the ACTION runs outside the lock so an injected sleep
    never serializes unrelated points."""

    def __init__(self, rules, seed: int = 0):
        self.rules: List[FaultRule] = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._hits: Dict[str, int] = {}
        self.fired: List[dict] = []   # audit log for test assertions
        self._lock = threading.Lock()

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def fired_at(self, point: str) -> List[dict]:
        with self._lock:
            return [f for f in self.fired if f["point"] == point]

    def hit(self, point: str, ctx: dict) -> None:
        due: List[FaultRule] = []
        with self._lock:
            idx = self._hits.get(point, 0)
            self._hits[point] = idx + 1
            for rule in self.rules:
                if rule.point == point and rule.wants(idx, self._rng):
                    rule.fired += 1
                    self.fired.append({
                        "point": point, "hit": idx, "action": rule.action,
                    })
                    due.append(rule)
        for rule in due:
            if rule.action == "sleep":
                time.sleep(rule.delay_s)
            elif rule.action == "call":
                if rule.fn is not None:
                    rule.fn(ctx)
            elif rule.action == "torn":
                raise TornWrite(f"injected torn write at {point}")
            elif rule.action == "kill":
                raise ThreadKilled(f"injected thread kill at {point}")
            else:
                raise rule.exc if rule.exc is not None else RuntimeError(
                    f"injected fault at {point}"
                )


def device_loss_rule(shard: int = 0, **trigger) -> FaultRule:
    """The ``device_loss`` fault class: one mesh shard's device dies at
    the chosen occurrence of the ``step.dispatch`` point. The injected
    exception is a real :class:`~flink_tpu.runtime.elastic.
    DeviceLostError` naming the lost shard, so the containment under
    test — the elastic re-plan in the executor's recovery path — takes
    exactly the branch a production chip loss would. ``trigger`` passes
    through to :class:`FaultRule` (``at=``/``every=``/``prob=``/
    ``times=``)."""
    # lazy import: runtime modules import this module at load time
    from flink_tpu.runtime.elastic import DeviceLostError

    return FaultRule(
        "step.dispatch",
        exc=DeviceLostError(
            f"injected device loss: mesh shard {int(shard)}",
            lost_shards=(int(shard),),
        ),
        **trigger,
    )


# -- installation ------------------------------------------------------
# ONE process-global active injector: the hooks live in hot-adjacent
# modules, and per-job plumbing would thread a handle through a dozen
# constructors for a facility that is off outside tests.

_ACTIVE: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> None:
    global _ACTIVE
    _ACTIVE = injector


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def get() -> Optional[FaultInjector]:
    return _ACTIVE


@contextlib.contextmanager
def active(injector: FaultInjector):
    """Scoped installation for tests; always uninstalls."""
    install(injector)
    try:
        yield injector
    finally:
        uninstall()


def inject(point: str, **ctx) -> None:
    """The production-side hook: a no-op unless an injector is
    installed. May raise whatever the matching rule schedules."""
    inj = _ACTIVE
    if inj is not None:
        inj.hit(point, ctx)
