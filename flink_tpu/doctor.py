"""``python -m flink_tpu.doctor`` — the pipeline doctor CLI.

Runs the ranked-findings rule engine (flink_tpu/metrics/doctor.py)
over a telemetry snapshot and reports what to change. The snapshot is
either a JSON file (saved from ``GET /jobs/<jid>/doctor?snapshot=1``
or assembled by hand / in tests) or fetched live from a running web
monitor with ``--url``.

Exit codes mirror ``tools.lint``: 0 the pipeline is clean, 1 findings
were reported, 2 the doctor itself failed (unreadable snapshot, bad
URL, malformed JSON) — so CI and cron wrappers can tell "healthy"
from "sick" from "the check is broken".

Usage:
    python -m flink_tpu.doctor snapshot.json
    python -m flink_tpu.doctor snapshot.json --json
    python -m flink_tpu.doctor --url http://host:8081/jobs/<jid>/doctor
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict

from flink_tpu.metrics.doctor import diagnose

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2


def _load_snapshot(args) -> Dict[str, Any]:
    if args.url:
        from urllib.request import urlopen

        with urlopen(args.url, timeout=args.timeout) as resp:
            data = json.loads(resp.read().decode("utf-8"))
    else:
        with open(args.snapshot, "r", encoding="utf-8") as f:
            data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError("snapshot must be a JSON object")
    return data


def _render_text(payload: Dict[str, Any]) -> str:
    lines = []
    findings = payload.get("findings", [])
    if not findings:
        lines.append("doctor: pipeline is clean "
                     f"({len(payload.get('rules', []))} rules checked)")
        return "\n".join(lines)
    lines.append(f"doctor: {len(findings)} finding(s), ranked:")
    for i, f in enumerate(findings, 1):
        lines.append(
            f"\n{i}. [{f['severity'].upper()}] {f['rule']} "
            f"(score {f['score']})"
        )
        lines.append(f"   {f['summary']}")
        ev = f.get("evidence") or {}
        if ev:
            lines.append("   evidence: " + json.dumps(ev, sort_keys=True))
        rem = f.get("remedy") or {}
        if rem:
            lines.append(
                f"   remedy: {rem.get('key')} — {rem.get('suggestion')}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flink_tpu.doctor",
        description="rank pipeline-health findings from a telemetry "
                    "snapshot (exit 0 clean / 1 findings / 2 error)",
    )
    ap.add_argument("snapshot", nargs="?",
                    help="path to a snapshot JSON (a saved "
                         "/jobs/<jid>/doctor payload with its "
                         "'snapshot' block, or a hand-assembled one)")
    ap.add_argument("--url",
                    help="fetch the snapshot live from a web-monitor "
                         "doctor endpoint instead of a file")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the stable machine-readable payload")
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="HTTP timeout for --url (seconds)")
    args = ap.parse_args(argv)
    if bool(args.snapshot) == bool(args.url):
        ap.print_usage(sys.stderr)
        print("doctor: pass exactly one of <snapshot> or --url",
              file=sys.stderr)
        return EXIT_ERROR
    try:
        data = _load_snapshot(args)
    except Exception as exc:
        print(f"doctor: cannot load snapshot: {exc}", file=sys.stderr)
        return EXIT_ERROR
    # accept either a raw snapshot (telemetry planes at top level) or a
    # served doctor payload that embeds one under "snapshot"
    snap = data.get("snapshot", data)
    thresholds = data.get("thresholds")
    try:
        payload = diagnose(snap, thresholds)
    except Exception as exc:
        print(f"doctor: rule engine failed: {exc}", file=sys.stderr)
        return EXIT_ERROR
    if args.as_json:
        print(json.dumps(payload, indent=1, sort_keys=True))
    else:
        print(_render_text(payload))
    return EXIT_CLEAN if payload["clean"] else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
