"""ML pipelines — the FlinkML analog (ref flink-ml, SURVEY §2.7)."""

from flink_tpu.ml.pipeline import (
    KNN,
    SVM,
    KMeans,
    MinMaxScaler,
    MultipleLinearRegression,
    Pipeline,
    PolynomialFeatures,
    Predictor,
    StandardScaler,
    Transformer,
)

__all__ = [
    "Pipeline", "Transformer", "Predictor", "StandardScaler",
    "MinMaxScaler", "PolynomialFeatures", "MultipleLinearRegression",
    "SVM", "KMeans", "KNN",
]
