"""ML pipelines — the FlinkML analog (ref flink-ml, SURVEY §2.7)."""

from flink_tpu.ml.pipeline import (
    ALS,
    KNN,
    SVM,
    KMeans,
    MinMaxScaler,
    MultipleLinearRegression,
    Pipeline,
    PolynomialFeatures,
    Predictor,
    StandardScaler,
    Transformer,
)

__all__ = [
    "ALS", "Pipeline", "Transformer", "Predictor", "StandardScaler",
    "MinMaxScaler", "PolynomialFeatures", "MultipleLinearRegression",
    "SVM", "KMeans", "KNN",
]
