"""Distance metrics — the flink-ml metrics.distances package analog
(ref flink-libraries/flink-ml/.../metrics/distances/: Euclidean,
SquaredEuclidean, Manhattan, Chebyshev, Minkowski, Cosine, Tanimoto).

Each metric is a vectorized pairwise function: distance(A [n, d],
B [m, d]) -> [n, m], one fused XLA program (the reference computes one
scalar per vector pair in a JVM UDF)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def _ab(a, b):
    A = jnp.asarray(a, jnp.float32)
    B = jnp.asarray(b, jnp.float32)
    if A.ndim == 1:
        A = A[None, :]
    if B.ndim == 1:
        B = B[None, :]
    return A, B


def squared_euclidean_distance(a, b) -> np.ndarray:
    A, B = _ab(a, b)
    sq = (
        jnp.sum(A * A, axis=1)[:, None]
        + jnp.sum(B * B, axis=1)[None, :]
        - 2.0 * A @ B.T
    )
    return np.asarray(jnp.maximum(sq, 0.0))


def euclidean_distance(a, b) -> np.ndarray:
    return np.sqrt(squared_euclidean_distance(a, b))


def manhattan_distance(a, b) -> np.ndarray:
    A, B = _ab(a, b)
    return np.asarray(jnp.sum(jnp.abs(A[:, None, :] - B[None, :, :]),
                              axis=2))


def chebyshev_distance(a, b) -> np.ndarray:
    A, B = _ab(a, b)
    return np.asarray(jnp.max(jnp.abs(A[:, None, :] - B[None, :, :]),
                              axis=2))


def minkowski_distance(a, b, p: float = 3.0) -> np.ndarray:
    A, B = _ab(a, b)
    return np.asarray(
        jnp.sum(jnp.abs(A[:, None, :] - B[None, :, :]) ** p, axis=2)
        ** (1.0 / p)
    )


def cosine_distance(a, b) -> np.ndarray:
    A, B = _ab(a, b)
    na = jnp.linalg.norm(A, axis=1)[:, None]
    nb = jnp.linalg.norm(B, axis=1)[None, :]
    sim = (A @ B.T) / jnp.maximum(na * nb, 1e-12)
    return np.asarray(1.0 - sim)


def tanimoto_distance(a, b) -> np.ndarray:
    A, B = _ab(a, b)
    dot = A @ B.T
    na = jnp.sum(A * A, axis=1)[:, None]
    nb = jnp.sum(B * B, axis=1)[None, :]
    sim = dot / jnp.maximum(na + nb - dot, 1e-12)
    return np.asarray(1.0 - sim)
