"""ML pipelines — the FlinkML analog (ref flink-ml: Pipeline/Estimator/
Predictor/Transformer contracts + SVM (CoCoA), MultipleLinearRegression
(SGD), KNN, StandardScaler/MinMaxScaler/PolynomialFeatures, SURVEY §2.7),
redesigned for the accelerator:

The reference trains with per-partition JVM loops over Breeze vectors.
Here every estimator is a jit-compiled JAX program over [N, D] device
arrays — full-batch matmul-dominated updates (MXU work), `lax.fori_loop`
training loops, and jit'd predict paths. The Pipeline chaining contract
(chainTransformer/chainPredictor) is preserved: transformers fit/transform
in sequence, the trailing predictor fits on the transformed features.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _as2d(x) -> jnp.ndarray:
    a = jnp.asarray(x, jnp.float32)
    return a[:, None] if a.ndim == 1 else a


class Transformer:
    """ref Transformer: fit(X) learns parameters, transform(X) applies."""

    def fit(self, X, y=None) -> "Transformer":
        return self

    def transform(self, X) -> jnp.ndarray:
        raise NotImplementedError

    def fit_transform(self, X, y=None) -> jnp.ndarray:
        return self.fit(X, y).transform(X)


class Predictor:
    """ref Predictor: fit(X, y) + predict(X)."""

    def fit(self, X, y) -> "Predictor":
        raise NotImplementedError

    def predict(self, X) -> jnp.ndarray:
        raise NotImplementedError


class Pipeline:
    """ref Pipeline chaining: transformers then an optional predictor."""

    def __init__(self, stages: List[Any]):
        self.stages = stages

    def fit(self, X, y=None) -> "Pipeline":
        cur = _as2d(X)
        for i, s in enumerate(self.stages):
            if isinstance(s, Predictor) or (
                i == len(self.stages) - 1 and hasattr(s, "predict")
            ):
                s.fit(cur, y)
            else:
                cur = s.fit_transform(cur, y)
        return self

    def transform(self, X) -> jnp.ndarray:
        cur = _as2d(X)
        for s in self.stages:
            if hasattr(s, "transform"):
                cur = s.transform(cur)
        return cur

    def predict(self, X) -> jnp.ndarray:
        cur = _as2d(X)
        for s in self.stages[:-1]:
            cur = s.transform(cur)
        return self.stages[-1].predict(cur)


# ------------------------------------------------------------ transformers
class StandardScaler(Transformer):
    """ref preprocessing.StandardScaler (mean/std)."""

    def fit(self, X, y=None):
        X = _as2d(X)
        self.mean = jnp.mean(X, axis=0)
        self.std = jnp.maximum(jnp.std(X, axis=0), 1e-9)
        return self

    def transform(self, X):
        return (_as2d(X) - self.mean) / self.std


class MinMaxScaler(Transformer):
    """ref preprocessing.MinMaxScaler."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo, self.hi = lo, hi

    def fit(self, X, y=None):
        X = _as2d(X)
        self.data_min = jnp.min(X, axis=0)
        self.data_range = jnp.maximum(
            jnp.max(X, axis=0) - self.data_min, 1e-9
        )
        return self

    def transform(self, X):
        z = (_as2d(X) - self.data_min) / self.data_range
        return z * (self.hi - self.lo) + self.lo


class PolynomialFeatures(Transformer):
    """ref preprocessing.PolynomialFeatures: powers up to `degree`."""

    def __init__(self, degree: int = 2):
        self.degree = degree

    def transform(self, X):
        X = _as2d(X)
        return jnp.concatenate(
            [X**d for d in range(1, self.degree + 1)], axis=1
        )


# -------------------------------------------------------------- predictors
class MultipleLinearRegression(Predictor):
    """ref regression.MultipleLinearRegression: squared-loss linear model.
    Full-batch gradient descent under lax.fori_loop (the reference uses
    per-partition SGD); one [N,D]@[D] matmul per step."""

    def __init__(self, iterations: int = 200, stepsize: float = 0.1):
        self.iterations = iterations
        self.stepsize = stepsize

    def fit(self, X, y):
        X = _as2d(X)
        y = jnp.asarray(y, jnp.float32).reshape(-1)
        N, D = X.shape
        Xb = jnp.concatenate([X, jnp.ones((N, 1), jnp.float32)], axis=1)

        def step(_, w):
            grad = Xb.T @ (Xb @ w - y) / N
            return w - self.stepsize * grad

        self.weights = jax.lax.fori_loop(
            0, self.iterations, step, jnp.zeros(D + 1, jnp.float32)
        )
        return self

    def predict(self, X):
        X = _as2d(X)
        Xb = jnp.concatenate(
            [X, jnp.ones((X.shape[0], 1), jnp.float32)], axis=1
        )
        return Xb @ self.weights

    def squared_residual_sum(self, X, y) -> float:
        r = self.predict(X) - jnp.asarray(y, jnp.float32).reshape(-1)
        return float(jnp.sum(r * r))


class SVM(Predictor):
    """ref classification.SVM (CoCoA dual solver): linear soft-margin SVM,
    labels in {-1, +1}. Trained with pegasos-style subgradient descent on
    the hinge loss — full-batch, matmul-dominated."""

    def __init__(self, iterations: int = 300, regularization: float = 1e-3):
        self.iterations = iterations
        self.lam = regularization

    def fit(self, X, y):
        X = _as2d(X)
        y = jnp.asarray(y, jnp.float32).reshape(-1)
        N, D = X.shape
        Xb = jnp.concatenate([X, jnp.ones((N, 1), jnp.float32)], axis=1)

        def step(t, w):
            margins = y * (Xb @ w)
            active = (margins < 1.0).astype(jnp.float32)
            grad = self.lam * w - (Xb.T @ (active * y)) / N
            eta = 1.0 / (self.lam * (t + 1.0))
            return w - eta * grad

        self.weights = jax.lax.fori_loop(
            0, self.iterations, step, jnp.zeros(D + 1, jnp.float32)
        )
        return self

    def decision_function(self, X):
        X = _as2d(X)
        Xb = jnp.concatenate(
            [X, jnp.ones((X.shape[0], 1), jnp.float32)], axis=1
        )
        return Xb @ self.weights

    def predict(self, X):
        return jnp.sign(self.decision_function(X))


class KMeans(Predictor):
    """ref the KMeans batch example (+ FlinkML pipelines): Lloyd iterations
    with an [N,K] distance matmul per step — pure MXU work."""

    def __init__(self, k: int, iterations: int = 50, seed: int = 0):
        self.k = k
        self.iterations = iterations
        self.seed = seed

    def fit(self, X, y=None):
        X = _as2d(X)
        N, D = X.shape
        # k-means++ seeding (host-side, one pass per center): spreads the
        # initial centers so Lloyd doesn't collapse clusters
        Xh = np.asarray(X)
        rng = np.random.default_rng(self.seed)
        centers = [Xh[rng.integers(N)]]
        for _ in range(1, self.k):
            d2 = np.min(
                [((Xh - c) ** 2).sum(axis=1) for c in centers], axis=0
            )
            p = d2 / max(d2.sum(), 1e-12)
            centers.append(Xh[rng.choice(N, p=p)])
        centers0 = jnp.asarray(np.stack(centers), jnp.float32)

        def assign(centers):
            # |x-c|^2 = |x|^2 - 2 x.c + |c|^2 ; argmin over K
            d = (
                jnp.sum(X * X, axis=1, keepdims=True)
                - 2.0 * (X @ centers.T)
                + jnp.sum(centers * centers, axis=1)[None, :]
            )
            return jnp.argmin(d, axis=1)

        def step(_, centers):
            a = assign(centers)
            sums = jnp.zeros((self.k, D), jnp.float32).at[a].add(X)
            counts = jnp.zeros((self.k,), jnp.float32).at[a].add(1.0)
            new = sums / jnp.maximum(counts[:, None], 1.0)
            # empty cluster keeps its old center
            return jnp.where(counts[:, None] > 0, new, centers)

        self.centers = jax.lax.fori_loop(
            0, self.iterations, step, centers0
        )
        return self

    def predict(self, X):
        X = _as2d(X)
        d = (
            jnp.sum(X * X, axis=1, keepdims=True)
            - 2.0 * (X @ self.centers.T)
            + jnp.sum(self.centers * self.centers, axis=1)[None, :]
        )
        return jnp.argmin(d, axis=1)


class KNN(Predictor):
    """ref nn.KNN: brute-force k-nearest-neighbors; the [Q,N] distance
    matrix is one matmul (exact, accelerator-friendly)."""

    def __init__(self, k: int = 5):
        self.k = k

    def fit(self, X, y):
        self.X = _as2d(X)
        self.y = jnp.asarray(y, jnp.float32).reshape(-1)
        return self

    def predict(self, X):
        Q = _as2d(X)
        d = (
            jnp.sum(Q * Q, axis=1, keepdims=True)
            - 2.0 * (Q @ self.X.T)
            + jnp.sum(self.X * self.X, axis=1)[None, :]
        )
        _, idx = jax.lax.top_k(-d, self.k)
        neigh = self.y[idx]                       # [Q, k]
        # regression-style mean of neighbor labels; round for voting
        return jnp.mean(neigh, axis=1)


class ALS(Predictor):
    """Alternating Least Squares matrix factorization — the reference
    FlinkML's flagship recommender (org.apache.flink.ml.recommendation.ALS).

    TPU-first formulation: instead of the reference's distributed block
    updates, both half-steps are BATCHED normal-equation solves — one
    einsum builds every user's (F x F) Gram matrix at once and one
    batched jnp.linalg.solve updates all factors simultaneously (MXU
    matmuls end to end). Ratings densify to [U, I] with a mask; suitable
    for the moderate matrix sizes the library targets.
    """

    def __init__(self, num_factors: int = 10, lambda_: float = 0.1,
                 iterations: int = 10, seed: int = 0):
        self.num_factors = num_factors
        self.lambda_ = lambda_
        self.iterations = iterations
        self.seed = seed
        self.user_factors = None
        self.item_factors = None
        self._users = None
        self._items = None

    def fit(self, ratings):
        """ratings: iterable of (user, item, rating)."""
        rows = list(ratings)
        users = sorted({r[0] for r in rows})
        items = sorted({r[1] for r in rows})
        u_ix = {u: i for i, u in enumerate(users)}
        i_ix = {it: i for i, it in enumerate(items)}
        U, I, F = len(users), len(items), self.num_factors
        R = np.zeros((U, I), np.float32)
        M = np.zeros((U, I), np.float32)
        for u, it, r in rows:
            R[u_ix[u], i_ix[it]] = r
            M[u_ix[u], i_ix[it]] = 1.0
        R = jnp.asarray(R)
        M = jnp.asarray(M)
        lam = self.lambda_

        key = jax.random.PRNGKey(self.seed)
        ku, ki = jax.random.split(key)
        uf = jax.random.normal(ku, (U, F), jnp.float32) * 0.1
        vf = jax.random.normal(ki, (I, F), jnp.float32) * 0.1
        eye = jnp.eye(F, dtype=jnp.float32)

        @jax.jit
        def half_step(fixed, R_, M_):
            # for every row r: solve (X^T diag(m_r) X + λ n_r I) w = X^T y_r
            A = jnp.einsum("if,ig,ri->rfg", fixed, fixed, M_)
            n = jnp.sum(M_, axis=1)
            A = A + lam * jnp.maximum(n, 1.0)[:, None, None] * eye
            b = jnp.einsum("if,ri->rf", fixed, R_ * M_)
            return jnp.linalg.solve(A, b[:, :, None])[:, :, 0]

        for _ in range(self.iterations):
            uf = half_step(vf, R, M)
            vf = half_step(uf, R.T, M.T)
        self.user_factors = uf
        self.item_factors = vf
        self._users = u_ix
        self._items = i_ix
        return self

    def predict(self, pairs):
        """pairs: iterable of (user, item) -> [n] predicted ratings
        (unseen users/items predict 0)."""
        out = []
        uf = np.asarray(self.user_factors)
        vf = np.asarray(self.item_factors)
        for u, it in pairs:
            iu = self._users.get(u)
            ii = self._items.get(it)
            out.append(
                float(uf[iu] @ vf[ii]) if iu is not None and ii is not None
                else 0.0
            )
        return np.asarray(out, np.float32)

    def empirical_risk(self, ratings) -> float:
        """Regularized squared loss over known ratings (the reference's
        empiricalRisk evaluation hook)."""
        rows = list(ratings)
        preds = self.predict([(u, i) for u, i, _ in rows])
        errs = preds - np.asarray([r for _, _, r in rows], np.float32)
        reg = self.lambda_ * (
            float(jnp.sum(self.user_factors ** 2))
            + float(jnp.sum(self.item_factors ** 2))
        )
        return float(np.sum(errs ** 2)) + reg
