"""MLUtils — libSVM/SVMLight file IO (ref flink-ml MLUtils.scala
readLibSVM/writeLibSVM): `<label> <index>:<value> ...` per line,
1-based indices, densified into numpy arrays."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def read_libsvm(path: str, n_features: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """-> (X [n, d] float32 dense, y [n] float32). d is inferred from
    the max index unless given."""
    labels = []
    rows = []
    max_idx = n_features or 0
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            feats = []
            for tok in parts[1:]:
                idx, _, val = tok.partition(":")
                i = int(idx)
                if i < 1:
                    raise ValueError(
                        f"libSVM indices are 1-based, got {i}"
                    )
                feats.append((i, float(val)))
                max_idx = max(max_idx, i)
            rows.append(feats)
    if n_features is not None and max_idx > n_features:
        raise ValueError(
            f"feature index {max_idx} exceeds n_features={n_features}"
        )
    X = np.zeros((len(rows), max_idx), np.float32)
    for r, feats in enumerate(rows):
        for i, v in feats:
            X[r, i - 1] = v
    return X, np.asarray(labels, np.float32)


def write_libsvm(path: str, X, y):
    X = np.asarray(X)
    y = np.asarray(y)
    with open(path, "w") as f:
        for r in range(len(X)):
            feats = " ".join(
                f"{i + 1}:{X[r, i]:.9g}"
                for i in np.nonzero(X[r])[0]
            )
            f.write(f"{y[r]:.9g} {feats}".rstrip() + "\n")
