"""Optimization framework — the flink-ml optimization package analog
(ref flink-libraries/flink-ml/.../optimization/: GradientDescent.scala,
LossFunction.scala, PartialLossFunction, RegularizationPenalty).

The reference composes a Solver from a pluggable loss and a
regularization penalty and iterates full-gradient steps as DataSet
iterations. Here the same composition compiles to ONE jitted
`lax.fori_loop`: per step, predictions/gradients are batched matvecs
(MXU work) and the penalty applies in closed form — no per-iteration
host round trips.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


# -- partial losses (ref PartialLossFunction: loss + derivative) ----------
@dataclass(frozen=True)
class SquaredLoss:
    """ref SquaredLoss.scala: 1/2 (wx - y)^2."""

    def loss(self, pred, y):
        return 0.5 * (pred - y) ** 2

    def gradient(self, pred, y):
        return pred - y


@dataclass(frozen=True)
class HingeLoss:
    """ref HingeLoss.scala: max(0, 1 - y*wx), labels in {-1, +1}."""

    def loss(self, pred, y):
        return jnp.maximum(0.0, 1.0 - y * pred)

    def gradient(self, pred, y):
        return jnp.where(y * pred < 1.0, -y, 0.0)


@dataclass(frozen=True)
class LogisticLoss:
    """ref LogisticLoss.scala: log(1 + exp(-y*wx)), labels in {-1, +1}."""

    def loss(self, pred, y):
        z = -y * pred
        # numerically stable log1p(exp(z))
        return jnp.logaddexp(0.0, z)

    def gradient(self, pred, y):
        return -y / (1.0 + jnp.exp(y * pred))


# -- regularization penalties (ref RegularizationPenalty) -----------------
@dataclass(frozen=True)
class NoRegularization:
    def apply(self, w, lr, reg):
        return w


@dataclass(frozen=True)
class L2Regularization:
    """ref L2Regularization: shrink by the gradient of reg/2 ||w||^2."""

    def apply(self, w, lr, reg):
        return w * (1.0 - lr * reg)


@dataclass(frozen=True)
class L1Regularization:
    """ref L1Regularization: soft-thresholding (proximal step)."""

    def apply(self, w, lr, reg):
        shrink = lr * reg
        return jnp.sign(w) * jnp.maximum(jnp.abs(w) - shrink, 0.0)


class GradientDescent:
    """ref GradientDescent.scala (SimpleGradientDescent/GradientDescentL1/
    L2 collapse into the penalty object). Linear model pred = X @ w + b.

    optimize(X, y) -> (weights [D], intercept): `iterations` full-gradient
    steps with step size lr / sqrt(t) (the reference's default decay).
    """

    def __init__(self, loss=None, penalty=None, iterations: int = 100,
                 stepsize: float = 0.1, regularization: float = 0.0):
        self.loss = loss or SquaredLoss()
        self.penalty = penalty or (
            L2Regularization() if regularization else NoRegularization()
        )
        self.iterations = iterations
        self.stepsize = stepsize
        self.regularization = regularization

    def optimize(self, X, y):
        X = jnp.asarray(X, jnp.float32)
        y = jnp.asarray(y, jnp.float32)
        n, d = X.shape
        loss, penalty, reg = self.loss, self.penalty, self.regularization
        base_lr = self.stepsize

        def step(t, carry):
            w, b = carry
            lr = base_lr / jnp.sqrt(t + 1.0)
            pred = X @ w + b
            g = loss.gradient(pred, y)          # [n]
            gw = X.T @ g / n
            gb = jnp.mean(g)
            w = penalty.apply(w - lr * gw, lr, reg)
            b = b - lr * gb
            return w, b

        w0 = jnp.zeros(d, jnp.float32)
        w, b = jax.lax.fori_loop(0, self.iterations, step,
                                 (w0, jnp.float32(0.0)))
        return np.asarray(w), float(b)

    def empirical_loss(self, X, y, w, b) -> float:
        pred = jnp.asarray(X, jnp.float32) @ jnp.asarray(w) + b
        return float(jnp.mean(self.loss.loss(pred, jnp.asarray(y))))
