"""Physical broadcast on the device mesh (ref BroadcastPartitioner.java:30).

The reference physically copies every record to every downstream subtask
over Netty — N network sends per record. On a device mesh, broadcast is
a SHARDING declaration: an operand with in_spec P() is materialized once
in EVERY shard's address space (XLA lowers the replication to one host
transfer plus an on-fabric broadcast), so "send to all" costs one
collective instead of N point-to-point copies.

`build_broadcast_join_step` is the canonical consumer: a small build
side (dimension/rules table) replicated to all shards, probed by each
shard's O(B/n) slice of the record stream — the broadcast hash join of
the reference's BROADCAST_HASH_FIRST/SECOND hints
(flink-runtime/.../operators/hash/MutableHashTable.java build side)
executed as one SPMD step.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flink_tpu.core.compat import shard_map
from jax.sharding import PartitionSpec as P

from flink_tpu.parallel.mesh import SHARD_AXIS, MeshContext


_STEP_CACHE: dict = {}


def build_broadcast_join_step(ctx: MeshContext):
    """Compile a broadcast-join step over the mesh (memoized per mesh:
    jax.jit caches by function identity, so rebuilding the shard_map
    closure per call would recompile the kernel on every join).

    step(keys, valid, tkeys, tvals) with
      keys/valid: [B] record lanes, SPLIT over shards (each device
        probes only its B/n slice — work scales with chips),
      tkeys: [K] SORTED unique build-side keys, REPLICATED to every shard,
      tvals: [K] build-side payload, replicated.
    Returns (joined [B], matched bool [B]) in lane order: joined[i] =
    tvals[searchsorted(tkeys, keys[i])] where keys match; 0 otherwise.
    """
    mesh = ctx.mesh
    cached = _STEP_CACHE.get(id(mesh))
    if cached is not None:
        return cached

    def shard_body(keys, valid, tkeys, tvals):
        pos = jnp.searchsorted(tkeys, keys)
        pos_c = jnp.minimum(pos, tkeys.shape[0] - 1)
        hit = valid & (tkeys[pos_c] == keys)
        joined = jnp.where(hit, tvals[pos_c], 0).astype(tvals.dtype)
        return joined, hit

    sharded = shard_map(
        shard_body, mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(),     # build side REPLICATED: the physical broadcast
        ),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,
    )

    @jax.jit
    def step(keys, valid, tkeys, tvals):
        return sharded(keys, valid, tkeys, tvals)

    _STEP_CACHE[id(mesh)] = step
    return step


def broadcast_join(keys, tkeys, tvals, ctx: MeshContext = None):
    """One-shot broadcast join of host arrays over all devices.

    keys: record stream keys ([B] int); tkeys/tvals: build side
    (unsorted ok, [K]). Returns (joined [B] float, matched [B] bool).
    B is padded up to a shard multiple internally."""
    ctx = ctx or MeshContext.create()
    n = ctx.n_shards
    keys = np.asarray(keys)
    order = np.argsort(tkeys, kind="stable")
    tkeys_s = np.asarray(tkeys)[order]
    tvals_s = np.asarray(tvals, np.float32)[order]
    B = len(keys)
    Bp = ((B + n - 1) // n) * n
    pad = Bp - B
    kp = np.concatenate([keys, np.zeros(pad, keys.dtype)])
    valid = np.concatenate([np.ones(B, bool), np.zeros(pad, bool)])
    step = build_broadcast_join_step(ctx)
    joined, hit = step(kp, valid, tkeys_s, tvals_s)
    return np.asarray(joined)[:B], np.asarray(hit)[:B]
