from flink_tpu.parallel.mesh import MeshContext, SHARD_AXIS  # noqa: F401
