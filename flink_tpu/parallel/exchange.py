"""ICI record exchange: the keyBy hash shuffle as an on-device all_to_all.

The reference's defining runtime feature is the keyed record shuffle:
KeyGroupStreamPartitioner.selectChannels (flink-streaming-java/.../runtime/
partitioner/KeyGroupStreamPartitioner.java:53) picks the target subtask per
record and RecordWriter.emit (flink-runtime/.../io/network/api/writer/
RecordWriter.java:82) serializes it into that subtask's Netty subpartition.

TPU-native redesign: the host splits each micro-batch across the mesh
(every device holds B/n lanes), and inside the compiled step each device

  1. hashes its lanes to key groups -> target shard indices,
  2. buckets lanes into a [n_shards, cap] send buffer (one cumsum +
     scatter; no per-record control flow),
  3. exchanges buckets with ONE jax.lax.all_to_all over the `shards` mesh
     axis — the collective rides ICI, replacing Netty/TCP,
  4. continues with only the lanes it owns.

Per-device update work is O(B/n) instead of the O(B) of replicate-and-mask
(parallel/mesh.py), so ingest throughput scales with chips.

Capacity: `cap` lanes per (sender, target) bucket. With a well-mixed hash
the expected fill is (B/n)/n; cap defaults to a multiple of that
(exchange.capacity-factor). Lanes overflowing their bucket are counted and
surfaced as capacity drops (strict mode raises), never silently lost —
the same failure contract as the device hash table.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.ops.hashing import route_hash
from flink_tpu.parallel.mesh import SHARD_AXIS


def bucket_capacity(batch_per_device: int, n_shards: int,
                    factor: float = 2.0) -> int:
    """Per-(sender, target) bucket capacity: factor x expected fill,
    clamped to [8, batch_per_device]."""
    expected = max(1, batch_per_device // max(1, n_shards))
    return max(8, min(batch_per_device, int(round(factor * expected))))


def exchange_records(
    cols: Dict[str, jax.Array],
    hi: jax.Array,
    lo: jax.Array,
    valid: jax.Array,
    n_shards: int,
    max_parallelism: int,
    cap: int,
) -> Tuple[Dict[str, jax.Array], jax.Array, jax.Array, jax.Array, jax.Array]:
    """Route a local [B_loc] lane slice to owning shards over ICI.

    Must run inside shard_map over the `shards` axis. Returns
    (cols', hi', lo', valid', n_overflow) where primed arrays have
    n_shards*cap lanes, every valid one owned by this shard.
    """
    kg = assign_to_key_group(route_hash(hi, lo, jnp), max_parallelism, jnp)
    tgt = (kg.astype(jnp.int32) * jnp.int32(n_shards)) // jnp.int32(
        max_parallelism
    )

    # rank of each lane within its target bucket (stable, per-target cumsum;
    # n_shards is small and static so the sweep unrolls)
    pos = jnp.zeros(hi.shape[0], jnp.int32)
    for t in range(n_shards):
        m = valid & (tgt == t)
        pos = jnp.where(m, jnp.cumsum(m.astype(jnp.int32)) - 1, pos)

    fits = valid & (pos < cap)
    n_overflow = jnp.sum(valid & ~fits, dtype=jnp.int32)
    idx = jnp.where(fits, tgt * jnp.int32(cap) + pos,
                    jnp.int32(n_shards * cap))

    def scatter(col):
        buf = jnp.zeros((n_shards * cap,) + col.shape[1:], col.dtype)
        return buf.at[idx].set(col, mode="drop")

    send_hi = scatter(hi)
    send_lo = scatter(lo)
    send_valid = jnp.zeros(n_shards * cap, bool).at[idx].set(
        jnp.ones_like(valid), mode="drop"
    )
    send_cols = {k: scatter(v) for k, v in cols.items()}

    a2a = lambda x: jax.lax.all_to_all(
        x, SHARD_AXIS, split_axis=0, concat_axis=0, tiled=True
    )
    recv_hi = a2a(send_hi)
    recv_lo = a2a(send_lo)
    recv_valid = a2a(send_valid)
    recv_cols = {k: a2a(v) for k, v in send_cols.items()}
    return recv_cols, recv_hi, recv_lo, recv_valid, n_overflow


def exchange_owned(
    cols: Dict[str, jax.Array],
    hi: jax.Array,
    lo: jax.Array,
    valid: jax.Array,
    n_shards: int,
    max_parallelism: int,
    cap: int,
    kg_start: jax.Array,
    kg_end: jax.Array,
) -> Tuple[Dict[str, jax.Array], jax.Array, jax.Array, jax.Array,
           jax.Array]:
    """``exchange_records`` + the owner mask: route this shard's lanes,
    then keep only the received lanes whose key group falls in
    [kg_start, kg_end]. ONE implementation of the route/mask pair so
    the single-host exchange step (runtime/step.py) and every DCN
    runner (runtime/dcn.py) cannot diverge in shuffle semantics.
    Returns (cols', hi', lo', mine, n_overflow)."""
    cols, r_hi, r_lo, r_valid, n_over = exchange_records(
        cols, hi, lo, valid, n_shards, max_parallelism, cap
    )
    kg = assign_to_key_group(route_hash(r_hi, r_lo, jnp),
                             max_parallelism, jnp)
    mine = r_valid & (kg >= kg_start.astype(jnp.uint32)) & (
        kg <= kg_end.astype(jnp.uint32)
    )
    return cols, r_hi, r_lo, mine, n_over
