"""Device mesh & sharding context — the TPU replacement for the reference's
TaskManager slot topology + Netty data plane (SURVEY §2.3).

Where the reference places subtasks in TM slots and wires them with TCP
partitions, we lay key-group shards over a `jax.sharding.Mesh` axis. The
`keyBy` hash exchange becomes either:

  * replicate-and-mask (default): every device sees the full micro-batch and
    masks the lanes whose key group it owns. Zero collective traffic on the
    records themselves (input is broadcast once from host); per-shard
    pre-aggregation makes the redundant compute cheap. Best at small batch.
  * all_to_all exchange (parallel/exchange.py): records are bucketed by
    target shard with fixed per-shard capacity and exchanged over ICI.
    Best when batches are large and value payloads wide.

One mesh axis ("shards") carries keyed-state parallelism (the reference's
"operator parallelism over key groups"); a second optional axis ("pipe") is
reserved for pipeline stages of chained jobs.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    check_parallelism,
    key_group_range_for_operator,
)

SHARD_AXIS = "shards"


def validate_kg_slices(max_parallelism: int, n_shards: int, slices):
    """Check a custom contiguous key-group slicing: ``slices`` is a
    sequence of ``n_shards`` (start, end) pairs with INCLUSIVE ends,
    non-empty, strictly increasing, covering [0, max_parallelism-1]
    exactly. The searchsorted ownership mapping
    (:meth:`MeshContext.shard_of_key_groups`) and the ingest route
    planner both assume exactly this shape, so a malformed slicing is a
    loud error here rather than silent misrouting there."""
    if len(slices) != n_shards:
        raise ValueError(
            f"kg_slices has {len(slices)} ranges for {n_shards} shards")
    lo = 0
    for i, (s, e) in enumerate(slices):
        s, e = int(s), int(e)
        if s != lo or e < s:
            raise ValueError(
                f"kg_slices[{i}]=({s},{e}) must start at {lo} and be "
                f"non-empty (inclusive ends, contiguous cover)")
        lo = e + 1
    if lo != max_parallelism:
        raise ValueError(
            f"kg_slices cover [0,{lo - 1}] but max_parallelism is "
            f"{max_parallelism}")


@dataclass
class MeshContext:
    """A job's device topology: n_shards over the `shards` mesh axis.

    ``kg_slices`` optionally overrides the uniform key-group
    partition with a custom contiguous slicing (the controller's
    heat-balanced rebalance, ISSUE 19): a tuple of inclusive
    (start, end) pairs, one per shard, validated to cover
    [0, max_parallelism-1]. Every ownership consumer reads through
    ``key_group_ranges``/``kg_bounds``/``shard_of_key_groups``, so the
    override is a single cut."""

    mesh: Mesh
    max_parallelism: int
    kg_slices: Optional[tuple] = None

    @staticmethod
    def create(
        n_shards: Optional[int] = None,
        max_parallelism: int = 128,
        devices=None,
        kg_slices=None,
    ) -> "MeshContext":
        devices = devices if devices is not None else jax.devices()
        n = n_shards or len(devices)
        if n > len(devices):
            raise ValueError(f"need {n} devices, have {len(devices)}")
        check_parallelism(max_parallelism, n)
        if kg_slices is not None:
            kg_slices = tuple(
                (int(s), int(e)) for s, e in kg_slices)
            validate_kg_slices(max_parallelism, n, kg_slices)
        mesh = Mesh(np.asarray(devices[:n]), (SHARD_AXIS,))
        return MeshContext(mesh, max_parallelism, kg_slices)

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[SHARD_AXIS]

    @cached_property
    def key_group_ranges(self):
        if self.kg_slices is not None:
            return [KeyGroupRange(s, e) for s, e in self.kg_slices]
        return [
            key_group_range_for_operator(self.max_parallelism, self.n_shards, i)
            for i in range(self.n_shards)
        ]

    def sharding(self, *axes) -> NamedSharding:
        """NamedSharding placing leading axis over shards: sharding('s')"""
        return NamedSharding(self.mesh, P(*axes))

    @property
    def state_sharding(self) -> NamedSharding:
        """State arrays carry a leading [n_shards] axis, one slice per shard."""
        return NamedSharding(self.mesh, P(SHARD_AXIS))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def kg_bounds(self):
        """(starts[n_shards], ends[n_shards]) int32 arrays of key-group ranges."""
        starts = np.asarray([r.start for r in self.key_group_ranges], np.int32)
        ends = np.asarray([r.end for r in self.key_group_ranges], np.int32)
        return starts, ends

    def shard_of_key_groups(self, kg: np.ndarray) -> np.ndarray:
        """Owning shard index per key group: searchsorted over the
        INCLUSIVE range ends (Flink key-group semantics — default
        side='left' is load-bearing; 'right' would shift every range
        boundary one shard over). This is the one ownership mapping the
        ingest route planner, the sharded batch ring, and the restore
        re-bucketer must all agree on."""
        return np.searchsorted(self.kg_bounds()[1], kg)
