"""YARN deployment glue: REST client, cluster descriptor, session client,
and an in-repo spec ResourceManager for tests.

Reference shape (flink-yarn/):
  - ``AbstractYarnClusterDescriptor.java`` /``YarnClusterDescriptor.java``
    — the client side: create a YARN application, build the AM container
    launch context (command + environment), submit it, poll the
    application report until the AM is up, hand back a cluster client.
  - ``YarnApplicationMasterRunner.java`` — the AM process: starts the
    JobManager runtime and the YARN-aware resource manager.
  - ``YarnFlinkResourceManager.java`` — requests/launches TaskManager
    containers and re-requests them when containers die.
  - ``YarnClusterClient.java`` — job submission against the deployed
    session plus ``shutdownCluster`` (kills the YARN application).

TPU-native mapping: the AM is a ``ProcessCluster`` controller
(runtime/process_cluster.py) whose worker spawns are redirected to YARN
container requests (deploy/appmaster.py); a TaskManager container runs
``python -m flink_tpu.runtime.worker`` — the per-job container pattern.
The framework protocol is the public Hadoop ResourceManager REST API
(``/ws/v1/cluster/...``: new-application, app submission with an
am-container-spec, application report, state PUT for kill), implemented
here from the spec with stdlib HTTP — no Hadoop client libraries. The
container-allocation leg (in Hadoop an RPC protocol between AM and
RM/NodeManagers, ``AMRMClient``/``NMClient``) is carried over the same
REST surface via ``/apps/<id>/containers`` routes; ``MiniYarnRM``
implements both the RM and NodeManager roles, launching container
commands as real OS processes, so the full deploy→AM→container→register
→run→kill loop is exercised end-to-end in tests (the seam where a real
Hadoop deployment would swap in the RPC clients is ``YarnRestClient``'s
``register_am``/``request_container``/``stop_container`` trio).
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from flink_tpu.runtime.process_cluster import _die_with_parent
from flink_tpu.runtime.spawner import AbandonableSpawner

# environment keys the descriptor plants in the AM container spec, the
# way the reference ships cluster coordinates through container env
# (YarnConfigKeys.java: ENV_APP_ID, ENV_CLIENT_HOME_DIR, ...)
ENV_RM_URL = "FLINK_TPU_YARN_RM_URL"
ENV_APP_ID = "FLINK_TPU_YARN_APP_ID"
ENV_AM_HA_DIR = "FLINK_TPU_YARN_AM_HA_DIR"


# --------------------------------------------------------------------------
# REST client (the YarnClient / AMRMClient / NMClient stand-in)
# --------------------------------------------------------------------------
class YarnRestClient:
    """From-spec client for the Hadoop RM REST API (v1 JSON).

    Client-side routes are the public Hadoop ones (Cluster Information,
    Cluster New Application, Cluster Applications Submission, Cluster
    Application State). AM-side routes (register/master, containers)
    carry the AM↔RM/NM protocols over the same HTTP surface — see the
    module docstring for the seam.
    """

    def __init__(self, base_url: str, timeout_s: float = 30.0):
        # 30s: a dead RM fails fast anyway (connection refused), but an
        # ALIVE one whose handler thread is starved by a co-located
        # container compiling at full tilt can legitimately take >10s
        # to answer on a single-core host.
        self.base = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              ok=(200, 202)) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                if r.status not in ok:
                    raise YarnError(f"{method} {path} -> HTTP {r.status}")
                payload = r.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:300]
            raise YarnError(
                f"{method} {path} -> HTTP {e.code}: {detail}"
            ) from None
        except (urllib.error.URLError, OSError) as e:
            # connection-level failures (refused, reset, timeout) must be
            # YarnError too: liveness guards catch YarnError to mean "RM
            # unreachable right now", and a raw URLError would instead
            # escape into ProcessCluster's monitor thread and kill it
            raise YarnError(f"{method} {path} -> {e}") from None
        return json.loads(payload) if payload else {}

    # -- client side -----------------------------------------------------
    def cluster_info(self) -> dict:
        return self._call("GET", "/ws/v1/cluster/info")["clusterInfo"]

    def new_application(self) -> dict:
        """POST Cluster New Application API -> application-id + caps."""
        return self._call("POST", "/ws/v1/cluster/apps/new-application")

    def submit_application(self, ctx: dict) -> None:
        """POST Cluster Applications API (Submit Application)."""
        self._call("POST", "/ws/v1/cluster/apps", ctx)

    def app_report(self, app_id: str) -> dict:
        return self._call("GET", f"/ws/v1/cluster/apps/{app_id}")["app"]

    def kill(self, app_id: str) -> None:
        """PUT Cluster Application State API with KILLED."""
        self._call("PUT", f"/ws/v1/cluster/apps/{app_id}/state",
                   {"state": "KILLED"})

    # -- AM side (AMRMClient / NMClient over REST) -----------------------
    def register_am(self, app_id: str, tracking_url: str) -> None:
        """registerApplicationMaster: flips the app ACCEPTED->RUNNING and
        publishes the tracking URL clients connect to."""
        self._call("POST", f"/ws/v1/cluster/apps/{app_id}/master",
                   {"trackingUrl": tracking_url})

    def finish_am(self, app_id: str, final_status: str = "SUCCEEDED"):
        self._call("POST", f"/ws/v1/cluster/apps/{app_id}/finish",
                   {"finalStatus": final_status})

    def request_container(self, app_id: str, command: str,
                          environment: Optional[Dict[str, str]] = None,
                          resource: Optional[dict] = None) -> str:
        """Allocate + launch a worker container; returns the container id
        (the AMRMClient.addContainerRequest -> NMClient.startContainer
        pair, collapsed because MiniYarnRM plays both roles)."""
        out = self._call(
            "POST", f"/ws/v1/cluster/apps/{app_id}/containers",
            {"command": command, "environment": environment or {},
             "resource": resource or {"memory": 1024, "vCores": 1}},
        )
        return out["container-id"]

    def container_report(self, app_id: str, container_id: str) -> dict:
        return self._call(
            "GET", f"/ws/v1/cluster/apps/{app_id}/containers/{container_id}"
        )["container"]

    def list_containers(self, app_id: str) -> List[dict]:
        return self._call(
            "GET", f"/ws/v1/cluster/apps/{app_id}/containers"
        )["containers"]

    def stop_container(self, app_id: str, container_id: str) -> None:
        self._call(
            "DELETE",
            f"/ws/v1/cluster/apps/{app_id}/containers/{container_id}",
        )


class YarnError(RuntimeError):
    pass


def resolve_controller(rest: "YarnRestClient", app_id: str,
                       timeout_s: float) -> Tuple[str, int]:
    """Poll the application report until the AM is registered (RUNNING
    + tracking URL) and parse the controller address. ONE implementation
    for the descriptor's deploy wait and the client's re-resolve after
    an AM restart. Transient report errors (the RM may be busy forking
    the replacement AM inside a report handler) retry until the
    deadline."""
    deadline = time.time() + timeout_s
    last_err: Optional[str] = None
    while True:
        try:
            report = rest.app_report(app_id)
        except YarnError as e:
            last_err = str(e)
            if time.time() > deadline:
                raise YarnError(
                    f"application {app_id} report unavailable: {e}"
                ) from None
            time.sleep(0.3)
            continue
        state = report["state"]
        if state in ("FAILED", "KILLED", "FINISHED"):
            raise YarnError(
                f"application {app_id} went {state}: "
                f"{report.get('diagnostics', '')}"
            )
        url = report.get("trackingUrl")
        if state == "RUNNING" and url:
            host, _, port = url.rpartition(":")
            try:
                return host, int(port)
            except ValueError:
                raise YarnError(
                    f"application {app_id} published a tracking URL "
                    f"without a host:port controller address: {url!r}"
                ) from None
        if time.time() > deadline:
            raise YarnError(
                f"application {app_id} still {state} after {timeout_s}s"
                + (f" (last error: {last_err})" if last_err else "")
            )
        time.sleep(0.2)


# --------------------------------------------------------------------------
# Cluster descriptor + session client
# --------------------------------------------------------------------------
class YarnClusterDescriptor:
    """Deploys a flink_tpu session cluster onto YARN.

    Mirrors ``AbstractYarnClusterDescriptor.deploySessionCluster``:
    new-application -> build the AM container launch context (command +
    environment entries) -> submit -> poll the application report until
    the AM registered (RUNNING + tracking URL) -> return a client.
    """

    def __init__(self, rm_url: str, am_resource: Optional[dict] = None,
                 worker_resource: Optional[dict] = None,
                 max_app_attempts: int = 1,
                 am_ha_dir: Optional[str] = None):
        """``max_app_attempts`` > 1 enables AM restart; ``am_ha_dir``
        (shared storage) is where the AM's HA job registry lives so a
        re-attempted AM recovers running jobs from their checkpoints
        (the reference's yarn.application-attempts +
        high-availability.zookeeper pairing)."""
        self.rest = YarnRestClient(rm_url)
        self.rm_url = rm_url
        self.am_resource = am_resource or {"memory": 2048, "vCores": 1}
        self.worker_resource = worker_resource or {
            "memory": 1024, "vCores": 1,
        }
        if max_app_attempts > 1 and not am_ha_dir:
            raise ValueError(
                "max_app_attempts > 1 requires am_ha_dir: without a "
                "durable job registry a re-attempted AM recovers nothing"
            )
        self.max_app_attempts = max_app_attempts
        self.am_ha_dir = am_ha_dir

    def deploy_session_cluster(
        self, name: str = "flink-tpu-session",
        extra_env: Optional[Dict[str, str]] = None,
        deploy_timeout_s: float = 120.0,
    ) -> "YarnClusterClient":
        app = self.rest.new_application()
        app_id = app["application-id"]
        env = {ENV_RM_URL: self.rm_url, ENV_APP_ID: app_id}
        if self.am_ha_dir:
            env[ENV_AM_HA_DIR] = self.am_ha_dir
        env.update(extra_env or {})
        worker_res = json.dumps(self.worker_resource)
        ctx = {
            "application-id": app_id,
            "application-name": name,
            "application-type": "flink-tpu",
            "am-container-spec": {
                "commands": {
                    "command": (
                        f"{shlex.quote(sys.executable)} -m "
                        f"flink_tpu.deploy.appmaster "
                        f"--worker-resource {shlex.quote(worker_res)}"
                    ),
                },
                "environment": {
                    "entry": [
                        {"key": k, "value": v} for k, v in env.items()
                    ],
                },
            },
            "resource": self.am_resource,
            "max-app-attempts": self.max_app_attempts,
        }
        self.rest.submit_application(ctx)
        host, port = resolve_controller(self.rest, app_id,
                                        deploy_timeout_s)
        return YarnClusterClient(self.rest, app_id, host, port)


class YarnClusterClient:
    """Job submission against a deployed session (YarnClusterClient.java):
    jobs go to the AM's controller over the normal control protocol;
    ``shutdown_cluster`` kills the YARN application via the RM."""

    def __init__(self, rest: YarnRestClient, app_id: str,
                 controller_host: str, controller_port: int):
        self.rest = rest
        self.app_id = app_id
        self.controller = (controller_host, controller_port)

    def _control(self, msg: dict) -> dict:
        from flink_tpu.runtime.cluster import control_request

        try:
            resp = control_request(*self.controller, msg)
        except (OSError, ValueError):
            # AM restart moved the controller (a dying AM can also cut a
            # response short: json decode errors are ValueError, not
            # OSError): re-resolve the tracking URL from the application
            # report and retry once (the reference client's
            # leader-retrieval-on-failure)
            self.controller = resolve_controller(
                self.rest, self.app_id, timeout_s=60
            )
            resp = control_request(*self.controller, msg)
        if not resp.get("ok", False):
            raise YarnError(f"controller error: {resp.get('error')}")
        return resp

    def submit_job(self, builder_ref: str, job_name: str = "job",
                   checkpoint_dir: str = "",
                   extra_env: Optional[dict] = None) -> str:
        return self._control({
            "action": "submit", "builder": builder_ref,
            "job_name": job_name, "checkpoint_dir": checkpoint_dir,
            "extra_env": extra_env,
        })["worker_id"]

    def list_workers(self) -> List[dict]:
        return self._control({"action": "list"})["workers"]

    def wait_job(self, worker_id: str, timeout_s: float = 180.0) -> str:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            for w in self.list_workers():
                if w["worker_id"] == worker_id and w["status"] in (
                    "FINISHED", "FAILED", "DEAD"
                ):
                    return w["status"]
            time.sleep(0.2)
        raise TimeoutError(f"job {worker_id} not terminal in {timeout_s}s")

    def app_report(self) -> dict:
        return self.rest.app_report(self.app_id)

    def shutdown_cluster(self, timeout_s: float = 30.0) -> dict:
        self.rest.kill(self.app_id)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            report = self.rest.app_report(self.app_id)
            if report["state"] in ("KILLED", "FINISHED", "FAILED"):
                return report
            time.sleep(0.2)
        raise TimeoutError(f"application {self.app_id} did not stop")


# --------------------------------------------------------------------------
# In-repo spec ResourceManager (RM + NodeManager roles)
# --------------------------------------------------------------------------
@dataclass
class _Container:
    container_id: str
    proc: subprocess.Popen
    command: str
    log_path: str
    state: str = "RUNNING"      # RUNNING|COMPLETE
    exit_status: Optional[int] = None


@dataclass
class _App:
    app_id: str
    name: str = ""
    app_type: str = ""
    state: str = "NEW"          # spec lifecycle subset:
    #                             NEW->SUBMITTED->ACCEPTED->RUNNING->final
    final_status: str = "UNDEFINED"
    tracking_url: str = ""
    diagnostics: str = ""
    am: Optional[_Container] = None
    containers: Dict[str, _Container] = field(default_factory=dict)
    seq: int = 0
    # AM restart (ref YarnApplicationMasterRunner + max-app-attempts):
    # the launch context is kept so a failed AM can be relaunched
    max_attempts: int = 1
    attempt: int = 1
    am_command: str = ""
    am_env: Dict[str, str] = field(default_factory=dict)


class MiniYarnRM:
    """In-repo Hadoop RM speaking the REST surface ``YarnRestClient``
    targets, playing the NodeManager too: an accepted application's AM
    command and every requested container command run as real OS
    processes (env from the launch context over the RM's own env, logs
    per container), so the glue is tested against real process
    lifecycles, not fakes. Same pattern as MiniKafkaBroker /
    MiniElasticsearch: the service is absent from the image, so the spec
    is implemented in-repo and the real client is pointed at it."""

    def __init__(self, workdir: str):
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.cluster_ts = int(time.time() * 1000)
        self.apps: Dict[str, _App] = {}
        self._new_seq = 0
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self.port: Optional[int] = None
        # forks must come from a long-lived thread: PR_SET_PDEATHSIG
        # fires when the forking THREAD exits, and HTTP handler threads
        # are per-request (runtime/spawner.py has the full rationale and
        # the abandoned-request claim protocol, shared with
        # ProcessCluster)
        self._spawner = AbandonableSpawner("miniyarn-spawner")

    # -- lifecycle -------------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        rm = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: Optional[dict] = None):
                payload = json.dumps(body or {}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n)) if n else {}

            def _route(self, method: str):
                try:
                    code, body = rm._dispatch(
                        method, self.path, self._body()
                    )
                except KeyError as e:
                    code, body = 404, {"RemoteException": {
                        "message": f"not found: {e}",
                    }}
                except Exception as e:
                    code, body = 400, {"RemoteException": {
                        "message": str(e),
                    }}
                self._reply(code, body)

            def do_GET(self):
                self._route("GET")

            def do_POST(self):
                self._route("POST")

            def do_PUT(self):
                self._route("PUT")

            def do_DELETE(self):
                self._route("DELETE")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="miniyarn-http",
        ).start()
        return self.port

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        with self._lock:
            for app in self.apps.values():
                self._kill_app_locked(app, "RM shutdown")
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self._spawner.stop()

    # -- spawner (NodeManager ContainerExecutor role) --------------------
    def _launch(self, app: _App, kind: str, command: str,
                env_entries: Dict[str, str]) -> _Container:
        with self._lock:
            app.seq += 1
            cid = (f"container_{self.cluster_ts}_"
                   f"{app.app_id.rsplit('_', 1)[1]}_01_{app.seq:06d}")
        cdir = os.path.join(self.workdir, app.app_id, cid)
        os.makedirs(cdir, exist_ok=True)
        env = dict(os.environ)
        env.update(env_entries)
        env["CONTAINER_ID"] = cid
        log_path = os.path.join(cdir, f"{kind}.log")

        def fork():
            log = open(log_path, "ab")
            # ``exec``: the container process must BE the command, not a
            # shell wrapping it — a SIGKILL aimed at the container
            # otherwise kills only the shell and orphans the worker,
            # which then runs CONCURRENTLY with its replacement
            # (duplicate emissions). Launch contexts here are single
            # commands, so exec is always legal. start_new_session gives
            # each container its own process group so the kill paths can
            # sweep descendants too.
            return subprocess.Popen(
                ["/bin/sh", "-c", "exec " + command],
                env=env, stdout=log, stderr=log,
                start_new_session=True,
                preexec_fn=_die_with_parent,
            )

        try:
            proc = self._spawner.submit(
                fork, on_abandon=lambda p: p.kill(), timeout_s=30,
            )
        except Exception as e:
            raise YarnError(f"container launch failed: {e}") from None
        return _Container(container_id=cid, proc=proc,
                          command=command, log_path=log_path)

    def _refresh(self, c: _Container):
        if c.state == "RUNNING" and c.proc.poll() is not None:
            c.state = "COMPLETE"
            c.exit_status = c.proc.returncode

    @staticmethod
    def _kill_container(c: _Container):
        """SIGKILL the container's whole process group (the container is
        its own session), falling back to the direct child."""
        try:
            os.killpg(os.getpgid(c.proc.pid), 9)
        except (ProcessLookupError, PermissionError, OSError):
            c.proc.kill()
        c.state = "COMPLETE"
        c.exit_status = -137

    def _kill_app_locked(self, app: _App, why: str):
        """Caller holds ``self._lock``; killpg is fast enough to hold it
        through the sweep, and flipping state under the same lock closes
        the register-after-kill race (a /master arriving mid-kill must
        not flip a KILLED app back to RUNNING)."""
        for c in ([app.am] if app.am else []) + list(
            app.containers.values()
        ):
            self._refresh(c)
            if c.state == "RUNNING":
                self._kill_container(c)
        if app.state not in ("FINISHED", "FAILED", "KILLED"):
            app.state = "KILLED"
            app.final_status = "KILLED"
            app.diagnostics = why

    # -- REST dispatch ---------------------------------------------------
    def _dispatch(self, method: str, path: str, body: dict):
        parts = [p for p in path.split("/") if p]
        if parts[:2] != ["ws", "v1"] or parts[2] != "cluster":
            raise KeyError(path)
        rest = parts[3:]
        if rest == ["info"] and method == "GET":
            return 200, {"clusterInfo": {
                "id": self.cluster_ts, "state": "STARTED",
                "resourceManagerVersion": "flink-tpu-mini",
            }}
        if rest == ["apps", "new-application"] and method == "POST":
            with self._lock:
                self._new_seq += 1
                app_id = f"application_{self.cluster_ts}_{self._new_seq:04d}"
                self.apps[app_id] = _App(app_id=app_id)
            return 200, {
                "application-id": app_id,
                "maximum-resource-capability": {
                    "memory": 8192, "vCores": 8,
                },
            }
        if rest == ["apps"] and method == "POST":
            return self._submit(body)
        if len(rest) >= 2 and rest[0] == "apps":
            app = self.apps[rest[1]]
            return self._app_route(method, app, rest[2:], body)
        raise KeyError(path)

    def _submit(self, ctx: dict):
        spec = ctx["am-container-spec"]
        command = spec["commands"]["command"]
        env_entries = {
            e["key"]: e["value"]
            for e in spec.get("environment", {}).get("entry", [])
        }
        with self._lock:
            app = self.apps[ctx["application-id"]]   # KeyError -> 404
            if app.state != "NEW":
                raise ValueError(f"application already {app.state}")
            app.name = ctx.get("application-name", "")
            app.app_type = ctx.get("application-type", "")
            app.max_attempts = int(ctx.get("max-app-attempts", 1))
            app.am_command = command
            app.am_env = dict(env_entries)
            app.state = "ACCEPTED"
        # fork outside the lock (spawner round-trips up to 30s)
        try:
            am = self._launch(app, "am", command, env_entries)
        except Exception as e:
            with self._lock:
                if app.state == "ACCEPTED":   # a concurrent kill wins
                    app.state = "FAILED"
                    app.final_status = "FAILED"
                    app.diagnostics = str(e)
            raise
        with self._lock:
            if app.state == "ACCEPTED":
                app.am = am
            else:                     # killed while the AM was forking
                self._kill_container(am)
        return 202, {}

    def _app_route(self, method: str, app: _App, rest: List[str],
                   body: dict):
        if rest == [] and method == "GET":
            relaunch = False
            with self._lock:
                if app.am is not None:
                    self._refresh(app.am)
                    if app.am.state == "COMPLETE" and app.state in (
                        "ACCEPTED", "RUNNING"
                    ):
                        if app.am.exit_status == 0:
                            app.state = "FINISHED"
                            app.final_status = "SUCCEEDED"
                        elif app.attempt < app.max_attempts:
                            # AM restart (YarnApplicationMasterRunner's
                            # re-attempt): the dead attempt's worker
                            # containers are killed first — the YARN
                            # default without keep-containers-across-
                            # application-attempts, and what prevents an
                            # orphan writer running beside the new
                            # attempt's recovered jobs
                            for c in list(app.containers.values()):
                                self._refresh(c)
                                if c.state == "RUNNING":
                                    self._kill_container(c)
                            app.attempt += 1
                            app.tracking_url = ""
                            app.state = "ACCEPTED"
                            # clear the dead handle UNDER the lock: a
                            # concurrent GET during the (slow) fork
                            # below must not re-detect the same death
                            # and fail the app / launch a second AM
                            app.am = None
                            relaunch = True
                        else:
                            app.state = "FAILED"
                            app.final_status = "FAILED"
                report = {"app": {
                    "id": app.app_id, "name": app.name,
                    "applicationType": app.app_type, "state": app.state,
                    "finalStatus": app.final_status,
                    "trackingUrl": app.tracking_url,
                    "diagnostics": app.diagnostics,
                    "currentAppAttemptId": app.attempt,
                    "runningContainers": 1 + sum(
                        1 for c in app.containers.values()
                        if c.state == "RUNNING"
                    ) if app.state == "RUNNING" else 0,
                }}
            if relaunch:
                # fork outside the lock; a kill racing the relaunch is
                # handled exactly like the submit path
                try:
                    am = self._launch(app, f"am-attempt{app.attempt}",
                                      app.am_command, app.am_env)
                except Exception as e:
                    with self._lock:
                        if app.state == "ACCEPTED":
                            app.state = "FAILED"
                            app.final_status = "FAILED"
                            app.diagnostics = str(e)
                    return 200, report
                with self._lock:
                    if app.state == "ACCEPTED" and app.am is None:
                        app.am = am
                    else:               # killed while relaunching
                        self._kill_container(am)
            return 200, report
        if rest == ["state"] and method == "PUT":
            if body.get("state") != "KILLED":
                raise ValueError(
                    f"only KILLED is a valid target state, "
                    f"got {body.get('state')!r}"
                )
            with self._lock:
                self._kill_app_locked(app, "killed via REST state API")
                return 202, {"state": app.state}
        if rest == ["master"] and method == "POST":
            with self._lock:
                # register is only legal while the submission is live —
                # an AM whose app was killed mid-startup must not flip
                # KILLED back to RUNNING (shutdown_cluster would spin)
                if app.state != "ACCEPTED":
                    raise ValueError(
                        f"cannot register master: application is "
                        f"{app.state}"
                    )
                app.tracking_url = body["trackingUrl"]
                app.state = "RUNNING"
            return 200, {}
        if rest == ["finish"] and method == "POST":
            with self._lock:
                if app.state in ("ACCEPTED", "RUNNING"):
                    app.final_status = body.get(
                        "finalStatus", "SUCCEEDED"
                    )
                    app.state = (
                        "FINISHED" if app.final_status == "SUCCEEDED"
                        else "FAILED"
                    )
            return 200, {}
        if rest == ["containers"] and method == "POST":
            with self._lock:
                if app.state != "RUNNING":
                    raise ValueError(
                        f"containers can only be requested by a RUNNING "
                        f"application (state={app.state})"
                    )
            # fork outside the lock, re-check on insert
            c = self._launch(app, "worker", body["command"],
                             dict(body.get("environment") or {}))
            with self._lock:
                if app.state != "RUNNING":   # killed while forking
                    self._kill_container(c)
                    raise ValueError(
                        f"application went {app.state} during the "
                        f"container launch"
                    )
                app.containers[c.container_id] = c
            return 200, {"container-id": c.container_id}
        if rest == ["containers"] and method == "GET":
            with self._lock:
                out = []
                for c in app.containers.values():
                    self._refresh(c)
                    out.append(self._container_json(c))
                return 200, {"containers": out}
        if len(rest) == 2 and rest[0] == "containers":
            with self._lock:
                c = app.containers[rest[1]]
                self._refresh(c)
                if method == "GET":
                    return 200, {"container": self._container_json(c)}
                if method == "DELETE":
                    if c.state == "RUNNING":
                        self._kill_container(c)
                    return 200, {}
        raise KeyError("/".join(rest))

    @staticmethod
    def _container_json(c: _Container) -> dict:
        return {
            "id": c.container_id, "state": c.state,
            "exitStatus": c.exit_status, "logUrl": c.log_path,
        }


# --------------------------------------------------------------------------
# CLI (bin/yarn-session.sh analog, ref flink-yarn/.../cli/FlinkYarnSessionCli)
# --------------------------------------------------------------------------
def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="yarn-session",
        description="Deploy a flink_tpu session cluster on YARN",
    )
    ap.add_argument("--rm", required=True,
                    help="ResourceManager REST URL, e.g. http://rm:8088")
    ap.add_argument("--name", default="flink-tpu-session")
    ap.add_argument("--am-memory", type=int, default=2048)
    ap.add_argument("--worker-memory", type=int, default=1024)
    ap.add_argument("--max-app-attempts", type=int, default=1,
                    help="> 1 enables AM restart (needs --am-ha-dir)")
    ap.add_argument("--am-ha-dir", default=None,
                    help="shared dir for the AM's HA job registry "
                         "(yarn.application-attempts pairing)")
    a = ap.parse_args(argv)
    desc = YarnClusterDescriptor(
        a.rm, am_resource={"memory": a.am_memory, "vCores": 1},
        worker_resource={"memory": a.worker_memory, "vCores": 1},
        max_app_attempts=a.max_app_attempts,
        am_ha_dir=a.am_ha_dir,
    )
    client = desc.deploy_session_cluster(a.name)
    print(json.dumps({
        "application-id": client.app_id,
        "controller": f"{client.controller[0]}:{client.controller[1]}",
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
