"""Cluster-framework deployment glue (ref flink-yarn/, flink-mesos/).

The reference ships YARN and Mesos modes whose job is to (1) submit an
ApplicationMaster to the cluster framework, (2) have the AM request
worker containers, and (3) wire the launched TaskManagers back to the
JobManager. Here the same three steps drive the TPU-native runtime:
the AM is a ``ProcessCluster`` controller, a worker container runs
``flink_tpu.runtime.worker`` (the per-job container pattern), and the
framework protocol is the public YARN ResourceManager REST API spoken
by a from-spec client (``deploy/yarn.py``).
"""

from flink_tpu.deploy.yarn import (  # noqa: F401
    MiniYarnRM,
    YarnClusterClient,
    YarnClusterDescriptor,
    YarnRestClient,
)
