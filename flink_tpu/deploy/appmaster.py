"""YARN ApplicationMaster: the controller runtime inside the AM container.

Ref ``YarnApplicationMasterRunner.java`` (starts the JobManager actor
system inside the AM container) + ``YarnFlinkResourceManager.java``
(requests TaskManager containers from YARN and re-requests them when
containers complete unexpectedly). TPU-native mapping: the AM runs the
ordinary ``ProcessCluster`` controller, and ``YarnProcessCluster``
redirects the single spawn seam — worker processes become YARN container
requests, and the returned handle speaks the RM's container-report API
in place of ``Popen.poll``. Everything above the seam (registration,
heartbeats, DeathWatch, restart-with-restore, HA, leases) is unchanged,
so a container death flows through the same restart machinery as a local
process death; the re-request happens because the restart loop calls the
same spawn seam again (YarnFlinkResourceManager.java's
``onContainersCompleted`` -> re-request loop, expressed structurally).

The RM coordinates arrive through the container environment
(``FLINK_TPU_YARN_RM_URL`` / ``FLINK_TPU_YARN_APP_ID``), the way the
reference ships them via ``YarnConfigKeys`` env entries.
"""

from __future__ import annotations

import json
import os
import shlex
import signal
import sys
import threading
import time
from typing import Optional

from flink_tpu.deploy.yarn import (
    ENV_AM_HA_DIR,
    ENV_APP_ID,
    ENV_RM_URL,
    YarnError,
    YarnRestClient,
)
from flink_tpu.runtime.process_cluster import ProcessCluster


class _YarnContainerHandle:
    """Duck-types the ``subprocess.Popen`` surface the controller's
    DeathWatch uses (``poll``/``kill``/``pid``) against the RM's
    container-report API, so ``ProcessCluster._monitor_loop`` watches a
    remote container exactly like a local child process."""

    # the DeathWatch scan runs every 0.25s over every worker; container
    # reports ride HTTP, so polls use a short-timeout client and a 1s
    # result cache to keep a slow RM from serializing death detection
    POLL_INTERVAL_S = 1.0

    def __init__(self, rest: YarnRestClient, app_id: str,
                 container_id: str):
        self._rest = YarnRestClient(rest.base, timeout_s=2.0)
        self._app_id = app_id
        self.container_id = container_id
        self.pid = container_id          # identifier for event logs
        self._exit: Optional[int] = None
        self._last_poll = 0.0

    def poll(self) -> Optional[int]:
        if self._exit is not None:
            return self._exit
        now = time.time()
        if now - self._last_poll < self.POLL_INTERVAL_S:
            return None
        self._last_poll = now
        try:
            report = self._rest.container_report(
                self._app_id, self.container_id
            )
        except YarnError:
            # RM briefly unreachable: report liveness; heartbeat
            # staleness still catches a truly dead worker
            return None
        if report["state"] == "COMPLETE":
            self._exit = report.get("exitStatus")
            if self._exit is None:
                self._exit = -1
        return self._exit

    def kill(self, budget_s: float = 6.0):
        """Stop the container and CONFIRM it stopped before recording an
        exit. Pretending an unconfirmed kill succeeded would let the
        restart loop respawn a replacement while the old worker still
        runs — two writers, duplicate emissions. If the RM is
        unreachable the exit stays unrecorded; the subsequent respawn's
        ``request_container`` fails against the same dead RM, so no
        second writer can start either way.

        Wall-clock budgeted, NOT iteration-counted: against a hung RM
        every HTTP call burns its own 2s timeout, and kill() runs on the
        single shared spawner thread — an unbounded loop there would
        stall every other worker's respawn behind one stuck stop."""
        if self._exit is not None:
            return
        deadline = time.time() + budget_s
        while time.time() < deadline:
            try:
                self._rest.stop_container(self._app_id, self.container_id)
                report = self._rest.container_report(
                    self._app_id, self.container_id
                )
            except YarnError:
                time.sleep(0.2)
                continue
            if report["state"] == "COMPLETE":
                self._exit = report.get("exitStatus", -137)
                return
            time.sleep(0.2)


class YarnProcessCluster(ProcessCluster):
    """ProcessCluster whose worker spawns are YARN container requests."""

    def __init__(self, rest: YarnRestClient, app_id: str,
                 worker_resource: Optional[dict] = None, **kw):
        super().__init__(**kw)
        self._rest = rest
        self._app_id = app_id
        self._worker_resource = worker_resource or {
            "memory": 1024, "vCores": 1,
        }
        # worker_id -> last issued handle, for the replacement barrier
        self._handles: dict = {}

    # -- recovery ordering (AM restart) ----------------------------------
    # ProcessCluster recovers registered jobs the moment leadership is
    # granted — but a recovered job's worker is a CONTAINER REQUEST, and
    # the RM only grants containers to a REGISTERED (RUNNING) AM. Defer
    # recovery until after register_am (YarnApplicationMasterRunner
    # registers before the resource manager starts allocating).
    _defer_recovery = True
    _recovery_pending = False

    def _recover_jobs(self):
        if self._defer_recovery:
            self._recovery_pending = True
            return
        super()._recover_jobs()

    def recover_after_registration(self):
        self._defer_recovery = False
        if self._recovery_pending:
            self._recovery_pending = False
            super()._recover_jobs()

    def _spawn_inner(self, worker_id, builder_ref, job_name,
                     checkpoint_dir, restore, extra_env=None):
        # replacement barrier: NEVER request a new container for a worker
        # whose previous container is not confirmed dead — kill() gives
        # up quietly when the stop cannot be confirmed, and two live
        # containers for one worker means two writers and duplicate
        # emissions. Failing the spawn here surfaces as restart-failed
        # (job FAILED) instead of silent corruption.
        prior = self._handles.get(worker_id)
        if prior is not None and prior.poll() is None:
            deadline = time.time() + 15.0
            while time.time() < deadline:
                # cap each kill attempt so the barrier's own deadline is
                # honored even against a hung RM (kill() runs HTTP calls)
                prior.kill(
                    budget_s=min(3.0, max(0.5, deadline - time.time()))
                )
                if prior.poll() is not None:
                    break
                time.sleep(0.3)
            if prior.poll() is None:
                raise YarnError(
                    f"previous container {prior.container_id} for "
                    f"{worker_id} cannot be confirmed stopped; refusing "
                    f"to start a concurrent replacement"
                )
        cmd = [
            sys.executable, "-m", "flink_tpu.runtime.worker",
            "--controller", f"{self.advertise_host}:{self._port}",
            "--worker-id", worker_id,
            "--builder", builder_ref,
            "--job-name", job_name,
            "--checkpoint-dir", checkpoint_dir,
        ]
        if restore:
            cmd.append("--restore")
        env = {}
        if self.auth_token:
            from flink_tpu.runtime import security

            env[security.ENV_TOKEN] = self.auth_token
        if extra_env:
            env.update(extra_env)
        cid = self._rest.request_container(
            self._app_id, shlex.join(cmd), environment=env,
            resource=self._worker_resource,
        )
        self._event("container-requested", worker=worker_id,
                    container=cid)
        handle = _YarnContainerHandle(self._rest, self._app_id, cid)
        self._handles[worker_id] = handle
        return handle


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="flink-tpu-appmaster")
    ap.add_argument("--rm", default=os.environ.get(ENV_RM_URL))
    ap.add_argument("--app-id", default=os.environ.get(ENV_APP_ID))
    ap.add_argument("--ha-dir",
                    default=os.environ.get(ENV_AM_HA_DIR) or None,
                    help="durable job-registry dir: a re-attempted AM "
                         "recovers running jobs from it "
                         "(yarn.application-attempts pairing)")
    ap.add_argument("--worker-resource", default=None,
                    help="JSON resource dict for worker containers")
    ap.add_argument("--heartbeat-timeout-s", type=float, default=10.0)
    a = ap.parse_args(argv)
    if not a.rm or not a.app_id:
        print("appmaster: missing RM url / application id "
              f"({ENV_RM_URL}/{ENV_APP_ID})", file=sys.stderr)
        return 2
    rest = YarnRestClient(a.rm)
    cluster = YarnProcessCluster(
        rest, a.app_id,
        worker_resource=(
            json.loads(a.worker_resource) if a.worker_resource else None
        ),
        heartbeat_timeout_s=a.heartbeat_timeout_s,
        ha_dir=a.ha_dir,
    )
    # with ha_dir the previous attempt's flock released at its death, so
    # leadership is immediate; recovery of registered jobs runs on grant
    port = cluster.start(block_for_leadership_s=60.0)
    rest.register_am(a.app_id, f"{cluster.advertise_host}:{port}")
    cluster.recover_after_registration()
    print(f"[appmaster] {a.app_id} serving on {port}", flush=True)

    done = threading.Event()

    def on_term(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    while not done.wait(0.5):
        pass
    cluster.shutdown()
    try:
        rest.finish_am(a.app_id, "SUCCEEDED")
    except YarnError:
        pass                     # RM already gone or app already killed
    return 0


if __name__ == "__main__":
    sys.exit(main())
