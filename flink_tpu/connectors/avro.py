"""Avro object-container files — the flink-avro role (SURVEY §2.8,
ref flink-batch-connectors/flink-avro AvroInputFormat/AvroOutputFormat).

No Avro library exists in this runtime, so the binary encoding is
implemented from the specification (Apache Avro 1.8 spec: zig-zag varint
longs, length-prefixed bytes/strings, blocked arrays/maps, union index
prefix, and the object container format — magic ``Obj\\x01``, metadata
map carrying ``avro.schema``/``avro.codec``, 16-byte sync marker between
blocks). Supported schema subset: the primitives (null, boolean, int,
long, float, double, bytes, string), records, arrays, maps, enums, and
unions — the shapes the reference's Avro POJO round-trips exercise.
Codec ``null`` and ``deflate``.

    schema = {"type": "record", "name": "Event", "fields": [
        {"name": "key", "type": "long"},
        {"name": "value", "type": "double"},
        {"name": "tag", "type": ["null", "string"]},
    ]}
    write_container(path, schema, records)      # list of dicts
    rows = AvroInputFormat(path).read_all()
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterable, List, Optional

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------- primitives
def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: io.BytesIO, n: int):
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def read_long(buf: io.BytesIO) -> int:
    shift, acc = 0, 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("truncated varint")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _zigzag_decode(acc)
        shift += 7


def _write_bytes(buf, data: bytes):
    write_long(buf, len(data))
    buf.write(data)


def _read_bytes(buf) -> bytes:
    n = read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated bytes")
    return data


# ---------------------------------------------------------------- datum codec
def write_datum(buf: io.BytesIO, schema, value):
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return
        if t == "boolean":
            buf.write(b"\x01" if value else b"\x00")
        elif t in ("int", "long"):
            write_long(buf, int(value))
        elif t == "float":
            buf.write(struct.pack("<f", float(value)))
        elif t == "double":
            buf.write(struct.pack("<d", float(value)))
        elif t == "bytes":
            _write_bytes(buf, bytes(value))
        elif t == "string":
            _write_bytes(buf, str(value).encode("utf-8"))
        else:
            raise ValueError(f"unsupported primitive {t!r}")
        return
    if isinstance(schema, list):           # union: index prefix
        for i, branch in enumerate(schema):
            if _matches(branch, value):
                write_long(buf, i)
                write_datum(buf, branch, value)
                return
        raise ValueError(f"value {value!r} matches no union branch {schema}")
    t = schema["type"]
    if t == "record":
        for f in schema["fields"]:
            write_datum(buf, f["type"], value[f["name"]])
    elif t == "array":
        if value:
            write_long(buf, len(value))
            for item in value:
                write_datum(buf, schema["items"], item)
        write_long(buf, 0)
    elif t == "map":
        if value:
            write_long(buf, len(value))
            for k, v in value.items():
                _write_bytes(buf, str(k).encode("utf-8"))
                write_datum(buf, schema["values"], v)
        write_long(buf, 0)
    elif t == "enum":
        write_long(buf, schema["symbols"].index(value))
    elif t == "fixed":
        data = bytes(value)
        if len(data) != schema["size"]:
            raise ValueError("fixed size mismatch")
        buf.write(data)
    else:
        # named/nested simple type, e.g. {"type": "long"}
        write_datum(buf, t, value)


def _matches(branch, value) -> bool:
    t = branch if isinstance(branch, str) else branch.get("type")
    if t == "null":
        return value is None
    if value is None:
        return False
    if t == "boolean":
        return isinstance(value, bool)
    if t in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if t in ("float", "double"):
        # ints coerce to floating branches, as every mainstream writer
        # accepts (write_datum applies float())
        return isinstance(value, float) or (
            isinstance(value, int) and not isinstance(value, bool)
        )
    if t == "string":
        return isinstance(value, str)
    if t == "bytes":
        return isinstance(value, (bytes, bytearray))
    if t == "record":
        return isinstance(value, dict)
    if t == "array":
        return isinstance(value, list)
    if t == "map":
        return isinstance(value, dict)
    if t == "enum":
        return isinstance(value, str)
    return True


def read_datum(buf: io.BytesIO, schema):
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return None
        if t == "boolean":
            return buf.read(1) == b"\x01"
        if t in ("int", "long"):
            return read_long(buf)
        if t == "float":
            return struct.unpack("<f", buf.read(4))[0]
        if t == "double":
            return struct.unpack("<d", buf.read(8))[0]
        if t == "bytes":
            return _read_bytes(buf)
        if t == "string":
            return _read_bytes(buf).decode("utf-8")
        raise ValueError(f"unsupported primitive {t!r}")
    if isinstance(schema, list):
        idx = read_long(buf)
        return read_datum(buf, schema[idx])
    t = schema["type"]
    if t == "record":
        return {
            f["name"]: read_datum(buf, f["type"]) for f in schema["fields"]
        }
    if t == "array":
        out = []
        while True:
            n = read_long(buf)
            if n == 0:
                return out
            if n < 0:                      # block with byte size
                read_long(buf)
                n = -n
            for _ in range(n):
                out.append(read_datum(buf, schema["items"]))
    if t == "map":
        out = {}
        while True:
            n = read_long(buf)
            if n == 0:
                return out
            if n < 0:
                read_long(buf)
                n = -n
            for _ in range(n):
                k = _read_bytes(buf).decode("utf-8")
                out[k] = read_datum(buf, schema["values"])
    if t == "enum":
        return schema["symbols"][read_long(buf)]
    if t == "fixed":
        return buf.read(schema["size"])
    return read_datum(buf, t)


# ---------------------------------------------------------------- container
def write_container(path: str, schema: Dict, records: Iterable[dict],
                    codec: str = "null", sync: Optional[bytes] = None,
                    block_records: int = 1024):
    """Write an Avro object container file (spec: header + data blocks,
    each `count, size, payload, sync`)."""
    if codec not in ("null", "deflate"):
        raise ValueError(f"codec must be null|deflate, got {codec!r}")
    sync = sync or os.urandom(16)
    with open(path, "wb") as f:
        f.write(MAGIC)
        hdr = io.BytesIO()
        meta = {
            "avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode(),
        }
        write_long(hdr, len(meta))
        for k, v in meta.items():
            _write_bytes(hdr, k.encode())
            _write_bytes(hdr, v)
        write_long(hdr, 0)
        f.write(hdr.getvalue())
        f.write(sync)

        block: List[dict] = []

        def flush():
            if not block:
                return
            body = io.BytesIO()
            for r in block:
                write_datum(body, schema, r)
            payload = body.getvalue()
            if codec == "deflate":
                payload = zlib.compress(payload)[2:-4]   # raw deflate
            blk = io.BytesIO()
            write_long(blk, len(block))
            write_long(blk, len(payload))
            f.write(blk.getvalue())
            f.write(payload)
            f.write(sync)
            block.clear()

        for r in records:
            block.append(r)
            if len(block) >= block_records:
                flush()
        flush()


def read_container(path: str):
    """-> (schema, records list)."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    meta = {}
    while True:
        n = read_long(buf)
        if n == 0:
            break
        if n < 0:
            read_long(buf)
            n = -n
        for _ in range(n):
            k = _read_bytes(buf).decode()
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    sync = buf.read(16)
    records = []
    while buf.tell() < len(data):
        count = read_long(buf)
        size = read_long(buf)
        payload = buf.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, wbits=-15)
        body = io.BytesIO(payload)
        for _ in range(count):
            records.append(read_datum(body, schema))
        if buf.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt block)")
    return schema, records


# ---------------------------------------------------------------- formats
class AvroInputFormat:
    """ref AvroInputFormat.java: container file -> records (dicts)."""

    def __init__(self, path: str):
        self.path = path

    def read_all(self) -> List[dict]:
        _schema, records = read_container(self.path)
        return records


class AvroOutputFormat:
    """ref AvroOutputFormat.java: records -> container file."""

    def __init__(self, path: str, schema: Dict, codec: str = "null"):
        self.path = path
        self.schema = schema
        self.codec = codec

    def write(self, records: Iterable[dict]) -> str:
        write_container(self.path, self.schema, records, codec=self.codec)
        return self.path
