"""Partitioned replayable consumer — the Kafka-consumer contract.

Redesign of the reference's FlinkKafkaConsumerBase (SURVEY §2.8,
flink-connector-kafka-base/.../FlinkKafkaConsumerBase.java:65):

- partition discovery at open, offsets tracked per partition
  (the reference assigns partitions round-robin across subtasks; in the
  SPMD design ONE host loop feeds the whole mesh, so all partitions land
  here and the device all_to_all does the key distribution);
- offsets snapshot into every checkpoint (snapshotState:336 analog is
  `snapshot_offsets`);
- offsets are committed BACK to the external system only when the
  checkpoint completes (notifyCheckpointComplete:384 →
  `notify_checkpoint_complete`), so the external commit never runs ahead
  of a restorable state;
- restore seeks every partition to the snapshot offsets, replaying the
  exact records since the cut (exactly-once with deterministic fetch).

Subclass and implement `discover_partitions` + `fetch` (+ optionally
`commit_offsets`) for a real system; `InMemoryPartitionedSource` is the
reference test-double (MockFetcher role).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.runtime.sources import Source


class PartitionedConsumerBase(Source):
    def __init__(self):
        self.offsets: Dict[Any, int] = {}
        self._partitions: Optional[List[Any]] = None
        self._rr = 0
        self.committed: Dict[Any, int] = {}  # last externally-committed

    # -- subclass contract ----------------------------------------------
    def discover_partitions(self) -> List[Any]:
        raise NotImplementedError

    def fetch(self, partition, offset: int, max_records: int
              ) -> Tuple[List[Any], int, bool]:
        """-> (records, new_offset, partition_exhausted). Must be
        deterministic given (partition, offset) for exactly-once replay."""
        raise NotImplementedError

    def commit_offsets(self, offsets: Dict[Any, int], checkpoint_id: int):
        """External commit hook (e.g. Kafka consumer-group commit). Default
        records them locally so progress is observable."""
        self.committed = dict(offsets)

    # -- Source contract -------------------------------------------------
    def open(self):
        if self._partitions is None:
            self._partitions = list(self.discover_partitions())
            for p in self._partitions:
                self.offsets.setdefault(p, 0)
        self._done = {p: False for p in self._partitions}
        # a restored source may already sit past a partition's end; probe
        # lazily in poll instead of assuming liveness here

    def poll(self, max_records: int):
        parts = [p for p in self._partitions if not self._done[p]]
        if not parts:
            return [], True
        per = max(1, max_records // len(parts))
        out: List[Any] = []
        n = len(self._partitions)
        for i in range(n):
            p = self._partitions[(self._rr + i) % n]
            if self._done[p]:
                continue
            records, new_off, exhausted = self.fetch(p, self.offsets[p], per)
            out.extend(records)
            self.offsets[p] = new_off
            self._done[p] = exhausted
        self._rr = (self._rr + 1) % n
        return out, all(self._done.values())

    def snapshot_offsets(self):
        return dict(self.offsets)

    def restore_offsets(self, state):
        self.offsets = dict(state)
        if self._partitions is not None:
            self._done = {p: False for p in self._partitions}

    def notify_checkpoint_complete(self, checkpoint_id: int, offsets=None):
        self.commit_offsets(
            dict(offsets) if offsets is not None else dict(self.offsets),
            checkpoint_id,
        )


class InMemoryPartitionedSource(PartitionedConsumerBase):
    """Test-double topic: {partition_id: [records]}. Finite; a partition is
    exhausted when its list is consumed."""

    def __init__(self, partitions: Dict[Any, List[Any]]):
        super().__init__()
        self.data = partitions

    def discover_partitions(self):
        return list(self.data)

    def fetch(self, partition, offset, max_records):
        records = self.data[partition][offset : offset + max_records]
        new_off = offset + len(records)
        return records, new_off, new_off >= len(self.data[partition])
