"""Database connector over DB-API 2.0 — the flink-jdbc analog.

The reference's JDBCInputFormat / JDBCOutputFormat
(flink-batch-connectors/flink-jdbc/.../JDBCInputFormat.java,
JDBCOutputFormat.java) read query results as rows and write batched
prepared statements. Python's DB-API is the JDBC of this runtime, so the
connector takes a `connection_factory` (e.g. `lambda:
sqlite3.connect(path)`) and works against any driver.

* DbApiInputFormat — parameterized query splits (the reference's
  parameterValues array: one split per parameter tuple, each an
  independent replayable partition), exposed both as a DataSet source
  (`read_all`) and a streaming Source with offset snapshot/restore
  (row-position offsets per split; replay = re-run the query and skip —
  exactly-once given a deterministic query, the same contract as every
  replayable source here).
* DbApiSink — streaming sink with batched executemany writes. With an
  UPSERT statement (e.g. INSERT OR REPLACE) writes are idempotent, so
  checkpoint replay yields effectively-once results — the reference's
  recommended JDBC sink pattern; plain INSERT is at-least-once, as in
  JDBCOutputFormat.
* DbApiOutputFormat — batch (DataSet) writer: one transaction per
  flush interval.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

from flink_tpu.runtime.sinks import Sink
from flink_tpu.runtime.sources import Source


class DbApiInputFormat(Source):
    """Query splits as a replayable source (ref JDBCInputFormat.java).

    query: SQL with driver-style placeholders; parameters: list of
    parameter tuples — one SPLIT per tuple (None = single unparameterized
    split). Offsets are (split_index -> rows_consumed); restore re-runs
    each split's query and skips consumed rows, so the fetch is
    deterministic exactly-once replay (the query must be stable, e.g.
    ORDER BY a key — same determinism contract the reference documents).
    """

    columnar = False

    def __init__(self, connection_factory: Callable, query: str,
                 parameters: Optional[Sequence[Tuple]] = None,
                 fetch_size: int = 1024):
        self.connection_factory = connection_factory
        self.query = query
        self.parameters = list(parameters) if parameters else [()]
        self.fetch_size = fetch_size
        self.offsets = {i: 0 for i in range(len(self.parameters))}
        self._conn = None
        self._cursors = None
        self._done = None

    def open(self):
        self._conn = self.connection_factory()
        self._cursors = {}
        self._done = {i: False for i in range(len(self.parameters))}

    def _cursor(self, i: int):
        cur = self._cursors.get(i)
        if cur is None:
            cur = self._conn.cursor()
            cur.execute(self.query, self.parameters[i])
            # replay skip: the offset rows were consumed before the cut
            skip = self.offsets[i]
            while skip > 0:
                got = cur.fetchmany(min(skip, self.fetch_size))
                if not got:
                    break
                skip -= len(got)
            self._cursors[i] = cur
        return cur

    def poll(self, max_records: int):
        live = [i for i, d in self._done.items() if not d]
        if not live:
            return [], True
        out: List[Any] = []
        per = max(1, max_records // len(live))
        for i in live:
            rows = self._cursor(i).fetchmany(per)
            if not rows:
                self._done[i] = True
                continue
            self.offsets[i] += len(rows)
            out.extend(tuple(r) for r in rows)
        return out, all(self._done.values())

    def snapshot_offsets(self):
        return dict(self.offsets)

    def restore_offsets(self, state):
        self.offsets = {int(k): int(v) for k, v in state.items()}
        # drop live cursors: they resume from the restored offsets
        self._cursors = {}
        if self._done is not None:
            self._done = {i: False for i in range(len(self.parameters))}

    def read_all(self) -> List[tuple]:
        """Batch convenience (the DataSet entry point)."""
        self.open()
        rows: List[tuple] = []
        end = False
        while not end:
            got, end = self.poll(self.fetch_size)
            rows.extend(got)
        self.close()
        return rows

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None
            self._cursors = None


class DbApiSink(Sink):
    """Streaming sink: batched executemany per invoke, committed per
    batch (ref JDBCOutputFormat's batchInterval flush). Use an idempotent
    statement (INSERT OR REPLACE / ON CONFLICT DO UPDATE) for
    effectively-once under checkpoint replay."""

    def __init__(self, connection_factory: Callable, statement: str,
                 row_fn: Optional[Callable[[Any], tuple]] = None):
        self.connection_factory = connection_factory
        self.statement = statement
        self.row_fn = row_fn or (lambda e: tuple(e))
        self._conn = None
        self.rows_written = 0

    def open(self):
        self._conn = self.connection_factory()

    def invoke_batch(self, elements):
        if not elements:
            return
        rows = [self.row_fn(e) for e in elements]
        cur = self._conn.cursor()
        cur.executemany(self.statement, rows)
        self._conn.commit()
        self.rows_written += len(rows)

    def close(self):
        if self._conn is not None:
            self._conn.commit()
            self._conn.close()
            self._conn = None


class DbApiOutputFormat:
    """Batch writer for DataSet results (ref JDBCOutputFormat.java):
    one transaction around the whole write."""

    def __init__(self, connection_factory: Callable, statement: str,
                 row_fn: Optional[Callable[[Any], tuple]] = None):
        self.connection_factory = connection_factory
        self.statement = statement
        self.row_fn = row_fn or (lambda e: tuple(e))

    def write(self, rows: Sequence) -> int:
        conn = self.connection_factory()
        try:
            cur = conn.cursor()
            cur.executemany(self.statement, [self.row_fn(r) for r in rows])
            conn.commit()
            return len(rows)
        except Exception:
            conn.rollback()
            raise
        finally:
            conn.close()
