"""Wire-protocol replayable source: a real external broker over TCP.

The proof-of-exactly-once seam VERDICT r2 item 6 asks for: unlike
InMemoryPartitionedSource (a test double inside the job process), the
ReplayServer is a SEPARATE OS process holding partitioned, offset-
addressable records — the Kafka-broker role. The consumer speaks a small
line protocol and plugs into PartitionedConsumerBase, inheriting the
snapshot-offsets / commit-on-checkpoint-complete contract
(ref FlinkKafkaConsumerBase.java:336 snapshotState, :384
notifyCheckpointComplete).

Protocol (text lines over one TCP connection):
    LIST                          -> "<p0> <p1> ...\\n"
    FETCH <part> <offset> <n>     -> "<count> <new_offset> <exhausted>\\n"
                                     then <count> lines "<key> <value> <ts>"
    COMMIT <cid> <part>:<off>[,...] -> "OK\\n"  (persisted to commit file)
    COMMITTED                     -> "<cid> <part>:<off>[,...]\\n"

Determinism: records are derived from a seed, so FETCH(part, offset) is
reproducible across server restarts — the replay property exactly-once
restore depends on.

Run standalone:  python -m flink_tpu.connectors.socket_replay \
                     --port 0 --partitions 3 --records 10000 --seed 7 \
                     --commit-file /tmp/commits.json
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.connectors.partitioned import PartitionedConsumerBase


def gen_partition_records(seed: int, part: int, offset: int, n: int,
                          total: int):
    """Deterministic records of one partition: (key, value, ts_ms)."""
    n = max(0, min(n, total - offset))
    if n == 0:
        return []
    idx = np.arange(offset, offset + n, dtype=np.int64)
    rng_mix = (
        idx.astype(np.uint64) * np.uint64(6364136223846793005)
        + np.uint64((seed * 1442695040888963407 + part) % (1 << 64))
    )
    keys = (rng_mix % np.uint64(97)).astype(np.int64)
    vals = ((idx % 5) + 1).astype(np.int64)
    ts = idx * 2 + part
    return list(zip(keys.tolist(), vals.tolist(), ts.tolist()))


class ReplayServer:
    """External broker process body (also embeddable for tests)."""

    def __init__(self, partitions: int, records: int, seed: int,
                 commit_file: Optional[str] = None, port: int = 0,
                 host: str = "127.0.0.1"):
        self.n_partitions = partitions
        self.total = records
        self.seed = seed
        self.commit_file = commit_file
        self._commit_lock = threading.Lock()
        self._last_commit: Tuple[int, Dict[int, int]] = (0, {})
        # a restarted broker resumes from its durable commit record — the
        # property consumers rely on to resume from the external commit
        if commit_file and os.path.exists(commit_file):
            with open(commit_file) as f:
                rec = json.load(f)
            self._last_commit = (
                rec["cid"], {int(p): o for p, o in rec["offsets"].items()}
            )
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    try:
                        out = outer._dispatch(line.decode().strip())
                    except Exception as e:  # noqa: BLE001 — protocol error
                        out = f"ERR {type(e).__name__}: {e}\n"
                    self.wfile.write(out.encode())
                    self.wfile.flush()

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Srv((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="replay-server",
        )

    def start(self):
        self._thread.start()
        return self.port

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    # -- protocol --------------------------------------------------------
    def _dispatch(self, line: str) -> str:
        parts = line.split()
        if not parts:
            return "ERR empty\n"
        cmd = parts[0].upper()
        if cmd == "LIST":
            return " ".join(str(p) for p in range(self.n_partitions)) + "\n"
        if cmd == "FETCH":
            part, offset, n = int(parts[1]), int(parts[2]), int(parts[3])
            recs = gen_partition_records(self.seed, part, offset, n,
                                         self.total)
            new_off = offset + len(recs)
            exhausted = int(new_off >= self.total)
            body = "".join(f"{k} {v} {t}\n" for k, v, t in recs)
            return f"{len(recs)} {new_off} {exhausted}\n" + body
        if cmd == "COMMIT":
            cid = int(parts[1])
            offs = {}
            for item in parts[2].split(","):
                p, o = item.split(":")
                offs[int(p)] = int(o)
            # serialized write+replace: handler threads sharing one tmp
            # path would interleave and corrupt the durable record
            with self._commit_lock:
                if self.commit_file:
                    tmp = self.commit_file + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump({"cid": cid, "offsets": offs}, f)
                    os.replace(tmp, self.commit_file)
                self._last_commit = (cid, offs)
            return "OK\n"
        if cmd == "COMMITTED":
            cid, offs = self._last_commit
            body = ",".join(f"{p}:{o}" for p, o in sorted(offs.items()))
            return f"{cid} {body}\n"
        return "ERR unknown command\n"


class SocketReplayConsumer(PartitionedConsumerBase):
    """Wire client for ReplayServer, with reconnect-on-failure (a broker
    restart mid-job must not fail the source — fetches are deterministic,
    so a reconnected FETCH resumes exactly)."""

    def __init__(self, host: str, port: int, connect_timeout_s: float = 10.0,
                 retry_s: float = 20.0):
        super().__init__()
        self.host, self.port = host, port
        self.connect_timeout_s = connect_timeout_s
        self.retry_s = retry_s
        self._sock: Optional[socket.socket] = None
        self._rf = None

    # -- wire ------------------------------------------------------------
    def _connect(self):
        self._close()
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        self._rf = self._sock.makefile("rb")

    def _close(self):
        for x in (self._rf, self._sock):
            try:
                if x is not None:
                    x.close()
            except OSError:
                pass
        self._sock = self._rf = None

    def _request(self, line: str) -> str:
        """Send one command, return the header line; retries with
        reconnect until retry_s elapses (broker restart tolerance)."""
        deadline = time.monotonic() + self.retry_s
        last: Exception = RuntimeError("no attempt")
        while time.monotonic() < deadline:
            try:
                if self._sock is None:
                    self._connect()
                self._sock.sendall(line.encode())
                hdr = self._rf.readline()
                if not hdr:
                    raise ConnectionError("server closed connection")
                hdr = hdr.decode().strip()
                if hdr.startswith("ERR"):
                    raise RuntimeError(f"server error: {hdr}")
                return hdr
            except (OSError, ConnectionError) as e:
                last = e
                self._close()
                time.sleep(0.2)
        raise ConnectionError(
            f"replay server unreachable after {self.retry_s}s: {last}"
        )

    def _read_lines(self, n: int) -> List[str]:
        out = []
        for _ in range(n):
            ln = self._rf.readline()
            if not ln:
                raise ConnectionError("short read")
            out.append(ln.decode().strip())
        return out

    # -- PartitionedConsumerBase contract --------------------------------
    def discover_partitions(self):
        hdr = self._request("LIST\n")
        return [int(p) for p in hdr.split()]

    def fetch(self, partition, offset: int, max_records: int
              ) -> Tuple[List[Tuple[int, int, int]], int, bool]:
        last: Exception = ConnectionError("no attempt")
        for _ in range(2):
            # _request already reconnect-loops for retry_s; only the BODY
            # read below gets the local one-retry (a connection dying
            # mid-body re-issues the deterministic fetch once)
            hdr = self._request(f"FETCH {partition} {offset} {max_records}\n")
            count, new_off, exhausted = (int(x) for x in hdr.split())
            try:
                recs = []
                for ln in self._read_lines(count):
                    k, v, t = ln.split()
                    recs.append((int(k), int(v), int(t)))
                return recs, new_off, bool(exhausted)
            except (OSError, ConnectionError) as e:
                last = e
                self._close()     # body read failed mid-stream: one retry
        raise ConnectionError("fetch body failed after reconnect") from last

    def commit_offsets(self, offsets: Dict[int, int], checkpoint_id: int):
        body = ",".join(f"{p}:{o}" for p, o in sorted(offsets.items()))
        self._request(f"COMMIT {checkpoint_id} {body}\n")
        self.committed = dict(offsets)

    def committed_on_server(self) -> Tuple[int, Dict[int, int]]:
        hdr = self._request("COMMITTED\n")
        cid, _, body = hdr.partition(" ")
        offs = {}
        for item in body.split(","):
            if item:
                p, o = item.split(":")
                offs[int(p)] = int(o)
        return int(cid), offs

    def close(self):
        self._close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--partitions", type=int, default=3)
    ap.add_argument("--records", type=int, default=10_000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--commit-file", default=None)
    args = ap.parse_args()
    srv = ReplayServer(args.partitions, args.records, args.seed,
                       args.commit_file, port=args.port)
    port = srv.start()
    print(f"READY {port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
