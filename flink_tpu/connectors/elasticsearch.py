"""Elasticsearch connector — the flink-connector-elasticsearch2 analog
(SURVEY §2.8, ref flink-streaming-connectors/flink-connector-
elasticsearch2/ ElasticsearchSink.java + BulkProcessorIndexer; the
reference wraps the ES TransportClient's BulkProcessor).

This is a WIRE client: it speaks the public Elasticsearch REST protocol
over plain HTTP — `POST /_bulk` with NDJSON action/source line pairs,
per-item result statuses in the bulk response, `GET /` version ping —
implemented from the public API docs, not from any client library.

No Elasticsearch server exists in this image (zero egress), so tests run
the sink against ``MiniElasticsearch`` below — an in-repo HTTP server
implementing the same public spec (bulk indexing, doc get, search with
match_all/term, injectable 429 throttling). That proves the byte-level
seam; against a genuine cluster only the host:port changes.

Semantics (the reference's):
  * buffered bulk flushing — ``bulk.flush.max.actions`` and explicit
    flush, the BulkProcessor knobs;
  * FLUSH-ON-CHECKPOINT: ``snapshot_state`` drains the buffer, so a
    checkpoint never covers unsent actions (ElasticsearchSinkBase's
    flushOnCheckpoint=true is the at-least-once story);
  * retry on 429/503 with bounded backoff (BulkProcessor's backoff
    policy); other per-item failures go to the failure handler seam
    (ref ActionRequestFailureHandler) which defaults to raising;
  * exactly-once via DETERMINISTIC DOCUMENT IDS: replayed actions
    overwrite the same `_id` instead of duplicating — the reference's
    documented recipe for idempotent writes.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from flink_tpu.runtime.sinks import Sink


class BulkTransportError(ConnectionError):
    """A bulk could not be (fully) delivered; ``unsent`` carries exactly
    the actions that were NOT acknowledged, so the sink re-buffers only
    those — re-buffering already-indexed actions would duplicate auto-id
    documents and double-invoke the failure handler."""

    def __init__(self, message: str, unsent: List[dict]):
        super().__init__(message)
        self.unsent = unsent


class BulkItemError(RuntimeError):
    """A permanent per-item failure with no failure handler configured.
    ``unsent`` carries the TRANSIENT (429) items of the same response so
    the sink re-buffers them — a poison item must not drop its throttled
    batch-mates."""

    def __init__(self, message: str, unsent: List[dict]):
        super().__init__(message)
        self.unsent = unsent


class ElasticsearchSink(Sink):
    """ref ElasticsearchSink: elements -> index actions -> buffered
    `_bulk` requests.

    ``emitter(element) -> action dict or list of action dicts``; an
    action is ``{"index": <index>, "id": <id or None>, "source": doc}``
    (the IndexRequest shape). Deterministic ids give idempotent replay.
    """

    def __init__(self, host: str, port: int,
                 emitter: Callable[[Any], Any],
                 flush_max_actions: int = 500,
                 max_retries: int = 5,
                 failure_handler: Optional[Callable] = None,
                 timeout_s: float = 10.0):
        self.host = host
        self.port = port
        self.emitter = emitter
        self.flush_max_actions = flush_max_actions
        self.max_retries = max_retries
        self.failure_handler = failure_handler
        self.timeout_s = timeout_s
        self._buf: List[dict] = []
        self._conn: Optional[http.client.HTTPConnection] = None
        self.stats = {"bulk_requests": 0, "actions": 0, "retries": 0}

    # -- Sink contract ---------------------------------------------------
    def open(self):
        info = self._request("GET", "/")
        if "version" not in info:
            raise ConnectionError(
                f"not an Elasticsearch endpoint: {info!r}"
            )

    def invoke_batch(self, elements: List[Any]):
        for e in elements:
            actions = self.emitter(e)
            if isinstance(actions, dict):
                actions = [actions]
            self._buf.extend(actions)
            # threshold INSIDE the loop (BulkProcessor behavior): one
            # oversized element batch must not become one oversized bulk
            # body (real ES rejects those with 413)
            if len(self._buf) >= self.flush_max_actions:
                self.flush()

    def close(self):
        self.flush()
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def snapshot_state(self):
        # flush-on-checkpoint: the cut must not cover unsent actions
        self.flush()
        return None

    # -- bulk protocol ---------------------------------------------------
    def flush(self):
        if not self._buf:
            return
        actions, self._buf = self._buf, []
        try:
            self._send_rounds(actions)
        except (BulkTransportError, BulkItemError) as e:
            # put ONLY the unacknowledged actions back so a caller-level
            # retry (or the checkpoint-restart replay) still covers them
            # — at-least-once, never silent loss, never a duplicate of
            # an already-indexed auto-id document
            self._buf = list(e.unsent) + self._buf
            raise

    def _send_rounds(self, current: List[dict]):
        """Deliver `current` with bounded backoff; raises
        BulkTransportError carrying the UNSENT subset on transport
        failures, RuntimeError (no re-buffer: poison item, the
        checkpoint replay covers it) when the default handler rejects a
        permanent per-item failure."""
        delay = 0.05
        for attempt in range(self.max_retries + 1):
            try:
                status, resp = self._request_raw(
                    "POST", "/_bulk", self._bulk_body(current),
                    "application/x-ndjson",
                )
            except (OSError, http.client.HTTPException) as e:
                raise BulkTransportError(str(e), current) from e
            if status in (429, 503):
                # the whole bulk was throttled: back off and resend
                # (BulkProcessor's backoff policy)
                self.stats["retries"] += 1
                if attempt == self.max_retries:
                    raise BulkTransportError(
                        f"bulk rejected with {status} after "
                        f"{self.max_retries} retries", current,
                    )
                time.sleep(delay)
                delay = min(delay * 2, 2.0)
                continue
            if status != 200:
                raise BulkTransportError(
                    f"bulk failed: HTTP {status}", current
                )
            resp = json.loads(resp)
            self.stats["bulk_requests"] += 1
            if resp.get("errors") and \
                    len(resp.get("items", [])) != len(current):
                # a malformed/truncated response must not silently drop
                # the unmatched tail from delivery accounting: treat the
                # whole round as undelivered (at-least-once re-buffer)
                raise BulkTransportError(
                    f"bulk response item count "
                    f"{len(resp.get('items', []))} != {len(current)} "
                    f"actions sent", current,
                )
            if not resp.get("errors"):
                self.stats["actions"] += len(current)
                return
            # per-item results: 429s are TRANSIENT (a loaded cluster
            # throttles individual items inside an HTTP 200 bulk
            # response) — resend just those with backoff; other
            # failures go to the handler seam. The whole item list is
            # processed BEFORE any raise so a poison item can't drop its
            # throttled batch-mates.
            retry, permanent = [], []
            for item, action in zip(resp["items"], current):
                st = item.get("index", {}).get("status", 200)
                if st == 429:
                    retry.append(action)
                elif st >= 300:
                    if self.failure_handler is not None:
                        self.failure_handler(action, st, item)
                    else:
                        permanent.append((st, item))
                else:
                    self.stats["actions"] += 1   # delivered exactly here
            if permanent:
                st, item = permanent[0]
                raise BulkItemError(
                    f"index action failed with status {st}: {item} "
                    f"({len(permanent)} permanent failure(s))", retry,
                )
            if not retry:
                return
            self.stats["retries"] += 1
            if attempt == self.max_retries:
                raise BulkTransportError(
                    f"{len(retry)} bulk item(s) still throttled (429) "
                    f"after {self.max_retries} retries", retry,
                )
            current = retry
            time.sleep(delay)
            delay = min(delay * 2, 2.0)

    @staticmethod
    def _bulk_body(actions: List[dict]) -> bytes:
        lines = []
        for a in actions:
            meta: Dict[str, Any] = {"_index": a["index"]}
            if a.get("id") is not None:
                meta["_id"] = str(a["id"])
            lines.append(json.dumps({"index": meta}))
            lines.append(json.dumps(a["source"]))
        return ("\n".join(lines) + "\n").encode()

    # -- HTTP plumbing ---------------------------------------------------
    def _request(self, method: str, path: str, body: bytes = b"",
                 ctype: str = "application/json") -> dict:
        status, data = self._request_raw(method, path, body, ctype)
        if status >= 300:
            raise ConnectionError(f"{method} {path} -> HTTP {status}")
        return json.loads(data)

    def _request_raw(self, method, path, body=b"", ctype=""):
        """One persistent keep-alive connection (a bulk per request must
        not pay a TCP handshake RTT). A SEND-phase failure on a reused
        connection is the stale keep-alive race — retried once on a
        fresh socket. A RECEIVE-phase failure is NEVER blindly resent:
        the server may already have processed the request, and a resend
        would duplicate auto-id documents; the error propagates so the
        sink's unsent-tracking (at-least-once) decides."""
        headers = {"Content-Type": ctype} if ctype else {}
        for fresh in (False, True):
            reused = self._conn is not None and not fresh
            if self._conn is None or fresh:
                if self._conn is not None:
                    self._conn.close()
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s
                )
            try:
                self._conn.request(method, path, body, headers)
            except (http.client.HTTPException, OSError):
                self._conn.close()
                self._conn = None
                if reused:
                    continue        # stale keep-alive: one fresh retry
                raise
            try:
                r = self._conn.getresponse()
                return r.status, r.read()
            except (http.client.HTTPException, OSError):
                self._conn.close()
                self._conn = None
                raise
        raise AssertionError("unreachable")


# ---------------------------------------------------------------- test peer
class MiniElasticsearch:
    """In-repo HTTP server implementing the public Elasticsearch REST
    subset the sink speaks (the MiniKafkaBroker pattern: a spec
    implementation on a real socket, so the connector's bytes are tested
    end to end).

    Supported: GET / (version ping), POST /_bulk (NDJSON index actions),
    GET /<index>/_doc/<id>, GET|POST /<index>/_search with match_all or
    one-field term query, GET /<index>/_count. ``throttle(n)`` makes the
    next n bulk requests return 429 (retry-path testing);
    ``fail_ids(ids)`` rejects those document ids with per-item 400s
    (failure-handler testing)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.indices: Dict[str, Dict[str, dict]] = {}
        self.bulk_requests = 0
        self._throttle = 0
        self._fail_ids: set = set()
        self._item_throttle: Dict[str, int] = {}   # id -> remaining 429s
        self._lock = threading.Lock()
        mini = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, payload: dict):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                parts = [p for p in self.path.split("?")[0].split("/")
                         if p]
                if not parts:
                    return self._send(200, {
                        "name": "mini-es", "cluster_name": "flink-tpu",
                        "version": {"number": "2.3.0"},
                    })
                with mini._lock:
                    if len(parts) == 3 and parts[1] == "_doc":
                        doc = mini.indices.get(parts[0], {}).get(parts[2])
                        if doc is None:
                            return self._send(404, {"found": False})
                        return self._send(200, {
                            "_index": parts[0], "_id": parts[2],
                            "found": True, "_source": doc,
                        })
                    if len(parts) == 2 and parts[1] == "_count":
                        return self._send(200, {
                            "count": len(mini.indices.get(parts[0], {}))
                        })
                    if len(parts) == 2 and parts[1] == "_search":
                        return self._search(parts[0], {})
                return self._send(404, {"error": "unknown route"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                path = self.path.split("?")[0]
                if path == "/_bulk":
                    return self._bulk(body)
                parts = [p for p in path.split("/") if p]
                if len(parts) == 2 and parts[1] == "_search":
                    query = json.loads(body) if body else {}
                    with mini._lock:
                        return self._search(parts[0], query)
                return self._send(404, {"error": "unknown route"})

            def _bulk(self, body: bytes):
                with mini._lock:
                    mini.bulk_requests += 1
                    if mini._throttle > 0:
                        mini._throttle -= 1
                        return self._send(429, {
                            "error": "es_rejected_execution_exception"
                        })
                    lines = [ln for ln in body.decode().splitlines()
                             if ln.strip()]
                    items, errors = [], False
                    i = 0
                    while i < len(lines):
                        meta = json.loads(lines[i])
                        action = next(iter(meta))
                        m = meta[action]
                        src = json.loads(lines[i + 1])
                        i += 2
                        idx = m["_index"]
                        did = str(m.get("_id", len(
                            mini.indices.get(idx, {})
                        )))
                        if mini._item_throttle.get(did, 0) > 0:
                            # per-ITEM throttling: HTTP 200 bulk response
                            # carrying item-level 429s (a loaded real
                            # cluster's shape)
                            mini._item_throttle[did] -= 1
                            errors = True
                            items.append({"index": {
                                "_index": idx, "_id": did, "status": 429,
                                "error":
                                    "es_rejected_execution_exception",
                            }})
                            continue
                        if did in mini._fail_ids:
                            errors = True
                            items.append({"index": {
                                "_index": idx, "_id": did, "status": 400,
                                "error": "mapper_parsing_exception",
                            }})
                            continue
                        created = did not in mini.indices.setdefault(
                            idx, {})
                        mini.indices[idx][did] = src
                        items.append({"index": {
                            "_index": idx, "_id": did,
                            "status": 201 if created else 200,
                        }})
                    return self._send(200, {
                        "took": 1, "errors": errors, "items": items,
                    })

            def _search(self, index: str, query: dict):
                docs = mini.indices.get(index, {})
                q = query.get("query", {"match_all": {}})
                if "term" in q:
                    field, want = next(iter(q["term"].items()))
                    if isinstance(want, dict):
                        want = want["value"]
                    hits = [
                        {"_index": index, "_id": did, "_source": d}
                        for did, d in docs.items()
                        if d.get(field) == want
                    ]
                else:
                    hits = [
                        {"_index": index, "_id": did, "_source": d}
                        for did, d in docs.items()
                    ]
                return self._send(200, {"hits": {
                    "total": len(hits), "hits": hits,
                }})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mini-elasticsearch",
        )

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    def throttle(self, n: int):
        with self._lock:
            self._throttle = n

    def fail_ids(self, ids):
        with self._lock:
            self._fail_ids = {str(i) for i in ids}

    def throttle_ids(self, ids, times: int = 1):
        """The next ``times`` index attempts for each id return a
        per-item 429 inside an HTTP 200 bulk response (REPLACES the
        current throttle set; an empty list clears it)."""
        with self._lock:
            self._item_throttle = {str(i): times for i in ids}

    def doc_count(self, index: str) -> int:
        with self._lock:
            return len(self.indices.get(index, {}))
