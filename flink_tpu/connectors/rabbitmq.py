"""RabbitMQ connector — the flink-connector-rabbitmq analog
(SURVEY §2.8, ref flink-streaming-connectors/flink-connector-rabbitmq/
RMQSource.java + RMQSink.java; the reference wraps the com.rabbitmq
Java client).

This is a WIRE client: it speaks AMQP 0-9-1, the public Advanced
Message Queuing Protocol (the ``AMQP\\x00\\x00\\x09\\x01`` protocol
header; ``type(1) channel(2) size(4) payload CE`` frame grammar; the
connection.start/start-ok(PLAIN)/tune/tune-ok/open, channel.open,
queue.declare, basic.publish/consume/deliver/ack method exchanges;
content header + body frames with the correlation-id property),
implemented from the protocol spec — no client library.

No RabbitMQ broker exists in this image (zero egress), so tests run the
client against ``MiniRabbit`` below — an in-repo broker implementing
the same public framing on a real TCP socket with durable-enough
queues, unacked tracking, and requeue-on-disconnect. Against a genuine
broker only host:port changes.

Semantics (the reference's):
  * ``RMQSink``: ``basic.publish`` per element to a declared queue via
    the default exchange, optionally stamping a correlation id
    (RMQSink.java invoke; at-least-once on replay — exactly-once is the
    CONSUMER's dedup job, which is why the id is stamped here);
  * ``RMQSource``: manual-ack consumption where
      - delivery tags of emitted records ride EVERY checkpoint and are
        ``basic.ack``'d only when that checkpoint completes
        (MessageAcknowledgingSourceBase.snapshotState /
        notifyCheckpointComplete — the ack never runs ahead of a
        restorable state),
      - with ``uses_correlation_id=True`` the restored id-set dedupes
        the broker's redelivery of messages that were processed but
        unacked at the crash: exactly-once
        (MultipleIdsMessageAcknowledgingSourceBase + RMQSource.java:48),
      - without correlation ids, redelivery is at-least-once — the
        reference documents the same contract.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.runtime.sinks import Sink
from flink_tpu.runtime.sources import Source

PROTO_HEADER = b"AMQP\x00\x00\x09\x01"

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8
FRAME_END = 0xCE

# class / method ids (amqp0-9-1.xml)
CONNECTION = 10
C_START, C_START_OK, C_TUNE, C_TUNE_OK = 10, 11, 30, 31
C_OPEN, C_OPEN_OK, C_CLOSE, C_CLOSE_OK = 40, 41, 50, 51
CHANNEL = 20
CH_OPEN, CH_OPEN_OK, CH_CLOSE, CH_CLOSE_OK = 10, 11, 40, 41
QUEUE = 50
Q_DECLARE, Q_DECLARE_OK = 10, 11
BASIC = 60
B_QOS, B_QOS_OK = 10, 11
B_CONSUME, B_CONSUME_OK = 20, 21
B_PUBLISH = 40
B_DELIVER = 60
B_ACK = 80

# basic content property flag word (amqp0-9-1 basic class fields, MSB
# first): bit 15 content-type, 14 content-encoding, 13 headers,
# 12 delivery-mode, 11 priority, 10 correlation-id, 9 reply-to,
# 8 expiration, 7 message-id, 6 timestamp, 5 type, 4 user-id, 3 app-id,
# 2 cluster-id
PROP_CORRELATION_ID = 1 << 10
# (bit, decoder kind) in serialization order — properties are laid out
# in DESCENDING flag-bit order, so parsing must walk all of them to
# find any one (a real producer sets delivery-mode etc. routinely)
_BASIC_PROPS = [
    (1 << 15, "shortstr"),   # content-type
    (1 << 14, "shortstr"),   # content-encoding
    (1 << 13, "table"),      # headers
    (1 << 12, "octet"),      # delivery-mode
    (1 << 11, "octet"),      # priority
    (1 << 10, "shortstr"),   # correlation-id
    (1 << 9, "shortstr"),    # reply-to
    (1 << 8, "shortstr"),    # expiration
    (1 << 7, "shortstr"),    # message-id
    (1 << 6, "longlong"),    # timestamp
    (1 << 5, "shortstr"),    # type
    (1 << 4, "shortstr"),    # user-id
    (1 << 3, "shortstr"),    # app-id
    (1 << 2, "shortstr"),    # cluster-id
]


def parse_basic_properties(payload: bytes) -> Tuple[int, Optional[str]]:
    """Parse a basic content-header frame payload; returns
    (body_size, correlation_id). Walks the full property list in flag
    order so a correlation id is found regardless of which other
    properties the producer set."""
    _cls, _weight, size, flags = struct.unpack_from(">HHQH", payload, 0)
    off = 14
    correlation_id = None
    for bit, kind in _BASIC_PROPS:
        if not flags & bit:
            continue
        if kind == "shortstr":
            val, off = read_shortstr(payload, off)
            if bit == PROP_CORRELATION_ID:
                correlation_id = val
        elif kind == "octet":
            off += 1
        elif kind == "longlong":
            off += 8
        elif kind == "table":
            _t, off = decode_table(payload, off)
    return size, correlation_id


class AMQPError(RuntimeError):
    pass


# --------------------------------------------------------------------------
# wire primitives
# --------------------------------------------------------------------------
def shortstr(s: str) -> bytes:
    b = s.encode()
    if len(b) > 255:
        raise AMQPError("shortstr too long")
    return bytes([len(b)]) + b


def longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def read_shortstr(buf: bytes, off: int) -> Tuple[str, int]:
    n = buf[off]
    return buf[off + 1:off + 1 + n].decode(), off + 1 + n


def read_longstr(buf: bytes, off: int) -> Tuple[bytes, int]:
    (n,) = struct.unpack_from(">I", buf, off)
    return buf[off + 4:off + 4 + n], off + 4 + n


def encode_table(t: Dict[str, Any]) -> bytes:
    """Field table, the value kinds this connector needs: longstr (S),
    bool (t), long-int (I), nested table (F)."""
    out = b""
    for k, v in t.items():
        out += shortstr(k)
        if isinstance(v, bool):
            out += b"t" + bytes([int(v)])
        elif isinstance(v, int):
            out += b"I" + struct.pack(">i", v)
        elif isinstance(v, dict):
            inner = encode_table(v)
            out += b"F" + inner
        else:
            out += b"S" + longstr(str(v).encode())
    return longstr(out)


def decode_table(buf: bytes, off: int) -> Tuple[Dict[str, Any], int]:
    data, off = read_longstr(buf, off)
    t: Dict[str, Any] = {}
    i = 0
    while i < len(data):
        k, i = read_shortstr(data, i)
        kind = data[i:i + 1]
        i += 1
        if kind == b"t":
            t[k] = bool(data[i])
            i += 1
        elif kind == b"I":
            (t[k],) = struct.unpack_from(">i", data, i)
            i += 4
        elif kind == b"S":
            v, i = read_longstr(data, i)
            t[k] = v.decode(errors="replace")
        elif kind == b"F":
            t[k], i = decode_table(data, i)
        else:
            raise AMQPError(f"field table kind {kind!r} unsupported")
    return t, off


def frame(ftype: int, channel: int, payload: bytes) -> bytes:
    return (struct.pack(">BHI", ftype, channel, len(payload))
            + payload + bytes([FRAME_END]))


def method(channel: int, class_id: int, method_id: int,
           args: bytes = b"") -> bytes:
    return frame(FRAME_METHOD, channel,
                 struct.pack(">HH", class_id, method_id) + args)


def content_header(channel: int, body_len: int,
                   correlation_id: Optional[str]) -> bytes:
    flags = 0
    props = b""
    if correlation_id is not None:
        flags |= PROP_CORRELATION_ID
        props += shortstr(correlation_id)
    payload = struct.pack(">HHQH", BASIC, 0, body_len, flags) + props
    return frame(FRAME_HEADER, channel, payload)


class _FrameReader:
    """Incremental frame splitter over raw bytes."""

    def __init__(self):
        self.buf = b""

    def feed(self, data: bytes):
        self.buf += data

    def frames(self):
        while len(self.buf) >= 7:
            ftype, channel, size = struct.unpack_from(">BHI", self.buf, 0)
            total = 7 + size + 1
            if len(self.buf) < total:
                return
            payload = self.buf[7:7 + size]
            if self.buf[total - 1] != FRAME_END:
                raise AMQPError("missing frame-end octet")
            self.buf = self.buf[total:]
            yield ftype, channel, payload


# --------------------------------------------------------------------------
# client connection
# --------------------------------------------------------------------------
class AMQPConnection:
    """One AMQP 0-9-1 connection with one channel — the
    com.rabbitmq.client.Connection+Channel pair RMQSource/Sink hold
    (RMQConnectionConfig.java carries host/port/vhost/credentials)."""

    CHANNEL_ID = 1

    def __init__(self, host: str, port: int, user: str = "guest",
                 password: str = "guest", vhost: str = "/",
                 timeout_s: float = 10.0):
        self.timeout_s = timeout_s
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout_s)
        self._reader = _FrameReader()
        self._deliveries: List[dict] = []
        self._pending_deliver: Optional[dict] = None
        self._methods: List[Tuple[int, int, bytes]] = []
        self._wlock = threading.Lock()
        self._consumer_seq = 0
        # handshake: header -> start/start-ok -> tune/tune-ok -> open
        self.sock.sendall(PROTO_HEADER)
        cls, mid, args = self._wait_method()
        if (cls, mid) != (CONNECTION, C_START):
            raise AMQPError(f"expected connection.start, got {cls}.{mid}")
        response = b"\x00" + user.encode() + b"\x00" + password.encode()
        self._send(method(
            0, CONNECTION, C_START_OK,
            encode_table({"product": "flink-tpu"})
            + shortstr("PLAIN") + longstr(response) + shortstr("en_US"),
        ))
        cls, mid, args = self._wait_method()
        if (cls, mid) != (CONNECTION, C_TUNE):
            raise AMQPError(f"expected connection.tune, got {cls}.{mid}")
        ch_max, frame_max, hb = struct.unpack_from(">HIH", args, 0)
        self.frame_max = frame_max or (1 << 17)
        self._send(method(
            0, CONNECTION, C_TUNE_OK,
            struct.pack(">HIH", ch_max, self.frame_max, 0),
        ))
        self._send(method(
            0, CONNECTION, C_OPEN, shortstr(vhost) + shortstr("") + b"\x00"
        ))
        cls, mid, _ = self._wait_method()
        if (cls, mid) != (CONNECTION, C_OPEN_OK):
            raise AMQPError("connection.open refused")
        self._send(method(self.CHANNEL_ID, CHANNEL, CH_OPEN, shortstr("")))
        cls, mid, _ = self._wait_method()
        if (cls, mid) != (CHANNEL, CH_OPEN_OK):
            raise AMQPError("channel.open refused")

    # -- plumbing --------------------------------------------------------
    def _send(self, data: bytes):
        with self._wlock:
            self.sock.sendall(data)

    def _pump(self, blocking: bool) -> bool:
        """Read available bytes, dispatch frames. Returns True if any
        frame arrived. Blocking reads use a SHORT TIMEOUT SLICE, never
        setblocking(True) — an unbounded recv would make the caller's
        deadline checks dead code against a stalled broker."""
        if blocking:
            self.sock.settimeout(0.5)
        else:
            self.sock.setblocking(False)
        got = False
        try:
            data = self.sock.recv(1 << 16)
            if not data:
                raise AMQPError("connection closed by broker")
            self._reader.feed(data)
            got = True
        except (BlockingIOError, socket.timeout):
            pass
        finally:
            self.sock.settimeout(self.timeout_s)
        for ftype, channel, payload in self._reader.frames():
            self._dispatch(ftype, payload)
        return got

    def _dispatch(self, ftype: int, payload: bytes):
        if ftype == FRAME_METHOD:
            cls, mid = struct.unpack_from(">HH", payload, 0)
            if (cls, mid) == (BASIC, B_DELIVER):
                off = 4
                _tag, off = read_shortstr(payload, off)
                (dtag,) = struct.unpack_from(">Q", payload, off)
                off += 8
                redelivered = bool(payload[off])
                off += 1
                _ex, off = read_shortstr(payload, off)
                rk, off = read_shortstr(payload, off)
                self._pending_deliver = {
                    "delivery_tag": dtag, "redelivered": redelivered,
                    "routing_key": rk, "correlation_id": None,
                    "body": b"", "size": None,
                }
            elif (cls, mid) == (CONNECTION, C_CLOSE):
                code = struct.unpack_from(">H", payload, 4)[0]
                text, _ = read_shortstr(payload, 6)
                raise AMQPError(f"connection.close {code}: {text}")
            else:
                self._methods.append((cls, mid, payload[4:]))
        elif ftype == FRAME_HEADER and self._pending_deliver is not None:
            size, cid = parse_basic_properties(payload)
            d = self._pending_deliver
            d["correlation_id"] = cid
            d["size"] = size
            if size == 0:     # zero-length body: no body frame follows
                self._deliveries.append(d)
                self._pending_deliver = None
        elif ftype == FRAME_BODY and self._pending_deliver is not None:
            d = self._pending_deliver
            d["body"] += payload
            # a body larger than frame_max arrives as several frames;
            # the delivery completes at the header-declared size
            if len(d["body"]) >= d["size"]:
                self._deliveries.append(d)
                self._pending_deliver = None

    def _wait_method(self, timeout_s: float = 10.0
                     ) -> Tuple[int, int, bytes]:
        deadline = time.time() + timeout_s
        while not self._methods:
            if time.time() > deadline:
                raise AMQPError("timed out waiting for broker method")
            self._pump(blocking=True)
        return self._methods.pop(0)

    # -- operations ------------------------------------------------------
    def queue_declare(self, queue: str):
        self._send(method(
            self.CHANNEL_ID, QUEUE, Q_DECLARE,
            struct.pack(">H", 0) + shortstr(queue) + b"\x00"
            + encode_table({}),
        ))
        cls, mid, _ = self._wait_method()
        if (cls, mid) != (QUEUE, Q_DECLARE_OK):
            raise AMQPError("queue.declare refused")

    def basic_publish(self, queue: str, body: bytes,
                      correlation_id: Optional[str] = None):
        """Default-exchange publish: routing key == queue name. Bodies
        are split at the negotiated frame_max (minus the 8 octets of
        frame overhead) — a single oversized body frame is a framing
        error on a real broker."""
        chunk = self.frame_max - 8
        self._send(
            method(self.CHANNEL_ID, BASIC, B_PUBLISH,
                   struct.pack(">H", 0) + shortstr("") + shortstr(queue)
                   + b"\x00")
            + content_header(self.CHANNEL_ID, len(body), correlation_id)
            + b"".join(
                frame(FRAME_BODY, self.CHANNEL_ID, body[i:i + chunk])
                for i in range(0, len(body), chunk)
            )
        )

    def basic_consume(self, queue: str):
        self._consumer_seq += 1
        tag = f"ct-{self._consumer_seq}"
        self._send(method(
            self.CHANNEL_ID, BASIC, B_CONSUME,
            struct.pack(">H", 0) + shortstr(queue) + shortstr(tag)
            + b"\x00" + encode_table({}),
        ))
        cls, mid, _ = self._wait_method()
        if (cls, mid) != (BASIC, B_CONSUME_OK):
            raise AMQPError("basic.consume refused")
        return tag

    def basic_ack(self, delivery_tag: int, multiple: bool = False):
        self._send(method(
            self.CHANNEL_ID, BASIC, B_ACK,
            struct.pack(">QB", delivery_tag, int(multiple)),
        ))

    def drain_deliveries(self) -> List[dict]:
        self._pump(blocking=False)
        out, self._deliveries = self._deliveries, []
        return out

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# --------------------------------------------------------------------------
# sink
# --------------------------------------------------------------------------
class RMQSink(Sink):
    """Per-element publish (RMQSink.java invoke). ``correlation_id_from``
    stamps the id the consuming side's exactly-once dedup keys on
    (RMQSource.java:106 — ids must be unique at the PRODUCER)."""

    def __init__(self, host: str, port: int, queue: str,
                 serializer: Callable[[Any], bytes] = lambda e:
                 str(e).encode(),
                 correlation_id_from: Optional[Callable[[Any], str]] = None):
        self.host, self.port, self.queue = host, port, queue
        self.serializer = serializer
        self.correlation_id_from = correlation_id_from
        self._conn: Optional[AMQPConnection] = None

    def open(self, ctx=None):
        self._conn = AMQPConnection(self.host, self.port)
        self._conn.queue_declare(self.queue)

    def invoke_batch(self, elements):
        if self._conn is None:
            self.open()
        for e in elements:
            cid = (self.correlation_id_from(e)
                   if self.correlation_id_from else None)
            self._conn.basic_publish(self.queue, self.serializer(e), cid)

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None


# --------------------------------------------------------------------------
# source
# --------------------------------------------------------------------------
class RMQSource(Source):
    """Manual-ack consumer with checkpoint-gated acks and optional
    correlation-id exactly-once (RMQSource.java on
    MultipleIdsMessageAcknowledgingSourceBase)."""

    def __init__(self, host: str, port: int, queue: str,
                 deserializer: Callable[[bytes], Any] = lambda b:
                 b.decode(),
                 uses_correlation_id: bool = False,
                 idle_eof_polls: int = 0):
        self.host, self.port, self.queue = host, port, queue
        self.deserializer = deserializer
        self.uses_correlation_id = uses_correlation_id
        # finite-job support for tests/batch: report exhausted after N
        # consecutive empty polls (0 = stream forever, the reference's
        # behavior)
        self.idle_eof_polls = idle_eof_polls
        self._conn: Optional[AMQPConnection] = None
        # (delivery_tag, correlation_id) emitted but not yet ack'd;
        # ordered by tag (channel delivery order)
        self._unacked: List[Tuple[int, Optional[str]]] = []
        # ids restored from the snapshot: processed pre-crash, unacked —
        # their redelivery must be swallowed (and then acked)
        self._restored_ids: set = set()
        self._idle = 0

    def open(self):
        self._conn = AMQPConnection(self.host, self.port)
        self._conn.queue_declare(self.queue)
        self._conn.basic_consume(self.queue)

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def poll(self, max_records: int):
        out: List[Any] = []
        for d in self._conn.drain_deliveries():
            tag, cid = d["delivery_tag"], d["correlation_id"]
            if self.uses_correlation_id and cid is None:
                raise AMQPError(
                    "uses_correlation_id=True but a delivery carries no "
                    "correlation id (RMQSource.java:106 contract)"
                )
            # processed-but-unacked before the crash: swallow the
            # redelivery, but still ack it at the next checkpoint
            if (
                self.uses_correlation_id
                and cid in self._restored_ids
            ):
                self._restored_ids.discard(cid)
                self._unacked.append((tag, cid))
                continue
            self._unacked.append((tag, cid))
            out.append(self.deserializer(d["body"]))
        if out:
            self._idle = 0
        elif self.idle_eof_polls:
            self._idle += 1
            if self._idle >= self.idle_eof_polls:
                return out, True
            time.sleep(0.02)
        return out, False

    # -- exactly-once hooks ---------------------------------------------
    def snapshot_offsets(self):
        return {"unacked": list(self._unacked)}

    def restore_offsets(self, state):
        self._restored_ids = {
            cid for _tag, cid in (state or {}).get("unacked", [])
            if cid is not None
        }
        self._unacked = []

    def notify_checkpoint_complete(self, checkpoint_id: int, offsets=None):
        """Ack everything the now-durable checkpoint contains — a
        multiple-ack at the highest tag covers all earlier tags on this
        channel, which are exactly the earlier checkpoints' (already
        acked) plus this one's (MessageAcknowledgingSourceBase
        .notifyCheckpointComplete)."""
        tags = [t for t, _ in (offsets or {}).get("unacked", [])]
        if not tags or self._conn is None:
            return
        top = max(tags)
        self._conn.basic_ack(top, multiple=True)
        self._unacked = [(t, c) for t, c in self._unacked if t > top]


# --------------------------------------------------------------------------
# In-repo spec broker
# --------------------------------------------------------------------------
class _BrokerConn:
    """Server side of one client connection (one channel)."""

    def __init__(self, broker: "MiniRabbit", sock: socket.socket):
        self.broker = broker
        self.sock = sock
        self.reader = _FrameReader()
        self.wlock = threading.Lock()
        self.delivery_seq = 0
        self.unacked: Dict[int, Tuple[str, tuple]] = {}   # tag -> (q, msg)
        self.consuming: List[str] = []                    # queue names
        self.pending_publish: Optional[dict] = None
        self.alive = True

    def send(self, data: bytes):
        with self.wlock:
            self.sock.sendall(data)

    def deliver(self, queue: str, msg: tuple):
        """msg = (body, correlation_id, redelivered)."""
        self.delivery_seq += 1
        tag = self.delivery_seq
        self.unacked[tag] = (queue, msg)
        body, cid, redelivered = msg
        args = (shortstr("ct-1") + struct.pack(">Q", tag)
                + bytes([int(redelivered)]) + shortstr("") + shortstr(queue))
        chunk = (1 << 17) - 8    # the tune-advertised frame_max
        self.send(
            method(AMQPConnection.CHANNEL_ID, BASIC, B_DELIVER, args)
            + content_header(AMQPConnection.CHANNEL_ID, len(body), cid)
            + b"".join(
                frame(FRAME_BODY, AMQPConnection.CHANNEL_ID,
                      body[i:i + chunk])
                for i in range(0, len(body), chunk)
            )
        )


class MiniRabbit:
    """In-repo AMQP 0-9-1 broker over real TCP: the full client
    handshake, queue.declare, basic.publish routing (default exchange),
    basic.consume push deliveries, manual acks with multiple=true, and
    REQUEUE-OF-UNACKED on connection loss with the redelivered flag —
    the broker behavior the source's exactly-once story depends on.
    The MiniKafkaBroker pattern: the public protocol is the test
    boundary, not a mock of the client."""

    def __init__(self):
        self.queues: Dict[str, List[tuple]] = {}
        self.consumers: Dict[str, List[_BrokerConn]] = {}
        self._lock = threading.Lock()
        self._server_sock: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._stop = threading.Event()

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server_sock = socket.create_server((host, port))
        self.port = self._server_sock.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="minirabbit-accept").start()
        return self.port

    def stop(self):
        self._stop.set()
        if self._server_sock is not None:
            self._server_sock.close()
            self._server_sock = None

    def message_count(self, queue: str) -> int:
        with self._lock:
            return len(self.queues.get(queue, []))

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, _ = self._server_sock.accept()
            except OSError:
                return
            conn = _BrokerConn(self, sock)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True, name="minirabbit-conn").start()

    # -- per-connection protocol loop ------------------------------------
    def _serve(self, conn: _BrokerConn):
        try:
            self._handshake(conn)
            while not self._stop.is_set():
                data = conn.sock.recv(1 << 16)
                if not data:
                    break
                conn.reader.feed(data)
                for ftype, _ch, payload in conn.reader.frames():
                    self._on_frame(conn, ftype, payload)
        except (OSError, AMQPError):
            pass
        finally:
            conn.alive = False
            self._requeue_unacked(conn)
            try:
                conn.sock.close()
            except OSError:
                pass

    def _handshake(self, conn: _BrokerConn):
        header = b""
        while len(header) < 8:
            chunk = conn.sock.recv(8 - len(header))
            if not chunk:
                raise AMQPError("client hung up during header")
            header += chunk
        if header != PROTO_HEADER:
            conn.sock.sendall(PROTO_HEADER)   # spec: reply with supported
            raise AMQPError(f"bad protocol header {header!r}")
        conn.send(method(
            0, CONNECTION, C_START,
            struct.pack(">BB", 0, 9) + encode_table({"product": "mini"})
            + longstr(b"PLAIN") + longstr(b"en_US"),
        ))

    def _on_frame(self, conn: _BrokerConn, ftype: int, payload: bytes):
        if ftype == FRAME_HEARTBEAT:
            return
        if ftype == FRAME_HEADER and conn.pending_publish is not None:
            size, cid = parse_basic_properties(payload)
            conn.pending_publish.update(size=size, correlation_id=cid)
            if size == 0:
                self._route(conn)
            return
        if ftype == FRAME_BODY and conn.pending_publish is not None:
            conn.pending_publish["body"] += payload
            if (len(conn.pending_publish["body"])
                    >= conn.pending_publish["size"]):
                self._route(conn)
            return
        if ftype != FRAME_METHOD:
            return
        cls, mid = struct.unpack_from(">HH", payload, 0)
        args = payload[4:]
        if (cls, mid) == (CONNECTION, C_START_OK):
            conn.send(method(0, CONNECTION, C_TUNE,
                             struct.pack(">HIH", 2047, 1 << 17, 0)))
        elif (cls, mid) == (CONNECTION, C_TUNE_OK):
            pass
        elif (cls, mid) == (CONNECTION, C_OPEN):
            conn.send(method(0, CONNECTION, C_OPEN_OK, shortstr("")))
        elif (cls, mid) == (CHANNEL, CH_OPEN):
            conn.send(method(AMQPConnection.CHANNEL_ID, CHANNEL,
                             CH_OPEN_OK, longstr(b"")))
        elif (cls, mid) == (QUEUE, Q_DECLARE):
            q, _ = read_shortstr(args, 2)
            with self._lock:
                self.queues.setdefault(q, [])
            conn.send(method(
                AMQPConnection.CHANNEL_ID, QUEUE, Q_DECLARE_OK,
                shortstr(q) + struct.pack(">II", 0, 0),
            ))
        elif (cls, mid) == (BASIC, B_PUBLISH):
            off = 2
            _ex, off = read_shortstr(args, off)
            rk, off = read_shortstr(args, off)
            conn.pending_publish = {"queue": rk, "body": b"",
                                    "size": None, "correlation_id": None}
        elif (cls, mid) == (BASIC, B_CONSUME):
            q, off = read_shortstr(args, 2)
            tag, off = read_shortstr(args, off)
            with self._lock:
                self.consumers.setdefault(q, []).append(conn)
                conn.consuming.append(q)
                backlog = self.queues.get(q, [])
                self.queues[q] = []
            conn.send(method(AMQPConnection.CHANNEL_ID, BASIC,
                             B_CONSUME_OK, shortstr(tag)))
            for msg in backlog:
                conn.deliver(q, msg)
        elif (cls, mid) == (BASIC, B_ACK):
            dtag, multiple = struct.unpack_from(">QB", args, 0)
            if multiple:
                for t in [t for t in conn.unacked if t <= dtag]:
                    del conn.unacked[t]
            else:
                conn.unacked.pop(dtag, None)
        elif (cls, mid) == (CONNECTION, C_CLOSE):
            conn.send(method(0, CONNECTION, C_CLOSE_OK))
        else:
            raise AMQPError(f"method {cls}.{mid} unsupported")

    def _route(self, conn: _BrokerConn):
        p, conn.pending_publish = conn.pending_publish, None
        msg = (p["body"], p["correlation_id"], False)
        q = p["queue"]
        with self._lock:
            self.queues.setdefault(q, [])
            targets = [c for c in self.consumers.get(q, []) if c.alive]
            if not targets:
                self.queues[q].append(msg)
                return
            target = targets[0]
        target.deliver(q, msg)

    def _requeue_unacked(self, conn: _BrokerConn):
        """Connection died with unacked deliveries: back on the queue
        front, redelivered=true (AMQP basic.recover semantics on
        connection loss)."""
        with self._lock:
            for q in conn.consuming:
                if conn in self.consumers.get(q, []):
                    self.consumers[q].remove(conn)
            items = sorted(conn.unacked.items())
            conn.unacked.clear()
            requeued: Dict[str, List[tuple]] = {}
            for _tag, (q, (body, cid, _r)) in items:
                requeued.setdefault(q, []).append((body, cid, True))
            for q, msgs in requeued.items():
                self.queues.setdefault(q, [])
                self.queues[q] = msgs + self.queues[q]
                targets = [c for c in self.consumers.get(q, []) if c.alive]
                if targets:
                    backlog = self.queues[q]
                    self.queues[q] = []
                    for msg in backlog:
                        targets[0].deliver(q, msg)
