"""Kinesis connector — the flink-connector-kinesis analog (SURVEY §2.8,
ref flink-streaming-connectors/flink-connector-kinesis/
FlinkKinesisConsumer.java + FlinkKinesisProducer.java; the reference
wraps the AWS SDK / KPL).

This is a WIRE client: it speaks the public Kinesis Data Streams API —
JSON over HTTP POST with ``X-Amz-Target: Kinesis_20131202.<Action>``
headers and **AWS Signature Version 4** request signing — implemented
from the public AWS docs (the SigV4 canonical-request / string-to-sign /
derived-key chain), not from any SDK.

No AWS endpoint exists in this image (zero egress), so tests run against
``MiniKinesis`` below — an in-repo HTTP server implementing the same
public spec: sharded streams with MD5 hash-key ranges, per-shard
monotone sequence numbers, shard iterators (TRIM_HORIZON / LATEST /
AT_/AFTER_SEQUENCE_NUMBER), PutRecords with per-record results and
injectable ProvisionedThroughputExceededException throttling — and it
**verifies every request's SigV4 signature** by recomputing it with the
shared secret, so the signing implementation is proven byte-for-byte,
not assumed. Against genuine AWS only endpoint/credentials change.

Semantics (the reference's):
  * consumer: one logical source consuming every shard of the stream
    (the reference distributes shards over subtasks; here the per-shard
    iterator set lives in one Source and the mesh parallelism is
    downstream), with the per-shard **sequence-number map as operator
    state** — ``snapshot_offsets`` / ``restore_offsets`` resume each
    shard AFTER_SEQUENCE_NUMBER, giving exactly-once replay through the
    checkpoint cut (ref FlinkKinesisConsumer.snapshotState:
    sequenceNumsToRestore);
  * producer: buffered PutRecords batches (<=500 records, the API
    limit), per-record failure retry of ONLY the failed subset with
    bounded backoff (the KPL retry story), flush-on-checkpoint so a
    barrier never covers unsent records. Kinesis has no idempotent
    write, so the producer is at-least-once — exactly what the
    reference documents for FlinkKinesisProducer.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.connectors.partitioned import PartitionedConsumerBase
from flink_tpu.runtime.sinks import Sink

_ALGO = "AWS4-HMAC-SHA256"
MAX_HASH_KEY = 1 << 128   # partition-key space: MD5 is 128 bits


# ---------------------------------------------------------------- SigV4
def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(method: str, path: str, headers: Dict[str, str], payload: bytes,
            region: str, service: str, access_key: str, secret_key: str,
            amz_date: str) -> str:
    """Return the SigV4 ``Authorization`` header value.

    The canonical-request -> string-to-sign -> derived-signing-key chain
    from the public AWS SigV4 spec. ``headers`` must already contain
    every header to be signed (lowercase names are computed here).
    """
    date = amz_date[:8]
    signed_names = sorted(h.lower() for h in headers)
    canonical_headers = "".join(
        f"{n}:{headers[k].strip()}\n"
        for n, k in sorted((h.lower(), h) for h in headers)
    )
    signed_headers = ";".join(signed_names)
    canonical = "\n".join([
        method, path, "",            # Kinesis actions use an empty query
        canonical_headers, signed_headers,
        hashlib.sha256(payload).hexdigest(),
    ])
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        _ALGO, amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])
    k = _hmac(("AWS4" + secret_key).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    return (f"{_ALGO} Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}")


class KinesisApiError(Exception):
    """A non-200 API response (validation, missing resource, rejected
    signature, …). Deliberately NOT an OSError subclass: transport-level
    retry handlers catch OSError, and a permanent API failure
    masquerading as a transient transport failure would be re-buffered
    and retried forever instead of propagating."""


class ThroughputExceeded(KinesisApiError):
    """ProvisionedThroughputExceededException — transient, retried."""


class PutUndelivered(ConnectionError):  # transport-flavored: retryable
    """A PutRecords batch could not be fully delivered; ``unsent``
    carries exactly the records NOT acknowledged so the sink re-buffers
    only those — re-buffering acknowledged records would duplicate
    (Kinesis has no idempotent write to absorb it)."""

    def __init__(self, message: str, unsent: List[dict]):
        super().__init__(message)
        self.unsent = unsent


class KinesisClient:
    """Minimal Kinesis Data Streams API client (signed JSON over HTTP)."""

    def __init__(self, host: str, port: int, region: str = "us-east-1",
                 access_key: str = "AKIDEXAMPLE",
                 secret_key: str = "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
                 timeout_s: float = 10.0, use_tls: bool = False):
        self.host, self.port, self.region = host, port, region
        self.access_key, self.secret_key = access_key, secret_key
        self.timeout_s = timeout_s
        # genuine AWS endpoints are HTTPS-only; MiniKinesis is plain HTTP
        self.use_tls = use_tls
        self._conn: Optional[http.client.HTTPConnection] = None

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def call(self, action: str, body: dict) -> dict:
        payload = json.dumps(body).encode()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        headers = {
            "Host": f"{self.host}:{self.port}",
            "X-Amz-Date": amz_date,
            "X-Amz-Target": f"Kinesis_20131202.{action}",
            "Content-Type": "application/x-amz-json-1.1",
        }
        headers["Authorization"] = sign_v4(
            "POST", "/", headers, payload, self.region, "kinesis",
            self.access_key, self.secret_key, amz_date,
        )
        if self._conn is None:
            cls = (http.client.HTTPSConnection if self.use_tls
                   else http.client.HTTPConnection)
            self._conn = cls(self.host, self.port, timeout=self.timeout_s)
        try:
            self._conn.request("POST", "/", payload, headers)
            resp = self._conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException):
            self.close()
            raise
        out = json.loads(data) if data else {}
        if resp.status == 400 and \
                "ProvisionedThroughputExceeded" in out.get("__type", ""):
            raise ThroughputExceeded(out.get("message", ""))
        if resp.status != 200:
            raise KinesisApiError(
                f"{action} failed: HTTP {resp.status} {out!r}")
        return out

    # -- typed wrappers over the API actions ----------------------------
    def list_shards(self, stream: str) -> List[dict]:
        return self.call("ListShards", {"StreamName": stream})["Shards"]

    def get_shard_iterator(self, stream: str, shard_id: str,
                           iterator_type: str,
                           sequence_number: Optional[str] = None) -> str:
        body = {"StreamName": stream, "ShardId": shard_id,
                "ShardIteratorType": iterator_type}
        if sequence_number is not None:
            body["StartingSequenceNumber"] = sequence_number
        return self.call("GetShardIterator", body)["ShardIterator"]

    def get_records(self, iterator: str, limit: int) -> dict:
        return self.call("GetRecords",
                         {"ShardIterator": iterator, "Limit": limit})

    def put_records(self, stream: str, records: List[dict]) -> dict:
        return self.call("PutRecords",
                         {"StreamName": stream, "Records": records})


# ---------------------------------------------------------------- source
class KinesisSource(PartitionedConsumerBase):
    """ref FlinkKinesisConsumer: every shard consumed with the per-shard
    sequence-number map as checkpoint state (sequenceNumsToRestore).

    Built on ``PartitionedConsumerBase`` — the repo's Kafka-consumer
    contract: partitions are shard ids, the per-shard "offset" is the
    last-emitted SequenceNumber string (``0`` = not started -> the
    configured initial position). ``fetch`` is deterministic given
    (shard, sequence): GetShardIterator AFTER_SEQUENCE_NUMBER +
    GetRecords is exactly Kinesis's replay story, so a restored source
    re-emits precisely the records since the checkpoint cut. The live
    iterator cache advances only after a successful GetRecords, so a
    mid-poll transport error or deserializer failure never skips
    records. A closed shard (post-reshard) drains to
    ``NextShardIterator: null`` and is marked exhausted.

    ``deserializer(data_bytes, partition_key) -> element`` (the
    KinesisDeserializationSchema seam); default decodes UTF-8.
    """

    def __init__(self, host: str, port: int, stream: str,
                 deserializer: Optional[Callable[[bytes, str], Any]] = None,
                 initial_position: str = "TRIM_HORIZON",
                 bounded: bool = False, **client_kw):
        super().__init__()
        self.stream = stream
        self.deserializer = deserializer or (lambda b, pk: b.decode())
        self.initial_position = initial_position
        # bounded: a shard is exhausted once caught up to the tip
        # (GetRecords: no records, MillisBehindLatest 0) — a finite read
        # of the current stream contents, for batch-style jobs and tests;
        # default is the streaming behavior (open shards never exhaust)
        self.bounded = bounded
        self._client = KinesisClient(host, port, **client_kw)
        self._iters: Dict[str, Optional[str]] = {}  # shard -> live iter

    # -- PartitionedConsumerBase contract --------------------------------
    def discover_partitions(self):
        return [sh["ShardId"]
                for sh in self._client.list_shards(self.stream)]

    def fetch(self, shard, offset, max_records):
        it = self._iters.get(shard)
        if it is None:
            if offset == 0:        # not started: the initial position
                it = self._client.get_shard_iterator(
                    self.stream, shard, self.initial_position)
            else:                  # resume AFTER the checkpointed seq
                it = self._client.get_shard_iterator(
                    self.stream, shard, "AFTER_SEQUENCE_NUMBER",
                    str(offset))
        resp = self._client.get_records(it, max_records)
        records = [
            self.deserializer(base64.b64decode(r["Data"]),
                              r["PartitionKey"])
            for r in resp["Records"]
        ]
        # commit the advance only now: everything above either fully
        # succeeded or left (iterator, offset) untouched for a clean retry
        nxt = resp.get("NextShardIterator")
        self._iters[shard] = nxt
        new_off = (resp["Records"][-1]["SequenceNumber"]
                   if resp["Records"] else offset)
        caught_up = (not resp["Records"]
                     and resp.get("MillisBehindLatest", 1) == 0)
        exhausted = (nxt is None and not resp["Records"]) or \
            (self.bounded and caught_up)
        return records, new_off, exhausted

    def restore_offsets(self, state):
        super().restore_offsets(state)
        self._iters = {}           # stale iterators don't survive a seek

    def close(self):
        self._client.close()


# ---------------------------------------------------------------- sink
class KinesisSink(Sink):
    """ref FlinkKinesisProducer: elements -> PutRecords batches.

    ``emitter(element) -> (partition_key, data_bytes)`` (the
    KinesisSerializationSchema + partition-key seam). At-least-once:
    flush-on-checkpoint plus failed-subset retry; Kinesis offers no
    idempotent write, matching the reference's documented guarantee.
    """

    API_MAX_BATCH = 500     # PutRecords hard limit from the public API

    def __init__(self, host: str, port: int, stream: str,
                 emitter: Callable[[Any], Tuple[str, bytes]],
                 flush_max_records: int = 500, max_retries: int = 6,
                 **client_kw):
        self.stream = stream
        self.emitter = emitter
        self.flush_max_records = max(
            1, min(flush_max_records, self.API_MAX_BATCH))
        self.max_retries = max_retries
        self._client = KinesisClient(host, port, **client_kw)
        self._buf: List[dict] = []
        self.stats = {"put_requests": 0, "records": 0, "retries": 0}

    def open(self):
        self._client.list_shards(self.stream)   # existence + auth check

    def invoke_batch(self, elements: List[Any]):
        for e in elements:
            pk, data = self.emitter(e)
            self._buf.append({
                "PartitionKey": pk,
                "Data": base64.b64encode(data).decode(),
            })
            if len(self._buf) >= self.flush_max_records:
                self.flush()

    def snapshot_state(self):
        self.flush()            # a barrier never covers unsent records
        return None

    def close(self):
        self.flush()
        self._client.close()

    def flush(self):
        while self._buf:
            batch = self._buf[:self.flush_max_records]
            self._buf = self._buf[self.flush_max_records:]
            try:
                self._send(batch)
            except PutUndelivered as e:
                # ONLY the unacknowledged records back in front:
                # at-least-once without duplicating the acknowledged
                # prefix of the same batch
                self._buf = list(e.unsent) + self._buf
                raise

    def _send(self, batch: List[dict]):
        """Deliver with bounded backoff, resending ONLY the failed
        subset each round (per-record ErrorCode results — the KPL
        behavior; resending delivered records would duplicate)."""
        current = batch
        delay = 0.05
        for attempt in range(self.max_retries + 1):
            try:
                resp = self._client.put_records(self.stream, current)
            except ThroughputExceeded as e:
                # whole request throttled: nothing delivered this round
                self.stats["retries"] += 1
                if attempt == self.max_retries:
                    raise PutUndelivered(str(e), current) from e
                time.sleep(delay)
                delay = min(delay * 2, 2.0)
                continue
            except (OSError, http.client.HTTPException) as e:
                raise PutUndelivered(str(e), current) from e
            self.stats["put_requests"] += 1
            failed = []
            for rec, res in zip(current, resp["Records"]):
                if "ErrorCode" in res:
                    failed.append(rec)
                else:
                    self.stats["records"] += 1
            if not failed:
                return
            self.stats["retries"] += 1
            if attempt == self.max_retries:
                raise PutUndelivered(
                    f"{len(failed)} record(s) undelivered after "
                    f"{self.max_retries} retries", failed)
            current = failed
            time.sleep(delay)
            delay = min(delay * 2, 2.0)


# ------------------------------------------------------------ MiniKinesis
class MiniKinesis:
    """In-repo Kinesis Data Streams spec server (the MiniKafkaBroker /
    MiniElasticsearch pattern): sharded streams, MD5 hash-key routing,
    shard iterators, per-record PutRecords results, injectable
    throttling — and SigV4 verification by recomputation, so the client's
    signing is byte-for-byte proven against an independent implementation
    of the spec's server side.
    """

    def __init__(self, shards: int = 2, region: str = "us-east-1",
                 access_key: str = "AKIDEXAMPLE",
                 secret_key: str = "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY"):
        self.region = region
        self.access_key, self.secret_key = access_key, secret_key
        self.streams: Dict[str, List[List[dict]]] = {}
        self.shard_ranges: Dict[str, List[Tuple[int, int]]] = {}
        self.default_shards = shards
        self.throttle_next_puts = 0      # whole-request throttles to inject
        self.throttle_next_records = 0   # per-record ErrorCode injections
        self.auth_failures = 0
        self.requests = 0
        self._lock = threading.Lock()
        self._srv: Optional[ThreadingHTTPServer] = None
        self.port: Optional[int] = None

    # -- stream admin ----------------------------------------------------
    def create_stream(self, name: str, shards: Optional[int] = None):
        n = shards or self.default_shards
        step = MAX_HASH_KEY // n
        self.streams[name] = [[] for _ in range(n)]
        self.shard_ranges[name] = [
            (i * step, MAX_HASH_KEY if i == n - 1 else (i + 1) * step)
            for i in range(n)
        ]

    def shard_for_key(self, stream: str, pk: str) -> int:
        hk = int(hashlib.md5(pk.encode()).hexdigest(), 16)
        for i, (lo, hi) in enumerate(self.shard_ranges[stream]):
            if lo <= hk < hi:
                return i
        return len(self.shard_ranges[stream]) - 1

    # -- lifecycle -------------------------------------------------------
    def start(self) -> int:
        mini = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                payload = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                status, body = mini.handle(
                    dict(self.headers), payload)
                data = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type",
                                 "application/x-amz-json-1.1")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return self.port

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None

    # -- request handling ------------------------------------------------
    def _verify_sig(self, headers: Dict[str, str], payload: bytes) -> bool:
        auth = headers.get("Authorization", "")
        if not auth.startswith(_ALGO):
            return False
        try:
            parts = dict(
                p.strip().split("=", 1)
                for p in auth[len(_ALGO):].split(",")
            )
            signed = parts["SignedHeaders"].split(";")
            sig = parts["Signature"]
        except (ValueError, KeyError):
            return False
        # recompute over the SAME signed header set with OUR secret
        hdrs = {}
        lower = {k.lower(): v for k, v in headers.items()}
        for name in signed:
            if name not in lower:
                return False
            hdrs[name] = lower[name]
        expect = sign_v4("POST", "/", hdrs, payload, self.region,
                         "kinesis", self.access_key, self.secret_key,
                         lower.get("x-amz-date", ""))
        expect_sig = expect.rsplit("Signature=", 1)[1]
        return hmac.compare_digest(sig, expect_sig)

    def handle(self, headers: Dict[str, str],
               payload: bytes) -> Tuple[int, dict]:
        self.requests += 1
        if not self._verify_sig(headers, payload):
            self.auth_failures += 1
            return 403, {"__type": "IncompleteSignatureException",
                         "message": "signature mismatch"}
        action = headers.get("X-Amz-Target", "").split(".")[-1]
        body = json.loads(payload) if payload else {}
        with self._lock:
            fn = getattr(self, f"_do_{action}", None)
            if fn is None:
                return 400, {"__type": "UnknownOperationException",
                             "message": action}
            return fn(body)

    def _need_stream(self, name):
        if name not in self.streams:
            return 400, {"__type": "ResourceNotFoundException",
                         "message": f"stream {name} not found"}
        return None

    def _do_ListShards(self, body):
        err = self._need_stream(body["StreamName"])
        if err:
            return err
        name = body["StreamName"]
        return 200, {"Shards": [
            {"ShardId": f"shardId-{i:012d}",
             "HashKeyRange": {"StartingHashKey": str(lo),
                              "EndingHashKey": str(hi - 1)}}
            for i, (lo, hi) in enumerate(self.shard_ranges[name])
        ]}

    def _do_GetShardIterator(self, body):
        err = self._need_stream(body["StreamName"])
        if err:
            return err
        name = body["StreamName"]
        sid = int(body["ShardId"].split("-")[-1])
        kind = body["ShardIteratorType"]
        shard = self.streams[name][sid]
        if kind == "TRIM_HORIZON":
            pos = 0
        elif kind == "LATEST":
            pos = len(shard)
        elif kind in ("AT_SEQUENCE_NUMBER", "AFTER_SEQUENCE_NUMBER"):
            seq = int(body["StartingSequenceNumber"])
            pos = seq + (1 if kind == "AFTER_SEQUENCE_NUMBER" else 0)
        else:
            return 400, {"__type": "InvalidArgumentException",
                         "message": kind}
        return 200, {"ShardIterator": json.dumps([name, sid, pos])}

    def _do_GetRecords(self, body):
        name, sid, pos = json.loads(body["ShardIterator"])
        err = self._need_stream(name)
        if err:
            return err
        limit = int(body.get("Limit", 1000))
        shard = self.streams[name][sid]
        recs = shard[pos:pos + limit]
        nxt = pos + len(recs)
        return 200, {
            "Records": recs,
            "NextShardIterator": json.dumps([name, sid, nxt]),
            "MillisBehindLatest": 0,
        }

    def _do_PutRecords(self, body):
        err = self._need_stream(body["StreamName"])
        if err:
            return err
        if self.throttle_next_puts > 0:
            self.throttle_next_puts -= 1
            return 400, {
                "__type": "ProvisionedThroughputExceededException",
                "message": "rate exceeded",
            }
        name = body["StreamName"]
        results, failed = [], 0
        for rec in body["Records"]:
            if self.throttle_next_records > 0:
                self.throttle_next_records -= 1
                failed += 1
                results.append({
                    "ErrorCode": "ProvisionedThroughputExceededException",
                    "ErrorMessage": "rate exceeded",
                })
                continue
            sid = self.shard_for_key(name, rec["PartitionKey"])
            shard = self.streams[name][sid]
            seq = str(len(shard))
            shard.append({
                "SequenceNumber": seq,
                "PartitionKey": rec["PartitionKey"],
                "Data": rec["Data"],
                "ApproximateArrivalTimestamp": time.time(),
            })
            results.append({"SequenceNumber": seq,
                            "ShardId": f"shardId-{sid:012d}"})
        return 200, {"FailedRecordCount": failed, "Records": results}
