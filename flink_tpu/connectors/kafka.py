"""Kafka wire-protocol connector — the flink-connector-kafka analog
(SURVEY §2.8, ref flink-streaming-connectors/flink-connector-kafka-0.9/
FlinkKafkaConsumer09 + FlinkKafkaConsumerBase.java:65 +
FlinkKafkaProducerBase).

This is a WIRE client: it speaks the public Apache Kafka binary protocol
(the 0.9/0.10-era core APIs, implemented from the protocol guide —
request framing `size int32 | api_key int16 | api_version int16 |
correlation_id int32 | client_id string`, and the v0 bodies of:

    Metadata    (api 3)  — topic/partition/leader discovery
    Produce     (api 0)  — MessageSet append, acks
    Fetch       (api 1)  — offset-addressed log reads
    ListOffsets (api 2)  — earliest/latest offset lookup

MessageSet v0 entries are `offset int64 | size int32 | crc uint32 |
magic int8 | attrs int8 | key bytes | value bytes` with CRC32 over the
message from the magic byte; the client validates CRCs on fetch.

No Kafka server exists in this image (zero egress), so tests run the
client against `MiniKafkaBroker` below — an in-repo broker implementing
the same public spec on a real TCP socket. That proves the byte-level
seam; against a genuine cluster only the host:port changes.

KafkaConsumer plugs into the PartitionedConsumerBase contract
(connectors/partitioned.py): partition discovery at open, per-partition
offsets snapshot into checkpoints, deterministic re-fetch on restore —
the exactly-once replay story of the reference consumer.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.connectors.partitioned import PartitionedConsumerBase
from flink_tpu.runtime.sinks import Sink

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3


# ------------------------------------------------------------ wire encoding
def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def i8(self):
        v = struct.unpack_from(">b", self.d, self.o)[0]
        self.o += 1
        return v

    def i16(self):
        v = struct.unpack_from(">h", self.d, self.o)[0]
        self.o += 2
        return v

    def i32(self):
        v = struct.unpack_from(">i", self.d, self.o)[0]
        self.o += 4
        return v

    def i64(self):
        v = struct.unpack_from(">q", self.d, self.o)[0]
        self.o += 8
        return v

    def u32(self):
        v = struct.unpack_from(">I", self.d, self.o)[0]
        self.o += 4
        return v

    def string(self):
        n = self.i16()
        if n < 0:
            return None
        v = self.d[self.o:self.o + n].decode()
        self.o += n
        return v

    def nbytes(self):
        n = self.i32()
        if n < 0:
            return None
        v = self.d[self.o:self.o + n]
        self.o += n
        return v


def encode_message(key: Optional[bytes], value: Optional[bytes]) -> bytes:
    """One MessageSet v0 entry body (magic 0): crc | magic | attrs |
    key | value, CRC32 from the magic byte."""
    body = struct.pack(">bb", 0, 0) + _bytes(key) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return struct.pack(">I", crc) + body


def encode_message_set(messages, base_offset: int = 0) -> bytes:
    out = []
    for i, (k, v) in enumerate(messages):
        m = encode_message(k, v)
        out.append(struct.pack(">qi", base_offset + i, len(m)))
        out.append(m)
    return b"".join(out)


def decode_message_set(data: bytes) -> List[Tuple[int, bytes, bytes]]:
    """-> [(offset, key, value)]; trailing partial messages (a Fetch may
    cut one off mid-stream, per spec) are dropped. CRC mismatches raise."""
    out = []
    o = 0
    while o + 12 <= len(data):
        offset, size = struct.unpack_from(">qi", data, o)
        o += 12
        if o + size > len(data):
            break                      # partial trailing message
        r = _Reader(data[o:o + size])
        crc = r.u32()
        body = data[o + 4:o + size]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise IOError(f"Kafka message CRC mismatch at offset {offset}")
        r.i8()                         # magic
        r.i8()                         # attributes
        key = r.nbytes()
        value = r.nbytes()
        out.append((offset, key, value))
        o += size
    return out


# ------------------------------------------------------------ client core
class KafkaWireClient:
    """Minimal broker connection: framed request/response with correlation
    ids (one in flight, reconnect on failure — the reference's
    NetworkClient role at its simplest)."""

    def __init__(self, host: str, port: int, client_id: str = "flink-tpu",
                 timeout_s: float = 30.0):
        self.addr = (host, port)
        self.client_id = client_id
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._corr = 0

    def _connect(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                self.addr, timeout=self.timeout_s
            )

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def request(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        self._corr += 1
        hdr = struct.pack(">hhi", api_key, api_version, self._corr) + \
            _str(self.client_id)
        payload = hdr + body
        framed = struct.pack(">i", len(payload)) + payload
        try:
            self._connect()
            self._sock.sendall(framed)
            resp = self._read_frame()
        except OSError:
            # one reconnect attempt (the broker may have restarted —
            # the reference consumer's transparent reconnect)
            self.close()
            self._connect()
            self._sock.sendall(framed)
            resp = self._read_frame()
        r = _Reader(resp)
        corr = r.i32()
        if corr != self._corr:
            raise IOError(f"correlation id mismatch: {corr} != {self._corr}")
        return r

    def _read_frame(self) -> bytes:
        raw = self._recvn(4)
        (n,) = struct.unpack(">i", raw)
        return self._recvn(n)

    def _recvn(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise IOError("broker closed connection")
            buf += chunk
        return buf

    # -- api calls --------------------------------------------------------
    def metadata(self, topics: List[str]) -> Dict[str, List[int]]:
        body = struct.pack(">i", len(topics)) + b"".join(
            _str(t) for t in topics
        )
        r = self.request(API_METADATA, 0, body)
        n_brokers = r.i32()
        for _ in range(n_brokers):
            r.i32()          # node id
            r.string()       # host
            r.i32()          # port
        out: Dict[str, List[int]] = {}
        errors: Dict[str, int] = {}
        n_topics = r.i32()
        for _ in range(n_topics):
            err = r.i16()
            topic = r.string()
            parts = []
            n_parts = r.i32()
            for _ in range(n_parts):
                r.i16()      # partition error
                pid = r.i32()
                r.i32()      # leader
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                parts.append(pid)
            if err == 0:
                out[topic] = sorted(parts)
            else:
                errors[topic] = err
        if errors:
            # NEVER silently drop an errored topic: a retriable
            # LEADER_NOT_AVAILABLE (or a typo'd name) would otherwise
            # read as "zero partitions" and the job would finish
            # instantly having consumed nothing
            raise IOError(
                f"metadata errors: "
                f"{', '.join(f'{t}: code {e}' for t, e in errors.items())}"
            )
        return out

    def produce(self, topic: str, partition: int,
                messages: List[Tuple[Optional[bytes], bytes]]) -> int:
        """-> base offset assigned by the broker."""
        ms = encode_message_set(messages)
        body = (
            struct.pack(">hi", 1, 10_000)          # acks=1, timeout
            + struct.pack(">i", 1) + _str(topic)
            + struct.pack(">i", 1) + struct.pack(">i", partition)
            + struct.pack(">i", len(ms)) + ms
        )
        r = self.request(API_PRODUCE, 0, body)
        n_topics = r.i32()
        base = -1
        for _ in range(n_topics):
            r.string()
            for _ in range(r.i32()):
                r.i32()                            # partition
                err = r.i16()
                base = r.i64()
                if err:
                    raise IOError(f"produce failed: error code {err}")
        return base

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20) -> Tuple[List, int]:
        """-> ([(offset, key, value)], high_watermark)."""
        body = (
            struct.pack(">iii", -1, 100, 1)        # replica, wait, min
            + struct.pack(">i", 1) + _str(topic)
            + struct.pack(">i", 1)
            + struct.pack(">iqi", partition, offset, max_bytes)
        )
        r = self.request(API_FETCH, 0, body)
        msgs: List = []
        hw = -1
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()                            # partition
                err = r.i16()
                hw = r.i64()
                ms = r.d[r.o + 4:r.o + 4 + r.i32()]
                r.o += len(ms)
                if err:
                    raise IOError(f"fetch failed: error code {err}")
                msgs.extend(decode_message_set(ms))
        return msgs, hw

    def list_offsets(self, topic: str, partition: int,
                     time_val: int = -1) -> int:
        """time -1 = latest, -2 = earliest (ListOffsets v0)."""
        body = (
            struct.pack(">i", -1)
            + struct.pack(">i", 1) + _str(topic)
            + struct.pack(">i", 1)
            + struct.pack(">iqi", partition, time_val, 1)
        )
        r = self.request(API_LIST_OFFSETS, 0, body)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                n = r.i32()
                offs = [r.i64() for _ in range(n)]
                if err:
                    raise IOError(f"list_offsets failed: {err}")
                return offs[0] if offs else 0
        return 0


# ------------------------------------------------------------ consumer/sink
class KafkaConsumer(PartitionedConsumerBase):
    """FlinkKafkaConsumer analog over the wire client: partitions from
    Metadata, records from Fetch, offsets checkpointed by the framework
    (exactly-once replay via deterministic offset-addressed re-fetch).
    `deserializer(key_bytes, value_bytes) -> record` (the
    DeserializationSchema role); default: value utf-8 text."""

    def __init__(self, host: str, port: int, topic: str,
                 deserializer=None, stop_at_latest: bool = True):
        super().__init__()
        self.client = KafkaWireClient(host, port)
        self.topic = topic
        self.deserializer = deserializer or (
            lambda k, v: v.decode() if v is not None else None
        )
        # bounded run for batch-style jobs: stop at the high watermark
        # observed per fetch (a live stream sets False and polls forever)
        self.stop_at_latest = stop_at_latest
        # wire fetches pull up to max_bytes; messages beyond the caller's
        # max_records buffer here instead of being re-downloaded on the
        # next poll (one wire fetch serves many polls)
        self._pending: Dict[int, List[Tuple[int, Any]]] = {}
        self._hw: Dict[int, int] = {}

    def discover_partitions(self):
        return self.client.metadata([self.topic]).get(self.topic, [])

    def fetch(self, partition, offset, max_records):
        pend = self._pending.get(partition)
        if not (pend and pend[0][0] == offset):
            # cold or restored to a different offset: wire fetch
            msgs, hw = self.client.fetch(self.topic, partition, offset)
            self._hw[partition] = hw
            pend = [(off, self.deserializer(k, v))
                    for off, k, v in msgs]
            self._pending[partition] = pend
        serve = pend[:max_records]
        self._pending[partition] = pend[max_records:]
        records = [rec for _off, rec in serve]
        new_off = serve[-1][0] + 1 if serve else offset
        exhausted = (
            self.stop_at_latest
            and not self._pending[partition]
            and new_off >= self._hw.get(partition, 0)
        )
        return records, new_off, exhausted

    def close(self):
        self.client.close()


class KafkaProducerSink(Sink):
    """FlinkKafkaProducer analog: serialize + Produce per batch.
    `serializer(record) -> (key_bytes|None, value_bytes)`."""

    def __init__(self, host: str, port: int, topic: str, partition: int = 0,
                 serializer=None):
        self.client = KafkaWireClient(host, port)
        self.topic = topic
        self.partition = partition
        self.serializer = serializer or (
            lambda r: (None, str(r).encode())
        )
        self.records_written = 0

    def invoke_batch(self, elements):
        if not elements:
            return
        msgs = [self.serializer(e) for e in elements]
        self.client.produce(self.topic, self.partition, msgs)
        self.records_written += len(elements)

    def close(self):
        self.client.close()


# ------------------------------------------------------------ mini broker
class MiniKafkaBroker:
    """In-repo broker speaking the same public wire protocol on a real
    TCP socket (the test double standing in for a Kafka cluster; ref the
    reference's KafkaTestEnvironment embedded brokers). Append-only
    in-memory logs per (topic, partition); thread-safe."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 topics: Optional[Dict[str, int]] = None):
        self.logs: Dict[Tuple[str, int], List[Tuple[bytes, bytes]]] = {}
        self.topics: Dict[str, int] = dict(topics or {})
        self._lock = threading.Lock()
        for t, n in self.topics.items():
            for p in range(n):
                self.logs[(t, p)] = []
        broker = self

        self._conns: list = []

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                broker._conns.append(self.request)

            def finish(self):
                # no unbounded dead-socket accumulation across the
                # broker's lifetime
                try:
                    broker._conns.remove(self.request)
                except ValueError:
                    pass

            def handle(self):
                try:
                    while True:
                        raw = self._recvn(4)
                        if raw is None:
                            return
                        (n,) = struct.unpack(">i", raw)
                        payload = self._recvn(n)
                        if payload is None:
                            return
                        resp = broker._dispatch(payload)
                        self.request.sendall(
                            struct.pack(">i", len(resp)) + resp
                        )
                except OSError:
                    pass

            def _recvn(self, n):
                buf = b""
                while len(buf) < n:
                    chunk = self.request.recv(n - len(buf))
                    if not chunk:
                        return None
                    buf += chunk
                return buf

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.host = host
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="mini-kafka-broker").start()

    def create_topic(self, topic: str, partitions: int = 1):
        with self._lock:
            self.topics[topic] = partitions
            for p in range(partitions):
                self.logs.setdefault((topic, p), [])

    def append(self, topic: str, partition: int, key, value):
        """Direct append (producer-side test hook)."""
        with self._lock:
            self.logs[(topic, partition)].append((key, value))

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        # sever live client connections too (a real broker restart RSTs
        # them; lingering handler threads would otherwise keep serving
        # the dead broker's in-memory logs)
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        self._conns.clear()

    # -- request dispatch -------------------------------------------------
    def _dispatch(self, payload: bytes) -> bytes:
        r = _Reader(payload)
        api = r.i16()
        r.i16()                        # api version (v0 served)
        corr = r.i32()
        r.string()                     # client id
        body = {
            API_METADATA: self._metadata,
            API_PRODUCE: self._produce,
            API_FETCH: self._fetch,
            API_LIST_OFFSETS: self._list_offsets,
        }[api](r)
        return struct.pack(">i", corr) + body

    def _metadata(self, r: _Reader) -> bytes:
        n = r.i32()
        want = [r.string() for _ in range(n)] or list(self.topics)
        out = [struct.pack(">i", 1),                 # one broker
               struct.pack(">i", 0), _str(self.host),
               struct.pack(">i", self.port)]
        out.append(struct.pack(">i", len(want)))
        for t in want:
            known = t in self.topics
            out.append(struct.pack(">h", 0 if known else 3))  # 3 = unknown
            out.append(_str(t))
            nparts = self.topics.get(t, 0)
            out.append(struct.pack(">i", nparts))
            for p in range(nparts):
                out.append(struct.pack(">hiii", 0, p, 0, 1))  # leader 0
                out.append(struct.pack(">i", 0))              # replicas
                out.append(struct.pack(">i", 0))              # isr...
        return b"".join(out)

    def _produce(self, r: _Reader) -> bytes:
        r.i16()                        # acks
        r.i32()                        # timeout
        out_topics = []
        for _ in range(r.i32()):
            topic = r.string()
            parts_out = []
            for _ in range(r.i32()):
                pid = r.i32()
                ms = r.d[r.o + 4:r.o + 4 + r.i32()]
                r.o += len(ms)
                msgs = decode_message_set(ms)
                with self._lock:
                    log = self.logs.get((topic, pid))
                    if log is None:
                        parts_out.append(struct.pack(">ihq", pid, 3, -1))
                        continue
                    base = len(log)
                    for _off, k, v in msgs:
                        log.append((k, v))
                parts_out.append(struct.pack(">ihq", pid, 0, base))
            out_topics.append(
                _str(topic) + struct.pack(">i", len(parts_out))
                + b"".join(parts_out)
            )
        return struct.pack(">i", len(out_topics)) + b"".join(out_topics)

    def _fetch(self, r: _Reader) -> bytes:
        r.i32(); r.i32(); r.i32()      # replica, max wait, min bytes
        out_topics = []
        for _ in range(r.i32()):
            topic = r.string()
            parts_out = []
            for _ in range(r.i32()):
                pid = r.i32()
                offset = r.i64()
                max_bytes = r.i32()
                with self._lock:
                    log = list(self.logs.get((topic, pid), []))
                hw = len(log)
                ms = encode_message_set(
                    log[offset:offset + 512], base_offset=offset
                )[:max(0, max_bytes)]
                parts_out.append(
                    struct.pack(">ihq", pid, 0, hw)
                    + struct.pack(">i", len(ms)) + ms
                )
            out_topics.append(
                _str(topic) + struct.pack(">i", len(parts_out))
                + b"".join(parts_out)
            )
        return struct.pack(">i", len(out_topics)) + b"".join(out_topics)

    def _list_offsets(self, r: _Reader) -> bytes:
        r.i32()                        # replica
        out_topics = []
        for _ in range(r.i32()):
            topic = r.string()
            parts_out = []
            for _ in range(r.i32()):
                pid = r.i32()
                tv = r.i64()
                r.i32()                # max offsets
                with self._lock:
                    n = len(self.logs.get((topic, pid), []))
                off = 0 if tv == -2 else n
                parts_out.append(
                    struct.pack(">ih", pid, 0)
                    + struct.pack(">i", 1) + struct.pack(">q", off)
                )
            out_topics.append(
                _str(topic) + struct.pack(">i", len(parts_out))
                + b"".join(parts_out)
            )
        return struct.pack(">i", len(out_topics)) + b"".join(out_topics)
