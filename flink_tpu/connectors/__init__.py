"""Connectors (ref flink-streaming-connectors, SURVEY §2.8)."""

from flink_tpu.connectors.files import (
    PROCESS_CONTINUOUSLY,
    PROCESS_ONCE,
    BucketingFileSink,
    ContinuousFileSource,
)
from flink_tpu.connectors.partitioned import (
    InMemoryPartitionedSource,
    PartitionedConsumerBase,
)

__all__ = [
    "PartitionedConsumerBase", "InMemoryPartitionedSource",
    "ContinuousFileSource", "BucketingFileSink",
    "PROCESS_ONCE", "PROCESS_CONTINUOUSLY",
]
