"""Cassandra connector — the flink-connector-cassandra analog
(SURVEY §2.8, ref flink-streaming-connectors/flink-connector-cassandra/
CassandraSink.java + CassandraSinkBase; the reference wraps the DataStax
driver's async session).

This is a WIRE client: it speaks the public CQL binary protocol v3
(the native_protocol_v3.spec frame layout — 9-byte header
``version int8 | flags int8 | stream int16 | opcode int8 | length
int32`` — and the STARTUP/READY, QUERY/RESULT, PREPARE/EXECUTE and
ERROR exchanges), implemented from the protocol spec, not from any
driver library.

No Cassandra server exists in this image (zero egress), so tests run
the client against ``MiniCassandra`` below — an in-repo server
implementing the same public frame protocol on a real TCP socket with a
tiny keyspace/table store and a CQL subset (CREATE TABLE, INSERT,
SELECT). That proves the byte-level seam; against a genuine cluster
only the host:port changes.

Semantics (the reference's):
  * ``CassandraSink``: per-element bound INSERTs through a PREPARED
    statement (CassandraSinkBase.send), batched per invoke;
  * at-least-once via flush-on-checkpoint (pending writes drain before
    the cut, ref CassandraSinkBase.snapshotState waiting on in-flight
    futures);
  * exactly-once effect through Cassandra's native upsert: INSERT on
    the same primary key overwrites, so deterministic keys make replay
    idempotent — the reference's documented story (WriteAheadSink is
    the alternative for non-idempotent updates).
"""

from __future__ import annotations

import re
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.runtime.sinks import Sink

# protocol v3 opcodes (native_protocol_v3.spec §2.4)
OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_OPTIONS = 0x05
OP_SUPPORTED = 0x06
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_PREPARE = 0x09
OP_EXECUTE = 0x0A

# RESULT kinds (§4.2.5)
RESULT_VOID = 0x0001
RESULT_ROWS = 0x0002
RESULT_SET_KEYSPACE = 0x0003
RESULT_PREPARED = 0x0004

CONSISTENCY_ONE = 0x0001


# ----------------------------------------------------------- wire encoding
def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">i", len(b)) + b


def _string_map(m: Dict[str, str]) -> bytes:
    out = struct.pack(">H", len(m))
    for k, v in m.items():
        out += _string(k) + _string(v)
    return out


def _bytes_value(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def _read_string(buf: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from(">H", buf, off)
    off += 2
    return buf[off:off + n].decode(), off + n


def _read_long_string(buf: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from(">i", buf, off)
    off += 4
    return buf[off:off + n].decode(), off + n


def _read_bytes(buf: bytes, off: int) -> Tuple[Optional[bytes], int]:
    (n,) = struct.unpack_from(">i", buf, off)
    off += 4
    if n < 0:
        return None, off
    return buf[off:off + n], off + n


def encode_value(v: Any) -> Optional[bytes]:
    """Python value -> CQL serialized bytes (the varchar/bigint/double
    subset the connector binds); None -> CQL null (length -1 on the
    wire), raw bytes pass through. Numeric ABCs cover numpy scalars
    (np.int64/np.float32 — the natural output of the pipeline) so they
    serialize as proper bigint/double wire bytes, and anything
    unrecognized is REJECTED rather than silently str()-encoded."""
    import numbers

    if v is None:
        return None
    if isinstance(v, bytes):
        return v
    if isinstance(v, bool) or (
        hasattr(v, "dtype") and getattr(v.dtype, "kind", "") == "b"
        and getattr(v, "ndim", 0) == 0      # scalar only, never arrays
    ):
        return b"\x01" if bool(v) else b"\x00"
    if isinstance(v, numbers.Integral):
        return struct.pack(">q", int(v))
    if isinstance(v, numbers.Real):
        return struct.pack(">d", float(v))
    if isinstance(v, str):
        return v.encode()
    raise TypeError(
        f"cannot bind {type(v).__name__} as a CQL value; pass "
        f"str/int/float/bool/bytes/None"
    )


class CqlError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(f"CQL error 0x{code:04x}: {message}")
        self.code = code


class CqlConnection:
    """One CQL v3 native-protocol connection: frame framing, STARTUP
    handshake, QUERY / PREPARE / EXECUTE round trips."""

    VERSION_REQ = 0x03        # protocol v3 request
    VERSION_RESP = 0x83

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout_s)
        self._stream = 0
        self._startup()

    # -- framing ---------------------------------------------------------
    def _send_frame(self, opcode: int, body: bytes):
        self._stream = (self._stream + 1) % 32768
        self.sock.sendall(struct.pack(
            ">BBhBi", self.VERSION_REQ, 0, self._stream, opcode, len(body)
        ) + body)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("cassandra peer closed")
            buf += chunk
        return buf

    def _recv_frame(self) -> Tuple[int, bytes]:
        hdr = self._recv_exact(9)
        version, _flags, _stream, opcode, length = struct.unpack(
            ">BBhBi", hdr
        )
        if version != self.VERSION_RESP:
            raise ConnectionError(
                f"unexpected protocol version 0x{version:02x}"
            )
        body = self._recv_exact(length) if length else b""
        if opcode == OP_ERROR:
            (code,) = struct.unpack_from(">i", body, 0)
            msg, _ = _read_string(body, 4)
            raise CqlError(code, msg)
        return opcode, body

    # -- handshake -------------------------------------------------------
    def _startup(self):
        self._send_frame(OP_STARTUP, _string_map({"CQL_VERSION": "3.0.0"}))
        opcode, _ = self._recv_frame()
        if opcode != OP_READY:
            raise ConnectionError(
                f"STARTUP not acknowledged (opcode 0x{opcode:02x})"
            )

    # -- requests --------------------------------------------------------
    def query(self, cql: str) -> Any:
        """QUERY with consistency ONE, no bound values."""
        body = _long_string(cql) + struct.pack(
            ">HB", CONSISTENCY_ONE, 0
        )
        self._send_frame(OP_QUERY, body)
        return self._result()

    def prepare(self, cql: str) -> bytes:
        self._send_frame(OP_PREPARE, _long_string(cql))
        opcode, body = self._recv_frame()
        (kind,) = struct.unpack_from(">i", body, 0)
        if opcode != OP_RESULT or kind != RESULT_PREPARED:
            raise ConnectionError("PREPARE did not return PREPARED")
        (n,) = struct.unpack_from(">H", body, 4)
        return body[6:6 + n]      # [short bytes] statement id

    def execute(self, stmt_id: bytes, values: List[Any]) -> Any:
        body = struct.pack(">H", len(stmt_id)) + stmt_id
        # <consistency><flags=0x01 VALUES><n><value...>
        body += struct.pack(">HBH", CONSISTENCY_ONE, 0x01, len(values))
        for v in values:
            body += _bytes_value(encode_value(v))
        self._send_frame(OP_EXECUTE, body)
        return self._result()

    def _result(self) -> Any:
        opcode, body = self._recv_frame()
        if opcode != OP_RESULT:
            raise ConnectionError(f"expected RESULT, got 0x{opcode:02x}")
        (kind,) = struct.unpack_from(">i", body, 0)
        if kind in (RESULT_VOID, RESULT_SET_KEYSPACE):
            return None
        if kind == RESULT_ROWS:
            return self._parse_rows(body[4:])
        raise ConnectionError(f"unsupported RESULT kind {kind}")

    @staticmethod
    def _parse_rows(body: bytes) -> List[List[Optional[bytes]]]:
        """Rows result: metadata (no paging) + raw cell bytes. Cells come
        back as bytes; the caller decodes by its own schema knowledge
        (the spec subset omits result metadata types: flag
        NO_METADATA-style minimalism, matching MiniCassandra)."""
        (flags, col_count) = struct.unpack_from(">ii", body, 0)
        off = 8
        if flags & 0x0001:       # global table spec
            _, off = _read_string(body, off)
            _, off = _read_string(body, off)
        names = []
        for _ in range(col_count):
            name, off = _read_string(body, off)
            names.append(name)
            off += 2             # option id (type); subset: opaque
        (row_count,) = struct.unpack_from(">i", body, off)
        off += 4
        rows = []
        for _ in range(row_count):
            row = []
            for _ in range(col_count):
                cell, off = _read_bytes(body, off)
                row.append(cell)
            rows.append(row)
        return rows

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class CassandraSink(Sink):
    """ref CassandraSink.addSink(...).setQuery(...): elements bind into a
    prepared INSERT. ``extractor(element) -> tuple of bind values``.
    INSERT on the same primary key upserts, so deterministic keys give
    idempotent replay (the reference's exactly-once recipe)."""

    def __init__(self, host: str, port: int, insert_cql: str,
                 extractor=lambda e: e, setup_cql: Optional[List[str]] = None):
        self.host = host
        self.port = port
        self.insert_cql = insert_cql
        self.extractor = extractor
        self.setup_cql = setup_cql or []
        self.conn: Optional[CqlConnection] = None
        self._stmt: Optional[bytes] = None
        self.stats = {"writes": 0}

    def open(self):
        self.conn = CqlConnection(self.host, self.port)
        for cql in self.setup_cql:
            self.conn.query(cql)
        self._stmt = self.conn.prepare(self.insert_cql)

    def invoke_batch(self, elements: List[Any]):
        for e in elements:
            self.conn.execute(self._stmt, list(self.extractor(e)))
            self.stats["writes"] += 1

    def snapshot_state(self):
        # writes are synchronous request/response here, so the cut never
        # covers an unacknowledged write (the reference waits on its
        # async futures at snapshot; ref CassandraSinkBase.checkAsyncErrors)
        return None

    def close(self):
        if self.conn is not None:
            self.conn.close()


# ---------------------------------------------------------------- test peer
class MiniCassandra:
    """In-repo CQL v3 native-protocol server (the MiniKafkaBroker
    pattern): real frames on a real TCP socket over a dict store.

    CQL subset: CREATE TABLE t (cols..., PRIMARY KEY (k)) | INSERT INTO
    t (cols) VALUES (?...) via PREPARE/EXECUTE or literals via QUERY |
    SELECT cols|* FROM t [WHERE k = v]. Types are schema-free: cells
    store the client's serialized bytes verbatim and SELECT returns
    them; key equality compares serialized forms."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.tables: Dict[str, Dict[bytes, dict]] = {}
        self.schemas: Dict[str, Tuple[List[str], str]] = {}  # cols, pk
        self.prepared: Dict[bytes, str] = {}
        self._next_stmt = 1
        self._lock = threading.Lock()
        mini = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        hdr = self._recv_exact(9)
                        if hdr is None:
                            return
                        version, _f, stream, opcode, length = \
                            struct.unpack(">BBhBi", hdr)
                        body = (self._recv_exact(length) if length
                                else b"")
                        resp_op, resp = mini._dispatch(opcode, body)
                        self.request.sendall(struct.pack(
                            ">BBhBi", 0x83, 0, stream, resp_op, len(resp)
                        ) + resp)
                except (ConnectionError, OSError):
                    return

            def _recv_exact(self, n):
                buf = b""
                while len(buf) < n:
                    chunk = self.request.recv(n - len(buf))
                    if not chunk:
                        return None
                    buf += chunk
                return buf

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Srv((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mini-cassandra",
        )

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    # -- protocol dispatch ----------------------------------------------
    def _dispatch(self, opcode: int, body: bytes) -> Tuple[int, bytes]:
        if opcode == OP_OPTIONS:
            return OP_SUPPORTED, _string_map({})
        if opcode == OP_STARTUP:
            return OP_READY, b""
        if opcode == OP_PREPARE:
            cql, _ = _read_long_string(body, 0)
            with self._lock:
                sid = struct.pack(">i", self._next_stmt)
                self._next_stmt += 1
                self.prepared[sid] = cql
            return OP_RESULT, (
                struct.pack(">i", RESULT_PREPARED)
                + struct.pack(">H", len(sid)) + sid
                + struct.pack(">ii", 0, 0)    # empty metadata
            )
        if opcode == OP_QUERY:
            cql, off = _read_long_string(body, 0)
            return self._run_cql(cql, [])
        if opcode == OP_EXECUTE:
            (n,) = struct.unpack_from(">H", body, 0)
            sid = body[2:2 + n]
            off = 2 + n
            _cons, flags = struct.unpack_from(">HB", body, off)
            off += 3
            values: List[Optional[bytes]] = []
            if flags & 0x01:
                (vn,) = struct.unpack_from(">H", body, off)
                off += 2
                for _ in range(vn):
                    v, off = _read_bytes(body, off)
                    values.append(v)
            with self._lock:
                cql = self.prepared.get(sid)
            if cql is None:
                return OP_ERROR, struct.pack(">i", 0x2500) + _string(
                    "unprepared statement")
            return self._run_cql(cql, values)
        return OP_ERROR, struct.pack(">i", 0x000A) + _string(
            f"unsupported opcode 0x{opcode:02x}")

    # -- CQL subset ------------------------------------------------------
    def _run_cql(self, cql: str, values: List[Optional[bytes]]
                 ) -> Tuple[int, bytes]:
        s = cql.strip().rstrip(";")
        m = re.match(
            r"CREATE TABLE (?:IF NOT EXISTS )?(\w+)\s*\((.*)\)$",
            s, re.IGNORECASE | re.DOTALL,
        )
        if m:
            name = m.group(1)
            inner = m.group(2)
            pk = re.search(r"PRIMARY KEY\s*\(\s*(\w+)\s*\)", inner,
                            re.IGNORECASE)
            cols = [
                c.strip().split()[0]
                for c in inner.split(",")
                if c.strip() and not c.strip().upper().startswith(
                    "PRIMARY")
            ]
            with self._lock:
                if name not in self.schemas:
                    self.schemas[name] = (
                        cols, pk.group(1) if pk else cols[0]
                    )
                    self.tables[name] = {}
            return OP_RESULT, struct.pack(">i", RESULT_VOID)
        m = re.match(
            r"INSERT INTO (\w+)\s*\(([^)]*)\)\s*VALUES\s*\(([^)]*)\)$",
            s, re.IGNORECASE,
        )
        if m:
            name = m.group(1)
            cols = [c.strip() for c in m.group(2).split(",")]
            vals_sql = [v.strip() for v in m.group(3).split(",")]
            with self._lock:
                if name not in self.schemas:
                    return OP_ERROR, struct.pack(">i", 0x2200) + _string(
                        f"unconfigured table {name}")
                _schema_cols, pk = self.schemas[name]
                row = {}
                qi = 0
                for c, vs in zip(cols, vals_sql):
                    if vs == "?":
                        row[c] = values[qi]
                        qi += 1
                    elif vs.startswith("'"):
                        row[c] = vs.strip("'").encode()
                    elif "." in vs:
                        row[c] = struct.pack(">d", float(vs))
                    else:
                        row[c] = struct.pack(">q", int(vs))
                key = row.get(pk, b"")
                self.tables[name][key] = row       # upsert by PK
            return OP_RESULT, struct.pack(">i", RESULT_VOID)
        m = re.match(
            r"SELECT (.*?) FROM (\w+)(?:\s+WHERE\s+(\w+)\s*=\s*(.*))?$",
            s, re.IGNORECASE,
        )
        if m:
            name = m.group(2)
            with self._lock:
                if name not in self.schemas:
                    return OP_ERROR, struct.pack(">i", 0x2200) + _string(
                        f"unconfigured table {name}")
                schema_cols, _pk = self.schemas[name]
                want = (
                    schema_cols if m.group(1).strip() == "*"
                    else [c.strip() for c in m.group(1).split(",")]
                )
                rows = list(self.tables[name].values())
                if m.group(3):
                    col, lit = m.group(3), m.group(4).strip()
                    if lit.startswith("'"):
                        target = lit.strip("'").encode()
                    elif "." in lit:
                        target = struct.pack(">d", float(lit))
                    else:
                        target = struct.pack(">q", int(lit))
                    rows = [r for r in rows if r.get(col) == target]
            body = struct.pack(">i", RESULT_ROWS)
            body += struct.pack(">ii", 0x0001, len(want))  # global spec
            body += _string("ks") + _string(name)
            for c in want:
                body += _string(c) + struct.pack(">H", 0)  # opaque type
            body += struct.pack(">i", len(rows))
            for r in rows:
                for c in want:
                    body += _bytes_value(r.get(c))
            return OP_RESULT, body
        return OP_ERROR, struct.pack(">i", 0x2000) + _string(
            f"unsupported CQL: {cql[:80]}")

    # -- test inspection -------------------------------------------------
    def row_count(self, table: str) -> int:
        with self._lock:
            return len(self.tables.get(table, {}))
