"""Redis connector — the flink-connector-redis analog (SURVEY §2.8,
ref flink-streaming-connectors/flink-connector-redis/RedisSink.java +
common/mapper/RedisCommand.java + common/container/RedisContainer.java;
the reference wraps the Jedis client library).

This is a WIRE client: it speaks RESP2, the public REdis Serialization
Protocol (inline framing ``*<n>\\r\\n`` arrays of ``$<len>\\r\\n`` bulk
strings for requests; ``+simple``, ``-error``, ``:integer``, ``$bulk``
and ``*array`` replies), implemented from the protocol spec — no redis
client library.

No Redis server exists in this image (zero egress), so tests run the
client against ``MiniRedis`` below — an in-repo server implementing the
same public RESP protocol on a real TCP socket over a small keyspace
(strings, hashes, lists, sets, sorted sets, pub/sub counters). That
proves the byte-level seam; against a genuine server only host:port
changes.

Semantics (the reference's):
  * ``RedisSink`` writes one command per element through a
    ``RedisMapper`` (command + key + value extraction —
    RedisMapper.java's getCommandDescription/getKeyFromData/
    getValueFromData triple);
  * the command catalog matches RedisCommand.java: LPUSH RPUSH SADD
    SET PFADD PUBLISH ZADD HSET, each bound to its data type so
    misconfiguration fails fast (RedisCommandDescription.java validates
    the additional-key requirement for HASH/SORTED_SET);
  * at-least-once via flush-on-checkpoint (writes are synchronous
    request/reply, so the sink is flushed at every invoke return);
    exactly-once effect for SET/HSET/ZADD/SADD/PFADD through Redis's
    native last-write-wins/set semantics — deterministic keys make
    replay idempotent; LPUSH/RPUSH/PUBLISH replay at-least-once (the
    reference documents the same split by data type).
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.runtime.sinks import Sink

# command -> (data type, needs additional key) — RedisCommand.java +
# RedisCommandDescription.java's validation table
COMMANDS: Dict[str, Tuple[str, bool]] = {
    "LPUSH": ("LIST", False),
    "RPUSH": ("LIST", False),
    "SADD": ("SET", False),
    "SET": ("STRING", False),
    "PFADD": ("HYPER_LOG_LOG", False),
    "PUBLISH": ("PUBSUB", False),
    "ZADD": ("SORTED_SET", True),
    "HSET": ("HASH", True),
}


class RedisError(RuntimeError):
    pass


# --------------------------------------------------------------------------
# RESP2 wire protocol
# --------------------------------------------------------------------------
def encode_command(*parts: str) -> bytes:
    """Request framing: an array of bulk strings (RESP spec,
    'Sending commands to a Redis server')."""
    out = [b"*%d\r\n" % len(parts)]
    for p in parts:
        b = p.encode() if isinstance(p, str) else bytes(p)
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


class _Reader:
    """Incremental RESP reply parser over a socket file."""

    def __init__(self, rfile):
        self.rfile = rfile

    def _line(self) -> bytes:
        line = self.rfile.readline()
        if not line:
            raise RedisError("connection closed mid-reply")
        return line.rstrip(b"\r\n")

    def read(self):
        line = self._line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            body = self.rfile.read(n + 2)
            return body[:-2].decode()
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self.read() for _ in range(n)]
        raise RedisError(f"bad RESP type byte {kind!r}")


class RedisConnection:
    """One RESP connection (the Jedis-instance analog in
    RedisContainer.java)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout_s)
        self.rfile = self.sock.makefile("rb")
        self._reader = _Reader(self.rfile)
        self._lock = threading.Lock()

    def execute(self, *parts: str):
        with self._lock:
            self.sock.sendall(encode_command(*parts))
            return self._reader.read()

    def close(self):
        try:
            self.rfile.close()
            self.sock.close()
        except OSError:
            pass


# --------------------------------------------------------------------------
# Sink
# --------------------------------------------------------------------------
class RedisMapper:
    """Command + key/value extraction triple (RedisMapper.java).
    ``additional_key`` names the hash / sorted set that HSET / ZADD
    target (RedisCommandDescription.java)."""

    def __init__(self, command: str,
                 key_from: Callable[[Any], str],
                 value_from: Callable[[Any], str],
                 additional_key: Optional[str] = None):
        cmd = command.upper()
        if cmd not in COMMANDS:
            raise ValueError(
                f"unknown redis command {command!r}; "
                f"supported: {sorted(COMMANDS)}"
            )
        dtype, needs_extra = COMMANDS[cmd]
        if needs_extra and additional_key is None:
            # fail at construction, not on the hot path
            raise ValueError(
                f"{cmd} writes to a {dtype}: additional_key (the "
                f"{dtype.lower()} name) is required"
            )
        self.command = cmd
        self.data_type = dtype
        self.key_from = key_from
        self.value_from = value_from
        self.additional_key = additional_key


class RedisSink(Sink):
    """Per-element command writes through a RedisMapper
    (RedisSink.java invoke -> RedisCommandsContainer dispatch)."""

    def __init__(self, host: str, port: int, mapper: RedisMapper):
        self.host = host
        self.port = port
        self.mapper = mapper
        self._conn: Optional[RedisConnection] = None

    def open(self, ctx=None):
        self._conn = RedisConnection(self.host, self.port)

    def invoke_batch(self, elements):
        if self._conn is None:
            self.open()
        m = self.mapper
        for e in elements:
            key, value = m.key_from(e), m.value_from(e)
            if m.command == "ZADD":
                # ZADD <set> <score> <member>: the mapped "value" is the
                # score and the key is the member (RedisContainer.zadd)
                self._conn.execute("ZADD", m.additional_key, value, key)
            elif m.command == "HSET":
                self._conn.execute("HSET", m.additional_key, key, value)
            else:
                self._conn.execute(m.command, key, value)

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None


# --------------------------------------------------------------------------
# In-repo spec server
# --------------------------------------------------------------------------
class _Simple(str):
    """Marker: encode as a RESP simple string (+OK) rather than a bulk
    string. A plain-``str`` reply is ALWAYS bulk-encoded — user data may
    legitimately start with '+' or contain CRLF, and simple-string
    framing would corrupt it / desync the connection."""


class MiniRedis:
    """In-repo RESP2 server over a real TCP socket: strings, hashes,
    lists, sets, sorted sets, PFADD (exact-set stand-in), PUBLISH
    (delivery counted), PING/ECHO/DEL/FLUSHALL and read-back commands
    for tests. The MiniKafkaBroker pattern: the public protocol is the
    test boundary, not a mock of the client."""

    def __init__(self):
        self.strings: Dict[str, str] = {}
        self.hashes: Dict[str, Dict[str, str]] = {}
        self.lists: Dict[str, List[str]] = {}
        self.sets: Dict[str, set] = {}
        self.zsets: Dict[str, Dict[str, float]] = {}
        self.published: Dict[str, List[str]] = {}
        self._lock = threading.Lock()
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self.port: Optional[int] = None

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        store = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                reader = _Reader(self.rfile)
                while True:
                    try:
                        parts = reader.read()
                    except RedisError:
                        return
                    if not isinstance(parts, list) or not parts:
                        return
                    try:
                        reply = store._exec([str(p) for p in parts])
                    except RedisError as e:
                        reply = e
                    except Exception as e:
                        # malformed arguments (bad ZADD score, missing
                        # args) answer -ERR like a real server instead of
                        # killing the connection with a stack trace
                        reply = RedisError(
                            f"{type(e).__name__}: {e}"
                        )
                    self.wfile.write(store._encode_reply(reply))
                    self.wfile.flush()

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Srv((host, port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="miniredis").start()
        return self.port

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @staticmethod
    def _encode_reply(r) -> bytes:
        if isinstance(r, RedisError):
            return b"-ERR %s\r\n" % str(r).encode()
        if isinstance(r, bool):
            return b":%d\r\n" % int(r)
        if isinstance(r, int):
            return b":%d\r\n" % r
        if r is None:
            return b"$-1\r\n"
        if isinstance(r, _Simple):
            return b"+%s\r\n" % str(r).encode()
        if isinstance(r, str):
            b = r.encode()
            return b"$%d\r\n%s\r\n" % (len(b), b)
        if isinstance(r, list):
            return b"*%d\r\n" % len(r) + b"".join(
                MiniRedis._encode_reply(x) for x in r
            )
        raise TypeError(type(r))

    def _exec(self, parts: List[str]):
        cmd, args = parts[0].upper(), parts[1:]
        with self._lock:
            if cmd == "PING":
                return _Simple("PONG")
            if cmd == "ECHO":
                return args[0]
            if cmd == "SET":
                self.strings[args[0]] = args[1]
                return _Simple("OK")
            if cmd == "GET":
                return self.strings.get(args[0])
            if cmd == "DEL":
                n = 0
                for k in args:
                    for store in (self.strings, self.hashes, self.lists,
                                  self.sets, self.zsets):
                        if k in store:
                            del store[k]
                            n += 1
                return n
            if cmd == "FLUSHALL":
                for store in (self.strings, self.hashes, self.lists,
                              self.sets, self.zsets, self.published):
                    store.clear()
                return _Simple("OK")
            if cmd == "HSET":
                h = self.hashes.setdefault(args[0], {})
                new = args[1] not in h
                h[args[1]] = args[2]
                return new
            if cmd == "HGET":
                return self.hashes.get(args[0], {}).get(args[1])
            if cmd == "HGETALL":
                out: List[str] = []
                for k, v in self.hashes.get(args[0], {}).items():
                    out.extend((k, v))
                return out
            if cmd in ("LPUSH", "RPUSH"):
                lst = self.lists.setdefault(args[0], [])
                for v in args[1:]:
                    lst.insert(0, v) if cmd == "LPUSH" else lst.append(v)
                return len(lst)
            if cmd == "LRANGE":
                lst = self.lists.get(args[0], [])
                start, stop = int(args[1]), int(args[2])
                stop = len(lst) if stop == -1 else stop + 1
                return lst[start:stop]
            if cmd in ("SADD", "PFADD"):
                s = self.sets.setdefault(args[0], set())
                n = sum(1 for v in args[1:] if v not in s)
                s.update(args[1:])
                return n
            if cmd == "SCARD":
                return len(self.sets.get(args[0], set()))
            if cmd == "SMEMBERS":
                return sorted(self.sets.get(args[0], set()))
            if cmd == "ZADD":
                z = self.zsets.setdefault(args[0], {})
                n = 0
                for score, member in zip(args[1::2], args[2::2]):
                    if member not in z:
                        n += 1
                    z[member] = float(score)
                return n
            if cmd == "ZSCORE":
                v = self.zsets.get(args[0], {}).get(args[1])
                return None if v is None else repr(v) if v != int(v) \
                    else str(int(v))
            if cmd == "ZRANGE":
                z = self.zsets.get(args[0], {})
                members = sorted(z, key=lambda m: (z[m], m))
                start, stop = int(args[1]), int(args[2])
                stop = len(members) if stop == -1 else stop + 1
                return members[start:stop]
            if cmd == "PUBLISH":
                self.published.setdefault(args[0], []).append(args[1])
                return 1
            raise RedisError(f"unknown command '{cmd}'")
