"""File connectors: continuous directory monitoring source + exactly-once
rolling file sink.

ContinuousFileSource — ref ContinuousFileMonitoringFunction +
ContinuousFileReaderOperator (SURVEY §2.5 sources/sinks): scans a directory,
emits lines of new/grown files; PROCESS_ONCE ends after draining the initial
scan, PROCESS_CONTINUOUSLY keeps watching. Replay state = per-file byte
positions.

BucketingFileSink — ref BucketingSink/RollingSink (SURVEY §2.8): elements
are appended to an in-progress part file per bucket; each checkpoint records
the flushed valid length, and restore TRUNCATES files back to the snapshot
length (the reference's truncate/valid-length mechanism), making the sink
exactly-once end-to-end under replay. close() finalizes part files by
renaming away the in-progress suffix.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, Dict, List, Optional

from flink_tpu.runtime.sinks import Sink
from flink_tpu.runtime.sources import Source

PROCESS_ONCE = "once"
PROCESS_CONTINUOUSLY = "continuously"


class ContinuousFileSource(Source):
    def __init__(self, directory: str, pattern: str = "*",
                 mode: str = PROCESS_ONCE):
        self.directory = directory
        self.pattern = pattern
        self.mode = mode
        self.positions: Dict[str, int] = {}   # path -> bytes consumed
        self._initial: Optional[set] = None

    def _scan(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.directory, self.pattern)))

    def open(self):
        # PROCESS_ONCE fixes the file set at job start (ref
        # FileProcessingMode.PROCESS_ONCE: one monitoring pass); a restored
        # source keeps the ORIGINAL attempt's file set for deterministic
        # replay (see snapshot_offsets)
        if self.mode == PROCESS_ONCE and self._initial is None:
            self._initial = set(self._scan())

    def poll(self, max_records: int):
        once = self.mode == PROCESS_ONCE
        lines: List[str] = []
        paths = self._scan()
        if once:
            paths = [p for p in paths if p in self._initial]
        exhausted = True
        for path in paths:
            pos = self.positions.get(path, 0)
            try:
                size = os.path.getsize(path)
            except FileNotFoundError:
                continue  # deleted between scan and read (e.g. log rotation)
            if pos >= size:
                continue
            try:
                f = open(path, "rb")
            except FileNotFoundError:
                continue
            with f:
                f.seek(pos)
                while len(lines) < max_records:
                    line = f.readline()
                    if not line:
                        break
                    if not line.endswith(b"\n"):
                        if once:
                            # bounded input: the unterminated tail is final
                            pos += len(line)
                            lines.append(
                                line.decode("utf-8", errors="replace")
                            )
                        # else: a writer may still be appending; re-read
                        # next poll
                        break
                    pos += len(line)
                    lines.append(line.decode("utf-8", errors="replace")
                                 .rstrip("\n"))
                self.positions[path] = pos
                try:
                    if pos < os.path.getsize(path):
                        exhausted = False
                except FileNotFoundError:
                    pass  # deleted mid-read: treat as fully consumed
            if len(lines) >= max_records:
                exhausted = False
                break
        if self.mode == PROCESS_CONTINUOUSLY:
            return lines, False
        return lines, exhausted

    def snapshot_offsets(self):
        return {
            "positions": dict(self.positions),
            "initial": sorted(self._initial) if self._initial is not None
            else None,
        }

    def restore_offsets(self, state):
        if isinstance(state, dict) and "positions" in state:
            self.positions = dict(state["positions"])
            self._initial = (
                set(state["initial"]) if state["initial"] is not None else None
            )
        else:  # pre-initial-set snapshots (positions only)
            self.positions = dict(state)


class BucketingFileSink(Sink):
    IN_PROGRESS = ".in-progress"

    def __init__(self, base_path: str,
                 bucketer: Optional[Callable[[Any], str]] = None,
                 formatter: Callable[[Any], str] = str):
        self.base_path = base_path
        self.bucketer = bucketer or (lambda e: "bucket-0")
        self.formatter = formatter
        self._files: Dict[str, Any] = {}   # bucket -> open file object

    def _path(self, bucket: str, in_progress: bool = True) -> str:
        d = os.path.join(self.base_path, bucket)
        os.makedirs(d, exist_ok=True)
        return os.path.join(
            d, "part-0" + (self.IN_PROGRESS if in_progress else "")
        )

    def _file(self, bucket: str):
        f = self._files.get(bucket)
        if f is None:
            f = open(self._path(bucket), "ab")
            self._files[bucket] = f
        return f

    def invoke_batch(self, elements):
        for e in elements:
            b = self.bucketer(e)
            self._file(b).write(
                (self.formatter(e) + "\n").encode("utf-8")
            )

    # -- exactly-once hooks (driven by the executor's checkpoint cut) ----
    def snapshot_state(self):
        lengths = {}
        for bucket, f in self._files.items():
            f.flush()
            os.fsync(f.fileno())
            lengths[bucket] = f.tell()
        return {"valid_lengths": lengths}

    def restore_state(self, state):
        for bucket, f in list(self._files.items()):
            f.close()
        self._files.clear()
        valid = state.get("valid_lengths", {}) if state else {}
        # truncate any in-progress file back to its checkpointed length;
        # files unknown to the snapshot are leftovers of the failed attempt.
        # recursive glob: bucketers may return nested paths (date/hour)
        for path in glob.glob(
            os.path.join(self.base_path, "**", "part-0" + self.IN_PROGRESS),
            recursive=True,
        ):
            bucket = os.path.relpath(os.path.dirname(path), self.base_path)
            keep = valid.get(bucket, 0)
            with open(path, "ab") as f:
                f.truncate(keep)

    def close(self):
        for f in self._files.values():
            f.close()
        self._files.clear()
        # finalize EVERY in-progress part under the base path, including
        # buckets restored from a checkpoint but untouched since recovery —
        # their truncated contents are checkpoint-valid and must be published
        for path in glob.glob(
            os.path.join(self.base_path, "**", "part-0" + self.IN_PROGRESS),
            recursive=True,
        ):
            os.replace(path, path[: -len(self.IN_PROGRESS)])
