"""StreamTableEnvironment: SQL GROUP BY over event-time windows.

The flink-table streaming capability (SURVEY §2.7,
flink-table/.../StreamTableEnvironment.scala): a SQL query with a window
function in GROUP BY runs as a streaming job through the SAME device
window kernels the DataStream API uses (ops/window_kernels.py) — the SQL
front-end is a thin planner that lowers to key_by + window + aggregate.

Supported query shape (one aggregate, any number of group keys):

    SELECT k1[, k2...], AGG(vcol) [AS name]
    FROM <stream>
    [WHERE <pred over columns>]
    GROUP BY k1[, k2...], TUMBLE(rowtime, INTERVAL '<n>' SECOND)
                        | HOP(rowtime, INTERVAL '<slide>' SECOND,
                              INTERVAL '<size>' SECOND)
                        | SESSION(rowtime, INTERVAL '<gap>' SECOND)

AGG in SUM/COUNT/MIN/MAX. The rowtime argument of the window function
names a COLUMN of the stream (epoch milliseconds); event time is
assigned from it after any WHERE filter, so filtering never misaligns
timestamps. The result table carries the group keys, a `window_end_ms`
column (TUMBLE_END analog; sessions also get `window_start_ms`), and the
aggregate. Bounded streams run to completion; the collected emissions
are returned as a Table.

DOCUMENTED DIVERGENCE from the reference: one aggregate per query (the
device window state holds one reduce accumulator per key); run several
queries for several aggregates. The reference's retraction/dynamic-table
machinery is out of scope — append-only results, as its 1.x streaming SQL
examples produce.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Optional

import numpy as np

from flink_tpu.table.table import Table, _parse_expr, _split_commas

_WINFN = re.compile(
    r"^\s*(?P<kind>TUMBLE|HOP|SESSION)\s*\(\s*(?P<rowtime>\w+)\s*,\s*"
    r"INTERVAL\s+'(?P<a>\d+(?:\.\d+)?)'\s+(?P<ua>SECOND|MINUTE|HOUR)"
    r"(?:\s*,\s*INTERVAL\s+'(?P<b>\d+(?:\.\d+)?)'\s+"
    r"(?P<ub>SECOND|MINUTE|HOUR))?\s*\)\s*$",
    re.IGNORECASE,
)

_AGG = re.compile(
    r"^\s*(?P<fn>SUM|COUNT|MIN|MAX)\s*\(\s*(?P<col>\w+)\s*\)"
    r"(?:\s+AS\s+(?P<alias>\w+))?\s*$",
    re.IGNORECASE,
)

_SQL = re.compile(
    r"^\s*SELECT\s+(?P<select>.+?)\s+FROM\s+(?P<from>\w+)"
    r"(?:\s+WHERE\s+(?P<where>.+?))?"
    r"\s+GROUP\s+BY\s+(?P<group>.+?)\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)

_MS = {"SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000}


def _to_ms(val: str, unit: str) -> int:
    return int(float(val) * _MS[unit.upper()])


class StreamTableEnvironment:
    """SQL planner over registered columnar streams.

    register_stream(name, build) registers a factory returning
    (env, datastream) where the datastream's records are column dicts and
    `rowtime` timestamps ride the source (GeneratorSource-style); each
    sql_query() call builds and executes a fresh job from it.
    """

    def __init__(self):
        self._streams: Dict[str, Callable] = {}

    @staticmethod
    def create() -> "StreamTableEnvironment":
        return StreamTableEnvironment()

    def register_stream(self, name: str, build: Callable):
        """build() -> (StreamExecutionEnvironment, DataStream of column
        dicts — including the rowtime column window functions will name).
        A factory, not an instance: each query is its own job."""
        self._streams[name] = build

    # ------------------------------------------------------------------
    def sql_query(self, query: str) -> Table:
        m = _SQL.match(query)
        if not m:
            raise ValueError(f"unsupported streaming SQL shape: {query!r}")
        if m.group("from") not in self._streams:
            raise KeyError(f"unknown stream {m.group('from')!r}")

        # GROUP BY: plain keys + exactly one window function
        keys, winfn = [], None
        for item in _split_top(m.group("group")):
            wm = _WINFN.match(item)
            if wm:
                if winfn is not None:
                    raise ValueError("multiple window functions in GROUP BY")
                winfn = wm
            else:
                keys.append(item.strip())
        if winfn is None:
            raise ValueError(
                "streaming GROUP BY requires a TUMBLE/HOP/SESSION window "
                "(unbounded global aggregation has no append-only result)"
            )
        if not keys:
            raise ValueError("streaming GROUP BY needs at least one key")

        # SELECT: group keys (in any order) + one aggregate
        agg = None
        sel_keys = []
        for item in _split_top(m.group("select")):
            am = _AGG.match(item)
            if am:
                if agg is not None:
                    raise ValueError(
                        "one aggregate per streaming query (run another "
                        "query for another aggregate)"
                    )
                agg = am
            elif item.strip() in keys:
                sel_keys.append(item.strip())
            else:
                raise ValueError(
                    f"SELECT item {item.strip()!r} is neither a GROUP BY "
                    f"key nor an aggregate"
                )
        if agg is None:
            raise ValueError("streaming query needs an aggregate")
        fn = agg.group("fn").upper()
        vcol = agg.group("col")
        out_name = agg.group("alias") or f"{fn.lower()}_{vcol}"

        kind = winfn.group("kind").upper()
        where = m.group("where")
        return self._run(kind, winfn, keys, sel_keys, fn, vcol, out_name,
                         where, m.group("from"))

    # ------------------------------------------------------------------
    def _run(self, kind, winfn, keys, sel_keys, fn, vcol, out_name, where,
             stream_name) -> Table:
        from flink_tpu.datastream.window.assigners import (
            EventTimeSessionWindows,
        )
        from flink_tpu.runtime.sinks import CollectSink

        env, ds = self._streams[stream_name]()
        if where is not None:
            pred = _parse_expr(where)
            ds = ds.map(_filter_cols(pred))
        # event time comes from the rowtime COLUMN the window function
        # names, assigned AFTER any WHERE filter — deriving it from the
        # column keeps timestamps aligned with filtered rows (a source-
        # side timestamp array would keep pre-filter length and pair
        # survivors with the wrong rows' times)
        rt = winfn.group("rowtime")
        ds = ds.assign_timestamps_and_watermarks(
            lambda c, _rt=rt: c[_rt]
        )
        if len(keys) == 1:
            key_of = lambda c, k=keys[0]: c[k]
        else:
            def key_of(c):
                # composite key: an OBJECT array of tuples so KeyCodec
                # takes the stable per-object hash (a 2-D numeric array
                # would corrupt the identity encoding); originals come
                # back through the reverse map at emission
                arrs = [np.asarray(c[k]).tolist() for k in keys]
                out = np.empty(len(arrs[0]), dtype=object)
                out[:] = list(zip(*arrs))
                return out

        keyed = ds.key_by(key_of)
        if kind == "TUMBLE":
            size = _to_ms(winfn.group("a"), winfn.group("ua"))
            win = keyed.time_window(size)
        elif kind == "HOP":
            slide = _to_ms(winfn.group("a"), winfn.group("ua"))
            size = _to_ms(winfn.group("b"), winfn.group("ub"))
            win = keyed.time_window(size, slide)
        else:  # SESSION
            gap = _to_ms(winfn.group("a"), winfn.group("ua"))
            win = keyed.window(EventTimeSessionWindows.with_gap(gap))

        ext = (lambda c: c[vcol])
        if fn == "SUM":
            agg_stream = win.sum(ext)
        elif fn == "COUNT":
            agg_stream = win.count()
        elif fn == "MIN":
            agg_stream = win.min(ext)
        else:
            agg_stream = win.max(ext)

        sink = CollectSink()
        agg_stream.add_sink(sink)
        env.execute(f"sql-{kind.lower()}-{stream_name}")

        # results -> Table: unpack composite keys back into key columns
        cols: Dict[str, list] = {k: [] for k in (sel_keys or keys)}
        cols["window_end_ms"] = []
        if kind == "SESSION":
            cols["window_start_ms"] = []
        cols[out_name] = []
        for r in sink.results:
            kv = r.key if len(keys) > 1 else (r.key,)
            for k, v in zip(keys, kv):
                if k in cols:
                    cols[k].append(v)
            cols["window_end_ms"].append(r.window_end_ms)
            if kind == "SESSION":
                cols["window_start_ms"].append(r.window_start_ms)
            cols[out_name].append(r.value)
        return Table({k: np.asarray(v) for k, v in cols.items()})


def _filter_cols(pred):
    """Columnar WHERE: keep only rows matching the Expr predicate."""
    def f(cols):
        n = len(next(iter(cols.values())))
        mask = np.asarray(pred.eval(cols, n), bool)
        return {k: np.asarray(v)[mask] for k, v in cols.items()}

    return f


def _split_top(s: str):
    """table.py's paren-aware comma splitter, minus empty items."""
    return [x for x in (p.strip() for p in _split_commas(s)) if x]
