"""Table API + SQL subset (ref flink-table, SURVEY §2.7)."""

from flink_tpu.table.streaming import StreamTableEnvironment
from flink_tpu.table.table import Expr, Table, TableEnvironment, col, lit

__all__ = ["Table", "TableEnvironment", "StreamTableEnvironment", "Expr",
           "col", "lit"]
