"""Table API + SQL subset (ref flink-table, SURVEY §2.7)."""

from flink_tpu.table.table import Expr, Table, TableEnvironment, col, lit

__all__ = ["Table", "TableEnvironment", "Expr", "col", "lit"]
