"""Table API + minimal SQL — the flink-table analog (SURVEY §2.7:
Calcite-planned Table/SQL over DataSet/DataStream), columnar-native:

A Table IS a dict of equal-length numpy columns (the Row batch), and every
relational operator is a vectorized array program: selections are boolean
masks, projections are column arithmetic, grouped aggregations
dictionary-encode keys and segment-reduce values on the device (the same
kernel shape as the streaming window path — where the reference code-gens
Janino functions, this design lowers to XLA).

Expression DSL:    col("a") + 1, (col("a") > 5) & (col("b") == "x"),
                   col("a").sum.alias("total")
SQL subset:        SELECT ... FROM t [JOIN u ON t.k = u.k] [WHERE ...]
                   [GROUP BY ...] [ORDER BY ... [DESC]] [LIMIT n]
                   (JOIN: equi-joins, INNER/LEFT/RIGHT/FULL, lowered to the
                   columnar hash join; select columns post-join by their
                   bare names, right-side clashes as r_<name>)
The SQL front-end parses via Python's ast over translated operators —
deliberately small, covering the SELECT shape the reference's examples use.
Streaming GROUP BY over event-time windows lives in
table/streaming.py (StreamTableEnvironment: TUMBLE/HOP/SESSION).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

_AGGS = ("sum", "avg", "min", "max", "count")


class Expr:
    """Column expression tree evaluated against a column dict."""

    def __init__(self, fn: Callable[[Dict[str, np.ndarray], int], np.ndarray],
                 name: str, agg: Optional[Tuple[str, "Expr"]] = None):
        self._fn = fn
        self.name = name
        self.agg = agg          # ('sum', inner) for aggregate expressions

    def eval(self, cols: Dict[str, np.ndarray], n: int) -> np.ndarray:
        return self._fn(cols, n)

    def alias(self, name: str) -> "Expr":
        e = Expr(self._fn, name, self.agg)
        return e

    # -- operators -------------------------------------------------------
    def _bin(self, other, op, sym):
        o = other if isinstance(other, Expr) else lit(other)
        return Expr(
            lambda c, n: op(self.eval(c, n), o.eval(c, n)),
            f"({self.name}{sym}{o.name})",
        )

    def __add__(self, o):
        return self._bin(o, lambda a, b: a + b, "+")

    def __radd__(self, o):
        return lit(o)._bin(self, lambda a, b: a + b, "+")

    def __sub__(self, o):
        return self._bin(o, lambda a, b: a - b, "-")

    def __rsub__(self, o):
        return lit(o)._bin(self, lambda a, b: a - b, "-")

    def __mul__(self, o):
        return self._bin(o, lambda a, b: a * b, "*")

    def __rmul__(self, o):
        return lit(o)._bin(self, lambda a, b: a * b, "*")

    def __truediv__(self, o):
        return self._bin(o, lambda a, b: a / b, "/")

    def __mod__(self, o):
        return self._bin(o, lambda a, b: a % b, "%")

    def __gt__(self, o):
        return self._bin(o, lambda a, b: a > b, ">")

    def __ge__(self, o):
        return self._bin(o, lambda a, b: a >= b, ">=")

    def __lt__(self, o):
        return self._bin(o, lambda a, b: a < b, "<")

    def __le__(self, o):
        return self._bin(o, lambda a, b: a <= b, "<=")

    def __eq__(self, o):  # noqa: A003
        return self._bin(o, lambda a, b: a == b, "==")

    def __ne__(self, o):
        return self._bin(o, lambda a, b: a != b, "!=")

    def __and__(self, o):
        return self._bin(o, lambda a, b: a & b, "&")

    def __or__(self, o):
        return self._bin(o, lambda a, b: a | b, "|")

    def __invert__(self):
        return Expr(lambda c, n: ~self.eval(c, n), f"~{self.name}")

    def __hash__(self):
        return id(self)

    # -- aggregates ------------------------------------------------------
    def _mk_agg(self, kind: str) -> "Expr":
        return Expr(self._fn, f"{kind}_{self.name}", agg=(kind, self))

    @property
    def sum(self) -> "Expr":
        return self._mk_agg("sum")

    @property
    def avg(self) -> "Expr":
        return self._mk_agg("avg")

    @property
    def min(self) -> "Expr":  # noqa: A003
        return self._mk_agg("min")

    @property
    def max(self) -> "Expr":  # noqa: A003
        return self._mk_agg("max")

    @property
    def count(self) -> "Expr":
        return self._mk_agg("count")


def col(name: str) -> Expr:
    return Expr(lambda c, n, _k=name: c[_k], name)


def lit(v: Any) -> Expr:
    return Expr(lambda c, n, _v=v: np.full(n, _v), repr(v))


from flink_tpu.ops.segment import grouped_reduce as _segment  # noqa: E402
# (shared device scatter-reduce; same kernel the DataSet group_by path uses)


class Table:
    def __init__(self, cols: Dict[str, np.ndarray]):
        self.cols = {k: np.asarray(v) for k, v in cols.items()}
        ns = {len(v) for v in self.cols.values()}
        if len(ns) > 1:
            raise ValueError("ragged columns")
        self.n = ns.pop() if ns else 0

    # -- info ------------------------------------------------------------
    @property
    def schema(self) -> List[str]:
        return list(self.cols)

    def count(self) -> int:
        return self.n

    def to_rows(self) -> List[tuple]:
        names = self.schema
        return list(zip(*[self.cols[c].tolist() for c in names]))

    def to_dicts(self) -> List[dict]:
        names = self.schema
        return [dict(zip(names, r)) for r in self.to_rows()]

    # -- relational ops --------------------------------------------------
    def select(self, *exprs) -> "Table":
        exprs = [col(e) if isinstance(e, str) else e for e in exprs]
        if any(e.agg for e in exprs):
            # global aggregation (no grouping): one group
            return self._aggregate(None, exprs)
        return Table({e.name: e.eval(self.cols, self.n) for e in exprs})

    def where(self, pred: Expr) -> "Table":
        mask = np.asarray(pred.eval(self.cols, self.n), bool)
        return Table({k: v[mask] for k, v in self.cols.items()})

    filter = where  # noqa: A003

    def group_by(self, *keys: str) -> "GroupedTable":
        return GroupedTable(self, [
            k.name if isinstance(k, Expr) else k for k in keys
        ])

    def _aggregate(self, keys: Optional[List[str]], exprs) -> "Table":
        if keys:
            key_arrays = [self.cols[k] for k in keys]
            rows = list(zip(*[a.tolist() for a in key_arrays]))
            # dict-based grouping (insertion order): np.unique cannot sort
            # object rows containing None (outer-join gaps) — SQL groups
            # NULL keys as their own group
            first: Dict[tuple, int] = {}
            gid = np.empty(self.n, np.int64)
            for i, r in enumerate(rows):
                g = first.setdefault(r, len(first))
                gid[i] = g
            uniq = list(first)
            G = len(uniq)
            out: Dict[str, np.ndarray] = {}
            for i, k in enumerate(keys):
                out[k] = np.asarray([u[i] for u in uniq])
        else:
            gid = np.zeros(self.n, np.int64)
            G = 1
            out = {}
        for e in exprs:
            if e.agg is None:
                if keys and e.name in keys:
                    continue
                raise ValueError(
                    f"non-aggregate column {e.name!r} outside GROUP BY keys"
                )
            kind, inner = e.agg
            vals = (
                inner.eval(self.cols, self.n) if kind != "count"
                else np.zeros(self.n)
            )
            out[e.name] = _segment(kind, gid, vals, G)
        return Table(out)

    def join(self, other: "Table", left_key: str,
             right_key: Optional[str] = None, how: str = "inner") -> "Table":
        if how not in ("inner", "left", "right", "full"):
            raise ValueError(f"unsupported join type {how!r}")
        rk = right_key or left_key
        build: Dict[Any, List[int]] = {}
        for i, v in enumerate(other.cols[rk].tolist()):
            build.setdefault(v, []).append(i)
        li, ri = [], []
        matched_right = set()
        for i, v in enumerate(self.cols[left_key].tolist()):
            rows = build.get(v)
            if rows:
                matched_right.add(v)
                for j in rows:
                    li.append(i)
                    ri.append(j)
            elif how in ("left", "full"):
                li.append(i)
                ri.append(-1)
        if how in ("right", "full"):
            for v, rows in build.items():
                if v not in matched_right:
                    for j in rows:
                        li.append(-1)
                        ri.append(j)
        li = np.asarray(li, np.int64)
        ri = np.asarray(ri, np.int64)

        def take(v, idx):
            t = v[np.maximum(idx, 0)]
            return np.where(idx >= 0, t, None) if (idx < 0).any() else t

        out = {k: take(v, li) for k, v in self.cols.items()}
        for k, v in other.cols.items():
            if k == rk and rk == left_key:
                # shared key column: fill left-side gaps from the right
                out[k] = np.where(li >= 0, out[k], take(v, ri))
                continue
            name = k if k not in out else f"r_{k}"
            out[name] = take(v, ri)
        return Table(out)

    def order_by(self, key: str, ascending: bool = True) -> "Table":
        k = key.name if isinstance(key, Expr) else key
        vals = self.cols[k]
        if vals.dtype == object and any(v is None for v in vals.tolist()):
            # outer joins produce None gaps: sort non-null values, NULLS
            # LAST (the SQL default for ascending order)
            none_mask = np.asarray([v is None for v in vals.tolist()])
            idx_non = np.nonzero(~none_mask)[0]
            idx_non = idx_non[np.argsort(vals[idx_non], kind="stable")]
            if not ascending:
                idx_non = idx_non[::-1]
            idx = np.concatenate([idx_non, np.nonzero(none_mask)[0]])
        else:
            idx = np.argsort(vals, kind="stable")
            if not ascending:
                idx = idx[::-1]
        return Table({c: v[idx] for c, v in self.cols.items()})

    def limit(self, n: int) -> "Table":
        return Table({c: v[:n] for c, v in self.cols.items()})

    def union_all(self, other: "Table") -> "Table":
        return Table({
            c: np.concatenate([self.cols[c], other.cols[c]])
            for c in self.schema
        })

    def distinct(self) -> "Table":
        rows = self.to_rows()
        seen, keep = set(), []
        for i, r in enumerate(rows):
            if r not in seen:
                seen.add(r)
                keep.append(i)
        idx = np.asarray(keep, np.int64)
        return Table({c: v[idx] for c, v in self.cols.items()})


class GroupedTable:
    def __init__(self, table: Table, keys: List[str]):
        self.table = table
        self.keys = keys

    def select(self, *exprs) -> Table:
        exprs = [col(e) if isinstance(e, str) else e for e in exprs]
        return self.table._aggregate(self.keys, exprs)


class TableEnvironment:
    """ref BatchTableEnvironment: table registry + SQL entry point."""

    def __init__(self):
        self._tables: Dict[str, Table] = {}

    @staticmethod
    def create() -> "TableEnvironment":
        return TableEnvironment()

    def from_columns(self, cols: Dict[str, Sequence]) -> Table:
        return Table({k: np.asarray(v) for k, v in cols.items()})

    def from_rows(self, rows: List[tuple], names: List[str]) -> Table:
        arrays = list(zip(*rows)) if rows else [[] for _ in names]
        return Table({n: np.asarray(a) for n, a in zip(names, arrays)})

    def from_dataset(self, ds, names: List[str]) -> Table:
        return self.from_rows(ds.collect(), names)

    def register_table(self, name: str, table: Table):
        self._tables[name] = table

    def scan(self, name: str) -> Table:
        return self._tables[name]

    # -- SQL subset ------------------------------------------------------
    _SQL = re.compile(
        r"^\s*SELECT\s+(?P<select>.+?)\s+FROM\s+(?P<from>\w+)"
        r"(?:\s+(?P<jhow>INNER|LEFT(?:\s+OUTER)?|RIGHT(?:\s+OUTER)?"
        r"|FULL(?:\s+OUTER)?)?\s*JOIN\s+(?P<jtable>\w+)\s+ON\s+"
        r"(?P<jleft>\w+(?:\.\w+)?)\s*=\s*(?P<jright>\w+(?:\.\w+)?))?"
        r"(?:\s+WHERE\s+(?P<where>.+?))?"
        r"(?:\s+GROUP\s+BY\s+(?P<group>.+?))?"
        r"(?:\s+ORDER\s+BY\s+(?P<order>.+?))?"
        r"(?:\s+LIMIT\s+(?P<limit>\d+))?\s*;?\s*$",
        re.IGNORECASE | re.DOTALL,
    )

    def sql_query(self, query: str) -> Table:
        m = self._SQL.match(query)
        if not m:
            raise ValueError(f"unsupported SQL shape: {query!r}")
        t = self.scan(m.group("from"))
        if m.group("jtable"):
            # equi-JOIN lowered to the columnar hash join (Table.join);
            # `a.k` qualifiers bind the key to its table — the ON clause
            # may list the two sides in either order (clashing right
            # columns surface under the r_ prefix, see join())
            how = (m.group("jhow") or "inner").split()[0].lower()
            jt = m.group("jtable")
            right = self.scan(jt)
            ft = m.group("from")

            def side_of(ref: str) -> Optional[str]:
                if "." in ref:
                    qual = ref.split(".")[0]
                    if qual not in (ft, jt):
                        raise ValueError(
                            f"ON qualifier {qual!r} names neither "
                            f"{ft!r} nor {jt!r}"
                        )
                    return "left" if qual == ft else "right"
                return None      # unqualified: resolve by schema below

            refs = [m.group("jleft"), m.group("jright")]
            sides = [side_of(r) for r in refs]
            cols_ = [r.split(".")[-1] for r in refs]
            if sides[0] == sides[1] and sides[0] is not None:
                raise ValueError("ON clause must reference both tables")
            if "left" in sides:
                lk = cols_[sides.index("left")]
                rk = cols_[1 - sides.index("left")]
            elif "right" in sides:
                rk = cols_[sides.index("right")]
                lk = cols_[1 - sides.index("right")]
            else:
                # both unqualified: bind by schema membership
                lk, rk = cols_
                if lk not in t.schema and rk in t.schema:
                    lk, rk = rk, lk
            t = t.join(right, lk, rk, how=how)
        if m.group("where"):
            t = t.where(_parse_expr(m.group("where")))
        select_items = _split_commas(m.group("select"))
        exprs = (
            None if select_items == ["*"]
            else [_parse_select_item(s) for s in select_items]
        )
        if m.group("group"):
            keys = [k.strip() for k in _split_commas(m.group("group"))]
            t = t.group_by(*keys).select(*(exprs or keys))
        elif exprs is not None:
            t = t.select(*exprs)
        if m.group("order"):
            spec = m.group("order").strip()
            desc = bool(re.search(r"\s+DESC$", spec, re.IGNORECASE))
            key = re.sub(r"\s+(DESC|ASC)$", "", spec, flags=re.IGNORECASE)
            t = t.order_by(key.strip(), ascending=not desc)
        if m.group("limit"):
            t = t.limit(int(m.group("limit")))
        return t


def _split_commas(s: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return out


def _parse_select_item(s: str) -> Expr:
    m = re.match(r"^(.+?)\s+AS\s+(\w+)$", s.strip(), re.IGNORECASE)
    alias = None
    if m:
        s, alias = m.group(1), m.group(2)
    e = _parse_expr(s)
    return e.alias(alias) if alias else e


def _parse_expr(s: str) -> Expr:
    """SQL fragment -> Expr via the Python ast (SQL operators translated
    first: = -> ==, AND/OR/NOT -> and/or/not, aggregate calls -> .agg
    props). String literals are pulled out BEFORE keyword rewriting so
    values like 'AND' or 'a=b' survive untouched."""
    literals: List[str] = []

    def stash(m):
        literals.append(m.group(1).replace("''", "'"))
        return f"__lit{len(literals) - 1}__"

    py = re.sub(r"'((?:[^']|'')*)'", stash, s)
    py = re.sub(r"(?<![<>=!])=(?!=)", "==", py)
    # python's `and`/`or`/`not` have SQL's precedence (below comparisons);
    # the builder turns BoolOp into elementwise &/|
    py = re.sub(r"\bAND\b", "and", py, flags=re.IGNORECASE)
    py = re.sub(r"\bOR\b", "or", py, flags=re.IGNORECASE)
    py = re.sub(r"\bNOT\b", "not", py, flags=re.IGNORECASE)
    py = re.sub(r"\bCOUNT\s*\(\s*\*\s*\)", "COUNT(__star__)", py,
                flags=re.IGNORECASE)
    tree = ast.parse(py, mode="eval")

    def build(node) -> Any:
        if isinstance(node, ast.Expression):
            return build(node.body)
        if isinstance(node, ast.Name):
            if node.id == "__star__":
                return lit(1.0)
            m = re.fullmatch(r"__lit(\d+)__", node.id)
            if m:
                return lit(literals[int(m.group(1))])
            return col(node.id)
        if isinstance(node, ast.Constant):
            return lit(node.value)
        if isinstance(node, ast.Compare):
            left = build(node.left)
            right = build(node.comparators[0])
            opmap = {
                ast.Gt: Expr.__gt__, ast.GtE: Expr.__ge__,
                ast.Lt: Expr.__lt__, ast.LtE: Expr.__le__,
                ast.Eq: Expr.__eq__, ast.NotEq: Expr.__ne__,
            }
            return opmap[type(node.ops[0])](left, right)
        if isinstance(node, ast.BinOp):
            opmap = {
                ast.Add: Expr.__add__, ast.Sub: Expr.__sub__,
                ast.Mult: Expr.__mul__, ast.Div: Expr.__truediv__,
                ast.Mod: Expr.__mod__, ast.BitAnd: Expr.__and__,
                ast.BitOr: Expr.__or__,
            }
            return opmap[type(node.op)](build(node.left), build(node.right))
        if isinstance(node, ast.BoolOp):
            parts = [build(v) for v in node.values]
            acc = parts[0]
            for p in parts[1:]:
                acc = (acc & p) if isinstance(node.op, ast.And) else (acc | p)
            return acc
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, (ast.Invert, ast.Not)):
                return ~build(node.operand)
            if isinstance(node.op, ast.USub):
                return lit(0) - build(node.operand)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            fname = node.func.id.lower()
            if fname in _AGGS:
                inner = build(node.args[0])
                return inner._mk_agg(fname)
        raise ValueError(f"unsupported SQL expression: {s!r}")

    return build(tree)
