"""Table API + minimal SQL — the flink-table analog (SURVEY §2.7:
Calcite-planned Table/SQL over DataSet/DataStream), columnar-native:

A Table IS a dict of equal-length numpy columns (the Row batch), and every
relational operator is a vectorized array program: selections are boolean
masks, projections are column arithmetic, grouped aggregations
dictionary-encode keys and segment-reduce values on the device (the same
kernel shape as the streaming window path — where the reference code-gens
Janino functions, this design lowers to XLA).

Expression DSL:    col("a") + 1, (col("a") > 5) & (col("b") == "x"),
                   col("a").sum.alias("total")
SQL subset:        SELECT ... FROM t [JOIN u ON t.k = u.k] [WHERE ...]
                   [GROUP BY ...] [ORDER BY ... [DESC]] [LIMIT n]
                   (JOIN: equi-joins, INNER/LEFT/RIGHT/FULL, lowered to the
                   columnar hash join; select columns post-join by their
                   bare names, right-side clashes as r_<name>)
The SQL front-end parses via Python's ast over translated operators —
deliberately small, covering the SELECT shape the reference's examples use.
Streaming GROUP BY over event-time windows lives in
table/streaming.py (StreamTableEnvironment: TUMBLE/HOP/SESSION).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

_AGGS = ("sum", "avg", "min", "max", "count")


class Expr:
    """Column expression tree evaluated against a column dict."""

    def __init__(self, fn: Callable[[Dict[str, np.ndarray], int], np.ndarray],
                 name: str, agg: Optional[Tuple[str, "Expr"]] = None):
        self._fn = fn
        self.name = name
        self.agg = agg          # ('sum', inner) for aggregate expressions

    def eval(self, cols: Dict[str, np.ndarray], n: int) -> np.ndarray:
        return self._fn(cols, n)

    def alias(self, name: str) -> "Expr":
        e = Expr(self._fn, name, self.agg)
        return e

    # -- operators -------------------------------------------------------
    def _bin(self, other, op, sym):
        o = other if isinstance(other, Expr) else lit(other)
        return Expr(
            lambda c, n: op(self.eval(c, n), o.eval(c, n)),
            f"({self.name}{sym}{o.name})",
        )

    def __add__(self, o):
        return self._bin(o, lambda a, b: a + b, "+")

    def __radd__(self, o):
        return lit(o)._bin(self, lambda a, b: a + b, "+")

    def __sub__(self, o):
        return self._bin(o, lambda a, b: a - b, "-")

    def __rsub__(self, o):
        return lit(o)._bin(self, lambda a, b: a - b, "-")

    def __mul__(self, o):
        return self._bin(o, lambda a, b: a * b, "*")

    def __rmul__(self, o):
        return lit(o)._bin(self, lambda a, b: a * b, "*")

    def __truediv__(self, o):
        return self._bin(o, lambda a, b: a / b, "/")

    def __mod__(self, o):
        return self._bin(o, lambda a, b: a % b, "%")

    def __gt__(self, o):
        return self._bin(o, lambda a, b: a > b, ">")

    def __ge__(self, o):
        return self._bin(o, lambda a, b: a >= b, ">=")

    def __lt__(self, o):
        return self._bin(o, lambda a, b: a < b, "<")

    def __le__(self, o):
        return self._bin(o, lambda a, b: a <= b, "<=")

    def __eq__(self, o):  # noqa: A003
        return self._bin(o, lambda a, b: a == b, "==")

    def __ne__(self, o):
        return self._bin(o, lambda a, b: a != b, "!=")

    def __and__(self, o):
        return self._bin(o, lambda a, b: a & b, "&")

    def __or__(self, o):
        return self._bin(o, lambda a, b: a | b, "|")

    def __invert__(self):
        return Expr(lambda c, n: ~self.eval(c, n), f"~{self.name}")

    def __hash__(self):
        return id(self)

    # -- aggregates ------------------------------------------------------
    def _mk_agg(self, kind: str) -> "Expr":
        return Expr(self._fn, f"{kind}_{self.name}", agg=(kind, self))

    @property
    def sum(self) -> "Expr":
        return self._mk_agg("sum")

    @property
    def avg(self) -> "Expr":
        return self._mk_agg("avg")

    @property
    def min(self) -> "Expr":  # noqa: A003
        return self._mk_agg("min")

    @property
    def max(self) -> "Expr":  # noqa: A003
        return self._mk_agg("max")

    @property
    def count(self) -> "Expr":
        return self._mk_agg("count")


def col(name: str) -> Expr:
    return Expr(lambda c, n, _k=name: c[_k], name)


def lit(v: Any) -> Expr:
    return Expr(lambda c, n, _v=v: np.full(n, _v), repr(v))


# -- scalar function catalog (the Calcite operator-table slice the
# reference's examples use; flink-table/.../codegen/calls/ScalarOperators.
# scala generates Janino for these — here each is one vectorized numpy op)
def _str_map(fn):
    ufn = np.frompyfunc(fn, 1, 1)

    def apply(a):
        return ufn(np.asarray(a, object))

    return apply


def _like_to_re(pat: str):
    out = []
    for ch in pat:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


_MS = {"second": 1000, "minute": 60_000, "hour": 3_600_000,
       "day": 86_400_000}


def _extract(unit: str, ms):
    """EXTRACT(unit FROM epoch_ms) — temporal field access in UTC (ref
    Calcite EXTRACT lowering in ScalarOperators.scala)."""
    import datetime as _dt

    unit = unit.lower()
    if unit not in ("year", "month", "day", "hour", "minute", "second"):
        raise ValueError(f"EXTRACT unit {unit!r} unsupported")
    arr = np.asarray(ms, np.int64)

    def one(v):
        d = _dt.datetime.fromtimestamp(v / 1000, _dt.timezone.utc)
        return getattr(d, unit)

    return np.frompyfunc(one, 1, 1)(arr).astype(np.int64)


def _fn1(name, f):
    def make(a: Expr) -> Expr:
        return Expr(lambda c, n: f(a.eval(c, n)), f"{name}({a.name})")

    return make


_SCALAR_FNS: Dict[str, Callable] = {
    # arithmetic
    "abs": _fn1("ABS", np.abs),
    "round": _fn1("ROUND", np.round),
    "floor": _fn1("FLOOR", np.floor),
    "ceil": _fn1("CEIL", np.ceil),
    "sqrt": _fn1("SQRT", np.sqrt),
    "exp": _fn1("EXP", np.exp),
    "ln": _fn1("LN", np.log),
    "log10": _fn1("LOG10", np.log10),
    # string
    "upper": _fn1("UPPER", _str_map(lambda s: s.upper())),
    "lower": _fn1("LOWER", _str_map(lambda s: s.lower())),
    "trim": _fn1("TRIM", _str_map(lambda s: s.strip())),
    "length": _fn1("LENGTH", lambda a: np.asarray(
        [len(s) for s in np.asarray(a, object)], np.int64
    )),
}


def power(a: Expr, b: Expr) -> Expr:
    return Expr(lambda c, n: np.power(a.eval(c, n), b.eval(c, n)),
                f"POWER({a.name},{b.name})")


def concat(*parts: Expr) -> Expr:
    def f(c, n):
        evs = [np.asarray(p.eval(c, n), object) for p in parts]
        out = evs[0]
        for e in evs[1:]:
            out = np.asarray(
                [str(x) + str(y) for x, y in zip(out, e)], object
            )
        return out

    return Expr(f, f"CONCAT({','.join(p.name for p in parts)})")


def substring(a: Expr, start: Expr, length: Optional[Expr] = None) -> Expr:
    def f(c, n):
        s0 = np.asarray(start.eval(c, n), np.int64)
        ln = (np.asarray(length.eval(c, n), np.int64)
              if length is not None else None)
        vals = np.asarray(a.eval(c, n), object)
        out = []
        for i, s in enumerate(vals):
            b = max(0, int(s0[i]) - 1)          # SQL: 1-based
            out.append(
                s[b:b + int(ln[i])] if ln is not None else s[b:]
            )
        return np.asarray(out, object)

    return Expr(f, f"SUBSTRING({a.name})")


def like(a: Expr, pattern: str) -> Expr:
    rx = _like_to_re(pattern)

    def f(c, n):
        return np.asarray(
            [bool(rx.match(str(s))) for s in np.asarray(a.eval(c, n), object)]
        )

    return Expr(f, f"({a.name} LIKE {pattern!r})")


def if_(cond: Expr, then: Expr, else_: Expr) -> Expr:
    return Expr(
        lambda c, n: np.where(cond.eval(c, n), then.eval(c, n),
                              else_.eval(c, n)),
        f"IF({cond.name},{then.name},{else_.name})",
    )


from flink_tpu.ops.segment import grouped_reduce as _segment  # noqa: E402
# (shared device scatter-reduce; same kernel the DataSet group_by path uses)


def join_output_names(lschema, rschema, lks, rks) -> Dict[str, str]:
    """Right-column -> post-join name, shared by plan-time schema
    inference (TableEnvironment._build_logical) and the join executors
    so the two can never drift: merged key columns (same-named equi key)
    are absent (the left column carries them), clashing names get the
    ``r_`` prefix."""
    out_names = set(lschema)
    mapping: Dict[str, str] = {}
    for k in rschema:
        if k in rks and lks[rks.index(k)] == k:
            continue
        name = k if k not in out_names else f"r_{k}"
        mapping[k] = name
        out_names.add(name)
    return mapping


class Table:
    def __init__(self, cols: Dict[str, np.ndarray]):
        self.cols = {k: np.asarray(v) for k, v in cols.items()}
        ns = {len(v) for v in self.cols.values()}
        if len(ns) > 1:
            raise ValueError("ragged columns")
        self.n = ns.pop() if ns else 0

    # -- info ------------------------------------------------------------
    @property
    def schema(self) -> List[str]:
        return list(self.cols)

    def count(self) -> int:
        return self.n

    def to_rows(self) -> List[tuple]:
        names = self.schema
        return list(zip(*[self.cols[c].tolist() for c in names]))

    def to_dicts(self) -> List[dict]:
        names = self.schema
        return [dict(zip(names, r)) for r in self.to_rows()]

    # -- relational ops --------------------------------------------------
    def select(self, *exprs) -> "Table":
        exprs = [col(e) if isinstance(e, str) else e for e in exprs]
        if any(e.agg for e in exprs):
            # global aggregation (no grouping): one group
            return self._aggregate(None, exprs)
        return Table({e.name: e.eval(self.cols, self.n) for e in exprs})

    def where(self, pred: Expr) -> "Table":
        mask = np.asarray(pred.eval(self.cols, self.n), bool)
        return Table({k: v[mask] for k, v in self.cols.items()})

    filter = where  # noqa: A003

    def group_by(self, *keys: str) -> "GroupedTable":
        return GroupedTable(self, [
            k.name if isinstance(k, Expr) else k for k in keys
        ])

    def _aggregate(self, keys: Optional[List[str]], exprs) -> "Table":
        if keys:
            key_arrays = [self.cols[k] for k in keys]
            rows = list(zip(*[a.tolist() for a in key_arrays]))
            # dict-based grouping (insertion order): np.unique cannot sort
            # object rows containing None (outer-join gaps) — SQL groups
            # NULL keys as their own group
            first: Dict[tuple, int] = {}
            gid = np.empty(self.n, np.int64)
            for i, r in enumerate(rows):
                g = first.setdefault(r, len(first))
                gid[i] = g
            uniq = list(first)
            G = len(uniq)
            out: Dict[str, np.ndarray] = {}
            for i, k in enumerate(keys):
                out[k] = np.asarray([u[i] for u in uniq])
        else:
            gid = np.zeros(self.n, np.int64)
            G = 1
            out = {}
        for e in exprs:
            if e.agg is None:
                if keys and e.name in keys:
                    continue
                raise ValueError(
                    f"non-aggregate column {e.name!r} outside GROUP BY keys"
                )
            kind, inner = e.agg
            vals = (
                inner.eval(self.cols, self.n) if kind != "count"
                else np.zeros(self.n)
            )
            out[e.name] = _segment(kind, gid, vals, G)
        return Table(out)

    def join(self, other: "Table", left_key,
             right_key=None, how: str = "inner",
             residual: Optional[Expr] = None,
             _plan: Optional[List[str]] = None) -> "Table":
        """Hash join, single or composite keys (pass lists for multi-key
        ON conjuncts). For INNER joins the hash table is BUILT over the
        smaller side (the reference's cost-based build-side choice,
        JoinOperatorBase.JoinHint OPTIMIZER_CHOOSES); outer joins keep
        the right side as build (their missing-row bookkeeping is
        side-specific). `residual` filters the joined rows — the
        non-equi remainder of a composite ON clause."""
        if how not in ("inner", "left", "right", "full"):
            raise ValueError(f"unsupported join type {how!r}")
        lks = [left_key] if isinstance(left_key, str) else list(left_key)
        rks = (
            [right_key] if isinstance(right_key, str)
            else list(right_key) if right_key is not None else list(lks)
        )
        if len(lks) != len(rks):
            raise ValueError("left/right join key counts differ")

        def keyrows(t: "Table", names):
            arrays = [t.cols[k].tolist() for k in names]
            return (
                list(zip(*arrays)) if len(arrays) > 1 else arrays[0]
            )

        lrows = keyrows(self, lks)
        rrows = keyrows(other, rks)
        # cost-based build side: probe the bigger input, hash the smaller
        build_left = how == "inner" and self.n < other.n
        if _plan is not None:
            _plan.append(
                f"HashJoin(how={how}, keys={list(zip(lks, rks))}, "
                f"build={'left' if build_left else 'right'}"
                f"[{self.n if build_left else other.n} rows], "
                f"probe={other.n if build_left else self.n} rows"
                + (f", residual={residual.name}" if residual is not None
                   else "") + ")"
            )
        li, ri = [], []
        if build_left:
            build: Dict[Any, List[int]] = {}
            for i, v in enumerate(lrows):
                build.setdefault(v, []).append(i)
            for j, v in enumerate(rrows):
                for i in build.get(v, ()):
                    li.append(i)
                    ri.append(j)
        else:
            build = {}
            for j, v in enumerate(rrows):
                build.setdefault(v, []).append(j)
            matched_right = set()
            for i, v in enumerate(lrows):
                rows = build.get(v)
                if rows:
                    matched_right.add(v)
                    for j in rows:
                        li.append(i)
                        ri.append(j)
                elif how in ("left", "full"):
                    li.append(i)
                    ri.append(-1)
            if how in ("right", "full"):
                for v, rows in build.items():
                    if v not in matched_right:
                        for j in rows:
                            li.append(-1)
                            ri.append(j)
        li = np.asarray(li, np.int64)
        ri = np.asarray(ri, np.int64)

        def take(v, idx):
            t = v[np.maximum(idx, 0)]
            return np.where(idx >= 0, t, None) if (idx < 0).any() else t

        out = {k: take(v, li) for k, v in self.cols.items()}
        names = join_output_names(list(self.cols), list(other.cols),
                                  lks, rks)
        for k, v in other.cols.items():
            if k not in names:
                # shared key column: fill left-side gaps from the right
                out[k] = np.where(li >= 0, out[k], take(v, ri))
                continue
            out[names[k]] = take(v, ri)
        joined = Table(out)
        if residual is not None:
            joined = joined.where(residual)
        return joined

    def cross_join(self, other: "Table",
                   residual: Optional[Expr] = None,
                   _plan: Optional[List[str]] = None) -> "Table":
        """Nested-loop product for joins with NO equi conjunct (pure
        theta joins, ref NestedLoopJoin); `residual` is the ON predicate."""
        li = np.repeat(np.arange(self.n, dtype=np.int64), other.n)
        ri = np.tile(np.arange(other.n, dtype=np.int64), self.n)
        if _plan is not None:
            _plan.append(
                f"NestedLoopJoin({self.n}x{other.n} rows"
                + (f", on={residual.name}" if residual is not None else "")
                + ")"
            )
        out = {k: v[li] for k, v in self.cols.items()}
        names = join_output_names(list(self.cols), list(other.cols),
                                  [], [])
        for k, v in other.cols.items():
            out[names[k]] = v[ri]
        joined = Table(out)
        if residual is not None:
            joined = joined.where(residual)
        return joined

    def order_by(self, key: str, ascending: bool = True) -> "Table":
        k = key.name if isinstance(key, Expr) else key
        vals = self.cols[k]
        if vals.dtype == object and any(v is None for v in vals.tolist()):
            # outer joins produce None gaps: sort non-null values, NULLS
            # LAST (the SQL default for ascending order)
            none_mask = np.asarray([v is None for v in vals.tolist()])
            idx_non = np.nonzero(~none_mask)[0]
            idx_non = idx_non[np.argsort(vals[idx_non], kind="stable")]
            if not ascending:
                idx_non = idx_non[::-1]
            idx = np.concatenate([idx_non, np.nonzero(none_mask)[0]])
        else:
            idx = np.argsort(vals, kind="stable")
            if not ascending:
                idx = idx[::-1]
        return Table({c: v[idx] for c, v in self.cols.items()})

    def limit(self, n: int) -> "Table":
        return Table({c: v[:n] for c, v in self.cols.items()})

    def union_all(self, other: "Table") -> "Table":
        return Table({
            c: np.concatenate([self.cols[c], other.cols[c]])
            for c in self.schema
        })

    def distinct(self) -> "Table":
        rows = self.to_rows()
        seen, keep = set(), []
        for i, r in enumerate(rows):
            if r not in seen:
                seen.add(r)
                keep.append(i)
        idx = np.asarray(keep, np.int64)
        return Table({c: v[idx] for c, v in self.cols.items()})


class GroupedTable:
    def __init__(self, table: Table, keys: List[str]):
        self.table = table
        self.keys = keys

    def select(self, *exprs) -> Table:
        exprs = [col(e) if isinstance(e, str) else e for e in exprs]
        return self.table._aggregate(self.keys, exprs)


class TableEnvironment:
    """ref BatchTableEnvironment: table registry + SQL entry point."""

    def __init__(self):
        self._tables: Dict[str, Table] = {}

    @staticmethod
    def create() -> "TableEnvironment":
        return TableEnvironment()

    def from_columns(self, cols: Dict[str, Sequence]) -> Table:
        return Table({k: np.asarray(v) for k, v in cols.items()})

    def from_rows(self, rows: List[tuple], names: List[str]) -> Table:
        arrays = list(zip(*rows)) if rows else [[] for _ in names]
        return Table({n: np.asarray(a) for n, a in zip(names, arrays)})

    def from_dataset(self, ds, names: List[str]) -> Table:
        return self.from_rows(ds.collect(), names)

    def register_table(self, name: str, table: Table):
        self._tables[name] = table

    def scan(self, name: str) -> Table:
        return self._tables[name]

    # -- SQL subset ------------------------------------------------------
    _SQL = re.compile(
        r"^\s*SELECT\s+(?P<select>.+?)\s+FROM\s+(?P<from>\w+)"
        r"(?:\s+(?P<jhow>INNER|LEFT(?:\s+OUTER)?|RIGHT(?:\s+OUTER)?"
        r"|FULL(?:\s+OUTER)?)?\s*JOIN\s+(?P<jtable>\w+)\s+ON\s+"
        r"(?P<on>.+?))?"
        r"(?:\s+WHERE\s+(?P<where>.+?))?"
        r"(?:\s+GROUP\s+BY\s+(?P<group>.+?))?"
        r"(?:\s+HAVING\s+(?P<having>.+?))?"
        r"(?:\s+ORDER\s+BY\s+(?P<order>.+?))?"
        r"(?:\s+LIMIT\s+(?P<limit>\d+))?\s*;?\s*$",
        re.IGNORECASE | re.DOTALL,
    )

    def _analyze_on(self, ft: str, jt: str, on_sql: str, how: str,
                    lschema: List[str], rschema: List[str]):
        """ON condition -> equi conjuncts (composite hash-join keys) +
        residual predicate (the non-equi remainder, filtered post-join,
        rewritten to post-join column names). No equi conjunct at all
        lowers to the nested-loop product (inner only) — ref
        FlinkPlannerImpl's join condition split between hash-join keys
        and the remaining filter. Returns (lks, rks, residual_sql,
        clash)."""

        def side_of(ref: str) -> Optional[str]:
            if "." in ref:
                qual = ref.split(".")[0]
                if qual not in (ft, jt):
                    raise ValueError(
                        f"ON qualifier {qual!r} names neither "
                        f"{ft!r} nor {jt!r}"
                    )
                return "left" if qual == ft else "right"
            return None

        conjuncts = re.split(r"\s+AND\s+", on_sql, flags=re.IGNORECASE)
        lks, rks, residual_parts = [], [], []
        for cj in conjuncts:
            m = re.fullmatch(
                r"\s*(\w+(?:\.\w+)?)\s*=\s*(\w+(?:\.\w+)?)\s*", cj
            )
            if m:
                refs = [m.group(1), m.group(2)]
                sides = [side_of(r) for r in refs]
                cols_ = [r.split(".")[-1] for r in refs]
                if sides[0] == sides[1] and sides[0] is not None:
                    residual_parts.append(cj)    # same-side equality
                    continue
                if "left" in sides:
                    i = sides.index("left")
                    lk, rk = cols_[i], cols_[1 - i]
                elif "right" in sides:
                    i = sides.index("right")
                    rk, lk = cols_[i], cols_[1 - i]
                else:
                    lk, rk = cols_
                    if lk not in lschema and rk in lschema:
                        lk, rk = rk, lk
                lks.append(lk)
                rks.append(rk)
            else:
                residual_parts.append(cj)

        clash = (set(lschema) & set(rschema)) - {
            rk for lk, rk in zip(lks, rks) if lk == rk
        }
        residual_sql = None
        if residual_parts:
            # rewrite qualified refs to post-join column names: left
            # names stay bare, clashing right names carry the r_ prefix

            def rw(s: str) -> str:
                def sub(m):
                    qual, name = m.group(1), m.group(2)
                    if qual == jt and name in clash:
                        return f"r_{name}"
                    return name

                # identifiers only: a decimal literal like 1.5 must NOT
                # match as qual=1, name=5
                return re.sub(
                    r"\b([A-Za-z_]\w*)\.([A-Za-z_]\w*)\b", sub, s
                )

            residual_sql = " AND ".join(rw(c) for c in residual_parts)
        if residual_sql is not None and how != "inner":
            # correct outer-join ON-residual semantics gate MATCHING (the
            # unmatched row stays, null-extended) — a post-join filter
            # would be silently wrong, so refuse instead
            raise ValueError(
                "non-equi ON conditions are supported for INNER joins "
                "only; move the predicate to WHERE for filter semantics"
            )
        if not lks and how != "inner":
            raise ValueError(
                "outer joins require at least one equi condition in ON"
            )
        return lks, rks, residual_sql, clash

    # -- logical planning (see table/planner.py) -------------------------
    def _build_logical(self, m):
        """Parsed query -> unoptimized logical tree (the AST the rule
        pipeline rewrites — ref FlinkPlannerImpl's rel() step)."""
        from flink_tpu.table import planner as pl

        ft = m.group("from")
        t = self.scan(ft)
        node: object = pl.LScan(ft, t.n, list(t.schema))
        if m.group("jtable"):
            jt = m.group("jtable")
            right = self.scan(jt)
            how = (m.group("jhow") or "inner").split()[0].lower()
            lks, rks, residual_sql, clash = self._analyze_on(
                ft, jt, m.group("on"), how, list(t.schema),
                list(right.schema),
            )
            names = join_output_names(list(t.schema),
                                      list(right.schema), lks, rks)
            out = list(t.schema) + list(names.values())
            node = pl.LJoin(
                node, pl.LScan(jt, right.n, list(right.schema)),
                how, lks, rks, residual_sql, out, clash,
            )
        if m.group("where"):
            node = pl.LFilter(node, pl.split_conjuncts(m.group("where")))
        select_items = _split_commas(m.group("select"))
        star = select_items == ["*"]
        if m.group("group"):
            keys = [k.strip() for k in _split_commas(m.group("group"))]
            items = keys if star else select_items
            node = pl.LAggregate(node, keys, items, list(items))
        elif not star:
            node = pl.LProject(node, select_items, list(select_items))
        if m.group("having"):
            if not m.group("group"):
                raise ValueError("HAVING requires GROUP BY")
            hv = m.group("having")
            from flink_tpu.table.planner import stash_literals
            hv_no_lit, _ = stash_literals(hv)
            if re.search(
                r"\b(" + "|".join(_AGGS) + r")\s*\(", hv_no_lit,
                re.IGNORECASE,
            ):
                raise ValueError(
                    "HAVING references SELECT aliases and group keys; "
                    "alias the aggregate in SELECT (e.g. SUM(x) AS "
                    "total) and write HAVING total > ..."
                )
            node = pl.LFilter(node, pl.split_conjuncts(hv))
        if m.group("order"):
            node = pl.LSort(node, m.group("order").strip())
        if m.group("limit"):
            node = pl.LLimit(node, int(m.group("limit")))
        return node

    def _execute_logical(self, node, plan: Optional[List[str]]) -> Table:
        """Lower the (optimized) logical tree onto the columnar Table
        operators, recording the measured physical plan."""
        from flink_tpu.table import planner as pl

        if isinstance(node, pl.LScan):
            t = self.scan(node.name)
            if node.empty:
                t = t.limit(0)
            if node.keep is not None:
                t = Table({k: t.cols[k] for k in node.keep})
            if plan is not None:
                extra = (
                    f", cols={node.keep}" if node.keep is not None else ""
                )
                plan.append(f"Scan({node.name}, {t.n} rows{extra})")
            return t
        if isinstance(node, pl.LFilter):
            t = self._execute_logical(node.input, plan)
            n_in = t.n
            sql = " AND ".join(f"({c})" for c in node.conjuncts)
            t = t.where(_parse_expr(sql))
            if plan is not None:
                plan.append(
                    f"Filter({' AND '.join(node.conjuncts)}, {n_in} -> "
                    f"{t.n} rows, selectivity "
                    f"{t.n / n_in if n_in else 0:.2f})"
                )
            return t
        if isinstance(node, pl.LJoin):
            left = self._execute_logical(node.left, plan)
            right = self._execute_logical(node.right, plan)
            residual = (
                _parse_expr(node.residual_sql)
                if node.residual_sql else None
            )
            if node.lks:
                return left.join(right, node.lks, node.rks, how=node.how,
                                 residual=residual, _plan=plan)
            return left.cross_join(right, residual=residual, _plan=plan)
        if isinstance(node, pl.LAggregate):
            t = self._execute_logical(node.input, plan)
            exprs = [_parse_select_item(s) for s in node.items]
            t = t.group_by(*node.keys).select(*exprs)
            if plan is not None:
                plan.append(
                    f"HashAggregate(keys={node.keys}, {t.n} groups)"
                )
            return t
        if isinstance(node, pl.LProject):
            t = self._execute_logical(node.input, plan)
            exprs = [_parse_select_item(s) for s in node.items]
            t = t.select(*exprs)
            if plan is not None:
                plan.append(f"Project({[e.name for e in exprs]})")
            return t
        if isinstance(node, pl.LSort):
            t = self._execute_logical(node.input, plan)
            spec = node.spec
            desc = bool(re.search(r"\s+DESC$", spec, re.IGNORECASE))
            key = re.sub(r"\s+(DESC|ASC)$", "", spec, flags=re.IGNORECASE)
            t = t.order_by(key.strip(), ascending=not desc)
            if plan is not None:
                plan.append(f"Sort({spec})")
            return t
        if isinstance(node, pl.LLimit):
            t = self._execute_logical(node.input, plan)
            t = t.limit(node.n)
            if plan is not None:
                plan.append(f"Limit({node.n})")
            return t
        raise TypeError(f"unknown logical node {type(node).__name__}")

    @staticmethod
    def _split_union(query: str):
        """Top-level UNION [ALL] split, literal-aware (a quoted string
        containing the word UNION never splits). Returns
        ([branch_sql...], [op...]) with ops[i] the combinator between
        branch i and i+1 ("all" | "distinct")."""
        masked, unstash = TableEnvironment._mask_literals(query)
        parts = re.split(r"\bUNION(\s+ALL)?\b", masked,
                         flags=re.IGNORECASE)
        branches = parts[0::2]
        ops = ["all" if a else "distinct" for a in parts[1::2]]
        return [unstash(b).strip() for b in branches], ops

    @staticmethod
    def _mask_literals(sql: str):
        """Stash string literals behind \\x00N\\x00 markers so clause
        regexes can never match keywords INSIDE a quoted value. ONE
        implementation — the planner's stash_literals — so the quoting
        rule can never drift between the layers. Returns
        (masked, unstash)."""
        from flink_tpu.table.planner import stash_literals

        return stash_literals(sql)

    @staticmethod
    def _strip_trailing_masked(masked: str):
        """_strip_trailing's core on ALREADY-masked text (no literal can
        interfere); order_spec comes back still masked."""
        limit = None
        m = re.search(r"\s+LIMIT\s+(\d+)\s*;?\s*$", masked, re.IGNORECASE)
        if m:
            limit = int(m.group(1))
            masked = masked[:m.start()]
        order = None
        m = re.search(
            r"\s+ORDER\s+BY\s+"
            r"((?:(?!\b(?:WHERE|GROUP|HAVING|UNION|LIMIT)\b).)+?)\s*;?\s*$",
            masked, re.IGNORECASE | re.DOTALL,
        )
        if m:
            order = m.group(1).strip()
            masked = masked[:m.start()]
        return masked, order, limit

    @classmethod
    def _strip_trailing(cls, branch: str):
        """Pull a trailing ORDER BY / LIMIT off a query. Used where the
        clause must apply AFTER a set operation (DISTINCT dedupes before
        ORDER BY/LIMIT; a union's trailing clauses order/bound the WHOLE
        union, not its last branch). Returns (core, order_spec, limit).

        Literal-aware like _split_union: the clause regexes run on a
        MASKED copy, so a trailing string literal containing 'ORDER BY
        x' or 'LIMIT 5' (WHERE name = 'a ORDER BY b') is never stripped
        as a clause."""
        masked, unstash = cls._mask_literals(branch)
        masked, order, limit = cls._strip_trailing_masked(masked)
        return (
            unstash(masked),
            unstash(order) if order is not None else None,
            limit,
        )

    @staticmethod
    def _apply_trailing(t: Table, order: Optional[str],
                        limit: Optional[int],
                        plan: Optional[List[str]]) -> Table:
        if order is not None:
            desc = bool(re.search(r"\s+DESC$", order, re.IGNORECASE))
            key = re.sub(r"\s+(DESC|ASC)$", "", order, flags=re.IGNORECASE)
            t = t.order_by(key.strip(), ascending=not desc)
            if plan is not None:
                plan.append(f"Sort({order})")
        if limit is not None:
            t = t.limit(limit)
            if plan is not None:
                plan.append(f"Limit({limit})")
        return t

    def _sql_single(self, query: str, _plan: Optional[List[str]],
                    optimize: bool) -> Table:
        return self._exec_branch(query, _plan, optimize)[0]

    def _exec_branch(self, branch: str, plan: Optional[List[str]],
                     optimize: bool, want_render: bool = False):
        """ONE implementation of the per-branch pipeline (DISTINCT strip
        + clause reordering, parse, optimize, execute) shared by
        sql_query and explain, so the two can never accept different
        grammars. Returns (table, render) with render =
        (ast_txt, optimized_txt, rules) when requested."""
        from flink_tpu.table import planner as pl

        # ONE literal mask for the whole branch pipeline: the DISTINCT
        # strip, the trailing-clause strip, AND the grammar regex run on
        # masked text — a quoted value containing ORDER BY/LIMIT/WHERE
        # can never be parsed as syntax. Clause texts unstash on access
        # (_UnstashingMatch), so the planner sees the real SQL.
        masked, unstash = self._mask_literals(branch)
        masked, n_distinct = re.subn(
            r"^(\s*SELECT)\s+DISTINCT\b", r"\1", masked, count=1,
            flags=re.IGNORECASE,
        )
        order = limit = None
        if n_distinct:
            # SQL evaluates DISTINCT before ORDER BY/LIMIT: dedupe the
            # full result, then sort and bound it
            masked, order, limit = self._strip_trailing_masked(masked)
            if order is not None:
                order = unstash(order)
        m = self._SQL.match(masked)
        if not m:
            raise ValueError(f"unsupported SQL shape: {branch!r}")
        root = self._build_logical(_UnstashingMatch(m, unstash))
        opt, rules = pl.optimize(root) if optimize else (root, [])
        render = (
            (pl.render(root), pl.render(opt), rules) if want_render
            else None
        )
        out = self._execute_logical(opt, plan)
        if n_distinct:
            out = out.distinct()
            if plan is not None:
                plan.append("Distinct(first occurrence)")
            out = self._apply_trailing(out, order, limit, plan)
        return out, render

    @staticmethod
    def _check_union_schemas(a: Table, b: Table):
        if list(a.cols) != list(b.cols):
            raise ValueError(
                f"UNION branches must have the same columns: "
                f"{list(a.cols)} vs {list(b.cols)}"
            )
        for k in a.cols:
            sa = a.cols[k].dtype.kind in "OUS"
            sb = b.cols[k].dtype.kind in "OUS"
            if sa != sb:
                raise ValueError(
                    f"UNION column {k!r} mixes string and numeric "
                    f"branches ({a.cols[k].dtype} vs {b.cols[k].dtype}); "
                    f"numpy promotion would silently stringify values"
                )

    def sql_query(self, query: str, _plan: Optional[List[str]] = None,
                  optimize: bool = True) -> Table:
        """Parse -> logical plan -> rule rewriting -> execute.
        ``optimize=False`` runs the unrewritten tree (the baseline for
        plan-diff tests and the planner benchmark). UNION [ALL] runs
        each branch through the same pipeline and concatenates
        (deduplicating for plain UNION, SQL set semantics); a trailing
        ORDER BY/LIMIT applies to the WHOLE union."""
        branches, ops = self._split_union(query)
        order = limit = None
        if ops:
            branches[-1], order, limit = self._strip_trailing(
                branches[-1]
            )
        out = self._sql_single(branches[0], _plan, optimize)
        for op, branch in zip(ops, branches[1:]):
            nxt = self._sql_single(branch, _plan, optimize)
            self._check_union_schemas(out, nxt)
            out = out.union_all(nxt)
            if op == "distinct":
                out = out.distinct()
            if _plan is not None:
                _plan.append(f"Union({op})")
        if ops:
            out = self._apply_trailing(out, order, limit, _plan)
        return out

    def explain(self, query: str) -> str:
        """AST + rewritten logical plan + measured physical plan (ref
        TableEnvironment.explain / FlinkPlannerImpl.scala:46 — a rule
        pipeline over a logical tree, not a Calcite port). UNION
        queries explain each branch with the combinator between; the
        same schema checks run, so explain never claims a plan for a
        query sql_query would reject."""
        branches, ops = self._split_union(query)
        g_order = g_limit = None
        if ops:
            branches[-1], g_order, g_limit = self._strip_trailing(
                branches[-1]
            )
        sections = []
        prev: Optional[Table] = None
        for i, branch in enumerate(branches):
            plan: List[str] = []
            t, render = self._exec_branch(branch, plan, optimize=True,
                                          want_render=True)
            ast_txt, opt_txt, rules = render
            if prev is not None:
                self._check_union_schemas(prev, t)
            prev = t
            sections.append(
                "== Abstract Syntax Tree ==\n" + ast_txt
                + "\n\n== Optimized Logical Plan ==\n" + opt_txt
                + "\napplied: "
                + (", ".join(rules) if rules else "(none)")
                + "\n\n== Physical Plan ==\n" + "\n".join(plan)
            )
            if i < len(ops):
                sections.append(f"== UNION {ops[i].upper()} ==")
        if ops and (g_order is not None or g_limit is not None):
            tail: List[str] = []
            self._apply_trailing(prev, g_order, g_limit, tail)
            sections.append("== Union Result ==\n" + "\n".join(tail))
        return "\n\n".join(sections)


class _UnstashingMatch:
    """re.Match proxy whose group() restores stashed string literals:
    the grammar regex runs on MASKED text (no quoted value can match a
    clause keyword), while the planner keeps seeing the real SQL."""

    def __init__(self, m, unstash):
        self._m = m
        self._unstash = unstash

    def group(self, *args):
        g = self._m.group(*args)
        if isinstance(g, str):
            return self._unstash(g)
        if isinstance(g, tuple):
            return tuple(
                self._unstash(x) if isinstance(x, str) else x for x in g
            )
        return g


def _split_commas(s: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return out


def _parse_select_item(s: str) -> Expr:
    m = re.match(r"^(.+?)\s+AS\s+(\w+)$", s.strip(), re.IGNORECASE)
    alias = None
    if m:
        s, alias = m.group(1), m.group(2)
    e = _parse_expr(s)
    return e.alias(alias) if alias else e


def _rewrite_case(py: str) -> str:
    """CASE expressions -> nested IF(cond, then, else) calls, both
    forms: searched (CASE WHEN c THEN v ... ELSE d END) and simple
    (CASE x WHEN v THEN r ... ELSE d END, each WHEN an equality on x).
    Innermost-first so nested CASEs resolve bottom-up. ELSE is required:
    the subset has no SQL NULL to default to, and a silent default
    would be a wrong answer, not a convenience."""
    pat = re.compile(
        r"\bCASE\b((?:(?!\bCASE\b)(?!\bEND\b).)*?)\bEND\b",
        re.IGNORECASE | re.DOTALL,
    )

    def one(m: "re.Match") -> str:
        body = m.group(1)
        pieces = re.split(r"\bWHEN\b", body, flags=re.IGNORECASE)
        subject = pieces[0].strip()
        if len(pieces) < 2:
            raise ValueError(f"CASE without WHEN in {body!r}")
        branches = []
        else_val = None
        for part in pieces[1:]:
            seg = re.split(r"\bTHEN\b", part, flags=re.IGNORECASE)
            if len(seg) != 2:
                raise ValueError(f"WHEN without THEN in CASE {body!r}")
            cond, rest = seg[0].strip(), seg[1]
            er = re.split(r"\bELSE\b", rest, flags=re.IGNORECASE)
            val = er[0].strip()
            if len(er) == 2:
                else_val = er[1].strip()
            if subject:
                cond = f"(({subject}) = ({cond}))"
            branches.append((cond, val))
        if else_val is None:
            raise ValueError(
                "CASE requires an ELSE branch (this SQL subset has no "
                "NULL to default to)"
            )
        out = f"({else_val})"
        for cond, val in reversed(branches):
            out = f"IF(({cond}), ({val}), {out})"
        return out

    while pat.search(py):
        py = pat.sub(one, py, count=1)
    return py


def _parse_expr(s: str) -> Expr:
    """SQL fragment -> Expr via the Python ast (SQL operators translated
    first: = -> ==, AND/OR/NOT -> and/or/not, aggregate calls -> .agg
    props). String literals are pulled out BEFORE keyword rewriting so
    values like 'AND' or 'a=b' survive untouched."""
    literals: List[str] = []

    def stash(m):
        literals.append(m.group(1).replace("''", "'"))
        return f"__lit{len(literals) - 1}__"

    py = re.sub(r"'((?:[^']|'')*)'", stash, s)
    # SQL-only syntactic forms -> plain calls the Python ast can parse
    py = _rewrite_case(py)
    py = re.sub(r"\bEXTRACT\s*\(\s*(\w+)\s+FROM\s+", r"extract_\1(",
                py, flags=re.IGNORECASE)
    py = re.sub(r"(\w+(?:\.\w+)?|__lit\d+__)\s+LIKE\s+(__lit\d+__)",
                r"like(\1, \2)", py, flags=re.IGNORECASE)
    # [NOT] BETWEEN: the left operand may be an arithmetic chain
    # (`a + b BETWEEN lo AND hi` bounds the SUM); the parenthesization
    # keeps the inner `and`/`or` below any surrounding OR. NOT BETWEEN
    # must rewrite FIRST or the plain rule would mis-bind it.
    _chain = (r"((?:-?[\w.]+|__lit\d+__)"
              r"(?:\s*[-+*/%]\s*(?:-?[\w.]+|__lit\d+__))*)")
    _operand = r"(-?[\w.]+|__lit\d+__)"
    py = re.sub(
        _chain + r"\s+NOT\s+BETWEEN\s+" + _operand + r"\s+AND\s+"
        + _operand,
        r"((\1 < \2) or (\1 > \3))", py, flags=re.IGNORECASE,
    )
    py = re.sub(
        _chain + r"\s+BETWEEN\s+" + _operand + r"\s+AND\s+" + _operand,
        r"((\1 >= \2) and (\1 <= \3))", py, flags=re.IGNORECASE,
    )
    if re.search(r"\bBETWEEN\b", py, re.IGNORECASE):
        raise ValueError(
            f"unsupported BETWEEN shape in {s!r}: operands must be "
            f"columns, literals, or arithmetic chains of them"
        )
    py = re.sub(r"(?<![<>=!])=(?!=)", "==", py)
    # python's `and`/`or`/`not` have SQL's precedence (below comparisons);
    # the builder turns BoolOp into elementwise &/|
    py = re.sub(r"\bAND\b", "and", py, flags=re.IGNORECASE)
    py = re.sub(r"\bOR\b", "or", py, flags=re.IGNORECASE)
    py = re.sub(r"\bNOT\b", "not", py, flags=re.IGNORECASE)
    py = re.sub(r"\bIN\b", "in", py, flags=re.IGNORECASE)
    py = re.sub(r"\bCOUNT\s*\(\s*\*\s*\)", "COUNT(__star__)", py,
                flags=re.IGNORECASE)
    tree = ast.parse(py, mode="eval")

    def build(node) -> Any:
        if isinstance(node, ast.Expression):
            return build(node.body)
        if isinstance(node, ast.Name):
            if node.id == "__star__":
                return lit(1.0)
            m = re.fullmatch(r"__lit(\d+)__", node.id)
            if m:
                return lit(literals[int(m.group(1))])
            return col(node.id)
        if isinstance(node, ast.Constant):
            return lit(node.value)
        if isinstance(node, ast.Compare):
            left = build(node.left)
            if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                # X IN (a, b, c): membership as an OR of equalities.
                # `X IN (a)` parses as a parenthesized scalar, not a
                # tuple — standard SQL, so treat it as a one-element list
                members = node.comparators[0]
                elts = (
                    members.elts
                    if isinstance(members, (ast.Tuple, ast.List))
                    else [members]
                )
                acc = None
                for elt in elts:
                    eq = Expr.__eq__(left, build(elt))
                    acc = eq if acc is None else (acc | eq)
                if acc is None:
                    return lit(False)
                return ~acc if isinstance(node.ops[0], ast.NotIn) else acc
            right = build(node.comparators[0])
            opmap = {
                ast.Gt: Expr.__gt__, ast.GtE: Expr.__ge__,
                ast.Lt: Expr.__lt__, ast.LtE: Expr.__le__,
                ast.Eq: Expr.__eq__, ast.NotEq: Expr.__ne__,
            }
            return opmap[type(node.ops[0])](left, right)
        if isinstance(node, ast.BinOp):
            opmap = {
                ast.Add: Expr.__add__, ast.Sub: Expr.__sub__,
                ast.Mult: Expr.__mul__, ast.Div: Expr.__truediv__,
                ast.Mod: Expr.__mod__, ast.BitAnd: Expr.__and__,
                ast.BitOr: Expr.__or__,
            }
            return opmap[type(node.op)](build(node.left), build(node.right))
        if isinstance(node, ast.BoolOp):
            parts = [build(v) for v in node.values]
            acc = parts[0]
            for p in parts[1:]:
                acc = (acc & p) if isinstance(node.op, ast.And) else (acc | p)
            return acc
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, (ast.Invert, ast.Not)):
                return ~build(node.operand)
            if isinstance(node.op, ast.USub):
                return lit(0) - build(node.operand)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            fname = node.func.id.lower()
            if fname in _AGGS:
                inner = build(node.args[0])
                return inner._mk_agg(fname)
            if fname == "round" and len(node.args) == 2:
                a, d = build(node.args[0]), node.args[1]
                if not (isinstance(d, ast.Constant)
                        and isinstance(d.value, int)):
                    raise ValueError("ROUND precision must be an int literal")
                return Expr(
                    lambda c, n, _a=a, _d=d.value: np.round(
                        _a.eval(c, n), _d
                    ),
                    f"ROUND({a.name},{d.value})",
                )
            if fname in _SCALAR_FNS:
                if len(node.args) != 1:
                    raise ValueError(
                        f"{fname.upper()} takes exactly 1 argument, "
                        f"got {len(node.args)}"
                    )
                return _SCALAR_FNS[fname](build(node.args[0]))
            if fname == "power":
                return power(build(node.args[0]), build(node.args[1]))
            if fname == "concat":
                return concat(*[build(a) for a in node.args])
            if fname == "substring":
                return substring(*[build(a) for a in node.args])
            if fname == "if":
                return if_(*[build(a) for a in node.args])
            if fname == "like":
                pat_node = node.args[1]
                if isinstance(pat_node, ast.Name):
                    m2 = re.fullmatch(r"__lit(\d+)__", pat_node.id)
                    pat = literals[int(m2.group(1))]
                elif isinstance(pat_node, ast.Constant):
                    pat = str(pat_node.value)
                else:
                    raise ValueError("LIKE pattern must be a literal")
                return like(build(node.args[0]), pat)
            m2 = re.fullmatch(r"extract_(\w+)", fname)
            if m2:
                unit = m2.group(1)
                inner = build(node.args[0])
                return Expr(
                    lambda c, n, _u=unit, _i=inner: _extract(
                        _u, _i.eval(c, n)
                    ),
                    f"EXTRACT({unit.upper()} FROM {inner.name})",
                )
        raise ValueError(f"unsupported SQL expression: {s!r}")

    return build(tree)
