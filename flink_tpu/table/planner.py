"""Rule-driven logical planner for the SQL subset — the planner SEAM.

The reference plans SQL through Calcite: parse -> logical RelNode tree ->
rule-based rewriting -> physical DataSet/DataStream plan
(flink-libraries/flink-table/src/main/scala/org/apache/flink/api/table/
FlinkPlannerImpl.scala:46, plans/rules/). This module is that seam sized
to the in-repo SQL subset: a small logical-operator tree built from the
parsed query, a fixpoint pass pipeline of rewrite rules, and a lowering
step onto the existing columnar Table operators. Not a Calcite port —
the rules are the classical relational-algebra rewrites chosen for where
this engine actually spends time (join input width and probe size):

  * FilterPushdown     — WHERE conjuncts that reference exactly one side
                         of a join move below it (smaller probe input;
                         outer-join legality respected: left-side pushes
                         need how in {inner,left}, right-side pushes
                         how in {inner,right})
  * FilterMerge        — adjacent Filter nodes collapse into one
  * ConstantFilter     — literal-only conjuncts fold: TRUE drops out,
                         FALSE empties the subtree's scans (the classic
                         reduce-expressions rule)
  * ColumnPruning      — scans materialize only the columns the plan
                         above actually references (narrower join
                         gathers; the projection-pushdown rule)

EXPLAIN shows the unoptimized tree, the optimized tree, and the applied
rule trace, ahead of the measured physical plan (parity with the
reference's explain(): AST / Optimized Logical Plan / Physical Plan).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# -- SQL fragment analysis ------------------------------------------------

_KEYWORDS = {
    "and", "or", "not", "like", "if", "true", "false", "null", "as",
    "between", "in", "is",
}


def stash_literals(sql: str):
    """Pull SQL string literals out before any keyword/identifier regex
    work (shared by refs/split_conjuncts/pushdown rename). Returns
    (stashed_sql, restore_fn)."""
    lits: List[str] = []

    def stash(m):
        lits.append(m.group(0))
        return f"\x00{len(lits) - 1}\x00"

    s = re.sub(r"'(?:[^']|'')*'", stash, sql)

    def restore(p: str) -> str:
        return re.sub(r"\x00(\d+)\x00",
                      lambda m: lits[int(m.group(1))], p)

    return s, restore


def refs(sql: str) -> Optional[Set[str]]:
    """Column identifiers a SQL fragment references. None = cannot be
    analyzed confidently (qualified refs survive only in ON clauses,
    which are handled separately) — callers must then be conservative."""
    s, _ = stash_literals(sql)
    if re.search(r"\b[A-Za-z_]\w*\s*\.\s*[A-Za-z_]\w*", s):
        return None                                   # qualified ref
    out = set()
    for m in re.finditer(r"\b([A-Za-z_]\w*)\b\s*(\()?", s):
        name, is_call = m.group(1), m.group(2)
        if is_call or name.lower() in _KEYWORDS:
            continue
        out.add(name)
    return out


def split_conjuncts(sql: str) -> List[str]:
    """Top-level AND split (parenthesized ORs stay whole; ANDs inside
    string literals don't split). A top-level un-parenthesized OR binds
    LOOSER than AND, so the expression is not a conjunction at all —
    return it whole rather than severing an OR operand."""
    s, restore = stash_literals(sql)
    # `X BETWEEN a AND b`: that AND is part of the operator, not a
    # conjunction — mask it before splitting, restore after
    s = re.sub(
        r"(\bBETWEEN\b\s+\S+\s+)\bAND\b", "\\1\x02", s,
        flags=re.IGNORECASE,
    )
    orig_restore = restore

    def restore(p: str) -> str:  # noqa: F811 — layered restore
        return orig_restore(p.replace("\x02", "AND"))

    depth = 0
    for tok in re.split(r"(\(|\))", s):
        if tok == "(":
            depth += 1
        elif tok == ")":
            depth -= 1
        elif depth == 0 and re.search(r"\bOR\b", tok, re.IGNORECASE):
            return [sql.strip()]
    parts, depth, cur = [], 0, []
    tokens = re.split(r"(\(|\)|\bAND\b)", s, flags=re.IGNORECASE)
    for tok in tokens:
        if tok == "(":
            depth += 1
        elif tok == ")":
            depth -= 1
        elif depth == 0 and re.fullmatch(r"AND", tok or "",
                                         re.IGNORECASE):
            parts.append("".join(cur).strip())
            cur = []
            continue
        cur.append(tok or "")
    if cur:
        parts.append("".join(cur).strip())
    return [restore(p) for p in parts if p]


# -- logical nodes --------------------------------------------------------

@dataclass
class LScan:
    name: str
    rows: int
    schema: List[str]
    keep: Optional[List[str]] = None    # ColumnPruning sets this
    empty: bool = False                 # ConstantFilter sets this

    def line(self) -> str:
        cols = f", cols={self.keep}" if self.keep is not None else ""
        emptied = ", emptied" if self.empty else ""
        return f"Scan({self.name}{cols}{emptied})"


@dataclass
class LFilter:
    input: "LNode"
    conjuncts: List[str]

    @property
    def schema(self):
        return self.input.schema

    def line(self) -> str:
        return f"Filter({' AND '.join(self.conjuncts)})"


@dataclass
class LJoin:
    left: "LNode"
    right: "LNode"
    how: str
    lks: List[str]
    rks: List[str]
    residual_sql: Optional[str]
    schema: List[str]
    clash: Set[str] = field(default_factory=set)

    def line(self) -> str:
        res = f", residual={self.residual_sql}" if self.residual_sql \
            else ""
        return (f"Join(how={self.how}, "
                f"keys={list(zip(self.lks, self.rks))}{res})")


@dataclass
class LProject:
    input: "LNode"
    items: List[str]
    schema: List[str]

    def line(self) -> str:
        return f"Project({self.items})"


@dataclass
class LAggregate:
    input: "LNode"
    keys: List[str]
    items: List[str]
    schema: List[str]

    def line(self) -> str:
        return f"Aggregate(keys={self.keys}, items={self.items})"


@dataclass
class LSort:
    input: "LNode"
    spec: str

    @property
    def schema(self):
        return self.input.schema

    def line(self) -> str:
        return f"Sort({self.spec})"


@dataclass
class LLimit:
    input: "LNode"
    n: int

    @property
    def schema(self):
        return self.input.schema

    def line(self) -> str:
        return f"Limit({self.n})"


LNode = object


def children(node) -> List[LNode]:
    if isinstance(node, LJoin):
        return [node.left, node.right]
    inp = getattr(node, "input", None)
    return [inp] if inp is not None else []


def render(node, indent: int = 0) -> str:
    pad = "  " * indent
    lines = [pad + node.line()]
    for c in children(node):
        lines.append(render(c, indent + 1))
    return "\n".join(lines)


# -- rewrite rules --------------------------------------------------------
# each rule: node -> (new_node, applied: bool); the optimizer recurses
# bottom-up and loops the pipeline to fixpoint.

def _join_side_of(name: str, join: LJoin) -> Optional[str]:
    """Which input of the join owns post-join column `name`; None =
    ambiguous or unknown."""
    lsch, rsch = set(join.left.schema), set(join.right.schema)
    if name.startswith("r_") and name[2:] in join.clash:
        return "right"
    if name in lsch and name not in rsch:
        return "left"
    if name in rsch and name not in lsch:
        return "right"
    if name in lsch and name in rsch:
        # shared merged key column: both sides hold it
        for lk, rk in zip(join.lks, join.rks):
            if lk == rk == name:
                return "both"
        return None    # clash column: bare name is the LEFT value
    return None


def rule_filter_pushdown(node):
    """WHERE conjuncts referencing exactly one join input move below it."""
    if not (isinstance(node, LFilter) and isinstance(node.input, LJoin)):
        return node, False
    join = node.input
    stay, to_left, to_right = [], [], []
    for cj in node.conjuncts:
        r = refs(cj)
        if r is None or not r:
            stay.append(cj)
            continue
        sides = {_join_side_of(n, join) for n in r}
        if sides == {"left"} or sides == {"left", "both"}:
            side = "left"
        elif sides <= {"right", "both"} and sides:
            side = "right"
        else:
            stay.append(cj)
            continue
        # outer-join legality: only the preserved side's predicates
        # commute with the null-extension
        if side == "left" and join.how in ("inner", "left"):
            to_left.append(cj)
        elif side == "right" and join.how in ("inner", "right"):
            # post-join names r_X -> right-side X; string literals are
            # stashed first so a value like 'r_credit' stays untouched
            s, restore = stash_literals(cj)
            s = re.sub(
                r"\br_([A-Za-z_]\w*)\b",
                lambda m: m.group(1) if m.group(1) in join.clash
                else m.group(0),
                s,
            )
            to_right.append(restore(s))
        else:
            stay.append(cj)
    if not to_left and not to_right:
        return node, False
    left = LFilter(join.left, to_left) if to_left else join.left
    right = LFilter(join.right, to_right) if to_right else join.right
    new_join = LJoin(left, right, join.how, join.lks, join.rks,
                     join.residual_sql, join.schema, join.clash)
    return (LFilter(new_join, stay) if stay else new_join), True


def rule_filter_merge(node):
    if isinstance(node, LFilter) and isinstance(node.input, LFilter):
        return LFilter(node.input.input,
                       node.conjuncts + node.input.conjuncts), True
    return node, False


def rule_having_pushdown(node):
    """HAVING conjuncts that reference only GROUP BY keys filter BEFORE
    the aggregation (the classic aggregate-pushdown: a key predicate
    selects whole groups, so applying it to the rows is equivalent and
    shrinks the hash-aggregate input)."""
    if not (isinstance(node, LFilter)
            and isinstance(node.input, LAggregate)):
        return node, False
    agg = node.input
    keys = set(agg.keys)
    # a SELECT alias that reuses a key's name SHADOWS it in the output:
    # `SUM(amount) AS region ... HAVING region > 3` filters the sum, so
    # pushing that conjunct to the raw key column would change results
    shadowed = set()
    for item in agg.items:
        m = re.match(r"^(.+?)\s+AS\s+(\w+)\s*$", item, re.IGNORECASE)
        if m and m.group(2) in keys and m.group(1).strip() != m.group(2):
            shadowed.add(m.group(2))
    pushable = keys - shadowed
    stay, push = [], []
    for cj in node.conjuncts:
        r = refs(cj)
        if r is not None and r and r <= pushable:
            push.append(cj)
        else:
            stay.append(cj)
    if not push:
        return node, False
    new_agg = LAggregate(LFilter(agg.input, push), agg.keys, agg.items,
                         agg.schema)
    return (LFilter(new_agg, stay) if stay else new_agg), True


def _empty_scans(node):
    if isinstance(node, LScan):
        return LScan(node.name, 0, node.schema, node.keep, empty=True)
    if isinstance(node, LJoin):
        return LJoin(_empty_scans(node.left), _empty_scans(node.right),
                     node.how, node.lks, node.rks, node.residual_sql,
                     node.schema, node.clash)
    out = type(node)(**{**node.__dict__, "input":
                        _empty_scans(node.input)})
    return out


def rule_constant_filter(node):
    """Literal-only conjuncts fold at plan time: TRUE drops, FALSE
    empties every scan under the filter (reduce-expressions)."""
    if not isinstance(node, LFilter):
        return node, False
    from flink_tpu.table.table import _parse_expr

    keep, false = [], False
    changed = False
    for cj in node.conjuncts:
        r = refs(cj)
        if r:       # references columns (or None = unanalyzable)
            keep.append(cj)
            continue
        if r is None:
            keep.append(cj)
            continue
        import numpy as np

        val = bool(np.asarray(_parse_expr(cj).eval({}, 1)).reshape(-1)[0])
        changed = True
        if not val:
            false = True
    if not changed:
        return node, False
    if false:
        return _empty_scans(node.input), True
    return (LFilter(node.input, keep) if keep else node.input), True


def _required_for(node, required: Optional[Set[str]]):
    """Push the required-column set down one node; None = all columns.
    Project/Aggregate BOUND demand regardless of what sits above them —
    they only read their own items."""
    if isinstance(node, (LProject, LAggregate)):
        out = set(getattr(node, "keys", []) or [])
        for item in node.items:
            r = refs(item)
            if r is None:
                return None
            out |= r
        return out
    if required is None:
        return None
    if isinstance(node, LFilter):
        extra = set()
        for cj in node.conjuncts:
            r = refs(cj)
            if r is None:
                return None
            extra |= r
        return required | extra
    if isinstance(node, LSort):
        key = re.sub(r"\s+(DESC|ASC)$", "", node.spec.strip(),
                     flags=re.IGNORECASE).strip()
        return required | {key}
    return required


def _prune(node, required: Optional[Set[str]]):
    """Recursive column pruning; returns (node, applied)."""
    required = _required_for(node, required)
    if isinstance(node, LScan):
        if required is None:
            return node, False
        keep = [c for c in node.schema if c in required]
        if not keep:       # e.g. SELECT COUNT(*): any column carries n
            keep = node.schema[:1]
        if len(keep) < len(node.schema) and node.keep is None:
            return LScan(node.name, node.rows, node.schema, keep,
                         node.empty), True
        return node, False
    if isinstance(node, LJoin):
        if required is None:
            lreq = rreq = None
        else:
            lreq, rreq = set(node.lks), set(node.rks)
            res = refs(node.residual_sql) if node.residual_sql else set()
            if res is None:
                lreq = rreq = None
            else:
                for name in required | res:
                    side = _join_side_of(name, node)
                    if name.startswith("r_") and name[2:] in node.clash:
                        # r_X demands right's X AND left's X: pruning
                        # the left copy would un-clash the name and the
                        # join output would call right's column X, not
                        # r_X — keep both so naming stays stable
                        lreq.add(name[2:])
                        rreq.add(name[2:])
                        continue
                    if side in ("left", "both", None):
                        lreq.add(name)
                    if side in ("right", "both", None):
                        rreq.add(name)
        left, a1 = _prune(node.left, lreq)
        right, a2 = _prune(node.right, rreq)
        if a1 or a2:
            return LJoin(left, right, node.how, node.lks, node.rks,
                         node.residual_sql, node.schema,
                         node.clash), True
        return node, False
    kids = children(node)
    if not kids:
        return node, False
    child, applied = _prune(kids[0], required)
    if applied:
        return type(node)(**{**node.__dict__, "input": child}), True
    return node, False


def rule_column_pruning(root):
    """Top-level rule: prune scans to the columns the plan references.
    The root's own output demand seeds the traversal."""
    if isinstance(root, (LProject, LAggregate)):
        return _prune(root, set())
    return _prune(root, None)


def rule_limit_pushdown(node):
    """LIMIT under a NON-AGGREGATING projection: project only the
    surviving rows (projection is row-wise and order-preserving, so the
    same rows come out — just fewer expression evaluations). A
    projection carrying aggregates is a global aggregation (one output
    row from ALL inputs) and must see every row, so it is skipped; a
    Sort between them never arises (the grammar orders LIMIT above
    ORDER BY above the projection)."""
    if not (isinstance(node, LLimit) and isinstance(node.input, LProject)):
        return node, False
    from flink_tpu.table.table import _AGGS

    proj = node.input
    for item in proj.items:
        s, _ = stash_literals(item)
        if re.search(r"\b(" + "|".join(_AGGS) + r")\s*\(", s,
                     re.IGNORECASE):
            return node, False
    return LProject(LLimit(proj.input, node.n), proj.items,
                    proj.schema), True


_LOCAL_RULES = [
    ("ConstantFilter", rule_constant_filter),
    ("FilterMerge", rule_filter_merge),
    ("HavingPushdown", rule_having_pushdown),
    ("FilterPushdown", rule_filter_pushdown),
    ("LimitPushdown", rule_limit_pushdown),
]


def _apply_local(node, applied: List[str]):
    """Bottom-up one pass of the per-node rules."""
    if isinstance(node, LJoin):
        node = LJoin(_apply_local(node.left, applied),
                     _apply_local(node.right, applied),
                     node.how, node.lks, node.rks, node.residual_sql,
                     node.schema, node.clash)
    elif children(node):
        node = type(node)(**{
            **node.__dict__,
            "input": _apply_local(node.input, applied),
        })
    for name, rule in _LOCAL_RULES:
        node, did = rule(node)
        if did:
            applied.append(name)
    return node


def optimize(root) -> Tuple[LNode, List[str]]:
    """Fixpoint over the local rules, then one column-pruning pass
    (pruning is a whole-plan property, so it runs once at the end)."""
    applied: List[str] = []
    for _ in range(10):
        before = len(applied)
        root = _apply_local(root, applied)
        if len(applied) == before:
            break
    root, did = rule_column_pruning(root)
    if did:
        applied.append("ColumnPruning")
    return root, applied
