"""Interactive shell — the flink-scala-shell analog (SURVEY §2.9,
ref flink-scala-shell/.../FlinkShell.scala + FlinkILoop.scala).

The reference starts a Scala REPL with pre-bound execution environments
(``benv``/``senv``) and ships the REPL session's compiled classes to the
cluster on execute. Redesigned for Python: a ``code.InteractiveConsole``
with pre-bound

  * ``env``   — StreamExecutionEnvironment (the ``senv`` analog)
  * ``benv``  — dataset ExecutionEnvironment (the ``benv`` analog)
  * ``submit(fn)`` — remote execution: the SESSION SOURCE (every line
    the console accepted, the FlinkILoop class-shipping analog) is
    written to a job file and submitted to the controller as a
    ``file.py:fn`` builder ref, so functions DEFINED IN THE REPL run on
    the cluster with their session context.

Local mode executes in-process; ``--controller HOST:PORT`` targets a
running ProcessCluster (bin/start-cluster.sh). ``--execute FILE`` runs
a script through the same console and exits (scripting/test seam).
"""

from __future__ import annotations

import argparse
import code
import os
import sys
import tempfile
import time
from typing import List, Optional

BANNER = r"""
      __ _ _       _        _
     / _| (_)_ __ | | __   | |_ _ __  _   _
    | |_| | | '_ \| |/ /   | __| '_ \| | | |
    |  _| | | | | |   <    | |_| |_) | |_| |
    |_| |_|_|_| |_|_|\_\____\__| .__/ \__,_|
                               |_|
  env   = StreamExecutionEnvironment (streaming)
  benv  = ExecutionEnvironment (batch / DataSet)
  submit(fn [, job_name, checkpoint_dir]) -> worker id (remote mode)
"""


class ShellConsole(code.InteractiveConsole):
    """Console that RECORDS accepted source — the session transcript is
    what remote submission ships (FlinkILoop's class shipping,
    expressed as source shipping)."""

    def __init__(self, namespace: dict):
        super().__init__(namespace)
        self.session_lines: List[str] = []
        self._pending: List[str] = []

    def push(self, line: str) -> bool:
        self._pending.append(line)
        more = super().push(line)
        if not more:
            src = "\n".join(self._pending)
            self._pending = []
            # record only source that COMPILED (runsource returned a
            # complete, syntactically valid block); runtime errors still
            # record — the reference ships every compiled REPL class too
            try:
                compile(src, "<shell>", "exec")
                if src.strip():
                    self.session_lines.append(src)
            except SyntaxError:
                pass
        return more


class FlinkShell:
    def __init__(self, controller: Optional[str] = None,
                 job_dir: Optional[str] = None):
        self.controller = None
        if controller:
            host, _, port = controller.rpartition(":")
            self.controller = (host or "127.0.0.1", int(port))
        self.job_dir = job_dir or tempfile.mkdtemp(prefix="flink-shell-")
        self._job_seq = 0
        from flink_tpu import StreamExecutionEnvironment
        from flink_tpu.dataset import ExecutionEnvironment

        self.namespace = {
            "env": StreamExecutionEnvironment.get_execution_environment(),
            "benv": ExecutionEnvironment.get_execution_environment(),
            "submit": self.submit,
            "__name__": "__console__",
        }
        self.console = ShellConsole(self.namespace)

    # console-only bindings that do not exist on a worker: a shipped
    # top-level statement referencing any of them would NameError when
    # the worker execs the session file
    _CONSOLE_NAMES = frozenset({"env", "benv", "submit", "shell"})

    def _shippable(self, block: str) -> bool:
        """A session block ships if it is a definition (import, def,
        class) or a statement free of console-only names — the
        FlinkILoop analog ships REPL class definitions, not the REPL's
        interactive actions (local executes, previous submit() calls)."""
        import ast

        try:
            tree = ast.parse(block)
        except SyntaxError:          # recorded pre-exec; defensive
            return False
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom,
                                 ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Name)
                        and sub.id in self._CONSOLE_NAMES):
                    return False
        return True

    # -- remote submission ----------------------------------------------
    def submit(self, fn, job_name: Optional[str] = None,
               checkpoint_dir: str = "") -> str:
        """Ship the session source + run ``fn`` as the job builder on
        the cluster (fn must return a configured
        StreamExecutionEnvironment, the worker builder contract).
        Definitions and console-independent statements ship; top-level
        statements touching the console's own bindings (env/benv/
        submit/shell) stay local — they are interactive actions, not
        session state a worker can replay."""
        if self.controller is None:
            raise RuntimeError(
                "submit() needs a cluster: start the shell with "
                "--controller HOST:PORT (bin/start-cluster.sh)"
            )
        name = getattr(fn, "__name__", None)
        if not name or name == "<lambda>":
            raise ValueError("submit() needs a named function")
        self._job_seq += 1
        os.makedirs(self.job_dir, exist_ok=True)
        path = os.path.join(self.job_dir, f"session_{self._job_seq}.py")
        shipped = [b for b in self.console.session_lines
                   if self._shippable(b)]
        with open(path, "w") as f:
            f.write(
                "# flink-tpu shell session shipment "
                "(FlinkILoop analog)\n"
            )
            f.write("\n\n".join(shipped))
            f.write("\n")
        from flink_tpu.runtime.cluster import control_request

        resp = control_request(*self.controller, {
            "action": "submit", "builder": f"{path}:{name}",
            "job_name": job_name or f"shell-job-{self._job_seq}",
            "checkpoint_dir": checkpoint_dir,
        })
        if not resp.get("ok"):
            raise RuntimeError(f"submit failed: {resp.get('error')}")
        return resp["worker_id"]

    def wait(self, worker_id: str, timeout_s: float = 180.0) -> str:
        if self.controller is None:
            raise RuntimeError(
                "wait() needs a cluster: start the shell with "
                "--controller HOST:PORT"
            )
        from flink_tpu.runtime.cluster import control_request

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            resp = control_request(
                *self.controller, {"action": "list"}
            )
            for w in resp.get("workers", []):
                if w["worker_id"] == worker_id and w["status"] in (
                    "FINISHED", "FAILED", "DEAD"
                ):
                    return w["status"]
            time.sleep(0.2)
        raise TimeoutError(worker_id)

    # -- driving ---------------------------------------------------------
    def run_source(self, source: str):
        """Feed source through the console (the --execute / test seam).
        The source is split into TOP-LEVEL STATEMENTS by the parser —
        not by indentation heuristics, which would split compound
        statements (try/except, if/else, decorated defs) at their
        dedented clauses — and each statement block runs and records
        like typed input."""
        import ast

        tree = ast.parse(source)     # SyntaxError surfaces to the caller
        lines = source.splitlines()
        for node in tree.body:
            block = "\n".join(lines[node.lineno - 1:node.end_lineno])
            self.console.runsource(block, symbol="exec")
            self.console.session_lines.append(block)

    def interact(self):
        self.namespace["shell"] = self
        self.console.interact(banner=BANNER, exitmsg="bye")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="flink-shell",
        description="Interactive flink-tpu shell (scala-shell analog)",
    )
    ap.add_argument("--controller", default=None,
                    help="HOST:PORT of a running cluster for submit()")
    ap.add_argument("--execute", default=None,
                    help="run a script through the shell and exit")
    ap.add_argument("--job-dir", default=None,
                    help="where shipped session jobs are written "
                         "(must be visible to the cluster's workers)")
    a = ap.parse_args(argv)
    sh = FlinkShell(controller=a.controller, job_dir=a.job_dir)
    if a.execute:
        with open(a.execute) as f:
            sh.run_source(f.read())
        return 0
    sh.interact()
    return 0


if __name__ == "__main__":
    sys.exit(main())
