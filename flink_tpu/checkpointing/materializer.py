"""Background materializer: the async phase of a checkpoint.

The step loop's only blocking work at a barrier is the *sync phase*:
drain due fires, fetch the (dirty subset of) device state into a host
staging buffer, capture source offsets / sink states, clear the dirty
bits. Everything downstream — entry extraction, delta filtering,
serialization, the atomic directory publish, and retention GC — runs
here, on one daemon thread, while the step loop is already dispatching
the next micro-batch. (Completion notifications are only QUEUED by
tasks; the step loop delivers them — connector callbacks mutate state
the hot path touches.)

Staging is double-buffered: at most ``slots`` snapshots may be pending.
``submit`` blocks when the buffer is full (the step loop briefly
backpressures instead of staging unboundedly — the wait is returned so
the caller can record it), and tasks execute strictly FIFO so checkpoint
ids publish in order and a delta can never be durable before its base.

Failure model: a task that raises poisons the materializer — queued and
subsequent tasks are dropped (their checkpoints never publish; a delta
must not chain over a hole) and the error re-raises at the next
``check()``/``submit()``/``flush()`` on the caller's thread, where the
executor's restart machinery treats it like any checkpoint failure. The
in-flight directory write goes through a ``.tmp`` staging dir + atomic
rename (runtime/checkpoint.py), so a crash mid-write leaves the previous
checkpoint fully recoverable.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from flink_tpu.testing import faults


class MaterializerError(RuntimeError):
    """An async checkpoint write failed (original exception chained)."""


class MaterializerStall(MaterializerError):
    """A bounded staging-slot wait expired: the in-flight
    materialization is not finishing. Surfaced on the CALLER's thread so
    the checkpoint policy can abort-and-count instead of the step loop
    blocking behind a wedged write forever."""


class Materializer:
    def __init__(self, slots: int = 2, name: str = "ckpt-materializer"):
        if slots < 1:
            raise ValueError("materializer needs at least one staging slot")
        self.slots = slots
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._error: Optional[BaseException] = None
        self._error_label: Optional[str] = None
        self._closed = False
        self._busy = False          # a task is executing right now
        self._thread = threading.Thread(
            target=self._main, daemon=True, name=name
        )
        self._thread.start()

    # -- caller side ----------------------------------------------------
    def pending(self) -> int:
        """Occupied staging slots (queued + executing)."""
        with self._cv:
            return len(self._q) + (1 if self._busy else 0)

    def check(self) -> None:
        """Surface (and clear) a stored async failure on the caller's
        thread. After the raise the materializer accepts work again —
        the caller is expected to recover (restore) first."""
        with self._cv:
            err, label = self._error, self._error_label
            if err is not None:
                # purge poisoned tasks UNDER the same lock: clearing the
                # error first would let the worker run a queued task whose
                # checkpoint chains over the failed (never-published) one
                self._q.clear()
            self._error = None
            self._error_label = None
            self._cv.notify_all()
        if err is not None:
            raise MaterializerError(
                f"async checkpoint {label!r} failed: {err}"
            ) from err

    def wait_for_slot(self, timeout: Optional[float] = None) -> float:
        """Block until a staging slot is free (or the materializer fails);
        returns the seconds waited. Callers with a single submitting
        thread use this to attribute the backpressure wait to the sync
        phase BEFORE building the task. ``timeout`` bounds the wait and
        raises :class:`MaterializerStall` on expiry (the failure-
        containment path: a wedged write becomes an abortable checkpoint
        failure, not an unbounded step-loop stall)."""
        t0 = time.perf_counter()
        with self._cv:
            while (len(self._q) + (1 if self._busy else 0)) >= self.slots \
                    and self._error is None and not self._closed:
                waited = time.perf_counter() - t0
                if timeout is not None and waited >= timeout:
                    raise MaterializerStall(
                        f"no staging slot freed in {waited:.1f}s "
                        f"(timeout {timeout:.1f}s, {len(self._q)} queued"
                        f"{', one executing' if self._busy else ''}) — "
                        f"the in-flight checkpoint write appears wedged"
                    )
                self._cv.wait(0.1)
        return time.perf_counter() - t0

    def submit(self, label: str, task: Callable[[], None]) -> None:
        """Queue one materialization task. Blocks while all staging slots
        are busy (callers that want the wait attributed separately call
        wait_for_slot() first; with a single submitting thread the slot
        cannot be stolen in between)."""
        self.check()
        self.wait_for_slot()
        with self._cv:
            if self._closed:
                raise RuntimeError("materializer is closed")
            self._q.append((label, task))
            self._cv.notify_all()
        self.check()

    def recover(self, timeout: Optional[float] = None) -> None:
        """Restore-time drain: let in-flight writes land (each is a valid
        cut the restore may pick up), then drop queued tasks and any
        stored failure — restoring IS the recovery from it. ``timeout``
        bounds the drain: a WEDGED write must not turn recovery into the
        indefinite hang it is recovering from — the abandoned task keeps
        running on the daemon thread, and whatever it eventually
        publishes (or fails) is a pre-restore cut the caller has already
        accounted for."""
        self.flush(raise_errors=False, timeout=timeout)
        with self._cv:
            self._q.clear()
            self._error = None
            self._error_label = None
            self._cv.notify_all()

    def flush(self, raise_errors: bool = True,
              timeout: Optional[float] = None) -> bool:
        """Wait until every queued task has completed (or the
        materializer failed, or ``timeout`` seconds elapsed). With
        raise_errors, surface the stored failure. Returns False when the
        timeout expired with work still in flight."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        done = True
        with self._cv:
            while (self._q or self._busy) and self._error is None \
                    and not self._closed:
                if deadline is not None and time.monotonic() >= deadline:
                    done = False
                    break
                self._cv.wait(0.1)
        if raise_errors:
            self.check()
        return done

    def close(self, flush: bool = True,
              timeout: Optional[float] = None) -> None:
        if flush:
            self.flush(raise_errors=False, timeout=timeout)
        with self._cv:
            self._closed = True
            self._q.clear()
            self._cv.notify_all()
        self._thread.join(timeout=10.0)

    # -- worker side ----------------------------------------------------
    def _main(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(0.2)
                if self._closed:
                    return
                if self._error is not None:
                    # poisoned: drop queued work (see module docstring)
                    self._q.clear()
                    self._cv.notify_all()
                    continue
                label, task = self._q.popleft()
                self._busy = True
            try:
                # fault seam: slow-I/O (sleep) and write-error injection
                # land here, on the materializer thread, exactly where a
                # slow/flaky filesystem would surface
                faults.inject("materializer.task", label=label)
                task()
            except BaseException as e:  # noqa: BLE001 — delivered via check()
                with self._cv:
                    self._error = e
                    self._error_label = label
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()
