"""Task-local snapshot cache (ref Flink task-local recovery,
TaskLocalStateStoreImpl): a host-side secondary copy of every published
checkpoint, so recovery fetches state from the machine it runs on
instead of re-pulling every blob from primary checkpoint storage.

The reference's insight is that the PRIMARY copy exists for durability
and the LOCAL copy exists for MTTR: a restore that finds its state on
local disk skips the remote fetch entirely, and a restore that finds the
local copy missing or corrupt falls back to primary per chain member —
the cache can only ever make recovery faster, never wrong. Three
properties make that safe here:

* **Mirror-at-publish.** ``CheckpointStorage.write`` mirrors the
  checkpoint directory into the cache only AFTER the primary's atomic
  rename, so the cache never holds a cut that is not durable. The mirror
  itself is also staged + renamed, so a crash mid-mirror leaves debris,
  never a half-entry that verifies.
* **Per-blob checksums.** Every cached file's CRC is recorded in a
  ``checksums.json`` manifest at mirror time and verified at read time;
  a flipped bit or truncated file surfaces as :class:`LocalCacheMiss`
  (the entry is dropped) and the read falls back to primary — local disk
  is treated as UNTRUSTED, exactly like the reference discards a local
  state handle that fails to open.
* **Retention follows the primary chain-closure GC.** ``prune(live)``
  receives the same live set (retained checkpoints + their manifest
  chains) the primary GC keeps, so the two tiers can never disagree
  about which cut is restorable: anything the primary may restore, the
  cache either holds verbatim or does not hold at all.

Mirroring is best-effort by contract: a cache failure (disk full,
permission) increments a counter and the checkpoint remains exactly as
durable as it was — the job must never fail because its MTTR
optimization did.
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from typing import Iterable, List, Optional

from flink_tpu.testing import faults

CHECKSUMS_NAME = "checksums.json"


class LocalCacheMiss(Exception):
    """The cache has no verified copy of the requested checkpoint —
    missing entry, missing/unreadable checksum manifest, or a blob whose
    CRC does not match. The caller falls back to primary storage."""


def file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


class LocalSnapshotCache:
    """One directory of mirrored checkpoint entries::

        <dir>/chk-<id>/{meta.json, entries.npz, ..., checksums.json}

    Same layout as primary so the storage-format readers work on a
    cached entry unchanged; ``checksums.json`` is the only addition.
    ``stats`` is the hit/miss/corruption ledger the recovery
    instrumentation (metrics/recovery.py) and /jobs/<jid>/recovery
    serve."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        # identity of the PRIMARY storage this cache mirrors (see
        # bind_identity): a cache entry is only trusted for the storage
        # incarnation that wrote it — cids restart when a checkpoint
        # directory is wiped and re-created, and a stale mirror's CRCs
        # are self-consistent, so CRC verification alone cannot catch it
        self.identity: Optional[str] = None
        self.stats = {
            "puts": 0, "put_failures": 0,
            "hits": 0, "misses": 0, "corrupt": 0, "stale": 0,
        }

    def bind_identity(self, identity: Optional[str]) -> None:
        """Record the primary storage's identity token (checkpoint.py
        stamps one per storage-directory incarnation). ``put`` embeds it
        in ``checksums.json`` and ``verify`` rejects entries recorded
        under any other identity — or under none, which an unbound
        writer produces — as stale. A ``None`` identity (token
        unavailable, e.g. read-only primary) disables the check."""
        self.identity = identity

    def path(self, cid: int) -> str:
        return os.path.join(self.dir, f"chk-{cid}")

    # -- write side -----------------------------------------------------
    def put(self, cid: int, src_dir: str) -> bool:
        """Mirror a just-published checkpoint directory into the cache.
        Staged + atomic rename (a crash mid-copy never leaves an entry
        that verifies); hard-links blobs where the filesystem allows it
        (primary and cache commonly share a local disk) and copies
        otherwise. Best-effort: returns False on failure instead of
        raising."""
        tmp = self.path(cid) + ".tmp"
        try:
            # fault seam: an injected OSError here (disk full, yanked
            # mount) exercises the best-effort contract — the mirror
            # fails, the checkpoint stays durable, the job lives
            faults.inject("ckpt.local.put", cid=cid)
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            sums = {}
            for name in os.listdir(src_dir):
                src = os.path.join(src_dir, name)
                if not os.path.isfile(src):
                    continue
                dst = os.path.join(tmp, name)
                try:
                    os.link(src, dst)
                except OSError:
                    shutil.copyfile(src, dst)
                sums[name] = file_crc32(dst)
            with open(os.path.join(tmp, CHECKSUMS_NAME), "w") as f:
                json.dump({"identity": self.identity, "blobs": sums}, f)
            final = self.path(cid)
            shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)
            self.stats["puts"] += 1
            return True
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            # any pre-existing entry under this cid is outdated by the
            # primary publish that triggered this put — a failed mirror
            # must not leave it behind to verify later
            self.drop(cid)
            self.stats["put_failures"] += 1
            return False

    # -- read side ------------------------------------------------------
    def verify(self, cid: int) -> str:
        """Return the cached directory path after verifying every
        recorded blob's CRC. Raises :class:`LocalCacheMiss` on a missing
        entry; a CORRUPT entry (bad manifest, CRC mismatch, missing
        blob) is dropped from the cache before the miss is raised, so a
        rotten copy can never be consulted twice."""
        p = self.path(cid)
        if not os.path.isdir(p):
            self.stats["misses"] += 1
            raise LocalCacheMiss(f"chk-{cid} not in local cache")
        try:
            # fault seam: an injected OSError/ValueError takes the
            # corrupt-entry branch — drop, count, fall back to primary
            faults.inject("ckpt.local.verify", cid=cid)
            with open(os.path.join(p, CHECKSUMS_NAME)) as f:
                manifest = json.load(f)
            if self.identity is not None and (
                manifest.get("identity") != self.identity
            ):
                # recorded under another primary incarnation (or none):
                # the blobs may CRC-verify perfectly and still be a
                # different job's chk-<cid> — drop, count, fall back
                self.stats["stale"] += 1
                self.drop(cid)
                raise LocalCacheMiss(
                    f"local copy of chk-{cid} belongs to a different "
                    f"primary storage incarnation; falling back"
                )
            for name, crc in manifest["blobs"].items():
                if file_crc32(os.path.join(p, name)) != int(crc):
                    raise ValueError(f"{name}: checksum mismatch")
        except (OSError, ValueError, KeyError, TypeError) as e:
            self.stats["corrupt"] += 1
            self.drop(cid)
            raise LocalCacheMiss(
                f"local copy of chk-{cid} failed verification ({e}); "
                f"falling back to primary storage"
            ) from e
        self.stats["hits"] += 1
        return p

    def has(self, cid: int) -> bool:
        return os.path.isdir(self.path(cid))

    def identity_ok(self, cid: int) -> bool:
        """Cheap staleness check without the full CRC sweep, for readers
        that bypass :meth:`verify` (the manifest fast path reads one tiny
        json and must not pay a whole-entry checksum pass). False means
        the entry was recorded under a different primary incarnation —
        or the manifest is unreadable — and primary must serve."""
        if self.identity is None:
            return True
        try:
            # fault seam: an unreadable manifest means primary serves
            faults.inject("ckpt.local.verify", cid=cid)
            with open(os.path.join(self.path(cid), CHECKSUMS_NAME)) as f:
                return json.load(f).get("identity") == self.identity
        except (OSError, ValueError, AttributeError):
            return False

    def drop(self, cid: int) -> None:
        shutil.rmtree(self.path(cid), ignore_errors=True)

    # -- retention ------------------------------------------------------
    def prune(self, live: Iterable[int]) -> None:
        """Drop every cached entry outside the primary's live set (the
        chain-closure the primary GC retains), plus any staging debris.
        Called after each primary GC so the tiers stay in lockstep."""
        keep = {int(c) for c in live}
        for cid in self.list_entries():
            if cid not in keep:
                self.drop(cid)
        for name in os.listdir(self.dir):
            if name.startswith("chk-") and name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.dir, name),
                              ignore_errors=True)

    def list_entries(self) -> List[int]:
        out = []
        if not os.path.isdir(self.dir):
            return out
        for name in os.listdir(self.dir):
            if name.startswith("chk-") and not name.endswith(".tmp"):
                try:
                    out.append(int(name[4:]))
                except ValueError:
                    pass
        return sorted(out)

    # -- observability --------------------------------------------------
    def state(self) -> dict:
        return {
            "directory": self.dir,
            "entries": self.list_entries(),
            **self.stats,
        }


def local_cache_from_config(config, primary_dir: str
                            ) -> Optional[LocalSnapshotCache]:
    """Build the cache from ``checkpoint.local.*`` config (None when
    disabled). The default directory is a ``<primary>-local`` sibling —
    on a production deployment ``checkpoint.local.dir`` points at node-
    local disk while the primary lives on shared/remote storage."""
    from flink_tpu.core.config import CoreOptions as CO

    if config is None or not config.get(CO.CHECKPOINT_LOCAL_ENABLED):
        return None
    directory = config.get(CO.CHECKPOINT_LOCAL_DIR)
    if not directory:
        directory = primary_dir.rstrip("/\\") + "-local"
    return LocalSnapshotCache(directory)
