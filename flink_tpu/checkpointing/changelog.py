"""Changelog: which key groups changed since the last checkpoint.

Device half: the window kernels fold a ``kg_dirty`` bool plane into the
shard state struct (ops/window_kernels.py) — one route-hash + bool
scatter per micro-batch marks the key groups each applied record belongs
to. At the step-boundary barrier the host fetches the plane with the
scalars and clears it (runtime/step.py ``clear_dirty``); the set of
dirty groups decides which shards' state is staged and which entries
ride the next delta.

Host half: ``HostChangelog`` gives heap-style backends (state/backend.py)
the same contract — mark-on-mutate, consume-at-snapshot — so a snapshot
can skip re-serializing key groups nothing touched.
"""

from __future__ import annotations

from typing import List, Set

import numpy as np

from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.ops.hashing import route_hash


def dirty_key_groups(kg_dirty_host: np.ndarray) -> np.ndarray:
    """[S, KG] (or [KG]) fetched dirty planes -> sorted dirty group ids."""
    arr = np.asarray(kg_dirty_host)
    if arr.ndim > 1:
        arr = arr.any(axis=tuple(range(arr.ndim - 1)))
    return np.nonzero(arr)[0]


def dirty_shard_rows(dirty_kgs, starts, ends) -> List[int]:
    """Shard rows whose owned key-group range [starts[s], ends[s]]
    intersects the dirty set — the only rows an incremental snapshot has
    to fetch from the device."""
    dirty_kgs = np.asarray(dirty_kgs)
    rows = []
    for s, (a, b) in enumerate(zip(np.asarray(starts), np.asarray(ends))):
        if bool(((dirty_kgs >= a) & (dirty_kgs <= b)).any()):
            rows.append(s)
    return rows


def entry_key_groups(key_hi, key_lo, max_parallelism: int) -> np.ndarray:
    """Logical snapshot entries -> key group per entry (host numpy; the
    same murmur route the device uses, so coverage filtering and device
    routing can never disagree)."""
    return assign_to_key_group(
        route_hash(np.asarray(key_hi), np.asarray(key_lo), np),
        max_parallelism, np,
    )


def filter_entries_to_key_groups(entries: dict, kgs,
                                 max_parallelism: int) -> dict:
    """Restrict a logical entries dict to the given key groups."""
    khi = entries["key_hi"]
    if len(khi) == 0:
        return entries
    kg = entry_key_groups(khi, entries["key_lo"], max_parallelism)
    keep = np.isin(kg, np.asarray(list(kgs), dtype=kg.dtype))
    return {k: v[keep] for k, v in entries.items()}


class HostChangelog:
    """Mark-on-mutate dirty-key-group set for host-side state backends.

    Thread-compatible with the executor model (all mutations happen on
    the task thread); ``consume()`` returns the dirty set and resets it —
    exactly the fetch-and-clear the device plane gets at a barrier."""

    def __init__(self):
        self._dirty: Set[int] = set()

    def mark(self, key_group: int) -> None:
        self._dirty.add(key_group)

    def consume(self) -> frozenset:
        out = frozenset(self._dirty)
        self._dirty.clear()
        return out
