"""Recovery: replay a manifest chain back into one logical snapshot.

A delta checkpoint's ``entries.npz`` holds the FULL current state of the
key groups it covers (not an op log), so recovery is a per-key-group
last-writer-wins merge over the chain: for every key group, take the
entries of the NEWEST chain member covering it. Scalars (watermark,
fired_through, max_pane, counters) are global and always fetched fully
at every checkpoint, so the newest member's scalars win outright; the
same goes for source offsets, sink states, and aux.

Two filters reconcile merged entries with what the device itself would
hold at the cut (older members may carry state the global sweeps have
since retired — sweeps are deliberately NOT marked dirty, see
ops/window_kernels.py):

* ring horizon — entries whose pane fell off the R-pane ring are dropped
  by ``restore_window_state`` already (pane <= max_pane - R);
* purge cutoff — entries every containing window of which has fired and
  passed the purge horizon are dropped HERE, mirroring the device's
  purge sweep (advance_and_fire). With allowed lateness 0 this is exact:
  cutoff = min(fired_through, watermark pane). Incremental mode is
  restricted to lateness-0 stages (runtime/executor.py enforces it), so
  the fresh/re-fire corner never reaches this code; a chain that somehow
  carries lateness skips the filter (conservative: resurrecting an
  already-purged pane never changes fires, only queryable reads).

The merged result feeds the existing ``restore_window_state``
re-bucketing unchanged, which is what makes chain recovery rescale-
compatible for free.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from flink_tpu.checkpointing import manifest as mf
from flink_tpu.checkpointing.changelog import entry_key_groups

PANE_NONE = -(2 ** 31) + 1


def _purge_cutoff(scalars: dict, slide: int) -> int:
    """The device's purge cutoff at the cut (advance_and_fire, L=0)."""
    wm = int(scalars["watermark"])
    base = max(wm, -(2 ** 31) + 1 + slide)
    wm_pane = (base + 1 - slide) // slide
    fired = int(scalars["fired_through"])
    if fired == PANE_NONE:
        return PANE_NONE
    return min(fired, wm_pane)


def replay_chain(storage, cid: int) -> Tuple[dict, dict, object, dict]:
    """Merge checkpoint ``cid``'s chain into one logical snapshot.

    ``storage`` is a CheckpointStorage (duck-typed: read_raw(cid) ->
    (entries, scalars, offsets, aux) and read_manifest(cid) -> dict|None).
    Returns the same 4-tuple ``read_raw`` does.
    """
    head = storage.read_manifest(cid)
    if head is None or head.get("kind") != "delta":
        return storage.read_raw(cid)
    chain = head["chain"]
    maxp = head["max_parallelism"]

    members = []
    for c in chain:
        m = storage.read_manifest(c)
        if c != chain[0] and (m is None or m.get("kind") != "delta"):
            # only the chain head (base) may be full / manifest-less
            raise ValueError(
                f"checkpoint {cid} chains over {c}, which is "
                f"{'missing its manifest' if m is None else repr(m.get('kind'))}"
                f" — a non-head chain member must be a delta (chain "
                f"broken or directory tampered with)"
            )
        cov = (
            mf.coverage_set(m, maxp) if m is not None
            else frozenset(range(maxp))
        )
        try:
            entries, scalars, offsets, aux = storage.read_raw(c)
        except FileNotFoundError as e:
            raise FileNotFoundError(
                f"checkpoint {cid} chains over missing member {c}: {e}"
            ) from e
        members.append((c, cov, entries, scalars, offsets, aux))

    # last-writer-wins ownership per key group
    owner = np.full(maxp, -1, np.int64)
    for i, (_c, cov, *_rest) in enumerate(members):
        owner[np.asarray(sorted(cov), np.int64)] = i

    parts = []
    for i, (_c, _cov, entries, *_rest) in enumerate(members):
        khi = entries["key_hi"]
        if len(khi) == 0:
            continue
        kg = entry_key_groups(khi, entries["key_lo"], maxp)
        keep = owner[kg] == i
        if keep.any():
            parts.append({k: v[keep] for k, v in entries.items()})

    newest = members[-1]
    _c, _cov, newest_entries, scalars, offsets, aux = newest
    if parts:
        merged = {
            k: np.concatenate([p[k] for p in parts])
            for k in parts[0]
        }
    else:
        merged = {k: v[:0] for k, v in newest_entries.items()}

    # purge-cutoff filter (exact for lateness-0 stages; see module doc)
    slide = int(aux.get("slide_ms", 0) or 0)
    size = int(aux.get("size_ms", 0) or 0)
    lateness = int(aux.get("lateness_ms", 0) or 0)
    if slide > 0 and lateness == 0 and len(merged["pane"]):
        k_panes = max(1, size // slide)
        cutoff = _purge_cutoff(scalars, slide)
        keep = merged["pane"].astype(np.int64) + (k_panes - 1) > cutoff
        fresh = merged.get("fresh")
        if fresh is not None and len(fresh):
            keep = keep | fresh.astype(bool)
        merged = {k: v[keep] for k, v in merged.items()}

    return merged, scalars, offsets, aux
