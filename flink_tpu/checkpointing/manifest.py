"""Manifest chain: the durable metadata tying deltas to their base.

Every checkpoint directory written in incremental mode carries a
``manifest.json``:

    {
      "manifest_version": 1,
      "checkpoint_id":    7,
      "kind":             "delta",          # or "full"
      "chain":            [4, 5, 6, 7],     # base first, this cp last
      "coverage":         [3, 17, 90],      # key groups in entries.npz
      "max_parallelism":  128,
      "entries":          1234,             # entry rows in this file
      "bytes":            0                 # filled after serialization
    }

``kind: full`` checkpoints are self-contained (``chain == [cid]``,
``coverage == "all"``); sync-full mode writes no manifest at all and is
treated as such. Recovery walks ``chain`` and merges coverage
last-writer-wins per key group (recovery.py). Retention GC keeps every
directory reachable from a retained checkpoint's chain
(``live_checkpoints``), so a base is never collected while a delta still
references it.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterable, List, Optional, Sequence, Union

from flink_tpu.testing import faults

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1

Coverage = Union[str, Sequence[int]]       # "all" | iterable of key groups


def build_manifest(cid: int, kind: str, chain: Sequence[int],
                   coverage: Coverage, max_parallelism: int,
                   entries: int = 0, nbytes: int = 0) -> dict:
    if kind not in ("full", "delta"):
        raise ValueError(f"manifest kind must be full|delta, got {kind!r}")
    if not chain or chain[-1] != cid:
        raise ValueError(f"chain {chain!r} must end with checkpoint {cid}")
    if kind == "full" and len(chain) != 1:
        raise ValueError(f"a full checkpoint is its own chain, got {chain!r}")
    return {
        "manifest_version": MANIFEST_VERSION,
        "checkpoint_id": int(cid),
        "kind": kind,
        "chain": [int(c) for c in chain],
        "coverage": (
            "all" if coverage == "all" else sorted(int(g) for g in coverage)
        ),
        "max_parallelism": int(max_parallelism),
        "entries": int(entries),
        "bytes": int(nbytes),
    }


def write_manifest(directory: str, manifest: dict) -> str:
    path = os.path.join(directory, MANIFEST_NAME)
    body = json.dumps(manifest)
    torn = None
    try:
        faults.inject("ckpt.manifest.write", path=path)
    except faults.TornWrite as tw:
        # a torn write leaves PARTIAL bytes on disk before failing —
        # the checkpoint directory is only ever published (renamed from
        # .tmp) after this returns, so the tear must surface as a write
        # failure the checkpoint policy aborts, never as a half-manifest
        # in a published directory
        body = body[: max(1, len(body) // 2)]
        torn = tw
    with open(path, "w") as f:
        f.write(body)
    if torn is not None:
        raise OSError(f"torn manifest write: {path}") from torn
    return path


def read_manifest(directory: str) -> Optional[dict]:
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    # fault seam: a failing/slow manifest read is the restore-time half
    # of the torn-write story (recovery walks the chain through here)
    faults.inject("ckpt.manifest.read", path=path)
    with open(path) as f:
        m = json.load(f)
    if m.get("manifest_version") != MANIFEST_VERSION:
        raise ValueError(f"unsupported checkpoint manifest: {m}")
    return m


def live_checkpoints(retained: Iterable[int],
                     manifest_for: Callable[[int], Optional[dict]]
                     ) -> set:
    """Reference closure of the retained checkpoint ids.

    ``manifest_for(cid)`` returns the cid's manifest dict or None (a
    manifest-less directory — sync-full era — is self-contained). A
    retained delta keeps its whole chain alive; GC may only collect
    checkpoints OUTSIDE this set."""
    live: set = set()
    for cid in retained:
        live.add(int(cid))
        m = manifest_for(cid)
        if m is not None:
            live.update(int(c) for c in m.get("chain", ()))
    return live


def coverage_set(manifest: dict, max_parallelism: int) -> frozenset:
    cov = manifest.get("coverage", "all")
    if cov == "all":
        return frozenset(range(max_parallelism))
    return frozenset(int(g) for g in cov)
