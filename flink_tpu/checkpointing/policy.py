"""Checkpoint failure containment (ref CheckpointFailureManager +
CheckpointCoordinator's tolerable-failure / timeout / min-pause knobs).

A production checkpoint failure is usually TRANSIENT — a filesystem
blip, a slow object store, one wedged materialization — and the
reference contains it: the checkpoint is *aborted and counted*, the job
keeps running, and only exhausting ``tolerable-checkpoint-failure-
number`` escalates to the restart strategy. This module is the
coordinator-side budget for the micro-batch design:

* ``tolerable_failures`` — CONSECUTIVE aborted checkpoints allowed
  before escalation (a completed checkpoint resets the count). The
  default 0 preserves the historical behavior: the first failure
  escalates.
* ``timeout_s`` — an async checkpoint still unpublished this long after
  its barrier is declared failed (the executor cancels its publish).
* ``min_pause_s`` — minimum pause between the END of one checkpoint
  attempt and the next trigger, so a struggling backend is not hammered
  with back-to-back snapshots.

The policy is bookkeeping only: the executor owns the abort mechanics
(tmp-dir discard, manifest-chain reset so a delta never chains over the
hole, publish cancellation). Thread-safe — completions land on the
materializer thread while triggers run on the step loop.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional


class CheckpointFailureBudgetExceeded(RuntimeError):
    """Consecutive checkpoint failures exceeded
    ``checkpoint.tolerable-failures``; escalate to the restart
    strategy."""


class CheckpointFailurePolicy:
    def __init__(self, tolerable_failures: int = 0,
                 timeout_s: float = 600.0, min_pause_s: float = 0.0):
        self.tolerable_failures = max(0, int(tolerable_failures))
        self.timeout_s = float(timeout_s)
        self.min_pause_s = max(0.0, float(min_pause_s))
        self._lock = threading.Lock()
        self._continuous_failures = 0
        self._total_failures = 0
        self._completed = 0
        self._last_attempt_end: Optional[float] = None   # monotonic
        self._aborts: List[dict] = []                    # bounded log

    # -- trigger gate ---------------------------------------------------
    def can_trigger(self, now: Optional[float] = None) -> bool:
        """min-pause gate: measured from the end of the last attempt
        (completed or aborted) to the next trigger, like the
        reference's minPauseBetweenCheckpoints."""
        if self.min_pause_s <= 0:
            return True
        with self._lock:
            last = self._last_attempt_end
        if last is None:
            return True
        return (now or time.monotonic()) - last >= self.min_pause_s

    # -- outcomes -------------------------------------------------------
    def on_completed(self, cid: int) -> None:
        with self._lock:
            self._continuous_failures = 0
            self._completed += 1
            self._last_attempt_end = time.monotonic()

    def on_aborted(self, cid: int, reason: str) -> bool:
        """Count one aborted checkpoint; returns True when the budget is
        now exhausted (caller escalates)."""
        with self._lock:
            self._continuous_failures += 1
            self._total_failures += 1
            self._last_attempt_end = time.monotonic()
            self._aborts.append({"id": int(cid), "reason": str(reason)})
            del self._aborts[:-20]
            return self._continuous_failures > self.tolerable_failures

    def exhausted_error(self, cid: int,
                        cause: Optional[BaseException] = None
                        ) -> CheckpointFailureBudgetExceeded:
        with self._lock:
            k = self._continuous_failures
        err = CheckpointFailureBudgetExceeded(
            f"checkpoint {cid} failed and {k} consecutive checkpoint "
            f"failure(s) exceed checkpoint.tolerable-failures="
            f"{self.tolerable_failures}"
            + (f": {cause}" if cause is not None else "")
        )
        err.__cause__ = cause
        return err

    # -- observability --------------------------------------------------
    def state(self) -> dict:
        """JSON-able budget snapshot for /jobs/<jid>/checkpoints."""
        with self._lock:
            return {
                "tolerable-failures": self.tolerable_failures,
                "continuous-failures": self._continuous_failures,
                "remaining": max(
                    0, self.tolerable_failures - self._continuous_failures
                ),
                "total-failures": self._total_failures,
                "completed": self._completed,
                "timeout-s": self.timeout_s,
                "min-pause-s": self.min_pause_s,
                "recent-aborts": list(self._aborts),
            }


def policy_from_config(config) -> CheckpointFailurePolicy:
    """Reads go through the declared ConfigOptions (core/config.py) so
    conf-file strings coerce strictly and parse failures name the
    key."""
    from flink_tpu.core.config import CoreOptions as CO

    return CheckpointFailurePolicy(
        tolerable_failures=config.get(CO.CHECKPOINT_TOLERABLE_FAILURES),
        timeout_s=config.get(CO.CHECKPOINT_TIMEOUT),
        min_pause_s=config.get(CO.CHECKPOINT_MIN_PAUSE),
    )
