"""Asynchronous incremental checkpointing.

The reference makes snapshots *asynchronous* (CheckpointCoordinator +
Chandy-Lamport barriers, SURVEY §3.4) so the processing thread never
stalls on durability, and *incremental* (RocksDB incremental checkpoints)
so a checkpoint's cost scales with what changed, not with what exists.
This package is the micro-batch SPMD redesign of both:

* ``changelog``   — which key groups changed since the last checkpoint.
  The device half is a per-shard ``kg_dirty`` bool plane folded into the
  window kernels' state struct (ops/window_kernels.py) and fetched with
  the scalars at the step-boundary barrier; the host half is a dirty-set
  tracker for heap state backends.
* ``materializer`` — the background thread that serializes and writes a
  staged snapshot while the step loop keeps running. The host staging
  area is double-buffered: at most ``slots`` snapshots may be in flight,
  and the sync phase blocks (backpressure, recorded) when both are busy.
* ``manifest``    — the durable chain format: every checkpoint directory
  carries a ``manifest.json`` naming its kind (full base | delta), the
  chain of checkpoint ids it depends on, and the key groups its entries
  cover. Retention GC never collects a directory still referenced by a
  retained manifest.
* ``recovery``    — replays base + deltas (last-writer-wins per key
  group, purge-cutoff filtered) back into one logical snapshot, so
  restore — including rescale re-bucketing — reuses the existing
  ``restore_window_state`` path unchanged.
* ``local``       — the task-local snapshot cache (ref task-local
  recovery): every published checkpoint mirrors into a checksum-
  verified host-side cache whose retention follows the primary
  chain-closure GC; restore prefers local per chain member and falls
  back to primary on miss/corruption (the MTTR fast path,
  docs/fault-tolerance.md).
* ``policy``      — the coordinator-side failure budget (ref
  CheckpointFailureManager): ``checkpoint.tolerable-failures`` /
  ``checkpoint.timeout`` / ``checkpoint.min-pause``, so a transient
  write failure aborts ONE checkpoint instead of restarting the job
  (docs/fault-tolerance.md).

The source cut a snapshot carries is the **applied-offset cut**
(runtime/ingest.py): with the pipelined ingest path, the prefetch
thread may have polled the source several batches past the state the
device has absorbed, so checkpoints/savepoints snapshot the offsets of
the last *applied* batch — never the live source position. Restore
rewinds the source to those offsets and the epoch bump discards every
in-flight prefetched batch, which then replays; state, offsets, and
sink state therefore always describe the same step boundary.
"""

from flink_tpu.checkpointing.changelog import (  # noqa: F401
    HostChangelog,
    dirty_shard_rows,
    entry_key_groups,
    filter_entries_to_key_groups,
)
from flink_tpu.checkpointing.local import (  # noqa: F401
    LocalCacheMiss,
    LocalSnapshotCache,
    local_cache_from_config,
)
from flink_tpu.checkpointing.manifest import (  # noqa: F401
    MANIFEST_NAME,
    build_manifest,
    live_checkpoints,
)
from flink_tpu.checkpointing.materializer import (  # noqa: F401
    Materializer,
    MaterializerError,
)
from flink_tpu.checkpointing.policy import (  # noqa: F401
    CheckpointFailureBudgetExceeded,
    CheckpointFailurePolicy,
    policy_from_config,
)
from flink_tpu.checkpointing.recovery import replay_chain  # noqa: F401
