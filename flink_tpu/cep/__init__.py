"""CEP — complex event processing over keyed streams (ref flink-cep,
SURVEY §2.7: Pattern API compiled to an NFA advanced per key)."""

from flink_tpu.cep.cep import CEP, PatternStream
from flink_tpu.cep.nfa import NFA
from flink_tpu.cep.pattern import Pattern

__all__ = ["CEP", "PatternStream", "NFA", "Pattern"]
