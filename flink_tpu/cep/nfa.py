"""NFA for CEP pattern matching (ref flink-cep nfa/NFA.java:132,
computeNextStates:229, SURVEY §2.7).

Semantics reproduced from the reference:
- every event can START a new partial match (the start state is always
  active — NFA.java keeps a start ComputationState alive);
- STRICT stages (next) have only a "take" transition: a non-matching event
  kills the partial;
- RELAXED stages (followedBy) also have an "ignore" self-transition: the
  partial survives non-matching events, AND survives a matching event (so
  [a, b1, b2] against `a followedBy b` yields (a,b1) and (a,b2), as the
  reference's shared-buffer branching does);
- `within` prunes partials whose first event is older than the horizon
  (NFA.java's window pruning on processing each event).

Partial matches store their event lists directly — the role of the
reference's SharedBuffer (a structure to share event prefixes between
branches with Dewey-number versioning) without the sharing optimization;
host memory is not the bottleneck here, the device stages are.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.cep.pattern import Pattern, RELAXED, STRICT


@dataclass(frozen=True)
class Partial:
    stage_idx: int            # index of the last MATCHED stage
    events: Tuple[Any, ...]
    start_ts: int


class NFA:
    """One NFA instance per key; state is the list of live partials."""

    def __init__(self, pattern: Pattern):
        self.pattern = pattern
        self.stages = pattern.stages
        self.within_ms = pattern.within_ms

    def initial_state(self) -> List[Partial]:
        return []

    def process(
        self, partials: List[Partial], event, ts: int
    ) -> Tuple[List[Partial], List[Dict[str, Any]]]:
        """Advance the NFA by one event; returns (new_partials, matches).
        A match is {stage_name: event} (ref Map<String, IN> from
        NFA.process)."""
        nxt: List[Partial] = []
        matches: List[Dict[str, Any]] = []
        last = len(self.stages) - 1

        def emit_or_keep(p: Partial):
            if p.stage_idx == last:
                matches.append({
                    s.name: ev for s, ev in zip(self.stages, p.events)
                })
            else:
                nxt.append(p)

        for p in partials:
            if self.within_ms is not None and ts - p.start_ts > self.within_ms:
                continue  # window pruning: partial expired
            stage = self.stages[p.stage_idx + 1]
            if stage.matches(event):
                emit_or_keep(Partial(
                    p.stage_idx + 1, p.events + (event,), p.start_ts
                ))
                if stage.contiguity == RELAXED:
                    nxt.append(p)  # branch: also wait for later matches
            elif stage.contiguity == RELAXED:
                nxt.append(p)      # ignore transition
            # STRICT + no match: partial dies

        if self.stages[0].matches(event):
            emit_or_keep(Partial(0, (event,), ts))

        return nxt, matches

    def prune(self, partials: List[Partial], watermark_ts: int) -> List[Partial]:
        """Drop partials that can no longer complete within the window."""
        if self.within_ms is None:
            return partials
        return [
            p for p in partials if watermark_ts - p.start_ts <= self.within_ms
        ]
