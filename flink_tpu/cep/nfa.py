"""NFA for CEP pattern matching over a versioned shared buffer (ref
flink-cep nfa/NFA.java:132, computeNextStates:229, SharedBuffer.java:76,
DeweyNumber.java, SURVEY §2.7).

Semantics reproduced from the reference:
- every event can START a new partial match (the start state is always
  active — NFA.java keeps a start ComputationState alive);
- STRICT stages (next) have only a "take" transition: a non-matching event
  kills the partial;
- RELAXED stages (followedBy) also have an "ignore" self-transition: the
  partial survives non-matching events, AND survives a matching event (so
  [a, b1, b2] against `a followedBy b` yields (a,b1) and (a,b2), as the
  reference's shared-buffer branching does);
- `within` prunes partials whose first event is older than the horizon
  (NFA.java's window pruning on processing each event).

Match storage is a SHARED BUFFER, redesigned from the reference's
SharedBuffer + DeweyNumber mechanics for this runtime:

- Matched events live in ``Entry`` nodes; a partial match holds only a
  POINTER to its last entry, and entries reached by several runs (two
  'a'-partials taking the same 'b' event) are ONE node with one back
  **edge per predecessor** — prefix storage is shared exactly like the
  reference's per-(state, event) pages (SharedBuffer.java:76). Sharing
  is structural (one Python object), and pickling a key's partial list
  preserves it (pickle memoizes shared references), so checkpoints carry
  the compressed form.
- Each run (each started partial) is stamped with a **version**; every
  back edge records the version of the run that laid it. Extraction
  walks back from the completing entry following only version-matched
  edges. This is the role of the reference's Dewey numbers: when an
  expired run and a live run share a buffered prefix event, the stale
  run's edges are invisible to the live run's extraction (the
  prefix-compatibility half of Dewey numbering serves looping states —
  oneOrMore — which this Pattern grammar doesn't have, so plain version
  equality is the whole requirement; see test_cep_shared_buffer.py's
  expired-prefix case).
- Runs that CONVERGE to identical computation states — same stage, same
  entry, same version, e.g. two two-path prefixes meeting at one shared
  mid event — are deduplicated into one partial whose extraction later
  enumerates every version-matched back path, emitting each distinct
  matched sequence exactly once (the reference's one-ComputationState-
  many-paths extraction, SharedBuffer.extractPatterns).
- Pruning is reachability: a dropped partial releases its pointer and
  unshared entries die with ordinary garbage collection (the reference
  counts locks per entry — SharedBuffer.release — to the same effect).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.cep.pattern import Pattern, RELAXED


class Entry:
    """One buffered (stage, event) occurrence. ``edges`` are back
    pointers: (predecessor Entry or None for a start, run version).
    Event timestamps live on the events themselves (every CEP input
    carries one); the entry adds no copy."""

    __slots__ = ("event", "edges")

    def __init__(self, event):
        self.event = event
        self.edges: List[Tuple[Optional["Entry"], int]] = []


@dataclass(frozen=True)
class Partial:
    stage_idx: int            # index of the last MATCHED stage
    ptr: Entry                # last entry of this run's chain
    version: int              # run stamp; edges laid by this run carry it
    start_ts: int


def _paths(entry: Entry, version: int) -> List[Tuple[Any, ...]]:
    """All event sequences ending at ``entry`` along version-matched
    edges (SharedBuffer.extractPatterns analog), oldest event first."""
    out: List[Tuple[Any, ...]] = []
    for pred, v in entry.edges:
        if v != version:
            continue
        if pred is None:
            out.append((entry.event,))
        else:
            out.extend(p + (entry.event,) for p in _paths(pred, version))
    return out


class NFA:
    """One NFA instance per job (stateless); per-key state is the list of
    live partials, whose pointers root the shared buffer."""

    def __init__(self, pattern: Pattern):
        self.pattern = pattern
        self.stages = pattern.stages
        self.within_ms = pattern.within_ms

    def initial_state(self) -> List[Partial]:
        return []

    def process(
        self, partials: List[Partial], event, ts: int
    ) -> Tuple[List[Partial], List[Dict[str, Any]]]:
        """Advance the NFA by one event; returns (new_partials, matches).
        A match is {stage_name: event} (ref Map<String, IN> from
        NFA.process)."""
        partials = self._upgrade_all(partials)
        nxt: List[Partial] = []
        seen = set()       # converged-run dedup: (stage, entry, version)
        matches: List[Dict[str, Any]] = []
        last = len(self.stages) - 1
        # one shared Entry per stage this event is taken into: several
        # runs taking the same event converge on one node (the shared
        # buffer's per-(state, event) page)
        taken: Dict[int, Entry] = {}

        def take(p: Optional[Partial], stage_idx: int, start_ts: int,
                 version: int):
            entry = taken.get(stage_idx)
            if entry is None:
                entry = taken[stage_idx] = Entry(event)
            entry.edges.append((p.ptr if p else None, version))
            if stage_idx == last:
                # enumerate only the paths through the edge just laid:
                # a sibling completion sharing this entry re-walks its
                # OWN edge on its own take, so nothing double-emits
                matches.extend(
                    {s.name: ev for s, ev in zip(self.stages, seq)}
                    for seq in _walk_edge(entry, p.ptr if p else None,
                                          version)
                )
            else:
                key = (stage_idx, id(entry), version)
                if key not in seen:    # converged runs dedupe here
                    seen.add(key)
                    nxt.append(Partial(stage_idx, entry, version,
                                       start_ts))

        def _walk_edge(entry: Entry, pred: Optional[Entry],
                       version: int) -> List[Tuple[Any, ...]]:
            """Paths through ONE specific just-laid edge of ``entry``."""
            if pred is None:
                return [(entry.event,)]
            return [p + (entry.event,) for p in _paths(pred, version)]

        live_versions = []
        for p in partials:
            live_versions.append(p.version)
            if self.within_ms is not None and \
                    ts - p.start_ts > self.within_ms:
                continue  # window pruning: partial expired
            stage = self.stages[p.stage_idx + 1]
            if stage.matches(event):
                take(p, p.stage_idx + 1, p.start_ts, p.version)
                if stage.contiguity == RELAXED:
                    nxt.append(p)  # branch: also wait for later matches
            elif stage.contiguity == RELAXED:
                nxt.append(p)      # ignore transition
            # STRICT + no match: partial dies

        if self.stages[0].matches(event):
            # fresh run number: distinct from every LIVE run (a dead
            # run's number may recur — its edges live only on entries
            # created before this run existed, which this run's chain
            # can never reach)
            take(None, 0, ts, max(live_versions, default=-1) + 1)

        return nxt, matches

    def prune(self, partials: List[Partial],
              watermark_ts: int) -> List[Partial]:
        """Drop partials that can no longer complete within the window;
        entries only they referenced are garbage-collected (the
        SharedBuffer.release analog)."""
        if self.within_ms is None:
            return partials
        return [
            p for p in self._upgrade_all(partials)
            if watermark_ts - p.start_ts <= self.within_ms
        ]

    # -- legacy state ----------------------------------------------------
    @staticmethod
    def _upgrade_all(partials: List) -> List[Partial]:
        """Accept pre-shared-buffer checkpointed partials, which stored
        the full event tuple (attribute ``events``) instead of a buffer
        pointer: rebuild unshared chains (correct, just uncompressed).
        Each restored run gets a DISTINCT negative version — stamping
        them all alike would let the convergence dedup conflate
        different runs (different start_ts) into one, dropping or
        resurrecting matches under within(); negatives can't collide
        with live non-negative run numbers."""
        if all(isinstance(p, Partial) and not hasattr(p, "events")
               for p in partials):
            return list(partials)
        out: List[Partial] = []
        for i, p in enumerate(partials):
            if isinstance(p, Partial) and not hasattr(p, "events"):
                out.append(p)
                continue
            entry = None
            version = -1 - i
            for ev in p.events:
                e = Entry(ev)
                e.edges.append((entry, version))
                entry = e
            out.append(Partial(p.stage_idx, entry, version, p.start_ts))
        return out
