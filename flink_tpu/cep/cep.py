"""CEP entry points (ref flink-cep CEP.java + PatternStream.java)."""

from __future__ import annotations

from typing import Callable

from flink_tpu.cep.operator import CEPProcessFunction
from flink_tpu.cep.pattern import Pattern
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.datastream.datastream import DataStream, KeyedStream


class PatternStream:
    """ref PatternStream: select/flatSelect over detected matches. A match
    is a dict {stage_name: event}."""

    def __init__(self, stream: DataStream, pattern: Pattern):
        self.stream = stream
        self.pattern = pattern

    def _keyed(self) -> KeyedStream:
        if isinstance(self.stream, KeyedStream):
            return self.stream
        # non-keyed pattern stream: single logical partition
        # (ref CEPOperatorUtils applying a NullByteKeySelector)
        return self.stream.key_by(lambda e: 0)

    def _run(self, fn: Callable, flat: bool) -> DataStream:
        keyed = self._keyed()
        event_time = (
            keyed.env.time_characteristic == TimeCharacteristic.EventTime
        )
        return keyed.process(CEPProcessFunction(
            self.pattern, fn, flat=flat, event_time=event_time,
        ))

    def select(self, fn: Callable) -> DataStream:
        """fn(match_dict) -> one result per match."""
        return self._run(fn, flat=False)

    def flat_select(self, fn: Callable) -> DataStream:
        """fn(match_dict) -> iterable of results per match."""
        return self._run(fn, flat=True)


class CEP:
    @staticmethod
    def pattern(stream: DataStream, pattern: Pattern) -> PatternStream:
        return PatternStream(stream, pattern)
