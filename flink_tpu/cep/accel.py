"""Production bridge putting the device CEP kernel behind CEP.pattern().

Division of labor (ref flink-cep NFA.java:132 / SharedBuffer — redesigned
for the batch/SPMD execution model instead of per-event JVM calls):

  * DEVICE (cep/device.py): per micro-batch, the segmented associative
    matrix scan advances EVERY key's match-count NFA and reports, per
    lane, how many matches COMPLETED there (`delta`). This is the
    detection engine — exact counts, no per-key host work.
  * HOST (this module): keeps, per key, only the COMPACTED stream of
    stage-matching events (the SharedBuffer analog — non-matching events
    are never stored) plus a one-bit gap marker per stored event ("were
    there intervening non-matching events of this key?"), which is all a
    linear NFA needs: a gap kills partials waiting on a STRICT stage and
    is invisible to RELAXED stages. Only when the device reports a
    completion for a key does the host replay that key's pending
    compacted events through the exact host NFA (cep/nfa.py) to build the
    {stage: event} match dicts.

Result: per-event Python work is O(predicate hits), NFA branching work is
O(events of completing keys), and the device scan decides both. For
detection workloads (rare matches over dense streams) this removes the
per-record NFA from the hot path entirely, the same way the window
kernels removed HeapReducingState.add.

Eligibility (executor falls back to the host operator otherwise):
processing-time mode (arrival order; the event-time buffer-and-sort
drain stays host-side). within() IS supported (round 4): partial counts
are bucketed by start-time pane on device (cep/device.py ring rotation
= expiry), semantics equal to the host NFA on pane-quantized timestamps
(cep.device.within-buckets config, default 8 buckets per within
horizon). parallelism>1 shards the count-NFA over the mesh by key group
(round 4, n_shards; replicate-and-mask + one psum). Checkpoint/
savepoint/restore are fully supported (snapshot()/restore() below; the
barrier is the step boundary).

Memory note: a key's compacted events stay buffered while it has live
partials that could still complete (exactly the events the reference's
SharedBuffer would be holding); keys whose device count-state is all
zero hold no buffer entries after their next replay.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from flink_tpu.cep.device import (
    CepShardState, DevicePatternSpec, advance, init_state,
)
from flink_tpu.cep.nfa import NFA
from flink_tpu.cep.pattern import Pattern, RELAXED
from flink_tpu.core.types import KeyCodec


def batch_gaps(inv: np.ndarray, hit: np.ndarray,
               trailing_in: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-hit-lane gap bits for one micro-batch, vectorized.

    inv[B]        factorized key id per lane (0..G-1)
    hit[B]        lane matched >=1 stage predicate
    trailing_in[G] per key-group: non-matching events of this key were
                  seen after its last stored event (carried across batches)

    Returns (gap[B] — True at hit lanes whose key saw >=1 non-hit event
    since its previous hit event; False elsewhere — and trailing_out[G]).
    """
    B = len(inv)
    if B == 0:
        return np.zeros(0, bool), trailing_in.copy()
    perm = np.argsort(inv, kind="stable")     # group by key, arrival order
    inv_s = inv[perm]
    hit_s = hit[perm]
    idx = np.arange(B)

    is_new = np.r_[True, inv_s[1:] != inv_s[:-1]]
    grp_id = np.cumsum(is_new) - 1            # dense group ids, sorted order
    grp_start = np.nonzero(is_new)[0]
    grp_key = inv_s[grp_start]                # group -> key factor id

    nh_before = np.cumsum(~hit_s) - (~hit_s)  # non-hits strictly before lane
    nhw = nh_before - nh_before[grp_start][grp_id]   # ...within the group

    ph = np.maximum.accumulate(np.where(hit_s, idx, -1))
    prev_hit = np.r_[-1, ph[:-1]]             # last hit at or before lane-1
    has_prev = prev_hit >= grp_start[grp_id]  # ...within the same group
    prev_nhw = np.where(has_prev, nhw[np.clip(prev_hit, 0, B - 1)], 0)

    tin_s = trailing_in[grp_key][grp_id]      # per-lane carried trailing bit
    gap_s = np.where(
        has_prev, (nhw - prev_nhw) > 0, (nhw > 0) | tin_s
    ) & hit_s

    # carry-out per key: non-hits after the key's last hit in this batch
    # (whole batch counts if the key had no hit — OR with the carried bit)
    grp_end = np.r_[grp_start[1:], B] - 1
    nh_total = nhw[grp_end] + (~hit_s[grp_end])
    last_hit = ph[grp_end]
    had_hit = last_hit >= grp_start
    nh_after = np.where(
        had_hit,
        nh_total - (nhw[np.clip(last_hit, 0, B - 1)]
                    + 0),                     # last_hit lane is a hit
        nh_total,
    )
    trailing_out = trailing_in.copy()
    trailing_out[grp_key] = np.where(
        had_hit, nh_after > 0, trailing_in[grp_key] | (nh_total > 0)
    )

    gap = np.zeros(B, bool)
    gap[perm] = gap_s
    return gap, trailing_out


class DeviceCepOperator:
    """Keyed CEP over micro-batches: device count-NFA detection + lazy
    host replay extraction. One instance per job.

    n_shards > 1 (round 4): the count-NFA state shards over the device
    mesh by key group — each shard holds its keys' tables and carry
    vectors and masks the batch to the key groups it owns
    (replicate-and-mask, the same exchange the window kernels default
    from at small batch); per-lane completion deltas are disjoint across
    shards, so one psum over the mesh axis reassembles the global [B]
    delta. The host side (compacted buffers, replay extraction) stays a
    single process, exactly like the executor's windowed path."""

    def __init__(self, pattern: Pattern, capacity: int = 1 << 16,
                 probe_len: int = 16, within_buckets: int = 8,
                 n_shards: int = 1, max_parallelism: int = 128):
        self.pattern = pattern
        self.spec = DevicePatternSpec.from_pattern(
            pattern, within_buckets=within_buckets
        )
        self.nfa = NFA(pattern)
        self.stages = pattern.stages
        self.codec = KeyCodec()
        self.capacity = 1 << max(1, int(capacity) - 1).bit_length()
        self.n_shards = n_shards
        self.max_parallelism = max_parallelism
        if n_shards > 1:
            self._init_sharded(probe_len)
        else:
            self.state: CepShardState = init_state(
                self.capacity, probe_len, self.spec
            )
            self._advance = jax.jit(
                advance, static_argnums=1, donate_argnums=0
            )
        # per-key host side (keyed by the 64-bit codec hash; original key
        # objects ride inside the buffered events for match extraction)
        self.buffers: Dict[int, List[Tuple[Any, bool, int]]] = {}
        self.partials: Dict[int, list] = {}
        self.trailing: Dict[int, bool] = {}
        # honesty metrics: the device count and host extraction must agree
        self.matches_detected = 0      # device-side completions
        self.matches_extracted = 0     # host-replay match dicts
        self.steps = 0
        # within(): panes rebase to the first batch's pane so epoch-ms
        # timestamps fit the device's int32 pane arithmetic
        self._pane_origin: Optional[int] = None

    def _init_sharded(self, probe_len: int):
        """Build the SPMD advance step: state sharded [S, ...] over the
        mesh, batch replicated, key-group masking per shard, deltas
        reassembled with one psum."""
        import jax.numpy as jnp
        from flink_tpu.core.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from flink_tpu.core.keygroups import assign_to_key_group
        from flink_tpu.ops.hashing import route_hash
        from flink_tpu.parallel.mesh import SHARD_AXIS, MeshContext

        ctx = MeshContext.create(self.n_shards, self.max_parallelism)
        self._ctx = ctx
        starts, ends = ctx.kg_bounds()
        starts_j = jnp.asarray(starts)
        ends_j = jnp.asarray(ends)
        spec = self.spec
        maxp = self.max_parallelism
        # `capacity` is PER SHARD (matching env.state_capacity_per_shard
        # and the single-shard path)
        cap_per_shard = self.capacity

        def shard_body(state, kg_start, kg_end, hi, lo, masks, valid,
                       pane):
            state = jax.tree_util.tree_map(lambda x: x[0], state)
            kg_start, kg_end = kg_start[0], kg_end[0]
            kg = assign_to_key_group(route_hash(hi, lo, jnp), maxp, jnp)
            mine = valid & (kg >= kg_start.astype(jnp.uint32)) & (
                kg <= kg_end.astype(jnp.uint32)
            )
            state, delta, _tot = advance(state, spec, hi, lo, masks,
                                         mine, pane)
            # owned lanes are disjoint across shards: psum reassembles
            total_delta = jax.lax.psum(delta, SHARD_AXIS)
            return (
                jax.tree_util.tree_map(lambda x: x[None], state),
                total_delta,
            )

        sharded = shard_map(
            shard_body, mesh=ctx.mesh,
            in_specs=(
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(), P(), P(), P(), P(),
            ),
            out_specs=(P(SHARD_AXIS), P()),
            check_vma=False,
        )

        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, hi, lo, masks, valid, pane):
            return sharded(state, starts_j, ends_j, hi, lo, masks, valid,
                           pane)

        def sharded_init():
            st = init_state(cap_per_shard, probe_len, spec)
            return jax.tree_util.tree_map(lambda x: x[None], st)

        init_fn = jax.jit(shard_map(
            sharded_init, mesh=ctx.mesh, in_specs=(),
            out_specs=P(SHARD_AXIS), check_vma=False,
        ))
        self.state = init_fn()
        self._sharded_step = step

        def adv(state, _spec, hi, lo, masks, valid, pane):
            st, delta = step(state, hi, lo, masks, valid, pane)
            # the caller discards the per-batch total; never pay an
            # extra eager device op for it on the hot path (per-op
            # dispatch latency is the cost model on this runtime)
            return st, delta, None

        self._advance = adv

    @property
    def dropped_capacity(self) -> int:
        return int(np.asarray(self.state.dropped_capacity).sum())

    def _masks(self, elements: Sequence) -> np.ndarray:
        S = len(self.stages)
        m = np.zeros((len(elements), S), bool)
        for j, st in enumerate(self.stages):
            # matches_batch evaluates vectorized where_batch predicates
            # once per micro-batch (and is exactly per-event equivalent
            # for scalar predicates)
            m[:, j] = st.matches_batch(elements)
        return m

    def process_batch(self, elements: Sequence, keys: Sequence,
                      ts: int, pad_to: Optional[int] = None) -> List[dict]:
        """Advance by one micro-batch (arrival order); returns the list of
        completed match dicts {stage_name: event}."""
        B = len(elements)
        if B == 0:
            return []
        # within(): device pruning is pane-bucketed (device.py), so the
        # host replay must see the SAME quantized timestamps or its exact
        # within check could disagree with the device's count decisions
        pane = 0
        if self.spec.pane_ms:
            pane = int(ts) // self.spec.pane_ms
            ts = pane * self.spec.pane_ms
            if self._pane_origin is None:
                self._pane_origin = pane
            pane -= self._pane_origin
        masks = self._masks(elements)
        hi, lo = self.codec.encode(list(keys), keep_reverse=False)
        hi = np.asarray(hi, np.uint32)
        lo = np.asarray(lo, np.uint32)
        k64 = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)

        n = pad_to or B
        valid = np.zeros(n, bool)
        valid[:B] = True
        if n != B:
            hi = np.pad(hi, (0, n - B))
            lo = np.pad(lo, (0, n - B))
            masks = np.pad(masks, ((0, n - B), (0, 0)))

        self.state, delta, _total = self._advance(
            self.state, self.spec, hi, lo, masks, valid, np.int32(pane)
        )
        delta = np.asarray(delta)[:B]
        masks = masks[:B]
        self.steps += 1

        # ---- host compaction: store hit events (+ gap bits) per key ----
        hit = masks.any(axis=1)
        uniq, inv = np.unique(k64, return_inverse=True)
        tin = np.fromiter(
            (self.trailing.get(int(u), False) for u in uniq),
            bool, count=len(uniq),
        )
        gap, tout = batch_gaps(inv, hit, tin)
        for g, u in zip(tout, uniq):
            self.trailing[int(u)] = bool(g)
        for i in np.nonzero(hit)[0]:
            self.buffers.setdefault(int(k64[i]), []).append(
                (elements[i], bool(gap[i]), ts)
            )

        # ---- lazy extraction: replay only keys the device flags --------
        out: List[dict] = []
        done = np.nonzero(delta > 0)[0]
        if len(done):
            self.matches_detected += int(round(float(delta[done].sum())))
            for u in np.unique(k64[done]):
                out.extend(self._replay(int(u)))
        self.matches_extracted += len(out)
        return out

    # -- checkpoint / savepoint / queryable seams -----------------------
    def snapshot(self) -> dict:
        """Full operator state as host objects (device arrays fetched),
        ready for CheckpointStorage.write_generic. The barrier is the
        step boundary, as everywhere in this framework (SURVEY §3.4)."""
        return {
            "device": jax.tree_util.tree_map(
                lambda x: np.asarray(x), jax.device_get(self.state)
            ),
            "buffers": dict(self.buffers),
            "partials": dict(self.partials),
            "trailing": dict(self.trailing),
            "matches_detected": self.matches_detected,
            "matches_extracted": self.matches_extracted,
            "steps": self.steps,
            "capacity": self.capacity,
            "pane_origin": self._pane_origin,
            # within() bucketing params: a restore under a different
            # cep.device.within-buckets would reinterpret the ring
            "pane_ms": self.spec.pane_ms,
            "within_panes": self.spec.within_panes,
            "n_shards": self.n_shards,
            "max_parallelism": self.max_parallelism,
        }

    def restore(self, snap: dict):
        import jax.numpy as jnp

        if snap["capacity"] != self.capacity:
            raise ValueError(
                f"device CEP capacity mismatch: snapshot {snap['capacity']} "
                f"vs configured {self.capacity}"
            )
        if snap.get("n_shards", 1) != self.n_shards:
            raise ValueError(
                f"device CEP shard-count mismatch: snapshot has "
                f"{snap.get('n_shards', 1)} shard(s), job configured for "
                f"{self.n_shards} — restore with the same parallelism"
            )
        snap_maxp = snap.get("max_parallelism", self.max_parallelism)
        if snap_maxp != self.max_parallelism:
            # the key-group routing baked into shard tables would silently
            # misroute keys (same contract as the executor's keyed paths)
            raise ValueError(
                f"device CEP max-parallelism mismatch: snapshot "
                f"{snap_maxp} vs configured {self.max_parallelism}"
            )
        snap_pane = (snap.get("pane_ms", self.spec.pane_ms),
                     snap.get("within_panes", self.spec.within_panes))
        if snap_pane != (self.spec.pane_ms, self.spec.within_panes):
            raise ValueError(
                f"device CEP within() bucketing mismatch: snapshot used "
                f"pane_ms={snap_pane[0]}, ring={snap_pane[1]} but the job "
                f"is configured for pane_ms={self.spec.pane_ms}, ring="
                f"{self.spec.within_panes} — restore with the same "
                f"cep.device.within-buckets setting"
            )
        self.state = jax.tree_util.tree_map(jnp.asarray, snap["device"])
        self.buffers = dict(snap["buffers"])
        self.partials = dict(snap["partials"])
        self.trailing = dict(snap["trailing"])
        self.matches_detected = snap["matches_detected"]
        self.matches_extracted = snap["matches_extracted"]
        self.steps = snap["steps"]
        self._pane_origin = snap.get("pane_origin")

    def peek_state(self, key):
        """Queryable-state read: this key's live partial matches, with
        pending (unreplayed) compacted events applied NON-destructively —
        pending events never contain a completion (the device would have
        flagged it), so no match is swallowed. Returns None when the key
        has no live partials (host-path 'cep-nfa-state' parity)."""
        hi, lo = self.codec.encode([key], keep_reverse=False)
        k = int((np.uint64(hi[0]) << np.uint64(32)) | np.uint64(lo[0]))
        partials, _ms = self._advance_partials(
            list(self.partials.get(k, [])), list(self.buffers.get(k, []))
        )
        # the carried trailing bit (non-matching events after the last
        # stored event) is normally folded into the NEXT hit's gap bit;
        # the host path kills strict-waiting partials the moment the
        # non-match arrives, so a parity read must apply it eagerly
        if partials and self.trailing.get(k, False):
            partials = [
                p for p in partials
                if self.stages[p.stage_idx + 1].contiguity == RELAXED
            ]
        return partials or None

    def _advance_partials(self, partials: list,
                          buf: Sequence) -> Tuple[list, List[dict]]:
        """The single replay loop shared by extraction and queryable
        reads: gap bits kill partials waiting on a STRICT stage, then the
        exact host NFA advances."""
        matches: List[dict] = []
        for ev, gap_before, ts in buf:
            if gap_before and partials:
                partials = [
                    p for p in partials
                    if self.stages[p.stage_idx + 1].contiguity == RELAXED
                ]
            partials, ms = self.nfa.process(partials, ev, ts)
            matches.extend(ms)
        return partials, matches

    def _replay(self, k: int) -> List[dict]:
        partials, matches = self._advance_partials(
            self.partials.get(k, []), self.buffers.pop(k, [])
        )
        self.partials[k] = partials
        return matches

    def prune_dead_keys(self) -> List[dict]:
        """Bound host memory to true NFA-partials size (the SharedBuffer
        pruning analog). Pending buffers of unflagged keys contain NO
        completions (the device would have flagged them), so they can be
        drained destructively into each key's partials; dead 'a x a x'
        histories then collapse to the <=1 live partial the host NFA
        would hold. Keys that never won a table slot (capacity overflow,
        counted in dropped_capacity) can never be flagged for replay —
        their state is freed outright. Returns any matches found during
        the drain (expected empty; emitted defensively by the runner
        rather than swallowed). One device fetch per call."""
        if not (self.buffers or self.partials or self.trailing):
            return []
        from flink_tpu.ops.hashtable import EMPTY

        # flattens both layouts: single-shard [C, 2] and sharded [S, C, 2]
        tk = np.asarray(jax.device_get(self.state.table.keys)).reshape(-1, 2)
        occ = ~np.all(tk == EMPTY, axis=1)
        k64 = (tk[:, 0].astype(np.uint64) << np.uint64(32)) | \
            tk[:, 1].astype(np.uint64)
        in_table = set(int(v) for v in k64[occ])

        unexpected: List[dict] = []
        for k in list(self.buffers):
            if k not in in_table:
                del self.buffers[k]          # capacity-dropped key
                continue
            partials, ms = self._advance_partials(
                self.partials.get(k, []), self.buffers.pop(k)
            )
            unexpected.extend(ms)
            if partials:
                self.partials[k] = partials
            else:
                self.partials.pop(k, None)
        for k in [k for k in self.partials
                  if not self.partials[k] or k not in in_table]:
            del self.partials[k]
        # trailing bits only matter for keys with live strict-waiting
        # partials; everything else regrows from scratch
        for k in [k for k in self.trailing if k not in self.partials]:
            del self.trailing[k]
        self.matches_extracted += len(unexpected)
        return unexpected
