"""CEP Pattern API (ref flink-cep pattern/Pattern.java, SURVEY §2.7).

A pattern is a linear sequence of named stages, each with a predicate and a
contiguity mode relative to its predecessor:

    Pattern.begin("start").where(p1).next("mid").where(p2) \
           .followed_by("end").where(p3).within(10_000)

- next       = strict contiguity (the very next event must match, else the
               partial match dies) — ref Pattern.next
- followed_by = relaxed contiguity (non-matching events are skipped; an
               "ignore" self-transition keeps the partial alive) —
               ref Pattern.followedBy
- where      adds a predicate (ANDed with any existing one — ref
               Pattern.where's FilterFunction conjunction); or_ ORs one
- subtype    restricts the stage to an isinstance check — ref Pattern.subtype
- within     bounds first-to-last event time — ref Pattern.within
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

STRICT = "strict"      # next()
RELAXED = "relaxed"    # followedBy()


@dataclass
class Stage:
    name: str
    contiguity: str            # STRICT for next(), RELAXED for followedBy()
    predicates: List[Callable] = field(default_factory=list)  # ANDed
    or_predicates: List[Callable] = field(default_factory=list)

    def matches(self, event) -> bool:
        base = all(p(event) for p in self.predicates)
        if self.or_predicates:
            return base or any(p(event) for p in self.or_predicates)
        return base


class Pattern:
    def __init__(self):
        self.stages: List[Stage] = []
        self.within_ms: Optional[int] = None

    @staticmethod
    def begin(name: str) -> "Pattern":
        p = Pattern()
        p.stages.append(Stage(name, RELAXED))
        return p

    def _add(self, name: str, contiguity: str) -> "Pattern":
        if any(s.name == name for s in self.stages):
            raise ValueError(f"duplicate stage name {name!r}")
        self.stages.append(Stage(name, contiguity))
        return self

    def next(self, name: str) -> "Pattern":
        return self._add(name, STRICT)

    def followed_by(self, name: str) -> "Pattern":
        return self._add(name, RELAXED)

    def where(self, predicate: Callable) -> "Pattern":
        self.stages[-1].predicates.append(predicate)
        return self

    def or_(self, predicate: Callable) -> "Pattern":
        self.stages[-1].or_predicates.append(predicate)
        return self

    def subtype(self, cls) -> "Pattern":
        self.stages[-1].predicates.append(lambda e, _c=cls: isinstance(e, _c))
        return self

    def within(self, ms: int) -> "Pattern":
        self.within_ms = int(ms)
        return self
