"""CEP Pattern API (ref flink-cep pattern/Pattern.java, SURVEY §2.7).

A pattern is a linear sequence of named stages, each with a predicate and a
contiguity mode relative to its predecessor:

    Pattern.begin("start").where(p1).next("mid").where(p2) \
           .followed_by("end").where(p3).within(10_000)

- next       = strict contiguity (the very next event must match, else the
               partial match dies) — ref Pattern.next
- followed_by = relaxed contiguity (non-matching events are skipped; an
               "ignore" self-transition keeps the partial alive) —
               ref Pattern.followedBy
- where      adds a predicate (ANDed with any existing one — ref
               Pattern.where's FilterFunction conjunction); or_ ORs one
- subtype    restricts the stage to an isinstance check — ref Pattern.subtype
- within     bounds first-to-last event time — ref Pattern.within
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

STRICT = "strict"      # next()
RELAXED = "relaxed"    # followedBy()


@dataclass
class Stage:
    name: str
    contiguity: str            # STRICT for next(), RELAXED for followedBy()
    predicates: List[Callable] = field(default_factory=list)  # ANDed
    or_predicates: List[Callable] = field(default_factory=list)
    # vectorized predicate: fn(Sequence[event]) -> bool array. ANDed with
    # the scalar predicates like any other where() clause; the device
    # engine evaluates it ONCE per micro-batch instead of per event
    # (per-event Python predicate calls are the host-side cost of the
    # CEP hot path — see cep/accel._masks)
    batch_predicates: List[Callable] = field(default_factory=list)

    def matches(self, event) -> bool:
        base = all(p(event) for p in self.predicates)
        if base and self.batch_predicates:
            base = all(bool(p([event])[0]) for p in self.batch_predicates)
        if self.or_predicates:
            return base or any(p(event) for p in self.or_predicates)
        return base

    def matches_batch(self, events) -> "object":
        """bool array over ``events`` — the vectorized form of
        ``matches``, exact by construction: scalar predicates evaluate
        per event, batch predicates once per batch, combined with the
        same AND/OR structure."""
        import numpy as np

        n = len(events)
        base = np.ones(n, bool)
        for p in self.predicates:
            base &= np.fromiter((bool(p(e)) for e in events), bool,
                                count=n)
        for p in self.batch_predicates:
            base &= np.asarray(p(events), bool)
        if self.or_predicates:
            alt = np.zeros(n, bool)
            for p in self.or_predicates:
                alt |= np.fromiter((bool(p(e)) for e in events), bool,
                                   count=n)
            return base | alt
        return base


class Pattern:
    def __init__(self):
        self.stages: List[Stage] = []
        self.within_ms: Optional[int] = None

    @staticmethod
    def begin(name: str) -> "Pattern":
        p = Pattern()
        p.stages.append(Stage(name, RELAXED))
        return p

    def _add(self, name: str, contiguity: str) -> "Pattern":
        if any(s.name == name for s in self.stages):
            raise ValueError(f"duplicate stage name {name!r}")
        self.stages.append(Stage(name, contiguity))
        return self

    def next(self, name: str) -> "Pattern":
        return self._add(name, STRICT)

    def followed_by(self, name: str) -> "Pattern":
        return self._add(name, RELAXED)

    def where(self, predicate: Callable) -> "Pattern":
        self.stages[-1].predicates.append(predicate)
        return self

    def where_batch(self, predicate: Callable) -> "Pattern":
        """Vectorized ``where``: ``predicate(events) -> bool array``
        evaluated once per micro-batch by the device engine (and exactly
        equivalent per event everywhere else). Worthwhile when the
        per-event predicate itself is expensive; note the host match-
        EXTRACTION replay evaluates conditions per event, where a batch
        predicate degenerates to a singleton call — on match-dense
        streams with cheap predicates the scalar ``where`` measures
        faster end to end."""
        self.stages[-1].batch_predicates.append(predicate)
        return self

    def or_(self, predicate: Callable) -> "Pattern":
        self.stages[-1].or_predicates.append(predicate)
        return self

    def subtype(self, cls) -> "Pattern":
        self.stages[-1].predicates.append(lambda e, _c=cls: isinstance(e, _c))
        return self

    def within(self, ms: int) -> "Pattern":
        self.within_ms = int(ms)
        return self
