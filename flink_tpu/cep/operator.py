"""Keyed CEP operator (ref flink-cep operator/AbstractKeyedCEPPatternOperator
+ KeyedCEPPatternOperator, SURVEY §2.7).

Event-time mode reproduces the reference's behavior: elements are buffered
per key in a priority queue keyed by timestamp, an event-time timer is
registered at each element's timestamp, and on watermark advance the buffer
is drained IN TIMESTAMP ORDER into the NFA (the event-time sort that makes
CEP deterministic under out-of-order input). Processing-time mode feeds the
NFA directly in arrival order (ref KeyedCEPPatternOperator.processElement's
processing-time branch).
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, List, Optional

from flink_tpu.cep.nfa import NFA, Partial
from flink_tpu.datastream.functions import (
    Collector, ProcessFunction, RuntimeContext,
)
from flink_tpu.state.descriptors import ValueStateDescriptor


class CEPProcessFunction(ProcessFunction):
    def __init__(self, pattern, select_fn: Callable, flat: bool,
                 event_time: bool):
        self.pattern = pattern     # executor routing: device kernel checks
        self.nfa = NFA(pattern)
        self.select_fn = select_fn
        self.flat = flat
        self.event_time = event_time

    def open(self, ctx: RuntimeContext):
        # per-key NFA computation state (ref keeping NFA in ValueState)
        self.partials = ctx.get_state(
            ValueStateDescriptor("cep-nfa-state", default=None)
        )
        # per-key event buffer for event-time ordering (ref the operator's
        # PriorityQueue<StreamRecord> kept in ValueState)
        self.buffer = ctx.get_state(
            ValueStateDescriptor("cep-buffer", default=None)
        )

    # -- helpers ---------------------------------------------------------
    def _advance(self, partials: List[Partial], event, ts: int,
                 out: Collector) -> List[Partial]:
        partials, matches = self.nfa.process(partials, event, ts)
        for m in matches:
            if self.flat:
                for r in self.select_fn(m):
                    out.collect(r)
            else:
                out.collect(self.select_fn(m))
        return partials

    # -- ProcessFunction contract ---------------------------------------
    def process_element(self, value, ctx, out):
        ts = ctx.timestamp()
        if not self.event_time:
            partials = self.partials.value() or []
            self.partials.update(
                self._advance(list(partials), value, ts, out)
            )
            return
        # arrival-order tiebreak lives IN the keyed state so it survives
        # restore (a reset counter would collide on (ts, seq) and make
        # heapq compare raw event payloads)
        state = self._buffer_state()
        heapq.heappush(state["heap"], (ts, state["seq"], value))
        state["seq"] += 1
        self.buffer.update(state)
        # fire once the watermark passes this element's timestamp
        ctx.timer_service().register_event_time_timer(ts)

    def _buffer_state(self) -> dict:
        state = self.buffer.value()
        if not state:
            return {"seq": 0, "heap": []}
        if isinstance(state, list):  # pre-dict snapshots (heap only)
            # seed past every live seq: earlier pops may have consumed low
            # seqs, and a collision would make heapq compare event payloads
            return {
                "seq": max((s for _, s, _ in state), default=-1) + 1,
                "heap": state,
            }
        return state

    def on_timer(self, timestamp, ctx, out):
        wm = ctx.timer_service().current_watermark()
        state = self._buffer_state()
        buf = state["heap"]
        partials = list(self.partials.value() or [])
        while buf and buf[0][0] <= wm:
            ts, _seq, event = heapq.heappop(buf)
            partials = self._advance(partials, event, ts, out)
        partials = self.nfa.prune(partials, wm)
        self.buffer.update(state)
        self.partials.update(partials)
