"""TPU-resident CEP: the NFA as a segmented associative matrix scan.

The reference advances one NFA per key one event at a time
(flink-cep/.../nfa/NFA.java:132, computeNextStates:229): per event, each
live partial match either takes, ignores, or dies. The TPU-native insight:
with per-stage PARTIAL COUNTS as state, that transition is LINEAR —

    state vector v = [c_0, ..., c_{S-2}, M, 1]
      c_s = number of live partials whose last matched stage is s
      M   = cumulative completed matches
      1   = homogeneous coordinate (lets "start a new partial" be linear)

    per event e with stage-match bits m_0..m_{S-1}:
      c_s'  = m_s * c_{s-1}              (take into stage s)
            + keep_s * c_s               (keep_s = 1 iff stage s+1 is
                                          relaxed: the ignore transition —
                                          a strict successor consumes or
                                          kills, NFA.java take/ignore edges)
      c_0' += m_0 * 1                    (every event may start a partial)
      M'    = M + m_{S-1} * c_{S-2}      (take into the final stage emits)

so one event is a (S+1)x(S+1) matrix T(e), and a KEY's whole event
sequence is the ordered product T(e_k) @ ... @ T(e_1). A micro-batch is
processed by sorting lanes by key slot (stable — preserves arrival order
within a key) and running ONE jax.lax.associative_scan with a segmented
matrix-product combiner. No per-event control flow, no per-key loops;
B events x (S+1)^3 x log2(B) MXU-friendly work.

Semantics vs the host NFA (cep/nfa.py — which stays as the generality
path): match COUNTS and completion positions are exact, including the
relaxed-contiguity branching explosion. What the count representation
drops is the per-partial event list — match *extraction* (the
{stage: event} maps) is host-side: the executor replays only the keys
that completed a match this batch through the host NFA (rare in
detection workloads). `within` pruning needs per-partial start
timestamps, so patterns with within() take the host path.

Counts saturate at INT32_MAX via int32 wraparound guard (clamped adds);
a pattern whose branching actually approaches 2^31 live partials is
degenerate under the reference too (its SharedBuffer would OOM).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.cep.pattern import Pattern, RELAXED
from flink_tpu.ops import hashtable
from flink_tpu.ops.hashtable import SlotTable

INT_MAX = np.float32(2**31 - 1)


@dataclass(frozen=True)
class DevicePatternSpec:
    """Static compile spec of a linear pattern for the device NFA.

    relaxed[s] — stage s's contiguity (relaxed=True for followedBy).
    Built from a Pattern via `from_pattern`; patterns with within() are
    rejected (host path handles them)."""

    n_stages: int
    relaxed: Tuple[bool, ...]

    @staticmethod
    def from_pattern(p: Pattern) -> "DevicePatternSpec":
        if p.within_ms is not None:
            raise ValueError(
                "device CEP does not support within() — per-partial start "
                "timestamps do not fit the count representation; use the "
                "host NFA path"
            )
        return DevicePatternSpec(
            n_stages=len(p.stages),
            relaxed=tuple(s.contiguity == RELAXED for s in p.stages),
        )

    @property
    def dim(self) -> int:
        # [c_0 .. c_{S-2}, M, 1]
        return self.n_stages + 1


def event_matrices(spec: DevicePatternSpec, masks: jax.Array) -> jax.Array:
    """masks: bool[B, S] stage-match bits per event -> T: f32[B, D, D].

    Row layout of v (column vector convention, v' = T @ v):
      rows 0..S-2: stage counts; row S-1: M; row S: const 1.
    """
    S = spec.n_stages
    D = spec.dim
    B = masks.shape[0]
    m = masks.astype(jnp.float32)
    T = jnp.zeros((B, D, D), jnp.float32)
    # const row stays 1
    T = T.at[:, D - 1, D - 1].set(1.0)
    # M row: M' = M + m_{S-1} * c_{S-2}   (S == 1: + m_0 * 1)
    T = T.at[:, S - 1, S - 1].set(1.0)
    if S == 1:
        T = T.at[:, 0, D - 1].add(m[:, 0])
    else:
        T = T.at[:, S - 1, S - 2].add(m[:, S - 1])
        # stage rows
        for s in range(S - 1):
            keep = 1.0 if spec.relaxed[s + 1] else 0.0
            T = T.at[:, s, s].add(keep)
            if s == 0:
                T = T.at[:, 0, D - 1].add(m[:, 0])   # start transition
            else:
                T = T.at[:, s, s - 1].add(m[:, s])   # take into stage s
    return T


def _seg_matmul(a, b):
    """Segmented combiner for associative_scan: a/b = (seg_id, matrix).
    Within a segment matrices compose; across a boundary the right
    element resets the product."""
    sa, Ma = a
    sb, Mb = b
    same = (sa == sb)[..., None, None]
    return sb, jnp.where(same, Mb @ Ma, Mb)


@jax.tree_util.register_pytree_node_class
@dataclass
class CepShardState:
    table: SlotTable
    carry: jax.Array          # f32 [C+1, D] per-key state vector (+1 spill row)
    dropped_capacity: jax.Array

    def tree_flatten(self):
        return (self.table, self.carry, self.dropped_capacity), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def init_state(capacity: int, probe_len: int,
               spec: DevicePatternSpec) -> CepShardState:
    D = spec.dim
    carry = jnp.zeros((capacity + 1, D), jnp.float32)
    carry = carry.at[:, D - 1].set(1.0)   # homogeneous 1
    return CepShardState(
        table=hashtable.create(capacity, probe_len),
        carry=carry,
        dropped_capacity=jnp.zeros((), jnp.int32),
    )


def advance(
    state: CepShardState,
    spec: DevicePatternSpec,
    hi: jax.Array,
    lo: jax.Array,
    masks: jax.Array,     # bool [B, S]
    valid: jax.Array,     # bool [B]
) -> Tuple[CepShardState, jax.Array, jax.Array]:
    """Advance every key's NFA by this micro-batch.

    Returns (state', match_delta f32[B], match_total_per_lane) where
    match_delta[i] = completed matches triggered exactly at lane i (in the
    ORIGINAL lane order) — the host uses nonzero lanes for extraction."""
    B = hi.shape[0]
    C = state.table.capacity
    D = spec.dim

    # 8 claim rounds: no spill tier here — see session_windows.py
    table, slot, ok = hashtable.upsert(state.table, hi, lo, valid,
                                       max_rounds=8)
    n_nofit = jnp.sum(valid & ~ok, dtype=jnp.int32)
    live = valid & ok
    seg = jnp.where(live, slot, jnp.int32(C))   # dead lanes -> spill row

    # stable sort by key slot: per-key event order preserved
    order = jnp.argsort(seg, stable=True)
    seg_s = seg[order]
    masks_s = masks[order] & live[order, None]

    T = event_matrices(spec, masks_s)
    # invalid lanes: identity (no transition)
    eye = jnp.eye(D, dtype=jnp.float32)
    T = jnp.where(live[order][:, None, None], T, eye[None])

    _, P = jax.lax.associative_scan(_seg_matmul, (seg_s, T))

    v0 = state.carry[seg_s]                       # [B, D] per-lane carry
    v = jnp.einsum("bij,bj->bi", P, v0)
    v = jnp.minimum(v, INT_MAX)                   # saturate counts

    # matches completed AT each sorted lane = M_i - M_{i-1} (same segment)
    M = v[:, D - 2]
    M_prev = jnp.concatenate([jnp.zeros(1, jnp.float32), M[:-1]])
    same_prev = jnp.concatenate(
        [jnp.zeros(1, bool), seg_s[1:] == seg_s[:-1]]
    )
    M0 = v0[:, D - 2]                             # carry M is 0 by reset
    delta_s = M - jnp.where(same_prev, M_prev, M0)

    # new carry = v of each segment's LAST lane, with M reset to 0
    is_last = jnp.concatenate([seg_s[1:] != seg_s[:-1], jnp.ones(1, bool)])
    v_out = v.at[:, D - 2].set(0.0)
    carry = state.carry.at[jnp.where(is_last, seg_s, C + 0)].set(
        jnp.where(is_last[:, None], v_out, 0.0), mode="drop"
    )
    # spill row stays the neutral vector
    neutral = jnp.zeros(D, jnp.float32).at[D - 1].set(1.0)
    carry = carry.at[C].set(neutral)

    # scatter deltas back to original lane order
    delta = jnp.zeros(B, jnp.float32).at[order].set(delta_s)

    new_state = CepShardState(
        table=table,
        carry=carry,
        dropped_capacity=state.dropped_capacity + n_nofit,
    )
    return new_state, delta, jnp.sum(delta_s)


def host_masks(pattern: Pattern, events: Sequence) -> np.ndarray:
    """Bridge for object-event tests: evaluate each stage's scalar
    predicate over a list of host events -> bool[B, S]."""
    S = len(pattern.stages)
    out = np.zeros((len(events), S), bool)
    for j, st in enumerate(pattern.stages):
        out[:, j] = [bool(st.matches(e)) for e in events]
    return out
