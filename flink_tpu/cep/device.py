"""TPU-resident CEP: the NFA as a segmented associative matrix scan.

The reference advances one NFA per key one event at a time
(flink-cep/.../nfa/NFA.java:132, computeNextStates:229): per event, each
live partial match either takes, ignores, or dies. The TPU-native insight:
with per-stage PARTIAL COUNTS as state, that transition is LINEAR —

    state vector v = [c_0, ..., c_{S-2}, M, 1]
      c_s = number of live partials whose last matched stage is s
      M   = cumulative completed matches
      1   = homogeneous coordinate (lets "start a new partial" be linear)

    per event e with stage-match bits m_0..m_{S-1}:
      c_s'  = m_s * c_{s-1}              (take into stage s)
            + keep_s * c_s               (keep_s = 1 iff stage s+1 is
                                          relaxed: the ignore transition —
                                          a strict successor consumes or
                                          kills, NFA.java take/ignore edges)
      c_0' += m_0 * 1                    (every event may start a partial)
      M'    = M + m_{S-1} * c_{S-2}      (take into the final stage emits)

so one event is a (S+1)x(S+1) matrix T(e), and a KEY's whole event
sequence is the ordered product T(e_k) @ ... @ T(e_1). A micro-batch is
processed by sorting lanes by key slot (stable — preserves arrival order
within a key) and running ONE jax.lax.associative_scan with a segmented
matrix-product combiner. No per-event control flow, no per-key loops;
B events x (S+1)^3 x log2(B) MXU-friendly work.

Semantics vs the host NFA (cep/nfa.py — which stays as the generality
path): match COUNTS and completion positions are exact, including the
relaxed-contiguity branching explosion. What the count representation
drops is the per-partial event list — match *extraction* (the
{stage: event} maps) is host-side: the executor replays only the keys
that completed a match this batch through the host NFA (rare in
detection workloads). `within` pruning needs per-partial start
timestamps, so patterns with within() take the host path.

Counts saturate at INT32_MAX via int32 wraparound guard (clamped adds);
a pattern whose branching actually approaches 2^31 live partials is
degenerate under the reference too (its SharedBuffer would OOM).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.cep.pattern import Pattern, RELAXED
from flink_tpu.ops import hashtable
from flink_tpu.ops.hashtable import SlotTable

INT_MAX = np.float32(2**31 - 1)


@dataclass(frozen=True)
class DevicePatternSpec:
    """Static compile spec of a linear pattern for the device NFA.

    relaxed[s] — stage s's contiguity (relaxed=True for followedBy).

    within() support (round 4): per-stage counts are BUCKETED by the
    partial's START time pane — state becomes c_{s,q} over a ring of Q
    panes of `pane_ms` each (the pane-ring trick of the window kernels
    applied to NFA state). A partial keeps its start bucket as it
    advances stages; expiry is the ring rotation zeroing a bucket column
    when its pane slot is reused — no per-partial timestamps needed, and
    the transition stays LINEAR, so the same segmented matrix scan runs.
    Semantics are exactly the host NFA's (Pattern.java:141 window
    pruning) on timestamps quantized to `pane_ms` buckets: with Q-1 =
    within // pane_ms live panes, a partial advances iff
    (pane(e) - pane(start)) * pane_ms <= within.

    Q == 1 (no within) degenerates to the original flat representation:
    one bucket, never rotated."""

    n_stages: int
    relaxed: Tuple[bool, ...]
    within_panes: int = 1            # Q: ring size (1 = no within)
    pane_ms: int = 0                 # bucket width (0 = no within)

    @staticmethod
    def from_pattern(p: Pattern,
                     within_buckets: int = 8) -> "DevicePatternSpec":
        S = len(p.stages)
        Q, pane_ms = 1, 0
        # single-stage patterns complete on their first event (duration
        # 0), so within() can never prune — keep the flat representation
        if p.within_ms is not None and S > 1:
            pane_ms = max(1, -(-p.within_ms // max(1, within_buckets)))
            Q = p.within_ms // pane_ms + 1
        return DevicePatternSpec(
            n_stages=S,
            relaxed=tuple(s.contiguity == RELAXED for s in p.stages),
            within_panes=Q,
            pane_ms=pane_ms,
        )

    @property
    def dim(self) -> int:
        # [c_{0,0} .. c_{S-2,Q-1}, M, 1]
        return (self.n_stages - 1) * self.within_panes + 2


def event_matrices(spec: DevicePatternSpec, masks: jax.Array,
                   q_t=None) -> jax.Array:
    """masks: bool[B, S] stage-match bits per event -> T: f32[B, D, D].

    Row layout of v (column vector convention, v' = T @ v):
      rows s*Q+q (s in 0..S-2, q in 0..Q-1): stage-s partials whose
      start fell in ring pane q; row D-2: M; row D-1: const 1.
    A partial keeps its start bucket q as it advances stages; expired
    buckets are zeroed by the ring rotation in advance(), so no aliveness
    terms appear here. ``q_t`` (traced int32 scalar) is the current
    batch's ring slot — new partials start there; None with Q == 1.
    """
    S = spec.n_stages
    Q = spec.within_panes
    D = spec.dim
    B = masks.shape[0]
    m = masks.astype(jnp.float32)
    T = jnp.zeros((B, D, D), jnp.float32)
    # const row stays 1
    T = T.at[:, D - 1, D - 1].set(1.0)
    # M row: M' = M (+ completion terms below)
    T = T.at[:, D - 2, D - 2].set(1.0)
    if S == 1:
        T = T.at[:, D - 2, D - 1].add(m[:, 0])   # instant completion
        return T
    # start bucket one-hot (Q == 1: always bucket 0)
    if Q == 1:
        start_hot = jnp.ones((1,), jnp.float32)
    else:
        start_hot = (jnp.arange(Q, dtype=jnp.int32) == q_t).astype(
            jnp.float32
        )
    for q in range(Q):
        # completion: M += m_{S-1} * c_{S-2, q} (every live bucket)
        T = T.at[:, D - 2, (S - 2) * Q + q].add(m[:, S - 1])
        # start transition: c_{0, q_t} += m_0
        T = T.at[:, 0 * Q + q, D - 1].add(m[:, 0] * start_hot[q])
        for s in range(S - 1):
            keep = 1.0 if spec.relaxed[s + 1] else 0.0
            if keep:
                T = T.at[:, s * Q + q, s * Q + q].add(keep)
            if s > 0:
                # take into stage s: the partial keeps its start bucket
                T = T.at[:, s * Q + q, (s - 1) * Q + q].add(m[:, s])
    return T


def _seg_matmul(a, b):
    """Segmented combiner for associative_scan: a/b = (seg_id, matrix).
    Within a segment matrices compose; across a boundary the right
    element resets the product."""
    sa, Ma = a
    sb, Mb = b
    same = (sa == sb)[..., None, None]
    return sb, jnp.where(same, Mb @ Ma, Mb)


PANE_NONE = np.int32(-(2**31) + 1)


@jax.tree_util.register_pytree_node_class
@dataclass
class CepShardState:
    table: SlotTable
    carry: jax.Array          # f32 [C+1, D] per-key state vector (+1 spill row)
    pane_ids: jax.Array       # int32 [Q]: absolute pane in each ring slot
    dropped_capacity: jax.Array

    def tree_flatten(self):
        return (self.table, self.carry, self.pane_ids,
                self.dropped_capacity), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def init_state(capacity: int, probe_len: int,
               spec: DevicePatternSpec) -> CepShardState:
    D = spec.dim
    carry = jnp.zeros((capacity + 1, D), jnp.float32)
    carry = carry.at[:, D - 1].set(1.0)   # homogeneous 1
    return CepShardState(
        table=hashtable.create(capacity, probe_len),
        carry=carry,
        pane_ids=jnp.full((spec.within_panes,), PANE_NONE, jnp.int32),
        dropped_capacity=jnp.zeros((), jnp.int32),
    )


def advance(
    state: CepShardState,
    spec: DevicePatternSpec,
    hi: jax.Array,
    lo: jax.Array,
    masks: jax.Array,     # bool [B, S]
    valid: jax.Array,     # bool [B]
    pane=0,               # int32 scalar: this batch's absolute time pane
) -> Tuple[CepShardState, jax.Array, jax.Array]:
    """Advance every key's NFA by this micro-batch.

    Returns (state', match_delta f32[B], match_total_per_lane) where
    match_delta[i] = completed matches triggered exactly at lane i (in the
    ORIGINAL lane order) — the host uses nonzero lanes for extraction.

    ``pane`` = ts // spec.pane_ms (0 without within): partials are
    bucketed by start pane, and rotation below IS the within() expiry —
    a ring slot reused for a newer pane zeroes every key's counts for
    partials started in the expired pane (window_kernels' stale sweep
    applied to NFA state)."""
    B = hi.shape[0]
    C = state.table.capacity
    D = spec.dim
    S = spec.n_stages
    Q = spec.within_panes

    # -- within() ring rotation: register this batch's pane coverage; any
    # slot whose newest covered pane changed holds expired partials —
    # zero that bucket's column across all keys and stages
    pane = jnp.asarray(pane, jnp.int32)
    carry = state.carry
    if Q > 1:
        r_idx = jnp.arange(Q, dtype=jnp.int32)
        p_r = pane - jnp.mod(pane - r_idx, jnp.int32(Q))
        stale = p_r != state.pane_ids                      # [Q]
        col_stale = jnp.zeros(D, bool)
        for s in range(S - 1):
            col_stale = col_stale.at[s * Q:(s + 1) * Q].set(stale)
        carry = jnp.where(col_stale[None, :], 0.0, carry)
        pane_ids = p_r
        q_t = jnp.mod(pane, jnp.int32(Q))
    else:
        pane_ids = state.pane_ids
        q_t = None

    # 8 claim rounds: no spill tier here — see session_windows.py
    table, slot, ok = hashtable.upsert(state.table, hi, lo, valid,
                                       max_rounds=8)
    n_nofit = jnp.sum(valid & ~ok, dtype=jnp.int32)
    live = valid & ok
    seg = jnp.where(live, slot, jnp.int32(C))   # dead lanes -> spill row

    # stable sort by key slot: per-key event order preserved
    order = jnp.argsort(seg, stable=True)
    seg_s = seg[order]
    masks_s = masks[order] & live[order, None]

    T = event_matrices(spec, masks_s, q_t)
    # invalid lanes: identity (no transition)
    eye = jnp.eye(D, dtype=jnp.float32)
    T = jnp.where(live[order][:, None, None], T, eye[None])

    _, P = jax.lax.associative_scan(_seg_matmul, (seg_s, T))

    v0 = carry[seg_s]                             # [B, D] per-lane carry
    v = jnp.einsum("bij,bj->bi", P, v0)
    v = jnp.minimum(v, INT_MAX)                   # saturate counts

    # matches completed AT each sorted lane = M_i - M_{i-1} (same segment)
    M = v[:, D - 2]
    M_prev = jnp.concatenate([jnp.zeros(1, jnp.float32), M[:-1]])
    same_prev = jnp.concatenate(
        [jnp.zeros(1, bool), seg_s[1:] == seg_s[:-1]]
    )
    M0 = v0[:, D - 2]                             # carry M is 0 by reset
    delta_s = M - jnp.where(same_prev, M_prev, M0)

    # new carry = v of each segment's LAST lane, with M reset to 0
    is_last = jnp.concatenate([seg_s[1:] != seg_s[:-1], jnp.ones(1, bool)])
    v_out = v.at[:, D - 2].set(0.0)
    carry = carry.at[jnp.where(is_last, seg_s, C + 0)].set(
        jnp.where(is_last[:, None], v_out, 0.0), mode="drop"
    )
    # spill row stays the neutral vector
    neutral = jnp.zeros(D, jnp.float32).at[D - 1].set(1.0)
    carry = carry.at[C].set(neutral)

    # scatter deltas back to original lane order
    delta = jnp.zeros(B, jnp.float32).at[order].set(delta_s)

    new_state = CepShardState(
        table=table,
        carry=carry,
        pane_ids=pane_ids,
        dropped_capacity=state.dropped_capacity + n_nofit,
    )
    return new_state, delta, jnp.sum(delta_s)


def host_masks(pattern: Pattern, events: Sequence) -> np.ndarray:
    """Bridge for object-event tests: evaluate each stage's scalar
    predicate over a list of host events -> bool[B, S]."""
    S = len(pattern.stages)
    out = np.zeros((len(events), S), bool)
    for j, st in enumerate(pattern.stages):
        out[:, j] = [bool(st.matches(e)) for e in events]
    return out
