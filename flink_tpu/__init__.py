"""flink_tpu — a TPU-native stream-processing framework.

Capabilities modeled on Apache Flink 1.2 (reference: kalmanchapman/flink), but
architected for JAX/XLA on TPU: records are micro-batched into pjit-ed SPMD step
functions over a device mesh; keyed state lives as hash-slot device arrays in HBM
sharded by key group; `keyBy` exchange rides ICI collectives; window updates are
segment-reduce kernels and window fires evaluate whole key panes as single
vectorized kernels.

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):
  core/       — config, types, time, key groups       (ref: flink-core)
  ops/        — device kernels: hashing, hash table, segment reduce, panes
  state/      — state descriptors + backends (device HBM / host heap)
  parallel/   — mesh & shard routing (ICI collectives) (ref: flink-runtime io.network)
  datastream/ — user-facing DataStream API             (ref: flink-streaming-java api)
  graph/      — StreamGraph / JobGraph translation
  runtime/    — executor, checkpoint coordinator, sources/sinks, mini-cluster
  cep/        — pattern matching (vectorized NFA)      (ref: flink-libraries/flink-cep)
"""

__version__ = "0.1.0"

# An explicit JAX_PLATFORMS environment variable wins over any
# sitecustomize-forced platform config. Without this, worker subprocesses
# (runtime/worker.py) and user scripts spawned with JAX_PLATFORMS=cpu can
# still dial an accelerator backend forced by the host's sitecustomize —
# jax.config is process state the env var does not override once set.
# Must run before any jax operation the imports below may perform.
import os as _os  # noqa: E402

_plat = _os.environ.get("JAX_PLATFORMS")
if _plat:
    import jax as _jax  # noqa: E402

    if _jax.config.jax_platforms != _plat:
        _jax.config.update("jax_platforms", _plat)

from flink_tpu.datastream.environment import StreamExecutionEnvironment  # noqa: F401,E402
