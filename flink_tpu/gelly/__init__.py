"""Graph processing — the Gelly analog (ref flink-gelly, SURVEY §2.7)."""

from flink_tpu.gelly.graph import Graph

__all__ = ["Graph"]
