"""Graph API — the Gelly analog (ref flink-gelly Graph.java + the
scatter-gather/`spargel`, gather-sum-apply/`gsa`, and `pregel` iteration
models, SURVEY §2.7), redesigned device-first:

The reference runs vertex-centric supersteps as DataSet delta iterations —
per-vertex JVM UDF calls joined against edges. Here a graph IS columnar
device state: vertex values [V] and an edge list (src[E], dst[E], w[E]) as
arrays, and one superstep is a fused XLA program:

    gather:  msg[e]   = combine(value[src[e]], w[e])      (vectorized)
    sum:     agg[v]   = segment-reduce msg over dst        (scatter-add/min)
    apply:   value[v] = update(value[v], agg[v])           (vectorized)

run with `lax.while_loop` on device — zero host round-trips per superstep.
Library algorithms (connected components, PageRank, SSSP — the reference's
library/ classes) are instances of this scatter-gather contract.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class Graph:
    """Vertex ids are dense [0, V); use from_edge_list for arbitrary ids."""

    vertex_values: jnp.ndarray        # [V] (any dtype / pytree leaf)
    src: jnp.ndarray                  # [E] int32
    dst: jnp.ndarray                  # [E] int32
    edge_values: Optional[jnp.ndarray] = None   # [E]
    ids: Optional[np.ndarray] = None  # [V] original vertex ids (host)

    # -- construction ----------------------------------------------------
    @staticmethod
    def from_edge_list(edges: List[Tuple[Any, Any]],
                       edge_values: Optional[List[float]] = None,
                       vertex_init: Optional[Callable[[Any], float]] = None,
                       undirected: bool = False) -> "Graph":
        e = np.asarray([(a, b) for a, b in edges], dtype=object)
        ids, inv = np.unique(e.reshape(-1), return_inverse=True)
        src = inv[0::2].astype(np.int32)
        dst = inv[1::2].astype(np.int32)
        ev = (
            np.asarray(edge_values, np.float32)
            if edge_values is not None else None
        )
        if undirected:
            src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
            if ev is not None:
                ev = np.concatenate([ev, ev])
        if vertex_init is None:
            values = np.arange(len(ids), dtype=np.float32)
        else:
            values = np.asarray([vertex_init(i) for i in ids], np.float32)
        return Graph(
            jnp.asarray(values), jnp.asarray(src), jnp.asarray(dst),
            jnp.asarray(ev) if ev is not None else None, ids,
        )

    @property
    def num_vertices(self) -> int:
        return int(self.vertex_values.shape[0])

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    def _resolve(self, v_idx: jnp.ndarray):
        """Device values -> {original_id: value} host dict."""
        vals = np.asarray(v_idx)
        keys = self.ids if self.ids is not None else np.arange(len(vals))
        return dict(zip(keys.tolist(), vals.tolist()))

    # -- transforms (ref Graph.mapVertices/mapEdges/subgraph/reverse) -----
    def map_vertices(self, fn) -> "Graph":
        return Graph(fn(self.vertex_values), self.src, self.dst,
                     self.edge_values, self.ids)

    def map_edges(self, fn) -> "Graph":
        ev = self.edge_values
        if ev is None:
            ev = jnp.ones_like(self.src, jnp.float32)
        return Graph(self.vertex_values, self.src, self.dst, fn(ev), self.ids)

    def reverse(self) -> "Graph":
        return Graph(self.vertex_values, self.dst, self.src,
                     self.edge_values, self.ids)

    def filter_on_edges(self, pred) -> "Graph":
        """pred over (src_idx, dst_idx, edge_value) -> bool mask (host
        materialization; structural change needs recompilation anyway)."""
        ev = (
            self.edge_values if self.edge_values is not None
            else jnp.ones_like(self.src, jnp.float32)
        )
        keep = np.asarray(pred(self.src, self.dst, ev))
        return Graph(
            self.vertex_values,
            jnp.asarray(np.asarray(self.src)[keep]),
            jnp.asarray(np.asarray(self.dst)[keep]),
            jnp.asarray(np.asarray(ev)[keep]),
            self.ids,
        )

    def out_degrees(self) -> Dict[Any, int]:
        deg = jnp.zeros(self.num_vertices, jnp.int32).at[self.src].add(1)
        return self._resolve(deg)

    def in_degrees(self) -> Dict[Any, int]:
        deg = jnp.zeros(self.num_vertices, jnp.int32).at[self.dst].add(1)
        return self._resolve(deg)

    # -- scatter-gather iteration (the spargel/GSA/pregel contract) -------
    def scatter_gather(
        self,
        message_fn: Callable,         # (src_values_per_edge, edge_values) -> msgs [E]
        combine: str,                 # 'min' | 'sum' | 'max' (the Sum phase)
        update_fn: Callable,          # (old_values [V], agg [V], has_msg [V]) -> new [V]
        max_supersteps: int,
        neutral: float,
    ) -> "Graph":
        """Runs supersteps entirely on device under lax.while_loop,
        terminating early when no vertex value changes (the reference's
        'vertex did not update -> halts' convergence rule)."""
        V = self.num_vertices
        src, dst = self.src, self.dst
        ev = (
            self.edge_values if self.edge_values is not None
            else jnp.ones_like(src, jnp.float32)
        )

        def superstep(values):
            msgs = message_fn(values[src], ev)
            agg0 = jnp.full((V,), neutral, values.dtype)
            if combine == "min":
                agg = agg0.at[dst].min(msgs)
            elif combine == "max":
                agg = agg0.at[dst].max(msgs)
            elif combine == "sum":
                agg = agg0.at[dst].add(msgs)
            else:
                raise ValueError(combine)
            has_msg = jnp.zeros((V,), bool).at[dst].set(True)
            return update_fn(values, agg, has_msg)

        def cond(carry):
            values, prev, it = carry
            return (it < max_supersteps) & jnp.any(values != prev)

        def body(carry):
            values, _, it = carry
            return superstep(values), values, it + 1

        init = (superstep(self.vertex_values), self.vertex_values, jnp.int32(1))
        final, _, _ = jax.lax.while_loop(cond, body, init)
        return Graph(final, self.src, self.dst, self.edge_values, self.ids)

    # -- library algorithms (ref flink-gelly library/) --------------------
    def connected_components(self, max_supersteps: int = 64) -> Dict[Any, Any]:
        """ref GSAConnectedComponents: propagate min component id."""
        g = Graph(
            jnp.arange(self.num_vertices, dtype=jnp.float32),
            self.src, self.dst, self.edge_values, self.ids,
        )
        out = g.scatter_gather(
            message_fn=lambda sv, ev: sv,
            combine="min",
            update_fn=lambda old, agg, has: jnp.where(
                has & (agg < old), agg, old
            ),
            max_supersteps=max_supersteps,
            neutral=jnp.inf,
        )
        comp = np.asarray(out.vertex_values).astype(int)
        if self.ids is not None:
            return {
                self.ids[i]: self.ids[c] for i, c in enumerate(comp.tolist())
            }
        return dict(enumerate(comp.tolist()))

    def page_rank(self, beta: float = 0.85,
                  num_iterations: int = 30) -> Dict[Any, float]:
        """ref PageRank library method: power iteration; dangling mass
        redistributed uniformly."""
        V = self.num_vertices
        out_deg = jnp.zeros(V, jnp.float32).at[self.src].add(1.0)
        src, dst = self.src, self.dst

        def body(_, rank):
            contrib = rank[src] / jnp.maximum(out_deg[src], 1.0)
            agg = jnp.zeros(V, jnp.float32).at[dst].add(contrib)
            dangling = jnp.sum(jnp.where(out_deg == 0, rank, 0.0))
            return (1 - beta) / V + beta * (agg + dangling / V)

        rank = jax.lax.fori_loop(
            0, num_iterations, body, jnp.full((V,), 1.0 / V, jnp.float32)
        )
        return self._resolve(rank)

    def single_source_shortest_paths(
        self, source: Any, max_supersteps: int = 64
    ) -> Dict[Any, float]:
        """ref SingleSourceShortestPaths: min-plus relaxation supersteps."""
        if self.ids is not None:
            src_idx = int(np.searchsorted(self.ids, source))
            if src_idx >= len(self.ids) or self.ids[src_idx] != source:
                raise KeyError(source)
        else:
            src_idx = int(source)
        dist0 = jnp.full((self.num_vertices,), jnp.inf, jnp.float32)
        dist0 = dist0.at[src_idx].set(0.0)
        g = Graph(dist0, self.src, self.dst, self.edge_values, self.ids)
        out = g.scatter_gather(
            message_fn=lambda sv, ev: sv + ev,
            combine="min",
            update_fn=lambda old, agg, has: jnp.minimum(old, agg),
            max_supersteps=max_supersteps,
            neutral=jnp.inf,
        )
        return self._resolve(out.vertex_values)

    def label_propagation(self, max_supersteps: int = 16) -> Dict[Any, Any]:
        """ref LabelPropagation (simplified: min-label consensus like CC but
        seeded with current vertex values as labels)."""
        out = self.scatter_gather(
            message_fn=lambda sv, ev: sv,
            combine="min",
            update_fn=lambda old, agg, has: jnp.where(has, jnp.minimum(old, agg), old),
            max_supersteps=max_supersteps,
            neutral=jnp.inf,
        )
        vals = np.asarray(out.vertex_values).astype(int)
        if self.ids is not None:
            return dict(zip(self.ids.tolist(), vals.tolist()))
        return dict(enumerate(vals.tolist()))

    def triangle_count(self) -> int:
        """ref TriangleEnumerator/Count: A ⊙ (A @ A) over the symmetric
        adjacency — a dense MXU matmul for small/medium graphs."""
        A = _sym_adjacency(self)
        tri = jnp.sum(A * (A @ A)) / 6.0
        return int(tri)

    # -- round-3 library breadth (ref flink-gelly library/*) --------------
    def hits(self, num_iterations: int = 30) -> Dict[Any, Tuple[float, float]]:
        """ref HITSAlgorithm: hubs & authorities by power iteration —
        alternating sparse mat-vecs with L2 normalization, all on device."""
        V = self.num_vertices
        src, dst = self.src, self.dst

        def body(_, hv):
            h, a = hv
            a2 = jnp.zeros(V, jnp.float32).at[dst].add(h[src])
            a2 = a2 / jnp.maximum(jnp.linalg.norm(a2), 1e-12)
            h2 = jnp.zeros(V, jnp.float32).at[src].add(a2[dst])
            h2 = h2 / jnp.maximum(jnp.linalg.norm(h2), 1e-12)
            return h2, a2

        h0 = jnp.full((V,), 1.0 / np.sqrt(max(V, 1)), jnp.float32)
        h, a = jax.lax.fori_loop(0, num_iterations, body, (h0, h0))
        hubs = np.asarray(h).tolist()
        auth = np.asarray(a).tolist()
        keys = (self.ids if self.ids is not None
                else np.arange(V)).tolist()
        return {k: (hb, au) for k, hb, au in zip(keys, hubs, auth)}

    def community_detection(self, max_supersteps: int = 32,
                            delta: float = 0.5) -> Dict[Any, Any]:
        """ref CommunityDetection: label propagation with hop-attenuated
        label scores. Device representation: per-vertex (label, score);
        each superstep a vertex adopts the incoming label with the highest
        summed score, its own score decaying by delta per hop."""
        V = self.num_vertices
        src, dst = self.src, self.dst
        labels0 = jnp.arange(V, dtype=jnp.float32)
        scores0 = jnp.ones(V, jnp.float32)

        def superstep(carry):
            labels, scores, prev, it = carry
            # score mass per (receiver, label): dense [V,V] scatter-add —
            # fine for the library's target graph sizes (the reference's
            # CommunityDetection is likewise an all-labels message pass)
            m = jnp.zeros((V, V), jnp.float32).at[
                dst, labels[src].astype(jnp.int32)
            ].add(scores[src])
            best = jnp.argmax(m, axis=1).astype(jnp.float32)
            best_mass = jnp.max(m, axis=1)
            has = best_mass > 0
            new_labels = jnp.where(has, best, labels)
            new_scores = jnp.where(
                has, jnp.maximum(best_mass * delta, 1e-6), scores
            )
            return new_labels, new_scores, labels, it + 1

        def cond(carry):
            labels, scores, prev, it = carry
            return (it < max_supersteps) & jnp.any(labels != prev)

        labels, _, _, _ = jax.lax.while_loop(
            cond, superstep, (labels0, scores0, labels0 - 1, jnp.int32(0))
        )
        lab = np.asarray(labels).astype(int)
        if self.ids is not None:
            return {self.ids[i]: self.ids[l]
                    for i, l in enumerate(lab.tolist())}
        return dict(enumerate(lab.tolist()))

    def jaccard_index(self) -> Dict[Tuple[Any, Any], float]:
        """ref JaccardIndex: |N(u) ∩ N(v)| / |N(u) ∪ N(v)| for every
        connected vertex pair — dense A@A over the symmetric adjacency
        (one MXU matmul), results for edges only."""
        V = self.num_vertices
        A = _sym_adjacency(self)
        common = A @ A                     # [V,V] shared-neighbor counts
        deg = jnp.sum(A, axis=1)
        union = deg[:, None] + deg[None, :] - common
        jac = jnp.where(union > 0, common / jnp.maximum(union, 1e-12), 0.0)
        s = np.asarray(self.src)
        d = np.asarray(self.dst)
        vals = np.asarray(jac[self.src, self.dst])
        keys = self.ids if self.ids is not None else np.arange(V)
        out = {}
        for i in range(len(s)):
            a, b = keys[s[i]], keys[d[i]]
            if a != b:
                out[(a, b)] = float(vals[i])
        return out

    def summarize(self) -> "Graph":
        """ref Summarization: condense vertices with equal values into one
        super-vertex; parallel edges between groups collapse with summed
        edge values. Vertex groups computed on device, edge dedup on host
        (structural change)."""
        vals = np.asarray(self.vertex_values)
        groups, ginv = np.unique(vals, return_inverse=True)
        s = ginv[np.asarray(self.src)]
        d = ginv[np.asarray(self.dst)]
        ev = (np.asarray(self.edge_values)
              if self.edge_values is not None
              else np.ones(len(s), np.float32))
        keep = s != d                       # intra-group edges vanish
        pair = s[keep].astype(np.int64) * len(groups) + d[keep]
        uniq_pair, pinv = np.unique(pair, return_inverse=True)
        agg = np.zeros(len(uniq_pair), np.float32)
        np.add.at(agg, pinv, ev[keep])
        return Graph(
            jnp.asarray(groups.astype(np.float32)),
            jnp.asarray((uniq_pair // len(groups)).astype(np.int32)),
            jnp.asarray((uniq_pair % len(groups)).astype(np.int32)),
            jnp.asarray(agg),
            None,
        )

    def union(self, other: "Graph") -> "Graph":
        """ref Graph.union: same vertex set (dense ids must agree), edge
        lists concatenate."""
        if self.num_vertices != other.num_vertices:
            raise ValueError("union requires identical vertex sets")
        ev_a = (self.edge_values if self.edge_values is not None
                else jnp.ones_like(self.src, jnp.float32))
        ev_b = (other.edge_values if other.edge_values is not None
                else jnp.ones_like(other.src, jnp.float32))
        return Graph(
            self.vertex_values,
            jnp.concatenate([self.src, other.src]),
            jnp.concatenate([self.dst, other.dst]),
            jnp.concatenate([ev_a, ev_b]),
            self.ids,
        )

    def subgraph(self, vertex_pred, edge_pred=None) -> "Graph":
        """ref Graph.subgraph: keep edges whose endpoints satisfy
        vertex_pred (over vertex values) and the edge satisfies
        edge_pred."""
        vmask = np.asarray(vertex_pred(self.vertex_values), bool)
        s = np.asarray(self.src)
        d = np.asarray(self.dst)
        ev = (np.asarray(self.edge_values)
              if self.edge_values is not None
              else np.ones(len(s), np.float32))
        keep = vmask[s] & vmask[d]
        if edge_pred is not None:
            keep &= np.asarray(edge_pred(self.src, self.dst,
                                         jnp.asarray(ev)), bool)
        return Graph(
            self.vertex_values, jnp.asarray(s[keep].astype(np.int32)),
            jnp.asarray(d[keep].astype(np.int32)),
            jnp.asarray(ev[keep]), self.ids,
        )


# -- round-4 library breadth: neighborhood reduces, clustering metrics,
# -- similarity, and graph mutations (ref flink-gelly Graph.java
# -- reduceOnEdges/reduceOnNeighbors, library/clustering +
# -- library/similarity, addVertex/removeVertex/addEdge/removeEdge)
def _neighbor_reduce(graph: "Graph", values_per_edge, combine: str,
                     neutral: float):
    """Segment-reduce per-edge values onto their DESTINATION vertex —
    one scatter, the Sum half of GSA (shared by the methods below)."""
    V = graph.num_vertices
    from flink_tpu.ops.segment import scatter_combine

    acc = jnp.full((V,), neutral, jnp.float32)
    return scatter_combine(
        acc, graph.dst, values_per_edge.astype(jnp.float32),
        jnp.ones_like(graph.dst, bool),
        {"sum": "add", "min": "min", "max": "max"}[combine],
    )


def _drop_edgeless(orig: "Graph", g: "Graph", out) -> Dict[Any, float]:
    """The reference's reduceOnEdges/reduceOnNeighbors emit NO result for
    vertices without edges in the requested direction; the scatter
    neutral (inf/-inf/0) must not leak into the user-facing dict."""
    has = np.zeros(orig.num_vertices, bool)
    has[np.asarray(g.dst)] = True
    full = orig._resolve(out)
    ids = (orig.ids if orig.ids is not None
           else np.arange(orig.num_vertices))
    return {k: v for k, v, h in zip(ids.tolist(), full.values(), has) if h}


def _ext_reduce_on_edges(self, combine: str = "sum",
                         direction: str = "in") -> Dict[Any, float]:
    """ref Graph.reduceOnEdges(EdgesFunction): per-vertex reduce of edge
    VALUES over its in-/out-/all edges."""
    ev = (self.edge_values if self.edge_values is not None
          else jnp.ones_like(self.src, jnp.float32))
    neutral = {"sum": 0.0, "min": np.inf, "max": -np.inf}[combine]
    g = {"in": self, "out": self.reverse(),
         "all": None}.get(direction, "bad")
    if g == "bad":
        raise ValueError("direction must be in|out|all")
    if g is None:
        both = Graph(self.vertex_values,
                     jnp.concatenate([self.src, self.dst]),
                     jnp.concatenate([self.dst, self.src]),
                     jnp.concatenate([ev, ev]), self.ids)
        return both.reduce_on_edges(combine, "in")
    out = _neighbor_reduce(g, ev, combine, neutral)
    return _drop_edgeless(self, g, out)


def _ext_reduce_on_neighbors(self, combine: str = "sum",
                             direction: str = "in") -> Dict[Any, float]:
    """ref Graph.reduceOnNeighbors(ReduceNeighborsFunction): per-vertex
    reduce of NEIGHBOR vertex values."""
    neutral = {"sum": 0.0, "min": np.inf, "max": -np.inf}[combine]
    if direction == "all":
        both = Graph(self.vertex_values,
                     jnp.concatenate([self.src, self.dst]),
                     jnp.concatenate([self.dst, self.src]),
                     None, self.ids)
        return both.reduce_on_neighbors(combine, "in")
    g = {"in": self, "out": self.reverse()}.get(direction)
    if g is None:
        raise ValueError("direction must be in|out|all")
    vals = g.vertex_values[g.src]
    out = _neighbor_reduce(g, vals, combine, neutral)
    return _drop_edgeless(self, g, out)


def _sym_adjacency(self) -> jnp.ndarray:
    """Symmetric simple-graph adjacency [V, V] (duplicates collapse via
    set, self-loops masked) — the ONE recipe shared by every dense
    metric (triangle_count, jaccard_index, clustering coefficients,
    adamic_adar), so adjacency semantics cannot drift between them."""
    V = self.num_vertices
    A = jnp.zeros((V, V), jnp.float32)
    A = A.at[self.src, self.dst].set(1.0)
    A = jnp.maximum(A, A.T)
    return A * (1 - jnp.eye(V))


def _ext_local_clustering_coefficient(self) -> Dict[Any, float]:
    """ref library/clustering LocalClusteringCoefficient: per vertex,
    2 * triangles(v) / (deg(v) * (deg(v) - 1)) over the undirected
    simple graph. Triangle counting per vertex via the dense adjacency
    matmul A @ A (MXU work) masked by A."""
    A = _sym_adjacency(self)
    paths2 = A @ A                      # [V, V] 2-paths between pairs
    tri_v = jnp.sum(paths2 * A, axis=1) / 2.0   # triangles through v
    deg = jnp.sum(A, axis=1)
    denom = deg * (deg - 1.0)
    coef = jnp.where(denom > 0, 2.0 * tri_v / denom, 0.0)
    return self._resolve(coef)


def _ext_global_clustering_coefficient(self) -> float:
    """ref library/clustering GlobalClusteringCoefficient:
    3 * triangles / open-or-closed triplets."""
    A = _sym_adjacency(self)
    tri = float(jnp.trace(A @ A @ A)) / 6.0
    deg = jnp.sum(A, axis=1)
    triplets = float(jnp.sum(deg * (deg - 1.0))) / 2.0
    return 3.0 * tri / triplets if triplets else 0.0


def _ext_adamic_adar(self) -> Dict[Tuple[Any, Any], float]:
    """ref library/similarity AdamicAdar: for vertex pairs sharing >= 1
    neighbor, sum of 1/log(deg(shared neighbor)) — computed as one
    weighted adjacency matmul (A_w = A / log deg broadcast)."""
    V = self.num_vertices
    A = _sym_adjacency(self)
    deg = jnp.sum(A, axis=1)
    w = jnp.where(deg > 1, 1.0 / jnp.log(jnp.maximum(deg, 2.0)), 0.0)
    S = A @ (A * w[:, None])           # S[i,j] = sum_k A[i,k] w[k] A[k,j]
    S = np.asarray(S)
    ids = self.ids if self.ids is not None else np.arange(V)
    out = {}
    ii, jj = np.nonzero(np.triu(S, k=1) > 1e-9)
    adj = np.asarray(A) > 0
    for i, j in zip(ii.tolist(), jj.tolist()):
        if not adj[i, j]:              # score only non-adjacent pairs
            out[(ids[i], ids[j])] = float(S[i, j])
    return out


def _ext_add_edges(self, edges, edge_values=None) -> "Graph":
    """ref Graph.addEdges: endpoints must already exist (unknown ids
    raise, matching the reference's semantics of ignoring invalid
    edges loudly rather than silently here)."""
    ids = self.ids if self.ids is not None else np.arange(self.num_vertices)
    index = {k: i for i, k in enumerate(ids.tolist())}
    try:
        s = np.asarray([index[a] for a, _b in edges], np.int32)
        d = np.asarray([index[b] for _a, b in edges], np.int32)
    except KeyError as e:
        raise ValueError(f"add_edges: unknown vertex {e.args[0]!r}; "
                         f"add_vertices first") from None
    ev = self.edge_values
    if ev is not None or edge_values is not None:
        old = (np.asarray(ev) if ev is not None
               else np.ones(self.num_edges, np.float32))
        new = (np.asarray(edge_values, np.float32)
               if edge_values is not None
               else np.ones(len(edges), np.float32))
        ev = jnp.asarray(np.concatenate([old, new]))
    return Graph(
        self.vertex_values,
        jnp.concatenate([self.src, jnp.asarray(s)]),
        jnp.concatenate([self.dst, jnp.asarray(d)]),
        ev, self.ids,
    )


def _ext_add_vertices(self, new_ids, values=None) -> "Graph":
    ids = self.ids if self.ids is not None else np.arange(self.num_vertices)
    existing = set(ids.tolist())
    new_ids = list(new_ids)
    if values is not None and len(values) != len(new_ids):
        raise ValueError(
            f"add_vertices: {len(new_ids)} ids but {len(values)} values"
        )
    seen = set(existing)
    keep = []
    for j, i in enumerate(new_ids):
        if i not in seen:                # dedup within new_ids too
            seen.add(i)
            keep.append(j)
    fresh = [new_ids[j] for j in keep]
    if not fresh:
        return self
    # values selected BY POSITION OF THE SURVIVING IDS — a duplicate id
    # must not shift its neighbor's value onto the wrong vertex
    vals = (np.asarray(values, np.float32)[keep]
            if values is not None else np.zeros(len(fresh), np.float32))
    return Graph(
        jnp.concatenate([self.vertex_values, jnp.asarray(vals)]),
        self.src, self.dst, self.edge_values,
        np.concatenate([np.asarray(ids, object),
                        np.asarray(fresh, object)]),
    )


def _ext_remove_vertices(self, victim_ids) -> "Graph":
    """ref Graph.removeVertices: drops the vertices AND every incident
    edge, recompacting indices."""
    ids = self.ids if self.ids is not None else np.arange(self.num_vertices)
    victims = set(victim_ids)
    keep_mask = np.asarray([i not in victims for i in ids.tolist()])
    remap = np.cumsum(keep_mask) - 1
    s = np.asarray(self.src)
    d = np.asarray(self.dst)
    ekeep = keep_mask[s] & keep_mask[d]
    ev = self.edge_values
    return Graph(
        jnp.asarray(np.asarray(self.vertex_values)[keep_mask]),
        jnp.asarray(remap[s[ekeep]].astype(np.int32)),
        jnp.asarray(remap[d[ekeep]].astype(np.int32)),
        jnp.asarray(np.asarray(ev)[ekeep]) if ev is not None else None,
        np.asarray(ids, object)[keep_mask],
    )


def _ext_remove_edges(self, edges) -> "Graph":
    ids = self.ids if self.ids is not None else np.arange(self.num_vertices)
    index = {k: i for i, k in enumerate(ids.tolist())}
    drop = {(index.get(a, -1), index.get(b, -2)) for a, b in edges}
    s = np.asarray(self.src)
    d = np.asarray(self.dst)
    keep = np.asarray([
        (int(a), int(b)) not in drop for a, b in zip(s, d)
    ], bool)                             # explicit dtype: E == 0 edges
    ev = self.edge_values
    return Graph(
        self.vertex_values,
        jnp.asarray(s[keep]), jnp.asarray(d[keep]),
        jnp.asarray(np.asarray(ev)[keep]) if ev is not None else None,
        self.ids,
    )


Graph.reduce_on_edges = _ext_reduce_on_edges
Graph.reduce_on_neighbors = _ext_reduce_on_neighbors
Graph.local_clustering_coefficient = _ext_local_clustering_coefficient
Graph.global_clustering_coefficient = _ext_global_clustering_coefficient
Graph.adamic_adar = _ext_adamic_adar
Graph.add_edges = _ext_add_edges
Graph.add_vertices = _ext_add_vertices
Graph.remove_vertices = _ext_remove_vertices
Graph.remove_edges = _ext_remove_edges
