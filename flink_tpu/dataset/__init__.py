"""Batch DataSet API (ref flink-java / DataSet, SURVEY §2.6)."""

from flink_tpu.dataset.dataset import DataSet, GroupedDataSet, JoinBuilder
from flink_tpu.dataset.environment import ExecutionEnvironment

__all__ = ["DataSet", "GroupedDataSet", "JoinBuilder", "ExecutionEnvironment"]
