"""DataSet API — bounded (batch) processing.

Mirrors the reference's DataSet surface (SURVEY §2.6: flink-java
DataSet.java — map/filter/flatMap/mapPartition/reduce/groupBy/aggregate/
join/coGroup/cross/union/distinct/sortPartition/first/iterate), TPU-adapted:

- datasets are LAZY plans (the role of the common-api Plan the reference
  hands to the Optimizer); collect()/count()/output() trigger evaluation
  with per-node memoization (an operator consumed by several downstream
  nodes — e.g. both sides of a join — materializes once, the DAG-sharing
  the reference's optimizer handles via plan caching);
- grouped numeric aggregation is the device path: python keys are
  dictionary-encoded host-side (np.unique) and the values segment-reduce
  on the accelerator (`jnp.zeros(G).at[gid].add/min/max`) — the batch
  analog of the streaming window kernels, replacing the reference's
  sort-based ReduceCombineDriver with one XLA scatter-reduce;
- joins are hash joins (build right / probe left, ref MutableHashTable
  strategy) with inner/left/right/full variants; coGroup groups both
  sides; everything structural stays host-side Python where the reference
  used JVM driver strategies, because the FLOPs live in the aggregations.

Iterations: bulk (ref IterativeDataSet / BulkIterationBase) and delta
(ref DeltaIterationBase: solution set keyed by K, workset driving
updates) as host loops — the reference's superstep synchronization
(IterationSynchronizationSinkTask) is the loop boundary itself.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np


def _extract(pos):
    if pos is None:
        return lambda e: e
    if callable(pos):
        return pos
    return lambda e: e[pos]


#: per-operator output-size selectivity relative to the (max) input — the
#: optimizer's size-estimation heuristics (ref Optimizer.java cost model /
#: CompilerHints; filters halve, flat_maps can expand, joins ~max side)
_SELECTIVITY = {
    "filter": 0.5,
    "flat_map": 1.5,
    "distinct": 0.7,
    "reduce": 0.0,
    "group_reduce": 0.3,
    "inner_join": 1.0,
    "left_join": 1.0,
    "right_join": 1.0,
    "full_join": 1.2,
    "cogroup_join": 0.5,
    "grouped_reduce": 0.3,
}


#: ship-strategy planner knobs (ref flink-optimizer CostEstimator /
#: Optimizer.java:396 shipping-strategy choice; overridable per
#: ExecutionEnvironment attribute of the same name)
BROADCAST_THRESHOLD_ROWS = 10_000   # a side this small may be broadcast
BROADCAST_SKEW_FACTOR = 4           # ...if the other side is ≥4x larger
HASH_MAX_BUILD_ROWS = 1_000_000     # past this, hash gives way to merge


def _decide_join_strategies(n_left: float, n_right: float, hint: str,
                            env) -> tuple:
    """(ship, local, build_left) from side sizes — the optimizer's
    shipping/local strategy assignment (ref Optimizer.java:396,
    JoinOperatorBase.JoinHint). Used with ESTIMATES at plan time and
    with exact materialized counts at run time, so EXPLAIN shows the
    same decision procedure the execution applies.

    ship:  broadcast-hash-first/second — the small side replicated to
           every parallel instance (cost ~ small * parallelism);
           repartition-hash — both sides hashed over the mesh
           (cost ~ left + right network volume).
    local: hash build-left/right, or sort-merge when neither side's
           hash table is expected to fit the build budget (the
           reference's hybrid-hash-vs-merge memory rationale).
    """
    bthresh = getattr(env, "broadcast_threshold_rows",
                      BROADCAST_THRESHOLD_ROWS)
    hmax = getattr(env, "hash_max_build_rows", HASH_MAX_BUILD_ROWS)
    skew = getattr(env, "broadcast_skew_factor", BROADCAST_SKEW_FACTOR)
    if hint == "build-left":
        ship = ("broadcast-hash-first" if n_left <= bthresh
                else "repartition-hash")
        return ship, "hash build-left (hinted)", True
    if hint == "build-right":
        ship = ("broadcast-hash-second" if n_right <= bthresh
                else "repartition-hash")
        return ship, "hash build-right (hinted)", False
    small, large = min(n_left, n_right), max(n_left, n_right)
    build_left = n_left <= n_right
    side = "first" if build_left else "second"
    if small <= bthresh and large >= skew * small:
        return (f"broadcast-hash-{side}",
                f"hash build-{'left' if build_left else 'right'}",
                build_left)
    if small > hmax:
        return "repartition-hash", "sort-merge", build_left
    return ("repartition-hash",
            f"hash build-{'left' if build_left else 'right'}",
            build_left)


class DataSet:
    def __init__(self, env, compute: Callable[[], List[Any]], name="op",
                 parents: tuple = ()):
        self.env = env
        self._compute = compute
        self._cache: Optional[List[Any]] = None
        self.name = name
        self.parents = parents
        #: strategy notes recorded by cost-based choices (explain())
        self.strategy: Optional[str] = None
        #: set on join nodes so plan() can re-derive strategies from
        #: estimates without executing
        self.join_hint: Optional[str] = None

    # -- planner ---------------------------------------------------------
    def plan(self) -> str:
        """Assign ship/local strategies to every join in the DAG from
        the cost model's ESTIMATES — without executing anything — and
        return the annotated plan (the reference optimizer's pre-flight
        plan, Optimizer.java compile() -> OptimizedPlan)."""
        def annotate(node):
            for p in node.parents:
                annotate(p)
            if node.join_hint is not None and len(node.parents) == 2:
                ship, local, _bl = _decide_join_strategies(
                    node.parents[0].estimate_size(),
                    node.parents[1].estimate_size(),
                    node.join_hint, node.env,
                )
                node.strategy = f"ship={ship}, local={local}"
        annotate(self)
        return self.explain()

    # -- evaluation ------------------------------------------------------
    def _data(self) -> List[Any]:
        if self._cache is None:
            self._cache = list(self._compute())
        return self._cache

    # -- cost model (ref flink-optimizer Optimizer.java:396) -------------
    def estimate_size(self) -> float:
        """Estimated row count WITHOUT executing: materialized caches are
        exact, sources use their declared size hint (from_collection sets
        it; file sources stay unknown until read — never forced here),
        union sums its inputs, cross multiplies, everything else applies
        per-operator selectivities to parent estimates."""
        if self._cache is not None:
            return float(len(self._cache))
        if not self.parents:
            hint = getattr(self, "size_hint", None)
            return float(hint) if hint is not None else 1000.0
        sizes = [p.estimate_size() for p in self.parents]
        if self.name == "union":
            return float(sum(sizes))
        if self.name == "cross":
            out = 1.0
            for v in sizes:
                out *= v
            return out
        return max(sizes) * _SELECTIVITY.get(self.name, 1.0)

    def explain(self, _depth: int = 0) -> str:
        """Operator tree with size estimates and chosen physical
        strategies (the reference's plan JSON / explain analog)."""
        pad = "  " * _depth
        line = f"{pad}{self.name} (est. {self.estimate_size():.0f} rows"
        if self.strategy:
            line += f", {self.strategy}"
        line += ")"
        return "\n".join(
            [line] + [p.explain(_depth + 1) for p in self.parents]
        )

    def collect(self) -> List[Any]:
        return list(self._data())

    def count(self) -> int:
        return len(self._data())

    def print_(self):
        for e in self._data():
            print(e)

    def write_as_text(self, path: str):
        from flink_tpu.core.filesystem import get_filesystem

        fs, p = get_filesystem(path)
        with fs.open(p, "w") as f:
            for e in self._data():
                f.write(str(e) + "\n")

    def write_as_csv(self, path: str, delimiter: str = ","):
        """ref CsvOutputFormat: tuples/lists become delimited rows."""
        import csv as _csv

        from flink_tpu.core.filesystem import get_filesystem

        fs, p = get_filesystem(path)
        with fs.open(p, "w", newline="") as f:
            w = _csv.writer(f, delimiter=delimiter)
            for e in self._data():
                w.writerow(
                    e if isinstance(e, (tuple, list)) else (e,)
                )

    def output(self, fn: Callable[[Any], None]):
        for e in self._data():
            fn(e)

    # -- element-wise ----------------------------------------------------
    def _derive(self, fn, name, *extra_parents) -> "DataSet":
        return DataSet(self.env, fn, name, parents=(self, *extra_parents))

    def map(self, fn) -> "DataSet":
        return self._derive(lambda: [fn(e) for e in self._data()], "map")

    def filter(self, fn) -> "DataSet":
        return self._derive(
            lambda: [e for e in self._data() if fn(e)], "filter"
        )

    def flat_map(self, fn) -> "DataSet":
        def run():
            out = []
            for e in self._data():
                out.extend(fn(e))
            return out

        return self._derive(run, "flat_map")

    def map_partition(self, fn) -> "DataSet":
        """fn(iterable) -> iterable over the whole partition (single
        logical partition in the host plan; ref MapPartitionFunction)."""
        return self._derive(lambda: list(fn(iter(self._data()))), "map_partition")

    # -- full-set reductions ---------------------------------------------
    def reduce(self, fn) -> "DataSet":
        def run():
            it = iter(self._data())
            try:
                acc = next(it)
            except StopIteration:
                return []
            for e in it:
                acc = fn(acc, e)
            return [acc]

        return self._derive(run, "reduce")

    def sum(self, pos=None) -> "DataSet":
        ex = _extract(pos)
        return self._derive(
            lambda: [float(np.sum([ex(e) for e in self._data()]))]
            if self._data() else [], "sum",
        )

    def min_by(self, pos=None) -> "DataSet":
        ex = _extract(pos)
        return self._derive(
            lambda: [min(self._data(), key=ex)] if self._data() else [],
            "min_by",
        )

    def max_by(self, pos=None) -> "DataSet":
        ex = _extract(pos)
        return self._derive(
            lambda: [max(self._data(), key=ex)] if self._data() else [],
            "max_by",
        )

    # -- set ops ----------------------------------------------------------
    def union(self, *others: "DataSet") -> "DataSet":
        def run():
            out = list(self._data())
            for o in others:
                out.extend(o._data())
            return out

        return self._derive(run, "union", *others)

    def distinct(self, pos=None) -> "DataSet":
        ex = _extract(pos)

        def run():
            seen, out = set(), []
            for e in self._data():
                k = ex(e)
                if k not in seen:
                    seen.add(k)
                    out.append(e)
            return out

        return self._derive(run, "distinct")

    def first(self, n: int) -> "DataSet":
        return self._derive(lambda: self._data()[:n], "first")

    def sort_partition(self, pos=None, ascending: bool = True) -> "DataSet":
        ex = _extract(pos)
        return self._derive(
            lambda: sorted(self._data(), key=ex, reverse=not ascending),
            "sort_partition",
        )

    def zip_with_index(self) -> "DataSet":
        return self._derive(
            lambda: list(enumerate(self._data())), "zip_with_index"
        )

    # -- partitioning annotations (no-ops on the single host plan) -------
    def partition_by_hash(self, pos=None) -> "DataSet":
        return self

    def rebalance(self) -> "DataSet":
        return self

    # -- keyed ------------------------------------------------------------
    def group_by(self, pos=None) -> "GroupedDataSet":
        return GroupedDataSet(self, _extract(pos))

    # -- binary -----------------------------------------------------------
    def join(self, other: "DataSet") -> "JoinBuilder":
        return JoinBuilder(self, other, "inner")

    def left_outer_join(self, other: "DataSet") -> "JoinBuilder":
        return JoinBuilder(self, other, "left")

    def right_outer_join(self, other: "DataSet") -> "JoinBuilder":
        return JoinBuilder(self, other, "right")

    def full_outer_join(self, other: "DataSet") -> "JoinBuilder":
        return JoinBuilder(self, other, "full")

    def co_group(self, other: "DataSet") -> "JoinBuilder":
        return JoinBuilder(self, other, "cogroup")

    def cross(self, other: "DataSet") -> "DataSet":
        def run():
            return [
                (a, b) for a in self._data() for b in other._data()
            ]

        return self._derive(run, "cross", other)

    # -- iterations --------------------------------------------------------
    def iterate(self, max_iterations: int,
                step: Callable[["DataSet"], "DataSet"],
                convergence: Optional[Callable[[List, List], bool]] = None,
                ) -> "DataSet":
        """Bulk iteration (ref IterativeDataSet.closeWith): applies `step`
        up to max_iterations times; optional convergence(prev, cur) stops
        early (the aggregator-based convergence criterion)."""

        def run():
            cur = self._data()
            for _ in range(max_iterations):
                nxt = step(self.env.from_collection(cur))._data()
                if convergence is not None and convergence(cur, nxt):
                    cur = nxt
                    break
                cur = nxt
            return cur

        return self._derive(run, "bulk_iteration")

    def delta_iterate(
        self, workset: "DataSet", key, max_iterations: int,
        step: Callable[["DataSet", "DataSet"], Tuple["DataSet", "DataSet"]],
    ) -> "DataSet":
        """Delta iteration (ref DeltaIterationBase): self is the initial
        solution set (keyed by `key`); `step(solution, workset)` returns
        (delta, next_workset); deltas merge into the solution by key;
        terminates when the workset empties or max_iterations is hit."""
        key_fn = _extract(key)

        def run():
            solution = {key_fn(e): e for e in self._data()}
            ws = workset._data()
            for _ in range(max_iterations):
                if not ws:
                    break
                delta, nxt_ws = step(
                    self.env.from_collection(list(solution.values())),
                    self.env.from_collection(ws),
                )
                for e in delta._data():
                    solution[key_fn(e)] = e
                ws = nxt_ws._data()
            return list(solution.values())

        return self._derive(run, "delta_iteration")


class GroupedDataSet:
    def __init__(self, ds: DataSet, key_fn: Callable):
        self.ds = ds
        self.key_fn = key_fn
        self._sort = None  # (extractor, ascending) for sorted groups

    def sort_group(self, pos=None, ascending: bool = True) -> "GroupedDataSet":
        self._sort = (_extract(pos), ascending)
        return self

    def _groups(self) -> Dict[Any, List[Any]]:
        groups: Dict[Any, List[Any]] = {}
        for e in self.ds._data():
            groups.setdefault(self.key_fn(e), []).append(e)
        if self._sort is not None:
            ex, asc = self._sort
            for g in groups.values():
                g.sort(key=ex, reverse=not asc)
        return groups

    def reduce(self, fn) -> DataSet:
        def run():
            out = []
            for g in self._groups().values():
                acc = g[0]
                for e in g[1:]:
                    acc = fn(acc, e)
                out.append(acc)
            return out

        return self.ds._derive(run, "grouped_reduce")

    def reduce_group(self, fn) -> DataSet:
        """fn(elements) -> iterable of results per group (ref
        GroupReduceFunction)."""

        def run():
            out = []
            for g in self._groups().values():
                out.extend(fn(g))
            return out

        return self.ds._derive(run, "group_reduce")

    def first(self, n: int) -> DataSet:
        return self.ds._derive(
            lambda: [e for g in self._groups().values() for e in g[:n]],
            "grouped_first",
        )

    # -- device-accelerated numeric aggregation ---------------------------
    def _segment_agg(self, pos, kind: str) -> DataSet:
        """key dictionary-encode on host, segment-reduce on device —
        the batch analog of the streaming window kernels."""
        ex = _extract(pos)

        def run():
            from flink_tpu.ops.segment import grouped_reduce

            data = self.ds._data()
            if not data:
                return []
            keys = [self.key_fn(e) for e in data]
            vals = (
                np.asarray([ex(e) for e in data], np.float32)
                if kind != "count" else np.zeros(len(data))
            )
            uniq, gid = np.unique(np.asarray(keys, dtype=object),
                                  return_inverse=True)
            agg = grouped_reduce(kind, gid, vals, len(uniq))
            return [(k, float(v)) for k, v in zip(uniq.tolist(), agg)]

        return self.ds._derive(run, f"segment_{kind}")

    def sum(self, pos=None) -> DataSet:
        return self._segment_agg(pos, "sum")

    def min(self, pos=None) -> DataSet:
        return self._segment_agg(pos, "min")

    def max(self, pos=None) -> DataSet:
        return self._segment_agg(pos, "max")

    def count(self) -> DataSet:
        return self._segment_agg(lambda e: 1.0, "count")

    def mean(self, pos=None) -> DataSet:
        return self._segment_agg(pos, "mean")

    def aggregate(self, kind: str, pos=None) -> DataSet:
        return self._segment_agg(pos, kind)

    def min_by(self, pos=None) -> DataSet:
        ex = _extract(pos)
        return self.ds._derive(
            lambda: [min(g, key=ex) for g in self._groups().values()],
            "grouped_min_by",
        )

    def max_by(self, pos=None) -> DataSet:
        ex = _extract(pos)
        return self.ds._derive(
            lambda: [max(g, key=ex) for g in self._groups().values()],
            "grouped_max_by",
        )


def _sort_merge_join(lefts, rights, k1, k2, kind, f):
    """Sort-merge local strategy (ref the optimizer's MERGE driver,
    flink-runtime operators/sort/MergeIterator + SortMergeJoinDriver
    rationale: chosen when no side's hash table fits the build budget —
    sorting spills gracefully where a hash table cannot). Returns None
    when keys don't admit a total order (mixed types): the caller falls
    back to hash and records it."""
    try:
        ls = sorted(((k1(e), e) for e in lefts), key=lambda p: p[0])
        rs = sorted(((k2(e), e) for e in rights), key=lambda p: p[0])
    except TypeError:
        return None
    out = []
    i = j = 0
    nl, nr = len(ls), len(rs)
    while i < nl and j < nr:
        kl, kr = ls[i][0], rs[j][0]
        if kl < kr:
            if kind in ("left", "full"):
                out.append(f(ls[i][1], None))
            i += 1
        elif kr < kl:
            if kind in ("right", "full"):
                out.append(f(None, rs[j][1]))
            j += 1
        else:
            # equal-key group: emit the cross product of both runs
            i2 = i
            while i2 < nl and ls[i2][0] == kl:
                i2 += 1
            j2 = j
            while j2 < nr and rs[j2][0] == kr:
                j2 += 1
            for a in range(i, i2):
                for b in range(j, j2):
                    out.append(f(ls[a][1], rs[b][1]))
            i, j = i2, j2
    if kind in ("left", "full"):
        out.extend(f(ls[a][1], None) for a in range(i, nl))
    if kind in ("right", "full"):
        out.extend(f(None, rs[b][1]) for b in range(j, nr))
    return out


def _device_broadcast_join(lefts, rights, k1, k2, build_left, f):
    """Physical broadcast ship on the device mesh for the common fast
    case: INNER join, unique integer build keys. The build side is
    replicated to every shard as a sharding declaration and each shard
    probes its slice (parallel/broadcast.py — the accelerator form of
    BROADCAST_HASH_FIRST/SECOND's copy-to-every-subtask). The kernel
    returns per-probe build-row INDICES, so arbitrary Python payloads
    join host-side from the positions. Returns None when the shape
    doesn't qualify (caller keeps the host hash path)."""
    build, probe = (lefts, rights) if build_left else (rights, lefts)
    bk, pk = (k1, k2) if build_left else (k2, k1)
    if len(build) == 0 or len(probe) == 0:
        return []
    try:
        bkeys = np.asarray([bk(e) for e in build])
        pkeys = np.asarray([pk(e) for e in probe])
    except (TypeError, ValueError, OverflowError):
        return None
    # GENUINE int64 keys only: float keys would silently truncate
    # (1.5 'matching' 1), big ints / mixed types land as object dtype
    if bkeys.dtype.kind != "i" or pkeys.dtype.kind != "i":
        return None
    bkeys = bkeys.astype(np.int64)
    pkeys = pkeys.astype(np.int64)
    if len(np.unique(bkeys)) != len(bkeys):
        return None                     # duplicate build keys: host path
    try:
        from flink_tpu.parallel.broadcast import broadcast_join
        # payload = build-row index; float32 is exact through 2^24
        if len(build) >= (1 << 24):
            return None
        idx, hit = broadcast_join(
            pkeys, bkeys, np.arange(len(build), dtype=np.float32))
    except Exception:                   # no usable mesh: host path
        return None
    out = []
    pos = idx.astype(np.int64)
    for i in np.nonzero(hit)[0]:
        b, p = build[int(pos[i])], probe[int(i)]
        out.append(f(b, p) if build_left else f(p, b))
    return out


class JoinBuilder:
    """a.join(b).where(k1).equal_to(k2).apply(fn) — hash-join execution
    with COST-BASED build-side selection (ref Optimizer.java:396 picking
    HASH_BUILD_FIRST vs HASH_BUILD_SECOND from size estimates, and the
    JoinHint the user may force): the hash table is built over the side
    estimated smaller, probed from the larger. Outer joins keep their
    side semantics regardless of the physical build side."""

    def __init__(self, left: DataSet, right: DataSet, kind: str):
        self.left, self.right, self.kind = left, right, kind
        self.k1 = self.k2 = None
        self.hint = "auto"   # auto | build-left | build-right

    def where(self, pos=None) -> "JoinBuilder":
        self.k1 = _extract(pos)
        return self

    def equal_to(self, pos=None) -> "JoinBuilder":
        self.k2 = _extract(pos)
        return self

    def with_hint(self, hint: str) -> "JoinBuilder":
        """ref JoinOperatorBase.JoinHint (BROADCAST_HASH_FIRST/SECOND):
        force the build side instead of the cost model's choice."""
        if hint not in ("auto", "build-left", "build-right"):
            raise ValueError(f"unknown join hint {hint!r}")
        self.hint = hint
        return self

    def apply(self, fn: Optional[Callable] = None) -> DataSet:
        if self.k1 is None or self.k2 is None:
            raise ValueError("join requires where(...).equal_to(...)")
        k1, k2, kind = self.k1, self.k2, self.kind
        node_holder = []

        def run():
            lefts, rights = self.left._data(), self.right._data()
            out = []
            if kind == "cogroup":
                build: Dict[Any, List[Any]] = {}
                for r in rights:
                    build.setdefault(k2(r), []).append(r)
                probe: Dict[Any, List[Any]] = {}
                for l in lefts:
                    probe.setdefault(k1(l), []).append(l)
                f = fn or (lambda ls, rs: [(ls, rs)])
                for k in {**build, **probe}:
                    out.extend(f(probe.get(k, []), build.get(k, [])))
                return out
            # strategy decision with EXACT sizes (both inputs are
            # materialized just above) through the same procedure the
            # plan-time estimate pass uses
            ship, local, build_left = _decide_join_strategies(
                len(lefts), len(rights), self.hint,
                self.left.env,
            )
            f = fn or (lambda l, r: (l, r))
            if local == "sort-merge":
                merged = _sort_merge_join(lefts, rights, k1, k2, kind, f)
                if merged is not None:
                    if node_holder:
                        node_holder[0].strategy = \
                            f"ship={ship}, local=sort-merge"
                    return merged
                local = (f"hash build-"
                         f"{'left' if build_left else 'right'} "
                         f"(keys unsortable)")
            if ship.startswith("broadcast-hash") and kind == "inner":
                dev = _device_broadcast_join(
                    lefts, rights, k1, k2, build_left, f)
                if dev is not None:
                    if node_holder:
                        node_holder[0].strategy = (
                            f"ship={ship} (device mesh), local={local}")
                    return dev
            if node_holder:
                node_holder[0].strategy = f"ship={ship}, local={local}"
            if build_left:
                build = {}
                for l in lefts:
                    build.setdefault(k1(l), []).append(l)
                matched = set()
                for r in rights:
                    key = k2(r)
                    ls = build.get(key)
                    if ls:
                        matched.add(key)
                        out.extend(f(l, r) for l in ls)
                    elif kind in ("right", "full"):
                        out.append(f(None, r))
                if kind in ("left", "full"):
                    for key, ls in build.items():
                        if key not in matched:
                            out.extend(f(l, None) for l in ls)
            else:
                build = {}
                for r in rights:
                    build.setdefault(k2(r), []).append(r)
                matched = set()
                for l in lefts:
                    key = k1(l)
                    rs = build.get(key)
                    if rs:
                        matched.add(key)
                        out.extend(f(l, r) for r in rs)
                    elif kind in ("left", "full"):
                        out.append(f(l, None))
                if kind in ("right", "full"):
                    for key, rs in build.items():
                        if key not in matched:
                            out.extend(f(None, r) for r in rs)
            return out

        node = self.left._derive(run, f"{kind}_join", self.right)
        if kind != "cogroup":          # cogroup never consults ship/local
            node.join_hint = self.hint  # plan() re-derives from estimates
        node_holder.append(node)
        return node

    # joining without a function yields (left, right) pairs, matching the
    # reference's DefaultJoin
    def project(self) -> DataSet:
        return self.apply(None)
