"""Batch ExecutionEnvironment (ref flink-java ExecutionEnvironment,
SURVEY §2.6)."""

from __future__ import annotations

import csv as _csv
from typing import Any, Iterable, List

import numpy as np

from flink_tpu.dataset.dataset import DataSet


class ExecutionEnvironment:
    @staticmethod
    def get_execution_environment() -> "ExecutionEnvironment":
        return ExecutionEnvironment()

    def from_collection(self, data: Iterable[Any]) -> DataSet:
        data = list(data)
        ds = DataSet(self, lambda: data, "source")
        ds.size_hint = len(data)   # exact, free: feeds the cost model
        return ds

    def from_elements(self, *elements: Any) -> DataSet:
        return self.from_collection(list(elements))

    def generate_sequence(self, start: int, end: int) -> DataSet:
        return DataSet(
            self, lambda: list(range(start, end + 1)), "sequence"
        )

    def read_text_file(self, path: str) -> DataSet:
        def run():
            from flink_tpu.core.filesystem import get_filesystem

            fs, p = get_filesystem(path)
            with fs.open(p, "r") as f:
                return [line.rstrip("\n") for line in f]

        return DataSet(self, run, "text_file")

    def read_avro_file(self, path: str) -> DataSet:
        """Avro object-container file -> records as dicts (ref
        AvroInputFormat; spec-implemented codec, connectors/avro.py)."""
        def run():
            from flink_tpu.connectors.avro import AvroInputFormat

            return AvroInputFormat(path).read_all()

        return DataSet(self, run, "avro_file")

    def read_jdbc(self, connection_factory, query: str,
                  parameters=None) -> DataSet:
        """Database query (splits per parameter tuple) -> row tuples
        (ref JDBCInputFormat over DB-API, connectors/jdbc.py)."""
        def run():
            from flink_tpu.connectors.jdbc import DbApiInputFormat

            return DbApiInputFormat(
                connection_factory, query, parameters
            ).read_all()

        return DataSet(self, run, "jdbc")

    def read_csv_file(self, path: str, types=None, delimiter=",") -> DataSet:
        def run():
            from flink_tpu.core.filesystem import get_filesystem

            fs, p = get_filesystem(path)
            out = []
            with fs.open(p, "r", newline="") as f:
                for row in _csv.reader(f, delimiter=delimiter):
                    if types:
                        row = [t(v) for t, v in zip(types, row)]
                    out.append(tuple(row))
            return out

        return DataSet(self, run, "csv_file")
