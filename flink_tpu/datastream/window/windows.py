"""Window types — TimeWindow / GlobalWindow.

Mirrors the reference's api/windowing/windows (TimeWindow.java,
GlobalWindow.java): a window is a hashable value object usable as a state
namespace; TimeWindow spans [start, end) and fires at max_timestamp() =
end - 1.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class TimeWindow:
    start: int
    end: int  # exclusive

    def max_timestamp(self) -> int:
        return self.end - 1

    def intersects(self, other: "TimeWindow") -> bool:
        return self.start <= other.end and other.start <= self.end

    def cover(self, other: "TimeWindow") -> "TimeWindow":
        return TimeWindow(min(self.start, other.start),
                          max(self.end, other.end))


@dataclass(frozen=True)
class GlobalWindow:
    """The single window of GlobalWindows (ref GlobalWindow.java)."""

    def max_timestamp(self) -> int:
        return 2**62  # never reached by watermarks

    _INSTANCE = None

    @staticmethod
    def get() -> "GlobalWindow":
        if GlobalWindow._INSTANCE is None:
            GlobalWindow._INSTANCE = GlobalWindow()
        return GlobalWindow._INSTANCE
