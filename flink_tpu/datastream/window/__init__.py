from flink_tpu.datastream.window.assigners import (  # noqa: F401
    EventTimeSessionWindows,
    ProcessingTimeSessionWindows,
    SlidingEventTimeWindows,
    SlidingProcessingTimeWindows,
    TumblingEventTimeWindows,
    TumblingProcessingTimeWindows,
    WindowAssigner,
)
