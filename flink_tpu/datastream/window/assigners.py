"""Window assigners — the catalog of the reference's
api/windowing/assigners (SURVEY §2.5), TPU-adapted.

In the reference an assigner maps each element to window objects
(TumblingEventTimeWindows etc.). Here aligned time windows compile to a
pane-ring `WindowSpec` (ops/window_kernels.py): panes of `slide` ticks,
windows of `size` ticks. Processing-time variants use the same machinery
with host-clock watermarks (the executor drives them). Session windows are
handled by a dedicated merging path (cep/session rounds); Global windows +
count triggers by the count-window path.
"""

from __future__ import annotations

from dataclasses import dataclass

from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.datastream.window.windows import GlobalWindow, TimeWindow


@dataclass(frozen=True)
class WindowAssigner:
    size_ms: int
    slide_ms: int
    is_event_time: bool = True

    @property
    def is_session(self) -> bool:
        return False

    # -- host semantics (generic window operator path) -------------------
    # Device stages compile the same arithmetic into the pane ring; these
    # mirror TumblingEventTimeWindows.assignWindows / SlidingEventTime-
    # Windows.assignWindows for the host operator.
    def assign_windows(self, ts: int):
        if self.size_ms == self.slide_ms:
            start = ts - (ts % self.size_ms)
            return [TimeWindow(start, start + self.size_ms)]
        last_start = ts - (ts % self.slide_ms)
        out = []
        start = last_start
        while start > ts - self.size_ms:
            out.append(TimeWindow(start, start + self.size_ms))
            start -= self.slide_ms
        return out

    def default_trigger(self):
        from flink_tpu.datastream.window import triggers as tg

        return (tg.EventTimeTrigger() if self.is_event_time
                else tg.ProcessingTimeTrigger())

    @property
    def is_merging(self) -> bool:
        return False


class TumblingEventTimeWindows(WindowAssigner):
    @staticmethod
    def of(size_ms: int) -> "WindowAssigner":
        return WindowAssigner(size_ms, size_ms, True)


class SlidingEventTimeWindows(WindowAssigner):
    @staticmethod
    def of(size_ms: int, slide_ms: int) -> "WindowAssigner":
        return WindowAssigner(size_ms, slide_ms, True)


class TumblingProcessingTimeWindows(WindowAssigner):
    @staticmethod
    def of(size_ms: int) -> "WindowAssigner":
        return WindowAssigner(size_ms, size_ms, False)


class SlidingProcessingTimeWindows(WindowAssigner):
    @staticmethod
    def of(size_ms: int, slide_ms: int) -> "WindowAssigner":
        return WindowAssigner(size_ms, slide_ms, False)


@dataclass(frozen=True)
class CountWindowAssigner:
    """countWindow(N): tumbling windows of N elements per key (ref
    KeyedStream.countWindow = GlobalWindows + CountTrigger + purge)."""

    size_n: int
    is_event_time: bool = False

    @property
    def is_session(self) -> bool:
        return False


@dataclass(frozen=True)
class GlobalWindows:
    """All elements into one global window; fires only via a custom
    trigger (ref GlobalWindows.java, default NeverTrigger)."""

    is_event_time: bool = False
    size_ms: int = 0
    slide_ms: int = 0

    @staticmethod
    def create() -> "GlobalWindows":
        return GlobalWindows()

    @property
    def is_session(self) -> bool:
        return False

    @property
    def is_merging(self) -> bool:
        return False

    def assign_windows(self, ts: int):
        return [GlobalWindow.get()]

    def default_trigger(self):
        from flink_tpu.datastream.window import triggers as tg

        return tg.NeverTrigger()


@dataclass(frozen=True)
class SessionWindowAssigner:
    """Session windows (gap-merged); executed by the session-merge path."""

    gap_ms: int
    is_event_time: bool = True

    @property
    def is_session(self) -> bool:
        return True

    @property
    def is_merging(self) -> bool:
        return True

    def assign_windows(self, ts: int):
        return [TimeWindow(ts, ts + self.gap_ms)]

    def default_trigger(self):
        from flink_tpu.datastream.window import triggers as tg

        return (tg.EventTimeTrigger() if self.is_event_time
                else tg.ProcessingTimeTrigger())


class EventTimeSessionWindows:
    @staticmethod
    def with_gap(gap_ms: int) -> SessionWindowAssigner:
        return SessionWindowAssigner(gap_ms, True)


class ProcessingTimeSessionWindows:
    @staticmethod
    def with_gap(gap_ms: int) -> SessionWindowAssigner:
        return SessionWindowAssigner(gap_ms, False)
