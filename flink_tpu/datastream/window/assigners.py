"""Window assigners — the catalog of the reference's
api/windowing/assigners (SURVEY §2.5), TPU-adapted.

In the reference an assigner maps each element to window objects
(TumblingEventTimeWindows etc.). Here aligned time windows compile to a
pane-ring `WindowSpec` (ops/window_kernels.py): panes of `slide` ticks,
windows of `size` ticks. Processing-time variants use the same machinery
with host-clock watermarks (the executor drives them). Session windows are
handled by a dedicated merging path (cep/session rounds); Global windows +
count triggers by the count-window path.
"""

from __future__ import annotations

from dataclasses import dataclass

from flink_tpu.core.time import TimeCharacteristic


@dataclass(frozen=True)
class WindowAssigner:
    size_ms: int
    slide_ms: int
    is_event_time: bool = True

    @property
    def is_session(self) -> bool:
        return False


class TumblingEventTimeWindows(WindowAssigner):
    @staticmethod
    def of(size_ms: int) -> "WindowAssigner":
        return WindowAssigner(size_ms, size_ms, True)


class SlidingEventTimeWindows(WindowAssigner):
    @staticmethod
    def of(size_ms: int, slide_ms: int) -> "WindowAssigner":
        return WindowAssigner(size_ms, slide_ms, True)


class TumblingProcessingTimeWindows(WindowAssigner):
    @staticmethod
    def of(size_ms: int) -> "WindowAssigner":
        return WindowAssigner(size_ms, size_ms, False)


class SlidingProcessingTimeWindows(WindowAssigner):
    @staticmethod
    def of(size_ms: int, slide_ms: int) -> "WindowAssigner":
        return WindowAssigner(size_ms, slide_ms, False)


@dataclass(frozen=True)
class CountWindowAssigner:
    """countWindow(N): tumbling windows of N elements per key (ref
    KeyedStream.countWindow = GlobalWindows + CountTrigger + purge)."""

    size_n: int
    is_event_time: bool = False

    @property
    def is_session(self) -> bool:
        return False


@dataclass(frozen=True)
class SessionWindowAssigner:
    """Session windows (gap-merged); executed by the session-merge path."""

    gap_ms: int
    is_event_time: bool = True

    @property
    def is_session(self) -> bool:
        return True


class EventTimeSessionWindows:
    @staticmethod
    def with_gap(gap_ms: int) -> SessionWindowAssigner:
        return SessionWindowAssigner(gap_ms, True)


class ProcessingTimeSessionWindows:
    @staticmethod
    def with_gap(gap_ms: int) -> SessionWindowAssigner:
        return SessionWindowAssigner(gap_ms, False)
