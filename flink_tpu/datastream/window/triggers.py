"""Trigger catalog — when windows fire.

Mirrors the reference's api/windowing/triggers (SURVEY §2.5: 9 files,
TriggerResult.java CONTINUE/FIRE/PURGE/FIRE_AND_PURGE): a Trigger decides,
per element and per timer, whether the window's contents are emitted and/or
cleared. Triggers keep their own per-(key, window) state through the
TriggerContext (partitioned state namespaced by window), exactly as the
reference's Trigger.TriggerContext.getPartitionedState does.

These drive the **generic host window operator** (runtime/window_operator).
The device window kernels implement the default EventTimeTrigger /
ProcessingTimeTrigger semantics natively; attaching any custom trigger
routes the stage to the generic operator.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Callable

from flink_tpu.state.descriptors import (
    ReducingStateDescriptor,
    ValueStateDescriptor,
)


class TriggerResult(enum.Enum):
    CONTINUE = (False, False)
    FIRE = (True, False)
    PURGE = (False, True)
    FIRE_AND_PURGE = (True, True)

    @property
    def is_fire(self) -> bool:
        return self.value[0]

    @property
    def is_purge(self) -> bool:
        return self.value[1]


class Trigger:
    """Trigger.java contract. ctx is a TriggerContext (window_operator.py):
    .current_watermark, .current_processing_time,
    .register_event_time_timer(ts), .register_processing_time_timer(ts),
    .delete_*_timer(ts), .get_partitioned_state(descriptor).
    """

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        raise NotImplementedError

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def can_merge(self) -> bool:
        return False

    def on_merge(self, window, ctx) -> None:
        raise NotImplementedError(f"{type(self).__name__} cannot merge")

    def clear(self, window, ctx) -> None:
        pass


class EventTimeTrigger(Trigger):
    """Fires once the watermark passes the window end (ref
    EventTimeTrigger.java)."""

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        if window.max_timestamp() <= ctx.current_watermark:
            return TriggerResult.FIRE  # late but within allowed lateness
        ctx.register_event_time_timer(window.max_timestamp())
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        return (TriggerResult.FIRE if time == window.max_timestamp()
                else TriggerResult.CONTINUE)

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        if window.max_timestamp() > ctx.current_watermark:
            ctx.register_event_time_timer(window.max_timestamp())

    def clear(self, window, ctx) -> None:
        ctx.delete_event_time_timer(window.max_timestamp())

    @staticmethod
    def create() -> "EventTimeTrigger":
        return EventTimeTrigger()


class ProcessingTimeTrigger(Trigger):
    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        ctx.register_processing_time_timer(window.max_timestamp())
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.FIRE

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        ctx.register_processing_time_timer(window.max_timestamp())

    def clear(self, window, ctx) -> None:
        ctx.delete_processing_time_timer(window.max_timestamp())

    @staticmethod
    def create() -> "ProcessingTimeTrigger":
        return ProcessingTimeTrigger()


class CountTrigger(Trigger):
    """Fires every `n` elements (ref CountTrigger.java); keeps the count in
    per-(key, window) ReducingState."""

    def __init__(self, n: int):
        self.n = n
        self._desc = ReducingStateDescriptor("trigger-count", kind="sum")

    @staticmethod
    def of(n: int) -> "CountTrigger":
        return CountTrigger(n)

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        st = ctx.get_partitioned_state(self._desc)
        st.add(1)
        if st.get() >= self.n:
            st.clear()
            return TriggerResult.FIRE
        return TriggerResult.CONTINUE

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        # sum the per-window counts of the merged windows into the result
        # window's namespace (ref Trigger.OnMergeContext.mergePartitionedState)
        ctx.merge_partitioned_state(self._desc)

    def clear(self, window, ctx) -> None:
        ctx.get_partitioned_state(self._desc).clear()


class ContinuousEventTimeTrigger(Trigger):
    """Fires every `interval` of event time within the window (ref
    ContinuousEventTimeTrigger.java)."""

    def __init__(self, interval_ms: int):
        self.interval = interval_ms
        self._desc = ReducingStateDescriptor(
            "trigger-fire-time", kind="min",
        )

    @staticmethod
    def of(interval_ms: int) -> "ContinuousEventTimeTrigger":
        return ContinuousEventTimeTrigger(interval_ms)

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        if window.max_timestamp() <= ctx.current_watermark:
            return TriggerResult.FIRE
        ctx.register_event_time_timer(window.max_timestamp())
        st = ctx.get_partitioned_state(self._desc)
        if st.get() is None:
            start = timestamp - (timestamp % self.interval)
            nxt = start + self.interval
            ctx.register_event_time_timer(nxt)
            st.add(nxt)
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        if time == window.max_timestamp():
            return TriggerResult.FIRE
        st = ctx.get_partitioned_state(self._desc)
        fire_ts = st.get()
        if fire_ts is not None and fire_ts == time:
            st.clear()
            nxt = time + self.interval
            ctx.register_event_time_timer(nxt)
            st.add(nxt)
            return TriggerResult.FIRE
        return TriggerResult.CONTINUE

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        # keep the earliest pending continuous-fire time across the merged
        # windows (min-reducing state merge), plus the end-of-window timer
        ctx.merge_partitioned_state(self._desc)
        st = ctx.get_partitioned_state(self._desc)
        if st.get() is not None:
            ctx.register_event_time_timer(st.get())
        ctx.register_event_time_timer(window.max_timestamp())

    def clear(self, window, ctx) -> None:
        ctx.get_partitioned_state(self._desc).clear()


class ContinuousProcessingTimeTrigger(Trigger):
    def __init__(self, interval_ms: int):
        self.interval = interval_ms
        self._desc = ReducingStateDescriptor("trigger-fire-time", kind="min")

    @staticmethod
    def of(interval_ms: int) -> "ContinuousProcessingTimeTrigger":
        return ContinuousProcessingTimeTrigger(interval_ms)

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        now = ctx.current_processing_time
        st = ctx.get_partitioned_state(self._desc)
        if st.get() is None:
            start = now - (now % self.interval)
            nxt = start + self.interval
            ctx.register_processing_time_timer(nxt)
            st.add(nxt)
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        st = ctx.get_partitioned_state(self._desc)
        st.clear()
        nxt = time + self.interval
        ctx.register_processing_time_timer(nxt)
        st.add(nxt)
        return TriggerResult.FIRE

    def clear(self, window, ctx) -> None:
        ctx.get_partitioned_state(self._desc).clear()


class DeltaTrigger(Trigger):
    """Fires when delta(last_fired_element, element) > threshold (ref
    DeltaTrigger.java)."""

    def __init__(self, threshold: float, delta_fn: Callable[[Any, Any], float]):
        self.threshold = threshold
        self.delta_fn = delta_fn
        self._desc = ValueStateDescriptor("trigger-last-element")

    @staticmethod
    def of(threshold: float, delta_fn) -> "DeltaTrigger":
        return DeltaTrigger(threshold, delta_fn)

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        st = ctx.get_partitioned_state(self._desc)
        last = st.value()
        if last is None:
            st.update(element)
            return TriggerResult.CONTINUE
        if self.delta_fn(last, element) > self.threshold:
            st.update(element)
            return TriggerResult.FIRE
        return TriggerResult.CONTINUE

    def clear(self, window, ctx) -> None:
        ctx.get_partitioned_state(self._desc).clear()


class PurgingTrigger(Trigger):
    """Turns any FIRE of the wrapped trigger into FIRE_AND_PURGE (ref
    PurgingTrigger.java)."""

    def __init__(self, inner: Trigger):
        self.inner = inner

    @staticmethod
    def of(inner: Trigger) -> "PurgingTrigger":
        return PurgingTrigger(inner)

    def _purge(self, r: TriggerResult) -> TriggerResult:
        return TriggerResult.FIRE_AND_PURGE if r.is_fire else r

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        return self._purge(self.inner.on_element(element, timestamp, window, ctx))

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        return self._purge(self.inner.on_event_time(time, window, ctx))

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        return self._purge(self.inner.on_processing_time(time, window, ctx))

    def can_merge(self) -> bool:
        return self.inner.can_merge()

    def on_merge(self, window, ctx) -> None:
        self.inner.on_merge(window, ctx)

    def clear(self, window, ctx) -> None:
        self.inner.clear(window, ctx)


class NeverTrigger(Trigger):
    """GlobalWindows' default: never fires (ref GlobalWindows.NeverTrigger)."""

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        pass
