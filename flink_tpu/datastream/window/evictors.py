"""Evictor catalog — element removal before/after window evaluation.

Mirrors the reference's api/windowing/evictors (SURVEY §2.5:
CountEvictor/DeltaEvictor/TimeEvictor with the 1.2 evictBefore/evictAfter
contract). Evicting windows buffer full element lists (the reference's
EvictingWindowOperator ListState path), so attaching an evictor routes the
stage to the generic host window operator.

Elements are (value, timestamp) pairs in insertion order; evict_* return the
retained list.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

TimestampedValue = Tuple[Any, int]


class Evictor:
    def evict_before(self, elements: List[TimestampedValue], size: int,
                     window) -> List[TimestampedValue]:
        return elements

    def evict_after(self, elements: List[TimestampedValue], size: int,
                    window) -> List[TimestampedValue]:
        return elements


class CountEvictor(Evictor):
    """Keeps at most `n` (most recent) elements (ref CountEvictor.java)."""

    def __init__(self, n: int, do_evict_after: bool = False):
        self.n = n
        self.do_evict_after = do_evict_after

    @staticmethod
    def of(n: int, do_evict_after: bool = False) -> "CountEvictor":
        return CountEvictor(n, do_evict_after)

    def _evict(self, elements, size, window):
        if size <= self.n:
            return elements
        return elements[size - self.n:]

    def evict_before(self, elements, size, window):
        return elements if self.do_evict_after else self._evict(
            elements, size, window)

    def evict_after(self, elements, size, window):
        return self._evict(elements, size, window) if self.do_evict_after \
            else elements


class DeltaEvictor(Evictor):
    """Evicts elements whose delta to the LAST element exceeds the
    threshold (ref DeltaEvictor.java)."""

    def __init__(self, threshold: float, delta_fn: Callable[[Any, Any], float],
                 do_evict_after: bool = False):
        self.threshold = threshold
        self.delta_fn = delta_fn
        self.do_evict_after = do_evict_after

    @staticmethod
    def of(threshold: float, delta_fn, do_evict_after: bool = False):
        return DeltaEvictor(threshold, delta_fn, do_evict_after)

    def _evict(self, elements, size, window):
        if not elements:
            return elements
        last = elements[-1][0]
        return [e for e in elements
                if self.delta_fn(e[0], last) < self.threshold]

    def evict_before(self, elements, size, window):
        return elements if self.do_evict_after else self._evict(
            elements, size, window)

    def evict_after(self, elements, size, window):
        return self._evict(elements, size, window) if self.do_evict_after \
            else elements


class TimeEvictor(Evictor):
    """Keeps elements within `window_size_ms` of the newest element's
    timestamp (ref TimeEvictor.java)."""

    def __init__(self, window_size_ms: int, do_evict_after: bool = False):
        self.window_size_ms = window_size_ms
        self.do_evict_after = do_evict_after

    @staticmethod
    def of(window_size_ms: int, do_evict_after: bool = False) -> "TimeEvictor":
        return TimeEvictor(window_size_ms, do_evict_after)

    def _evict(self, elements, size, window):
        if not elements:
            return elements
        has_ts = any(ts is not None for _, ts in elements)
        if not has_ts:
            return elements
        max_ts = max(ts for _, ts in elements if ts is not None)
        cutoff = max_ts - self.window_size_ms
        # the reference evicts ts <= cutoff (TimeEvictor.java evictedMaxTime
        # comparison), so the boundary element goes too
        return [e for e in elements if e[1] is None or e[1] > cutoff]

    def evict_before(self, elements, size, window):
        return elements if self.do_evict_after else self._evict(
            elements, size, window)

    def evict_after(self, elements, size, window):
        return self._evict(elements, size, window) if self.do_evict_after \
            else elements
