from flink_tpu.datastream.environment import StreamExecutionEnvironment  # noqa: F401
from flink_tpu.datastream.datastream import DataStream, KeyedStream, WindowedStream  # noqa: F401
