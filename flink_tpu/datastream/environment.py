"""StreamExecutionEnvironment — job configuration + execution entry.

Mirrors the reference's StreamExecutionEnvironment
(api/environment/StreamExecutionEnvironment.java:1496 execute), TPU-adapted:
execute() translates the recorded transformation graph into compiled SPMD
stages and drives them with the local executor over a device mesh (the
in-process analog of LocalStreamEnvironment + MiniCluster, SURVEY §3.1).
"""

from __future__ import annotations

from typing import Any, List, Optional

from flink_tpu.core.config import Configuration, CoreOptions
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.datastream.datastream import DataStream
from flink_tpu.graph import stream_graph as sg
from flink_tpu.runtime import sources as src_mod


class StreamExecutionEnvironment:
    def __init__(self, config: Optional[Configuration] = None):
        # global defaults (conf/flink-tpu-conf.yaml via $FLINK_TPU_CONF_DIR,
        # the GlobalConfiguration role) under the program's explicit
        # configuration — the reference's env.getConfig layering
        from flink_tpu.core.config import load_global_configuration

        self.config = load_global_configuration().merge(
            config or Configuration()
        )
        self.parallelism = self.config.get(CoreOptions.DEFAULT_PARALLELISM)
        self.max_parallelism = self.config.get(CoreOptions.MAX_PARALLELISM)
        self.batch_size = self.config.get(CoreOptions.BATCH_SIZE)
        self.time_characteristic = TimeCharacteristic.ProcessingTime
        self.checkpoint_interval_steps = self.config.get(
            CoreOptions.CHECKPOINT_INTERVAL_STEPS
        )
        self.checkpoint_dir = self.config.get(CoreOptions.CHECKPOINT_DIR)
        # validate here, not per stage loop: every stage kind consults
        # this key (a typo must fail loudly for ALL of them, not only
        # the windowed path)
        ck_mode = self.config.get_str("checkpoint.mode", "full")
        if ck_mode not in ("full", "incremental"):
            raise ValueError(
                f"checkpoint.mode must be full|incremental, "
                f"got {ck_mode!r}"
            )
        self.state_capacity_per_shard = self.config.get(
            CoreOptions.STATE_SLOTS_PER_SHARD
        )
        self._sinks: List[sg.SinkTransformation] = []
        self.last_job = None  # JobHandle of the last execute()
        from flink_tpu.metrics import MetricRegistry
        from flink_tpu.runtime.queryable import KvStateRegistry

        self.metric_registry = MetricRegistry()
        # config-driven wire reporters (metrics.reporters: "a,b" +
        # metrics.reporter.<name>.class keys, ref MetricRegistry-
        # Configuration.fromConfiguration)
        if self.config.get_str("metrics.reporters", ""):
            import weakref

            from flink_tpu.metrics.reporters import (
                configure_reporters,
                stop_reporters,
            )

            self._reporter_threads = configure_reporters(
                self.metric_registry, self.config
            )
            # reporter threads + sockets die with the environment: the
            # finalizer closes over (threads, registry) only, never the
            # env itself, so GC of a dropped env reclaims them instead of
            # leaking a thread + socket per environment forever
            weakref.finalize(self, stop_reporters,
                             self._reporter_threads, self.metric_registry)
        self._control = None  # cluster.JobControl when cluster-submitted
        self._kv_registry = KvStateRegistry()
        # job-scoped TypeSerializer registry (lazily forked from the
        # process default on first registration; ref
        # ExecutionConfig.registerTypeWithKryoSerializer)
        self.serializer_registry = None

    def register_type_serializer(self, py_type, serializer):
        """Pin a custom TypeSerializer for a Python type; state snapshots
        of this job route values of that type through it."""
        from flink_tpu.core.serializers import (
            DEFAULT_REGISTRY,
            SerializerRegistry,
        )

        if self.serializer_registry is None:
            self.serializer_registry = SerializerRegistry(
                copy_from=DEFAULT_REGISTRY
            )
        self.serializer_registry.register(py_type, serializer)
        return self

    def query_state(self, name: str, key):
        """Point lookup into a running/finished job's queryable state
        (ref QueryableStateClient against the local environment)."""
        return self._kv_registry.query(name, key)

    # -- configuration (fluent, reference-shaped) ------------------------
    @staticmethod
    def get_execution_environment(config=None) -> "StreamExecutionEnvironment":
        return StreamExecutionEnvironment(config)

    def set_parallelism(self, p: int) -> "StreamExecutionEnvironment":
        self.parallelism = p
        return self

    def set_max_parallelism(self, p: int) -> "StreamExecutionEnvironment":
        self.max_parallelism = p
        return self

    def set_stream_time_characteristic(self, tc: TimeCharacteristic):
        self.time_characteristic = tc
        return self

    def set_buffer_timeout(self, _ms: int):
        return self  # batching cadence is the executor's; accepted for parity

    def enable_checkpointing(self, interval_steps: int, directory=None):
        self.checkpoint_interval_steps = interval_steps
        if directory:
            self.checkpoint_dir = directory
        return self

    def set_state_capacity(self, slots_per_shard: int):
        self.state_capacity_per_shard = slots_per_shard
        return self

    # -- sources ---------------------------------------------------------
    def add_source(self, source: src_mod.Source, name="source") -> DataStream:
        t = sg.SourceTransformation(name, None, source=source)
        return DataStream(self, t)

    def from_collection(self, elements) -> DataStream:
        return self.add_source(src_mod.CollectionSource(list(elements)))

    def from_elements(self, *elements) -> DataStream:
        return self.from_collection(list(elements))

    def socket_text_stream(self, host: str, port: int) -> DataStream:
        return self.add_source(src_mod.SocketTextStreamSource(host, port))

    def read_text_file(self, path: str) -> DataStream:
        return self.add_source(src_mod.FileTextSource(path))

    def generate_sequence(self, start: int, end: int) -> DataStream:
        import numpy as np

        def gen(offset, n):
            vals = np.arange(start + offset, start + offset + n, dtype=np.int64)
            return {"value": vals}, None

        return self.add_source(
            src_mod.GeneratorSource(gen, total=end - start + 1)
        )

    # -- execution -------------------------------------------------------
    def execute(self, job_name: str = "flink-tpu-job",
                restore_from: Optional[str] = None):
        """restore_from: checkpoint/savepoint directory to resume from
        (the reference's `flink run -s <savepoint>` role)."""
        from flink_tpu.runtime.executor import LocalExecutor

        executor = LocalExecutor(self)
        self.last_job = executor.run(job_name, self._sinks, restore_from)
        return self.last_job
