"""User function contracts: ProcessFunction family + rich-function lifecycle.

Mirrors the reference's function API (SURVEY §2.1 api/common/functions and
the 1.2 ProcessFunction / TimelyFlatMapFunction at
api/functions/ProcessFunction and StreamTimelyFlatMap): open/close lifecycle,
keyed state access via a RuntimeContext, per-element processing with a
Collector, and event/processing-time timers via a TimerService.

This is the host-side generality path of the framework: arbitrary Python
logic over keyed state. The hot aggregation path compiles to device kernels
instead (runtime/step.py); both share the same key-group semantics so a job
can mix them.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


class Collector:
    """out.collect(x) sink buffer (ref util/Collector.java)."""

    def __init__(self):
        self.buf: List[Any] = []

    def collect(self, value):
        self.buf.append(value)

    def drain(self) -> List[Any]:
        out, self.buf = self.buf, []
        return out


class RichFunction:
    """RichFunction.java lifecycle + runtime context."""

    def open(self, runtime_context: "RuntimeContext"):
        pass

    def close(self):
        pass


class RuntimeContext:
    """Keyed-state access for rich functions (ref RuntimeContext.java +
    KeyedStateStore): get_state/get_list_state/... bound to the operator's
    keyed backend and the current key set by the runtime. Also carries the
    job's accumulator registry (ref addAccumulator/getAccumulator)."""

    def __init__(self, backend, metrics_group=None, subtask_index: int = 0,
                 parallelism: int = 1, accumulators=None,
                 operator_state=None):
        self._backend = backend
        self.metrics_group = metrics_group
        self.subtask_index = subtask_index
        self.parallelism = parallelism
        self._accumulators = accumulators
        self._operator_state = operator_state

    def get_state(self, descriptor):
        return self._backend.get_partitioned_state(descriptor)

    # aliases matching the reference's KeyedStateStore surface
    get_list_state = get_state
    get_reducing_state = get_state
    get_aggregating_state = get_state
    get_map_state = get_state

    # -- operator (non-keyed) state (ref OperatorStateStore) -------------
    def get_operator_list_state(self, name: str):
        """Per-operator list state snapshotting into checkpoints (ref
        CheckpointedFunction's OperatorStateStore.getListState)."""
        if self._operator_state is None:
            raise RuntimeError(
                "no operator state store bound to this operator"
            )
        return self._operator_state.get_list_state(name)

    get_union_list_state = get_operator_list_state

    # -- accumulators (ref RuntimeContext.addAccumulator) ----------------
    def add_accumulator(self, name: str, accumulator):
        if self._accumulators is None:
            raise RuntimeError("no accumulator registry bound to this job")
        self._accumulators.add(name, accumulator)

    def get_accumulator(self, name: str):
        if self._accumulators is None:
            raise RuntimeError("no accumulator registry bound to this job")
        return self._accumulators.get(name)

    def get_int_counter(self, name: str):
        """Convenience matching getIntCounter: register-or-get."""
        from flink_tpu.core.accumulators import IntCounter

        if self._accumulators is None:
            raise RuntimeError("no accumulator registry bound to this job")
        try:
            return self._accumulators.get(name)
        except KeyError:
            acc = IntCounter()
            self._accumulators.add(name, acc)
            return acc


class TimerService:
    """ctx.timer_service() facade (ref TimerService interface)."""

    def __init__(self, internal, current_key_fn: Callable[[], Any],
                 namespace=()):
        self._internal = internal
        self._key = current_key_fn
        self._ns = namespace

    def current_processing_time(self) -> int:
        return self._internal.current_processing_time

    def current_watermark(self) -> int:
        return self._internal.current_watermark

    def register_event_time_timer(self, ts: int):
        self._internal.register_event_time_timer(self._ns, self._key(), ts)

    def register_processing_time_timer(self, ts: int):
        self._internal.register_processing_time_timer(self._ns, self._key(), ts)

    def delete_event_time_timer(self, ts: int):
        self._internal.delete_event_time_timer(self._ns, self._key(), ts)

    def delete_processing_time_timer(self, ts: int):
        self._internal.delete_processing_time_timer(self._ns, self._key(), ts)


class ProcessContext:
    """ctx passed to process_element (ref ProcessFunction.Context)."""

    def __init__(self, timer_service: TimerService):
        self._ts = timer_service
        self.element_timestamp: Optional[int] = None

    def timestamp(self) -> Optional[int]:
        return self.element_timestamp

    def timer_service(self) -> TimerService:
        return self._ts


class OnTimerContext(ProcessContext):
    """ctx passed to on_timer; also exposes the firing key + time domain."""

    def __init__(self, timer_service: TimerService):
        super().__init__(timer_service)
        self.key = None
        self.namespace = None  # the timer's namespace (e.g. its window)
        self.time_domain: str = "event"  # 'event' | 'processing'

    def get_current_key(self):
        return self.key


class ProcessFunction(RichFunction):
    """ProcessFunction contract: per-element hook + timer callback.

    Subclass and override; or use KeyedStream.process(fn) with plain
    callables for the stateless case.
    """

    def process_element(self, value, ctx: ProcessContext, out: Collector):
        raise NotImplementedError

    def on_timer(self, timestamp: int, ctx: OnTimerContext, out: Collector):
        pass


KeyedProcessFunction = ProcessFunction  # 1.2 has one class; alias for parity


class CoMapFunction(RichFunction):
    """CoMapFunction.java — two-input map (ConnectedStreams.map)."""

    def map1(self, value):
        raise NotImplementedError

    def map2(self, value):
        raise NotImplementedError


class CoFlatMapFunction(RichFunction):
    def flat_map1(self, value):
        raise NotImplementedError

    def flat_map2(self, value):
        raise NotImplementedError


class CoProcessFunction(RichFunction):
    """CoProcessFunction — two-input process with shared keyed state."""

    def process_element1(self, value, ctx: ProcessContext, out: Collector):
        raise NotImplementedError

    def process_element2(self, value, ctx: ProcessContext, out: Collector):
        raise NotImplementedError

    def on_timer(self, timestamp: int, ctx: OnTimerContext, out: Collector):
        pass


class BroadcastProcessContext:
    """Writable context for process_broadcast_element: mutate the named
    broadcast states (ref KeyedBroadcastProcessFunction.Context — the
    broadcast state pattern; the reference's transport half is
    BroadcastPartitioner.java:30, the state half arrived in Flink 1.5)."""

    def __init__(self, states, base_ctx):
        self._states = states
        self._base = base_ctx

    def broadcast_state(self, descriptor_or_name) -> dict:
        name = getattr(descriptor_or_name, "name", descriptor_or_name)
        try:
            return self._states[name]
        except KeyError:
            raise ValueError(
                f"unknown broadcast state {name!r}; declare its "
                f"MapStateDescriptor in stream.broadcast(...)"
            ) from None

    def timestamp(self):
        return self._base.timestamp()


class ReadOnlyBroadcastContext(ProcessContext):
    """Context for process_element on the keyed side: broadcast states
    are READ-ONLY here (per-key mutation of replicated state would
    diverge across parallel instances — ref ReadOnlyContext), keyed
    state and timers work as in any ProcessFunction context."""

    def __init__(self, states, base_ctx):
        super().__init__(base_ctx._ts)
        self._states = states
        self._base = base_ctx

    def timestamp(self):
        return self._base.timestamp()

    def broadcast_state(self, descriptor_or_name):
        import types

        name = getattr(descriptor_or_name, "name", descriptor_or_name)
        try:
            return types.MappingProxyType(self._states[name])
        except KeyError:
            raise ValueError(
                f"unknown broadcast state {name!r}; declare its "
                f"MapStateDescriptor in stream.broadcast(...)"
            ) from None


class KeyedBroadcastProcessFunction(RichFunction):
    """Two-input function over keyed main + broadcast control streams
    (ref KeyedBroadcastProcessFunction): every parallel instance sees
    EVERY broadcast element, so identical state updates replicate
    deterministically; keyed elements read the replicated state."""

    def process_element(self, value, ctx: ReadOnlyBroadcastContext,
                        out: Collector):
        raise NotImplementedError

    def process_broadcast_element(self, value, ctx: BroadcastProcessContext,
                                  out: Collector):
        raise NotImplementedError

    def on_timer(self, timestamp: int, ctx: OnTimerContext, out: Collector):
        pass
